"""The device-backed placement engine — the columnar rewrite of
scheduler/stack.go's GenericStack.

Where the reference chains 15 pull-based iterators per node per
placement (stack.go:321-411), this engine:
  1. resolves all static feasibility (constraints, drivers, volumes,
     datacenters, eligibility) into one bool[N] mask via numpy columns
     (ops/targets.py), memoized per (job version, task group);
  2. dispatches ONE fused device kernel (ops/select.py) that places all
     requested instances of the task group, scoring every node each
     step and carrying usage/collision/histogram state in-scan;
  3. assigns concrete ports host-side for just the chosen nodes
     (SURVEY.md §7.3 item 1: only winners need port numbers).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..models import (
    AllocatedResources, AllocatedSharedResources, AllocatedTaskResources,
    AllocMetric, Job, NetworkIndex, Node, NodeScoreMeta, TaskGroup,
)
from ..models.constraints import (CONSTRAINT_DISTINCT_HOSTS,
                                  CONSTRAINT_DISTINCT_PROPERTY)
from ..models.resources import (AllocatedCpuResources,
                                AllocatedMemoryResources)
from ..ops import NodeTable, ProposedIndex, SelectKernel, SelectRequest
from ..ops import spread as spread_ops
from ..ops.select import TOP_K
from ..ops.tables import DIM_NAMES
from ..ops.targets import affinity_columns, constraint_mask
from ..utils.locks import make_lock


# -- cross-eval host-phase reuse (group-commit PR, tentpole part 2) ----
#
# Every eval builds a fresh PlacementEngine, and before this cache the
# per-eval host phase re-derived state that is pure function of
# (job version, task group, node table): the content-addressed static
# key (a walk over every constraint/driver/volume/device ask), the
# group ask vector, the port asks, and the combined static-feasibility
# mask + filter counts. The common case — many evals for the SAME job
# (deployments, batch dispatch, drains) — pays that walk every time.
#
# Two layers:
#   - _ENGINE_CACHE: (namespace, job_id, job_version, tg_name) ->
#     _EngineEntry{static_key, group_ask, port_asks}, pinned to the
#     exact Job object (the store serves one instance per version;
#     `entry.job is job` makes id-recycling and cross-store collisions
#     impossible — a different object with the same key recomputes).
#   - the combined (mask, counts) feasibility result, cached on the
#     TABLE's mask_cache keyed by (static key, datacenters). That dict
#     is shared across delta clones (node attribute/ready columns are
#     shared) and replaced on every node-set rebuild, i.e. exactly
#     when NodeTableCache epoch-bumps the (mirror, version) token —
#     invalidation rides the resident table's own lifecycle.
#
# ENGINE_CACHE_STATS feeds the bench artifact's engine-reuse hit rate
# and the governor's `engine_cache.entries` gauge.

ENGINE_CACHE_MAX = 4096

_ENGINE_CACHE: Dict[Tuple, "_EngineEntry"] = {}
_ENGINE_CACHE_L = make_lock()

ENGINE_CACHE_STATS: Dict[str, int] = {
    "entry_hits": 0, "entry_misses": 0,
    "mask_hits": 0, "mask_misses": 0,
    # feasibility calls on private tables (_dc_key is None): no
    # cross-eval cache exists there, so they are neither hits nor
    # misses — counting them as misses would deflate the hit rate the
    # ROADMAP's TPU validation reads
    "mask_uncached": 0,
}


class _EngineEntry:
    __slots__ = ("job", "static_key", "group_ask", "port_asks")

    def __init__(self, job, static_key, group_ask, port_asks):
        self.job = job
        self.static_key = static_key
        self.group_ask = group_ask
        self.port_asks = port_asks


# -- tasks_updated memo (columnar reconcile engine) --------------------
#
# A deployment wave asks "did the group spec change between job
# versions A and B?" once PER ALLOC; the verdict is a pure function of
# the two Job snapshots and the group name, so the wave should pay ONE
# deep structural diff per (old version, new version, tg) instead of
# one per alloc (BENCH_r05's dominant reconcile cost on 10k-alloc
# jobs). Entries pin BOTH Job objects and re-verify identity on hit —
# the store serves one instance per version, so a mutated or recycled
# object recomputes instead of trusting the key (the _ENGINE_CACHE
# idiom above). TASKS_UPDATED_STATS feeds the bench artifact's
# `tasks_updated_hit_rate` and the governor's
# `reconcile.tasks_updated_hit_rate` gauge.

TASKS_UPDATED_MAX = 4096

_TASKS_UPDATED: Dict[Tuple, tuple] = {}

TASKS_UPDATED_STATS: Dict[str, int] = {"hits": 0, "misses": 0}


def tasks_updated_cached(new_job, old_job, tg_name: str) -> bool:
    key = (new_job.namespace, new_job.id, old_job.version,
           old_job.create_index, new_job.version,
           new_job.job_modify_index, tg_name)
    with _ENGINE_CACHE_L:
        ent = _TASKS_UPDATED.get(key)
        if ent is not None and ent[0] is new_job and ent[1] is old_job:
            TASKS_UPDATED_STATS["hits"] += 1
            return ent[2]
    from .util import tasks_updated
    verdict = tasks_updated(new_job, old_job, tg_name)
    with _ENGINE_CACHE_L:
        TASKS_UPDATED_STATS["misses"] += 1
        while len(_TASKS_UPDATED) >= TASKS_UPDATED_MAX:
            _TASKS_UPDATED.pop(next(iter(_TASKS_UPDATED)))
        _TASKS_UPDATED[key] = (new_job, old_job, verdict)
    return verdict


def note_tasks_updated_broadcast(n_rows: int) -> None:
    """The columnar reconciler answers the spec-change question for
    n_rows allocs with ONE memoized diff, broadcast over the row mask.
    Account the n_rows-1 avoided diffs as hits so
    `tasks_updated_hit_rate` keeps meaning "fraction of per-alloc
    verdicts served without a deep structural diff" under either
    engine."""
    if n_rows > 1:
        with _ENGINE_CACHE_L:
            TASKS_UPDATED_STATS["hits"] += n_rows - 1


def tasks_updated_stats() -> Dict[str, int]:
    return dict(TASKS_UPDATED_STATS)


def tasks_updated_hit_rate() -> float:
    h = TASKS_UPDATED_STATS["hits"]
    m = TASKS_UPDATED_STATS["misses"]
    return h / max(h + m, 1)


def engine_cache_entries() -> int:
    return len(_ENGINE_CACHE)


def engine_cache_stats() -> Dict[str, int]:
    return dict(ENGINE_CACHE_STATS)


def clear_engine_cache() -> None:
    with _ENGINE_CACHE_L:
        _ENGINE_CACHE.clear()
        _TASKS_UPDATED.clear()


@dataclasses.dataclass
class SelectOptions:
    """stack.go SelectOptions."""
    penalty_node_ids: frozenset = frozenset()
    preferred_nodes: Tuple[Node, ...] = ()


@dataclasses.dataclass
class RankedNode:
    """One successful placement option (rank.go RankedNode)."""
    node: Node
    final_score: float
    task_resources: Dict[str, AllocatedTaskResources]
    alloc_resources: Optional[AllocatedSharedResources]
    metrics: AllocMetric
    preempted_allocs: Optional[list] = None


class PlacementEngine:
    def __init__(self, snapshot, sched_config=None):
        self.snapshot = snapshot
        self.config = sched_config or snapshot.scheduler_config()
        self.job: Optional[Job] = None
        self.table: Optional[NodeTable] = None
        self.by_dc: Dict[str, int] = {}
        self.kernel = SelectKernel()
        # dispatch hook: the batched worker swaps this for a gateway
        # that coalesces concurrent evals into one select_many call
        # (server/worker.py BatchGateway)
        self.dispatch = self.kernel.select
        self._mask_cache: Dict[Tuple, np.ndarray] = {}
        # datacenter key for the cross-eval combined-mask cache; None
        # until set_nodes (set_node_list paths stay uncached — private
        # tables don't outlive the eval anyway)
        self._dc_key: Optional[Tuple] = None
        # device-resident feasibility tokens by feas_key (ISSUE 17):
        # set when push_combined parks a combined mask on the mirror
        self._feas_tokens: Dict[Tuple, Tuple] = {}
        self._feas_push_s = 0.0
        # per-eval NetworkIndex cache: shared across select_batch calls so
        # port offers stay consistent between task groups of one plan
        self._net_cache: Dict[str, NetworkIndex] = {}
        # per-eval device accounters, same lifetime/purpose as _net_cache
        self._dev_cache: Dict[str, object] = {}
        self._shared_by_dc: Dict[str, int] = {}
        self._shared_filtered: Dict[str, int] = {}

    # -- setup ---------------------------------------------------------
    def set_job(self, job: Job) -> None:
        self.job = job
        self._mask_cache.clear()

    def set_nodes(self, datacenters: List[str]) -> int:
        """Point at the snapshot's resident node table; readiness and
        datacenter membership become per-eval mask components instead of
        a table rebuild (readyNodesInDCs, scheduler/util.go:233, as a
        cached column filter). Returns the ready-in-DC node count."""
        self.table = self.snapshot.node_table()
        mask, n_ready, by_dc = self.table.ready_in_dcs(datacenters)
        self._base_mask = mask
        self._dc_key = tuple(datacenters)
        self.by_dc = dict(by_dc)
        return n_ready

    def eligible_node_ids(self) -> set:
        """Node ids that are ready and in the eval's datacenters (the
        old readyNodesInDCs result set)."""
        t = self.table
        return {t.ids[i] for i in np.nonzero(self._base_mask)[0]}

    def set_node_list(self, nodes: List[Node]) -> None:
        """Restrict to an explicit node list (in-place update checks)."""
        self.table = NodeTable(nodes)
        for node in nodes:
            for alloc in self.snapshot.allocs_by_node(node.id):
                if not alloc.terminal_status():
                    self.table.add_alloc_usage(self.table.id_to_idx[node.id],
                                               alloc)
        self.table.finalize()
        self._base_mask = self.table.ready.copy()
        self._dc_key = None
        self.by_dc = {}
        for node in nodes:
            self.by_dc[node.datacenter] = self.by_dc.get(node.datacenter, 0) + 1

    # -- static feasibility -------------------------------------------
    def _combined_constraints(self, tg: TaskGroup) -> List:
        assert self.job is not None
        out = list(self.job.constraints) + list(tg.constraints)
        for t in tg.tasks:
            out.extend(t.constraints)
        return out

    def _static_key(self, tg: TaskGroup) -> Tuple:
        """Content-addressed key for the static feasibility columns:
        immune to job-object mutation, and shared between jobs with
        identical constraint sets (the columnar analog of computed-
        node-class memoization, feasible.go:1026-1118)."""
        drivers = tuple(t.driver for t in tg.tasks if t.driver)
        cons = tuple((c.ltarget, c.rtarget, c.operand)
                     for c in self._combined_constraints(tg)
                     if c.operand not in (CONSTRAINT_DISTINCT_HOSTS,
                                          CONSTRAINT_DISTINCT_PROPERTY))
        vols = tuple(sorted(
            (req.source, bool(getattr(req, "read_only", False)))
            for req in (tg.volumes or {}).values()
            if getattr(req, "type", "host") == "host"))
        devs = tuple(
            (r.name, r.count,
             tuple((c.ltarget, c.rtarget, c.operand) for c in r.constraints))
            for t in tg.tasks for r in t.resources.devices)
        return (drivers, cons, vols, devs)

    def _engine_entry(self, tg: TaskGroup) -> _EngineEntry:
        """Cross-eval static state for (job version, task group):
        static key, group ask, port asks. Pinned to the exact Job
        object — the store serves one instance per version, so a
        different object with the same (ns, id, version) recomputes
        rather than trusting a possibly-mutated spec."""
        job = self.job
        assert job is not None
        key = (job.namespace, job.id, job.version, tg.name)
        with _ENGINE_CACHE_L:
            ent = _ENGINE_CACHE.get(key)
            if ent is not None and ent.job is job:
                ENGINE_CACHE_STATS["entry_hits"] += 1
                return ent
        ent = _EngineEntry(job, self._static_key(tg),
                           self.group_ask(tg), self._port_asks(tg))
        with _ENGINE_CACHE_L:
            ENGINE_CACHE_STATS["entry_misses"] += 1
            # FIFO eviction (the ops/tables._memo_insert idiom): a full
            # clear would storm-recompute every active job's state
            while len(_ENGINE_CACHE) >= ENGINE_CACHE_MAX:
                _ENGINE_CACHE.pop(next(iter(_ENGINE_CACHE)))
            _ENGINE_CACHE[key] = ent
        return ent

    def _static_checks(self, tg: TaskGroup,
                       key: Optional[Tuple] = None
                       ) -> List[Tuple[str, np.ndarray]]:
        """Ordered (reason, bool[N]) columns for drivers, constraints and
        host volumes — cached on the table version (cross-eval), since
        they depend only on node attributes. Store-served tables route
        through the compiled feasibility engine
        (scheduler/feasible_compiler.py): interned code columns + per-
        unique-value predicate programs, masks cached across table
        rebuilds and row-patched on node update. Any decline (engine
        off, detached snapshot, overflowed interns) falls back to the
        scalar reference below — same masks, bit for bit."""
        t = self.table
        if key is None:
            key = self._static_key(tg)
        hit = t.mask_cache.get(key)
        if hit is not None:
            return hit
        checks: Optional[List[Tuple[str, np.ndarray]]] = None
        if self._dc_key is not None:
            from . import feasible_compiler
            compiled = feasible_compiler.static_checks(
                self.snapshot, t, tg, self._combined_constraints(tg), key)
            if compiled is not None:
                checks = list(compiled)   # the compiler owns its list
        if checks is None:
            checks = []
            # drivers (DriverChecker)
            for task in tg.tasks:
                if task.driver:
                    checks.append((f"missing drivers \"{task.driver}\"",
                                   t.driver_mask(task.driver)))
            # constraints (job + group + tasks)
            for c in self._combined_constraints(tg):
                if c.operand in (CONSTRAINT_DISTINCT_HOSTS,
                                 CONSTRAINT_DISTINCT_PROPERTY):
                    continue
                checks.append((str(c),
                               constraint_mask(t.cols, c.ltarget,
                                               c.rtarget, c.operand)))
            # host volumes
            if tg.volumes:
                checks.append(("missing compatible host volumes",
                               t.host_volume_mask(tg.volumes)))
        # devices: capability mask (DeviceChecker, feasible.go:1138) —
        # compiled as a flagged-row column when residue compilation is
        # on (ISSUE 20): only device-reporting rows run the scalar
        # group walk; deviceless rows are False by construction
        from .devices import combined_device_asks, static_device_mask
        asks = combined_device_asks(tg)
        if asks:
            dm = None
            if self._dc_key is not None:
                from . import feasible_compiler
                dm = feasible_compiler.device_rows_check(
                    self.snapshot, t, asks)
            if dm is None:
                dm = static_device_mask(t.nodes, asks)
            checks.append(("missing devices", dm))
        t.mask_cache[key] = checks
        return checks

    def feasibility(self, tg: TaskGroup) -> Tuple[np.ndarray, Dict[str, int]]:
        """(mask bool[N], filtered_counts per constraint string).
        Vectorized FeasibilityWrapper (feasible.go:994-1134). Static
        columns come from the cross-eval cache, and the COMBINED
        mask+counts result is itself cached on the table keyed by
        (static key, datacenters) — many evals for the same job skip
        the whole masking pass, not just the column builds. Callers
        must copy before mutating (select_batch does)."""
        from ..utils import stages
        if not stages.enabled:
            return self._feasibility(tg)
        t0 = time.perf_counter()
        out = self._feasibility(tg)
        dt = time.perf_counter() - t0
        # the device park inside _feasibility is upload traffic, not
        # mask production — report it under h2d like the other
        # host-to-device transfers so the feasibility stage stays the
        # mask-build attribution the bench compares across arms
        push = self._feas_push_s
        self._feas_push_s = 0.0
        stages.add("feasibility", max(dt - push, 0.0))
        if push > 0.0:
            stages.add("h2d", push)
        return out

    def _feasibility(self, tg: TaskGroup) -> Tuple[np.ndarray,
                                                   Dict[str, int]]:
        key = (id(self.job), self.job.version, tg.name)
        cached = self._mask_cache.get(key)
        if cached is not None:
            return cached
        ent = self._engine_entry(tg)
        t = self.table
        feas_key = None
        if self._dc_key is not None:
            feas_key = ("feasibility", ent.static_key, self._dc_key)
            hit = t.mask_cache.get(feas_key)
            if hit is not None:
                ENGINE_CACHE_STATS["mask_hits"] += 1
                self._mask_cache[key] = hit
                # recover the device-residency token too (ISSUE 20):
                # tokens live per-eval, but the parked mask outlives
                # the eval — push_combined early-returns the current
                # token without device work when the entry is fresh
                if t.device_mirror is not None:
                    from . import feasible_compiler
                    tok = feasible_compiler.push_combined(
                        t.device_mirror, feas_key, hit[0], self.snapshot,
                        ent.static_key)
                    if tok is not None:
                        self._feas_tokens[feas_key] = tok
                return hit
            ENGINE_CACHE_STATS["mask_misses"] += 1
        else:
            ENGINE_CACHE_STATS["mask_uncached"] += 1
        mask = self._base_mask.copy()
        counts: Dict[str, int] = {}
        for reason, m in self._static_checks(tg, ent.static_key):
            newly = mask & ~m
            n = int(newly.sum())
            if n:
                counts[reason] = counts.get(reason, 0) + n
            mask &= m
        out = (mask, counts)
        if feas_key is not None:
            t.mask_cache[feas_key] = out
            # device residency (ISSUE 17 part 3): park the combined
            # mask beside the mirror's resident columns; select_batch
            # hands the returned token to the kernel dispatch when the
            # mask reaches it unmutated (CSI/preferred/penalty residue
            # stays a host-shipped dense column)
            if t.device_mirror is not None:
                from . import feasible_compiler
                t1 = time.perf_counter()
                tok = feasible_compiler.push_combined(
                    t.device_mirror, feas_key, mask, self.snapshot,
                    ent.static_key)
                self._feas_push_s = time.perf_counter() - t1
                if tok is not None:
                    self._feas_tokens[feas_key] = tok
        self._mask_cache[key] = out
        return out

    # -- ask construction ---------------------------------------------
    @staticmethod
    def group_ask(tg: TaskGroup) -> np.ndarray:
        cpu = sum(t.resources.cpu for t in tg.tasks)
        mem = sum(t.resources.memory_mb for t in tg.tasks)
        disk = tg.ephemeral_disk.size_mb if tg.ephemeral_disk else 0
        mbits = sum(nw.mbits for nw in tg.networks)
        for t in tg.tasks:
            mbits += sum(nw.mbits for nw in t.resources.networks)
        return np.array([cpu, mem, disk, mbits], dtype=np.float32)

    @staticmethod
    def _port_asks(tg: TaskGroup) -> Tuple[int, List[int]]:
        """(dynamic_count, reserved_values) over group + task networks."""
        dyn = 0
        reserved: List[int] = []
        for nw in tg.networks:
            dyn += len(nw.dynamic_ports)
            reserved.extend(p.value for p in nw.reserved_ports)
        for t in tg.tasks:
            for nw in t.resources.networks:
                dyn += len(nw.dynamic_ports)
                reserved.extend(p.value for p in nw.reserved_ports)
        return dyn, reserved

    def _spread_inputs(self, tg: TaskGroup, proposed: ProposedIndex):
        """Build kernel spread state (spread.go computeSpreadInfo:232)."""
        assert self.job is not None
        spreads = list(tg.spreads) + list(self.job.spreads)
        if not spreads:
            return [], 0.0
        out = []
        sum_w = float(sum(s.weight for s in spreads))
        total_count = tg.count
        for s in spreads:
            # the encoding comes off the write-through interned columns
            # when residue compilation is on (ISSUE 20): a table
            # rebuild no longer costs an O(N) Python re-encode per
            # spread attribute
            if spread_ops.enabled():
                codes, values = spread_ops.attr_codes_fast(
                    self.table, s.attribute, self.snapshot)
            else:
                codes, values = self.table.attr_codes(s.attribute)
            counts, present = proposed.property_counts(s.attribute, values)
            c = len(values)
            desired = np.full(c + 1, -1.0, dtype=np.float32)
            has_targets = bool(s.spread_target)
            if has_targets:
                explicit = {st.value: st.percent for st in s.spread_target}
                sum_desired = 0.0
                for v, pct in explicit.items():
                    if v in values:
                        d = pct / 100.0 * total_count
                        desired[values.index(v)] = d
                    sum_desired += pct / 100.0 * total_count
                # implicit target for remaining values
                if 0 < sum_desired < total_count:
                    implicit = total_count - sum_desired
                    for i, v in enumerate(values):
                        if v not in explicit:
                            desired[i] = implicit
            out.append(dict(codes=codes, counts=counts, present=present,
                            desired=desired, weight=float(s.weight),
                            has_targets=has_targets))
        return out, sum_w

    def _distinct_prop_inputs(self, tg: TaskGroup, proposed: ProposedIndex):
        """distinct_property constraints -> kernel state
        (propertyset.go SatisfiesDistinctProperties)."""
        out = []
        assert self.job is not None
        for c, scope_tg in (
                [(c, None) for c in self.job.constraints
                 if c.operand == CONSTRAINT_DISTINCT_PROPERTY]
                + [(c, tg.name) for c in tg.constraints
                   if c.operand == CONSTRAINT_DISTINCT_PROPERTY]):
            if spread_ops.enabled():
                codes, values = spread_ops.attr_codes_fast(
                    self.table, c.ltarget, self.snapshot)
            else:
                codes, values = self.table.attr_codes(c.ltarget)
            counts, _present = proposed.property_counts(
                c.ltarget, values, tg_name=scope_tg)
            try:
                limit = int(c.rtarget) if c.rtarget else 1
            except ValueError:
                limit = 1
            out.append(dict(codes=codes, counts=counts, limit=float(limit)))
        return out

    def _has_distinct_hosts(self, tg: TaskGroup) -> bool:
        assert self.job is not None
        for c in self.job.constraints:
            if c.operand == CONSTRAINT_DISTINCT_HOSTS:
                return True
        for c in tg.constraints:
            if c.operand == CONSTRAINT_DISTINCT_HOSTS:
                return True
        return False

    # -- the main entry ------------------------------------------------
    def select_batch(self, tg: TaskGroup, count: int, proposed: ProposedIndex,
                     options: Optional[SelectOptions] = None,
                     preemption_round=None,
                     ) -> List[Tuple[Optional[RankedNode], AllocMetric]]:
        """Place `count` instances of tg in one kernel dispatch. Returns
        one (RankedNode-or-None, metrics) pair per requested instance.

        With a PreemptionRound, full nodes whose fit comes from evicting
        lower-priority allocs compete in the same argmax (rank.go
        :415-448 + PreemptionScoringIterator): their `used` rows are
        reduced by the victims' resources and they carry the logistic
        preemption scorer; victims are staged into the plan when such a
        node wins."""
        assert self.table is not None and self.job is not None
        t = self.table
        start = time.monotonic_ns()
        ent = self._engine_entry(tg)
        mask, filtered_counts = self.feasibility(tg)
        # the cached combined mask — the residue diff below compares
        # the mutated copy against it to keep the device token alive
        base_mask = mask
        mask = mask.copy()
        filtered_counts = dict(filtered_counts)

        # CSI volumes are transient feasibility (claims churn per plan,
        # so never memoized — CSIVolumeChecker, feasible.go:194): the
        # volume must exist, be claimable for the requested mode, and
        # the node must be inside its topology
        csi_reqs = [r for r in (tg.volumes or {}).values()
                    if getattr(r, "type", "host") == "csi"]
        csi_write_cap = None        # max placements this batch can claim
        csi_cap_source = ""
        for req in csi_reqs:
            vol = self.snapshot.csi_volume(self.job.namespace, req.source)
            before = int(mask.sum())
            if vol is None or not vol.claimable(bool(req.read_only)):
                mask[:] = False
            else:
                if vol.topology_node_ids:
                    # O(|topology|) id lookups, not an O(N) id scan
                    topo_mask = np.zeros(t.n, dtype=bool)
                    for nid in vol.topology_node_ids:
                        row = t.id_to_idx.get(nid)
                        if row is not None:
                            topo_mask[row] = True
                    mask &= topo_mask
                # the node must run the volume's plugin (fingerprinted
                # as csi.plugin.<id> by the client's csimanager;
                # feasible.go CSIVolumeChecker requires a healthy node
                # plugin) — without this, CSI workloads land on
                # plugin-less nodes and fail at mount time. The mask
                # depends only on node attributes, so it caches per
                # table version like the other static columns.
                attr = f"csi.plugin.{vol.plugin_id}"
                cache_key = ("csi_plugin_attr", attr)
                plug_mask = t.mask_cache.get(cache_key)
                if plug_mask is None:
                    if spread_ops.enabled():
                        # presence off the write-through interned
                        # column (ISSUE 20): survives table rebuilds
                        plug_mask = spread_ops.attr_present_mask(
                            t, "${attr." + attr + "}", self.snapshot)
                    if plug_mask is None:
                        plug_mask = np.fromiter(
                            (n.attributes.get(attr) is not None
                             for n in t.nodes), dtype=bool, count=t.n)
                    t.mask_cache[cache_key] = plug_mask
                mask &= plug_mask
            newly = before - int(mask.sum())
            if newly:
                filtered_counts[f"missing CSI Volume {req.source}"] = \
                    filtered_counts.get(
                        f"missing CSI Volume {req.source}", 0) + newly
            # single-writer volumes admit ONE write claim: a count>1
            # batch must not stage more placements than the volume can
            # claim (csi.go WriteFreeClaims:385 is per-claim; the plan
            # applier re-verifies against the freshest state)
            if vol is not None and not bool(req.read_only):
                from ..models.csi import (ACCESS_MULTI_NODE_SINGLE_WRITER,
                                          ACCESS_SINGLE_NODE_WRITER)
                if vol.access_mode in (ACCESS_SINGLE_NODE_WRITER,
                                       ACCESS_MULTI_NODE_SINGLE_WRITER):
                    free = 0 if vol.write_allocs else 1
                    if csi_write_cap is None or free < csi_write_cap:
                        csi_write_cap = free
                        csi_cap_source = req.source

        count_requested = count
        if csi_write_cap is not None and 0 < csi_write_cap < count:
            count = csi_write_cap

        options = options or SelectOptions()
        if options.preferred_nodes:
            pref_mask = np.zeros(t.n, dtype=bool)
            for n in options.preferred_nodes:
                row = t.id_to_idx.get(n.id)
                if row is not None:
                    pref_mask[row] = True
            mask &= pref_mask

        penalty = None
        if options.penalty_node_ids:
            penalty = np.zeros(t.n, dtype=bool)
            for nid in options.penalty_node_ids:
                row = t.id_to_idx.get(nid)
                if row is not None:
                    penalty[row] = True

        # affinities: job + group + tasks (rank.go NodeAffinityIterator)
        affinities = list(self.job.affinities) + list(tg.affinities)
        for task in tg.tasks:
            affinities.extend(task.affinities)
        aff_col, aff_sum = (None, 0.0)
        if affinities:
            aff_col, aff_sum = affinity_columns(t.cols, affinities)

        dyn_ports, reserved_ports = ent.port_asks
        port_ok = t.reserved_ports_ok(reserved_ports) if reserved_ports else None

        # device columns (scheduler/devices.py): per-eval slot counts
        # and the "devices" affinity scorer
        from .devices import combined_device_asks, device_columns
        dev_asks = combined_device_asks(tg)
        dev_slots = dev_score = None
        dev_fires = False
        if dev_asks:
            dev_slots, dev_score, dev_fires = device_columns(
                t.nodes, dev_asks,
                lambda nid: self._proposed_allocs_on(nid, proposed.plan))

        t_build = time.perf_counter()
        spreads, sum_spread_w = self._spread_inputs(tg, proposed)
        distinct_props = self._distinct_prop_inputs(tg, proposed)
        distinct_hosts = self._has_distinct_hosts(tg)
        if spreads or distinct_props:
            # per-arm build-time attribution: bench_feas_residue's
            # spread_score_speedup is the scalar/vector ratio of these
            spread_ops.note_build(time.perf_counter() - t_build)
        if count == 1 and (distinct_hosts or distinct_props) \
                and spread_ops.enabled() \
                and spread_ops.distinct_uncontended(
                    mask, proposed.job_count, distinct_props):
            # plan-time distinct fold (ISSUE 20): a single placement
            # can't self-collide, and no proposed alloc contends on
            # any feasible node — the kernel gates can never fire, so
            # drop the per-step distinct state from the request
            distinct_hosts = False
            distinct_props = []
            spread_ops.STATS["distinct_folds"] += 1

        used_arr = proposed.used()
        pre_score = None
        if preemption_round is not None:
            extra = None
            if dev_slots is not None:
                extra = dev_slots < 1.0
            if port_ok is not None:
                extra = (~port_ok) if extra is None else (extra | ~port_ok)
            pre_score, freed = preemption_round.columns(
                used_arr, extra_candidates=extra)
            if pre_score.any():
                # reflect hypothetical evictions so fit/binpack see the
                # post-eviction node (rank.go computes util after evict)
                used_arr = np.maximum(used_arr - freed, 0.0)
                pre_ok = pre_score > 0
                # evictions also unlock device slots and reserved ports
                # (one preempted placement per node per batch; the rest
                # re-evaluate next round)
                if dev_slots is not None:
                    dev_slots = np.where(pre_ok & (dev_slots < 1.0),
                                         1.0, dev_slots)
                if port_ok is not None:
                    port_ok = port_ok | pre_ok
            else:
                pre_score = None

        # device-resident dispatch (ops/device_table.py): hand the
        # kernel the table's mirror token plus the plan overlay in
        # sparse form, so used0 is computed on device from the
        # resident base. Valid only when used_arr is EXACTLY
        # base_used + plan overlay — a preemption rewrite of the used
        # rows falls back to dense shipping.
        table_ref = None
        used_rows = used_deltas = None
        if pre_score is None and proposed.table is t:
            table_ref = t
            used_rows, used_deltas = proposed.used_sparse()

        # device-resident feasibility (ISSUE 17 + 20): with residue
        # compilation on, the parked device copy substitutes for the
        # dense bool column even when transient residue (CSI claims,
        # quota caps, preferred-node restriction) mutated the mask —
        # the mutations ship as a sparse (rows, vals) scatter applied
        # on device per eval, so the token survives. Off-switch
        # (NOMAD_TPU_FEAS_RESIDUE=0) restores the ISSUE 17 gate: any
        # residue forces the dense host mask.
        feas_token = None
        feas_residue = None
        if self._dc_key is not None:
            tok = self._feas_tokens.get(
                ("feasibility", ent.static_key, self._dc_key))
            if tok is not None:
                from . import feasible_compiler as _fc
                touched = bool(csi_reqs) or bool(options.preferred_nodes)
                if not touched:
                    feas_token = tok
                elif _fc.residue_enabled():
                    from ..ops.device_table import SPARSE_MAX_FRAC
                    diff = np.flatnonzero(mask != base_mask)
                    if diff.size <= t.n * SPARSE_MAX_FRAC:
                        feas_token = tok
                        if diff.size:
                            feas_residue = (diff.astype(np.int32),
                                            mask[diff])
                        _fc.STATS["token_survivals"] += 1
                        _fc.STATS["residue_rows"] += int(diff.size)
                    else:
                        _fc.STATS["token_invalidations"] += 1
                else:
                    _fc.STATS["token_invalidations"] += 1

        req = SelectRequest(
            ask=ent.group_ask,
            count=count,
            feasible=mask,
            capacity=t.capacity,
            used=used_arr,
            desired_count=float(max(tg.count, 1)),
            tg_collisions=proposed.tg_counts(tg.name),
            job_count=proposed.job_count,
            distinct_hosts=distinct_hosts,
            scan_exclusive=bool(reserved_ports),
            penalty=penalty,
            affinity=aff_col,
            affinity_sum_weights=aff_sum,
            algorithm=self.config.effective_algorithm(),
            port_need=float(dyn_ports),
            free_ports=t.free_ports,
            port_ok=port_ok,
            dev_slots=dev_slots,
            dev_score=dev_score,
            dev_fires=dev_fires,
            pre_score=pre_score,
            spreads=spreads,
            sum_spread_weights=sum_spread_w,
            distinct_props=distinct_props,
            n_considered=int(self._base_mask.sum()),
            table=table_ref,
            used_base_rows=used_rows,
            used_base_deltas=used_deltas,
            feas_token=feas_token,
            feas_residue=feas_residue,
        )
        res = self.dispatch(req)
        elapsed = time.monotonic_ns() - start

        # host-side port assignment for winners, plan-consistent
        out: List[Tuple[Optional[RankedNode], AllocMetric]] = []
        self._shared_by_dc = dict(self.by_dc)
        self._shared_filtered = dict(filtered_counts)
        staged_victims = set()
        # winner materialization is the per-placement host loop — a
        # 10k-instance batch walks it 10k times, so everything step-
        # invariant is hoisted: numpy rows become Python lists once,
        # metric top-k change points are detected in one vectorized
        # pass, and steps with identical metric content share ONE
        # AllocMetric flyweight (nothing mutates a success metric after
        # placement; failure paths always copy first)
        node_idx_l = np.asarray(res.node_idx[:count]).tolist()
        score_l = np.asarray(res.final_score[:count]).tolist()
        ti_arr = np.asarray(res.top_idx[:count])
        ts_arr = np.asarray(res.top_scores[:count])
        ex_arr = np.asarray(res.exhausted_dim[:count])
        ex_any = ex_arr.any(axis=1) if count else ex_arr
        if count > 1:
            same_prev = np.concatenate((
                np.zeros(1, bool),
                np.all(ti_arr[1:] == ti_arr[:-1], axis=1)
                & np.all(ts_arr[1:] == ts_arr[:-1], axis=1)
                & (ex_any[1:] == ex_any[:-1])
                & np.all(ex_arr[1:] == ex_arr[:-1], axis=1))).tolist()
        else:
            same_prev = [False] * count
        per_step_ns = int(elapsed // max(count, 1))
        shared_metric: Optional[AllocMetric] = None
        # flyweight resources: with no ports and no devices every
        # winner of this batch gets identical AllocatedTaskResources —
        # build them once (the reference builds per RankedNode, but
        # those objects are read-only downstream; in-place updates
        # always construct fresh ones)
        simple_resources = (not tg.networks and not dev_asks
                            and not any(task.resources.networks
                                        for task in tg.tasks))
        fly_tr = fly_shared = None
        if simple_resources:
            fly_tr = {
                task.name: AllocatedTaskResources(
                    cpu=AllocatedCpuResources(task.resources.cpu),
                    memory=AllocatedMemoryResources(
                        task.resources.memory_mb))
                for task in tg.tasks}
            fly_shared = AllocatedSharedResources(
                disk_mb=tg.ephemeral_disk.size_mb
                if tg.ephemeral_disk else 0)
        # port-free networks (mbits-only asks): the kernel's network
        # column already gates bandwidth fit, and the offer depends
        # only on the node — one (task_resources, shared) flyweight
        # per node serves every step landing there
        simple_networks = (not simple_resources and not dev_asks
                           and dyn_ports == 0 and not reserved_ports)
        node_fly: Dict[int, Tuple] = {}
        for step in range(count):
            idx = node_idx_l[step]
            if same_prev[step] and shared_metric is not None:
                metrics = shared_metric
            else:
                metrics = self._metrics_for_row(
                    res, ti_arr[step], ts_arr[step],
                    ex_arr[step] if ex_any[step] else None, per_step_ns)
                shared_metric = metrics
            if idx < 0:
                out.append((None, metrics))
                continue
            node = t.nodes[idx]
            # a preempting winner stages its victims before resource
            # assignment (they free ports/devices too)
            victims = None
            saved_net = saved_dev = None
            if pre_score is not None and pre_score[idx] > 0 \
                    and idx not in staged_victims:
                victims = preemption_round.victims_for(idx)
                if victims:
                    staged_victims.add(idx)
                    for v in victims:
                        proposed.plan.append_preempted_alloc(v, "")
                    saved_net = self._net_cache.pop(node.id, None)
                    saved_dev = self._dev_cache.pop(node.id, None)
            if simple_resources:
                task_resources, shared, ok = fly_tr, fly_shared, True
            elif simple_networks and idx in node_fly:
                task_resources, shared, ok = node_fly[idx]
                # the offer objects are shared, but bandwidth must
                # still ACCUMULATE in the per-eval NetworkIndex — a
                # later task group's assignment on this node checks
                # it. Rebuild the index when preemption staging popped
                # the cache entry; skipping would under-count.
                nidx = self._net_index_for(node, proposed.plan)
                if shared is not None:
                    for off in shared.networks:
                        nidx.add_reserved(off)
                for tr_ in task_resources.values():
                    for off in (tr_.networks or []):
                        nidx.add_reserved(off)
            else:
                task_resources, shared, ok = self._assign_resources(
                    node, tg, proposed.plan)
                if simple_networks and ok:
                    node_fly[idx] = (task_resources, shared, ok)
            if not ok:
                # roll the staged victims back: an eviction without a
                # replacement placement must not reach the plan
                # (generic.py _try_preemption does the same one-shot)
                if victims:
                    staged_victims.discard(idx)
                    evicted = {v.id for v in victims}
                    kept = [a for a in proposed.plan.node_preemptions
                            .get(node.id, []) if a.id not in evicted]
                    if kept:
                        proposed.plan.node_preemptions[node.id] = kept
                    else:
                        proposed.plan.node_preemptions.pop(node.id, None)
                    # _assign_resources may have rebuilt the caches with
                    # the victims excluded; those entries are poison now
                    # that the victims are unstaged — drop them before
                    # restoring the pre-staging versions
                    self._net_cache.pop(node.id, None)
                    self._dev_cache.pop(node.id, None)
                    if saved_net is not None:
                        self._net_cache[node.id] = saved_net
                    if saved_dev is not None:
                        self._dev_cache[node.id] = saved_dev
                # never mutate the shared flyweight: failing steps get
                # their own metric copy
                metrics = metrics.copy()
                metrics.exhausted_node(node, "network: port assignment failed")
                out.append((None, metrics))
                continue
            out.append((RankedNode(
                node=node,
                final_score=score_l[step],
                task_resources=task_resources,
                alloc_resources=shared,
                metrics=metrics,
                preempted_allocs=victims,
            ), metrics))
        # instances beyond the CSI write-claim budget fail placement
        # with the volume named, instead of being staged unclaimable
        for _ in range(count_requested - count):
            m = AllocMetric()
            m.nodes_evaluated = int(self._base_mask.sum())
            m.constraint_filtered = {
                f"CSI volume {csi_cap_source} has exhausted its "
                "available writer claims": m.nodes_evaluated}
            out.append((None, m))
        return out

    def _metrics_for_row(self, res, top_idx_row, top_scores_row,
                         ex_row, elapsed_ns: int) -> AllocMetric:
        """AllocMetric for one placement step from precomputed numpy
        rows (select_batch hoists the per-step slicing; identical
        consecutive steps share the returned instance as a read-only
        flyweight)."""
        m = AllocMetric()
        m.nodes_evaluated = res.nodes_evaluated
        m.nodes_filtered = res.nodes_filtered
        # shared read-only dicts: a 10k-instance batch would otherwise
        # copy these per instance
        m.nodes_available = self._shared_by_dc
        m.constraint_filtered = self._shared_filtered
        if ex_row is not None:
            m.nodes_exhausted = int(ex_row.sum())
            for d, name in enumerate(DIM_NAMES):
                if int(ex_row[d]):
                    m.dimension_exhausted[name] = int(ex_row[d])
        m.allocation_time_ns = elapsed_ns
        ids = self.table.ids
        for ni, sc in zip(top_idx_row.tolist(), top_scores_row.tolist()):
            if ni < 0 or sc < -1e29:
                continue
            m.score_meta_data.append(NodeScoreMeta(
                node_id=ids[ni], scores={"final": sc}, norm_score=sc))
        return m

    def _proposed_allocs_on(self, node_id: str, plan) -> list:
        """This node's proposed allocations: snapshot minus plan
        stops/preemptions plus plan placements (context.go:120-157)."""
        stopped = set()
        if plan is not None:
            for a in plan.node_update.get(node_id, []):
                stopped.add(a.id)
            for a in plan.node_preemptions.get(node_id, []):
                stopped.add(a.id)
        out = [a for a in self.snapshot.allocs_by_node(node_id)
               if not a.terminal_status() and a.id not in stopped]
        if plan is not None:
            out.extend(plan.node_allocation.get(node_id, []))
        return out

    def _net_index_for(self, node: Node, plan) -> NetworkIndex:
        """NetworkIndex over the node's *proposed* allocations: snapshot
        allocs minus plan stops/preemptions plus plan placements (the
        reference feeds ProposedAllocs into the index, rank.go:204-206).
        Cached per engine (= per eval) so offers accumulate consistently."""
        idx = self._net_cache.get(node.id)
        if idx is None:
            idx = NetworkIndex()
            idx.set_node(node)
            stopped = set()
            if plan is not None:
                for a in plan.node_update.get(node.id, []):
                    stopped.add(a.id)
                for a in plan.node_preemptions.get(node.id, []):
                    stopped.add(a.id)
            idx.add_allocs([a for a in self.snapshot.allocs_by_node(node.id)
                            if a.id not in stopped])
            if plan is not None:
                idx.add_allocs(plan.node_allocation.get(node.id, []))
            self._net_cache[node.id] = idx
        return idx

    def _assign_resources(self, node: Node, tg: TaskGroup, plan=None):
        """Build AllocatedTaskResources + shared network offer for a
        chosen node (the tail of BinPackIterator rank.go:244-410, done
        host-side for winners only)."""
        idx = self._net_index_for(node, plan)

        shared = None
        if tg.networks:
            ask = tg.networks[0].copy()
            offer, err = idx.assign_network(ask)
            if offer is None:
                return {}, None, False
            idx.add_reserved(offer)
            shared = AllocatedSharedResources(
                disk_mb=tg.ephemeral_disk.size_mb if tg.ephemeral_disk else 0,
                networks=[offer])

        # device instance assignment for the winner (device.go
        # AssignDevice; failures surface like port failures). The
        # accounter is cached per eval so instances reserved for earlier
        # placements of this batch stay reserved.
        dev_offers = {}
        from .devices import assign_devices, combined_device_asks
        if combined_device_asks(tg):
            from ..models.device_accounting import DeviceAccounter
            acct = self._dev_cache.get(node.id)
            if acct is None:
                acct = DeviceAccounter(node)
                acct.add_allocs(self._proposed_allocs_on(node.id, plan))
                self._dev_cache[node.id] = acct
            dev_offers, _matched = assign_devices(node, tg, [], acct)
            if dev_offers is None:
                return {}, None, False

        task_resources: Dict[str, AllocatedTaskResources] = {}
        for task in tg.tasks:
            tr = AllocatedTaskResources(
                cpu=AllocatedCpuResources(task.resources.cpu),
                memory=AllocatedMemoryResources(task.resources.memory_mb))
            if task.resources.networks:
                ask = task.resources.networks[0].copy()
                offer, err = idx.assign_network(ask)
                if offer is None:
                    return {}, None, False
                idx.add_reserved(offer)
                tr.networks = [offer]
            if task.name in dev_offers:
                tr.devices = list(dev_offers[task.name])
            task_resources[task.name] = tr
        return task_resources, shared, True
