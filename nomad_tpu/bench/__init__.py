from .ladder import run_ladder  # noqa: F401
