"""bench_multichip: the mesh-residency ladder scenario (ISSUE 12).

The MULTICHIP artifacts prove the SPMD program runs; this scenario
measures what residency buys it: the SAME warm eval stream driven
through the full scheduler path twice — once with the node axis
sharded over a forced 8-device CPU mesh (NOMAD_TPU_MESH=1, the
mesh-resident table live) and once single-device (NOMAD_TPU_MESH=0) —
recording placements/s for both arms plus the mesh arm's H2D economics:
`mesh_reupload_bytes` (full-column sharded uploads inside the TIMED
window — ZERO in a healthy steady state; the cold upload lands in
`mesh_reupload_bytes_total`) against the dense per-dispatch column
footprint the un-resident path would ship every eval.

Run shape: the mesh needs 8 virtual CPU devices configured BEFORE jax
initializes a backend, and bench.py has already initialized one — so
`run_multichip_bench` drives this module's `main()` in a subprocess
(the same isolation idiom as bench.py's accelerator probe) and parses
its one JSON line.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Dict


def _seed_harness(n_nodes: int):
    from ..mock import fixtures as mock
    from ..scheduler.harness import Harness
    h = Harness()
    for i in range(n_nodes):
        node = mock.node()
        # deterministic ids: table order (sorted by id) must match
        # between the meshed and single-device arms
        node.id = f"9a51a7b0-{i:04d}-4000-8000-0000000{i:05d}"
        node.name = f"mc-{i}"
        node.datacenter = f"dc{(i % 4) + 1}"
        node.meta["rack"] = f"r{i % 8}"
        node.compute_class()
        h.store.upsert_node(h.next_index(), node)
    return h


def _make_job(i: int, count: int):
    from ..mock import fixtures as mock
    job = mock.job()
    job.id = f"mc-svc-{i}"
    job.datacenters = [f"dc{d}" for d in (1, 2, 3, 4)]
    tg = job.task_groups[0]
    tg.count = count
    for t in tg.tasks:
        t.resources.networks = []
    tg.networks = []
    return job


def _eval_for(job):
    from ..models import (Evaluation, EVAL_STATUS_PENDING,
                          TRIGGER_JOB_REGISTER)
    from ..utils.ids import generate_uuid
    return Evaluation(
        id=generate_uuid(), namespace=job.namespace,
        priority=job.priority, triggered_by=TRIGGER_JOB_REGISTER,
        job_id=job.id, status=EVAL_STATUS_PENDING, type=job.type)


def _run_arm(mesh_on: bool, n_nodes: int, n_evals: int,
             count: int) -> Dict:
    """One arm of the comparison: warm evals (compiles + the cold
    resident upload) outside the timer, then a timed eval stream whose
    plan applies drive the delta path between dispatches."""
    from ..ops.select import mesh_stats_snapshot
    os.environ["NOMAD_TPU_MESH"] = "1" if mesh_on else "0"
    h = _seed_harness(n_nodes)
    for w in range(3):
        job = _make_job(10**6 + w, count)
        h.store.upsert_job(h.next_index(), job)
        h.process("service", _eval_for(job))
    stats0 = mesh_stats_snapshot() if mesh_on else {}
    placed = 0
    n_warm_plans = len(h.plans)
    t0 = time.perf_counter()
    for i in range(n_evals):
        job = _make_job(i, count)
        h.store.upsert_job(h.next_index(), job)
        h.process("service", _eval_for(job))
    wall = time.perf_counter() - t0
    stats1 = mesh_stats_snapshot() if mesh_on else {}
    for plan in h.plans[n_warm_plans:]:
        placed += sum(len(a) for a in plan.node_allocation.values())
    out = {"rate": placed / max(wall, 1e-9), "placed": placed,
           "wall_s": wall}
    if mesh_on:
        for key in ("reshard_uploads", "reshard_bytes",
                    "delta_scatters", "resident_hits", "stale_misses"):
            out[key] = int(stats1.get(key, 0)) - int(stats0.get(key, 0))
        out["devices"] = int(stats1.get("devices", 0))
        out["reshard_bytes_total"] = int(stats1.get("reshard_bytes", 0))
        out["resident_bytes_per_device"] = float(
            stats1.get("resident_bytes_per_device", 0.0))
    return out


def run_scenario(n_nodes: int, n_evals: int, count: int) -> Dict:
    """Both arms, in-process (kernels re-read NOMAD_TPU_MESH per eval
    since engines rebuild them). Must run under a multi-device
    platform — main() forces the 8-device virtual CPU mesh."""
    prev = os.environ.get("NOMAD_TPU_MESH")
    try:
        on = _run_arm(True, n_nodes, n_evals, count)
        off = _run_arm(False, n_nodes, n_evals, count)
    finally:
        if prev is None:
            os.environ.pop("NOMAD_TPU_MESH", None)
        else:
            os.environ["NOMAD_TPU_MESH"] = prev
    # the dense per-dispatch footprint the un-resident mesh path paid:
    # capacity + used (n_pad x D x 4 B each) + free_ports (n_pad x 4 B)
    # per dispatch — the comparison basis for mesh_reupload_bytes
    from ..ops.select import _pad_n
    from ..ops.tables import RES_DIMS
    n_pad = _pad_n(n_nodes)
    dense = n_pad * (2 * RES_DIMS * 4 + 4)
    return {
        "mesh_devices": on.get("devices", 0),
        "mesh_placements_per_sec": round(on["rate"], 1),
        "mesh_placements_per_sec_off": round(off["rate"], 1),
        "mesh_speedup": round(on["rate"] / max(off["rate"], 1e-9), 2),
        "mesh_placed": on["placed"],
        # steady-state H2D economics: full-column re-uploads inside the
        # timed window (target 0 — the zero-reupload acceptance bar),
        # the cold/warmup upload total, and the per-dispatch dense
        # bytes the NOMAD_TPU_MESH=0-era mesh path shipped per eval
        "mesh_reupload_bytes": on.get("reshard_bytes", 0),
        "mesh_reupload_bytes_total": on.get("reshard_bytes_total", 0),
        "mesh_reshard_uploads": on.get("reshard_uploads", 0),
        "mesh_delta_scatters": on.get("delta_scatters", 0),
        "mesh_resident_hits": on.get("resident_hits", 0),
        "mesh_dense_bytes_per_dispatch_off": dense,
        "mesh_resident_bytes_per_device": round(
            on.get("resident_bytes_per_device", 0.0), 1),
    }


def main() -> None:
    """Subprocess entry: force the 8-device virtual CPU platform
    BEFORE any backend initializes, run both arms, print ONE JSON
    line."""
    from ..utils.platform import assert_cpu_devices, force_cpu_platform
    force_cpu_platform(8)
    assert_cpu_devices(8)
    quick = os.environ.get("NOMAD_TPU_BENCH_QUICK", "") not in ("", "0")
    out = run_scenario(n_nodes=192 if quick else 1000,
                       n_evals=6 if quick else 20,
                       count=8 if quick else 10)
    print(json.dumps(out))


def run_multichip_bench(quick: bool = False,
                        timeout_s: float = 600.0) -> Dict:
    """Drive main() in a subprocess (this process's jax backend is
    already initialized single-device) and return its artifact keys;
    failures land as multichip_error instead of a traceback."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["NOMAD_TPU_BENCH_QUICK"] = "1" if quick else "0"
    try:
        res = subprocess.run(
            [sys.executable, "-m", "nomad_tpu.bench.multichip"],
            capture_output=True, text=True, timeout=timeout_s, env=env,
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__)))))
        if res.returncode != 0:
            return {"multichip_error":
                    f"rc={res.returncode}: {res.stderr[-500:]}"}
        return json.loads(res.stdout.strip().splitlines()[-1])
    except Exception as e:
        return {"multichip_error": f"{type(e).__name__}: {e}"}


if __name__ == "__main__":
    main()
