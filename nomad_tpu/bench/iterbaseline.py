"""Stock pull-iterator scheduler — the measured same-host baseline.

This is a deliberate re-derivation of the reference's one-node-at-a-time
scheduling pipeline (scheduler/stack.go GenericStack, scheduler/
feasible.go FeasibilityWrapper, scheduler/rank.go BinPackIterator,
scheduler/select.go LimitIterator), used ONLY as the baseline the
columnar kernel path is benchmarked against on the same host, same
state store, same plan-apply path (VERDICT r4 item 1's second arm:
"a measured stock-iterator-scheduler baseline on the same host at C2M
proving >=20x against it").

Faithful reference semantics reproduced here:
  - nodes shuffle once per eval; every placement re-walks the shuffled
    order from the start (stack.go:71 shuffleNodes + iterator Reset)
  - batch jobs score the first `limit = 2` feasible+fitting candidates
    and take the better one — the power-of-two-choices rule
    (stack.go:77-90)
  - feasibility memoizes by computed node class
    (feasible.go:994-1134 FeasibilityWrapper)
  - BinPackIterator recomputes the node's proposed allocations from
    the store + in-flight plan for every scored candidate
    (rank.go:330 ProposedAllocs) and scores fit with the same
    20 - 10^fcpu - 10^fmem curve (structs/funcs.go ScoreFit)

It intentionally does NOT batch, vectorize, or cache across placements
beyond what the reference caches — that is the point of the comparison.
"""

from __future__ import annotations

import random
import time
from typing import Dict, List, Optional, Tuple

from ..models import (ALLOC_CLIENT_PENDING, ALLOC_DESIRED_RUN,
                      AllocatedResources, AllocatedSharedResources,
                      Allocation, Plan)
from ..ops.tables import _alloc_usage
from ..utils.ids import generate_uuid


def _comparable_ask(tg) -> Tuple[float, float, float]:
    cpu = float(sum(t.resources.cpu for t in tg.tasks))
    mem = float(sum(t.resources.memory_mb for t in tg.tasks))
    disk = float(tg.ephemeral_disk.size_mb if tg.ephemeral_disk else 0)
    return cpu, mem, disk


class IterBaselineScheduler:
    """One eval of a batch job through the stock iterator pipeline."""

    def __init__(self, snapshot, seed: int = 0):
        self.snapshot = snapshot
        self.rng = random.Random(seed)

    def process(self, job, count: int) -> Tuple[Plan, int]:
        snap = self.snapshot
        tg = job.task_groups[0]
        dcs = set(job.datacenters)
        drivers = {t.driver for t in tg.tasks if t.driver}

        # node walk order: shuffle once per eval (stack.go SetNodes)
        nodes = [n for n in snap.nodes() if n.ready()
                 and n.datacenter in dcs]
        self.rng.shuffle(nodes)
        limit = 2                      # batch: power-of-two choices

        # FeasibilityWrapper class memo
        class_ok: Dict[str, bool] = {}

        def feasible(node) -> bool:
            cls = node.computed_class
            hit = class_ok.get(cls)
            if hit is not None:
                return hit
            ok = all(node.attributes.get(f"driver.{d}") for d in drivers)
            class_ok[cls] = ok
            return ok

        ask_cpu, ask_mem, ask_disk = _comparable_ask(tg)
        plan = Plan(job=job)
        plan_rows: Dict[str, List[Allocation]] = plan.node_allocation
        placed = 0
        for _k in range(count):
            best_node = None
            best_score = -1e30
            scored = 0
            # every placement restarts the shuffled walk (iterator
            # Reset); full nodes are re-scored and rejected each pass,
            # exactly as BinPackIterator does
            for node in nodes:
                if not feasible(node):
                    continue
                # ProposedAllocs: live allocs from the store + the
                # in-flight plan's placements on this node
                res = node.comparable_resources()
                reserved = node.comparable_reserved_resources()
                cap_cpu = res.cpu_shares - reserved.cpu_shares
                cap_mem = res.memory_mb - reserved.memory_mb
                cap_disk = res.disk_mb - reserved.disk_mb
                used_cpu = used_mem = used_disk = 0.0
                for a in snap.allocs_by_node(node.id):
                    if a.terminal_status():
                        continue
                    u = _alloc_usage(a)
                    used_cpu += u[0]
                    used_mem += u[1]
                    used_disk += u[2]
                for a in plan_rows.get(node.id, ()):
                    u = _alloc_usage(a)
                    used_cpu += u[0]
                    used_mem += u[1]
                    used_disk += u[2]
                after_cpu = used_cpu + ask_cpu
                after_mem = used_mem + ask_mem
                after_disk = used_disk + ask_disk
                if after_cpu > cap_cpu or after_mem > cap_mem or \
                        after_disk > cap_disk:
                    continue            # no fit: walk on (rank.go:415)
                # ScoreFit (structs/funcs.go): 20 - 10^fcpu - 10^fmem
                score = 20.0 - 10.0 ** (after_cpu / max(cap_cpu, 1e-9)) \
                    - 10.0 ** (after_mem / max(cap_mem, 1e-9))
                if score > best_score:
                    best_score = score
                    best_node = node
                scored += 1
                if scored >= limit:
                    break
            if best_node is None:
                break
            alloc = Allocation(
                id=generate_uuid(),
                namespace=job.namespace,
                name=f"{job.id}.{tg.name}[{placed}]",
                job_id=job.id,
                task_group=tg.name,
                node_id=best_node.id,
                node_name=best_node.name,
                allocated_resources=AllocatedResources(
                    tasks={},
                    shared=AllocatedSharedResources(disk_mb=int(ask_disk))),
                desired_status=ALLOC_DESIRED_RUN,
                client_status=ALLOC_CLIENT_PENDING,
            )
            # carry usage on the alloc the way the kernel path does, so
            # downstream accounting (and this loop's own plan overlay)
            # sees identical numbers
            alloc.allocated_resources.tasks = {
                t.name: _task_res(t) for t in tg.tasks}
            plan_rows.setdefault(best_node.id, []).append(alloc)
            placed += 1
        return plan, placed


def _task_res(task):
    from ..models.resources import (AllocatedCpuResources,
                                    AllocatedMemoryResources,
                                    AllocatedTaskResources)
    return AllocatedTaskResources(
        cpu=AllocatedCpuResources(task.resources.cpu),
        memory=AllocatedMemoryResources(task.resources.memory_mb))


def bench_iter_baseline(h, job_proto, count: int = 1000,
                        n_evals: int = 3) -> Dict:
    """Measure the iterator baseline on an already-seeded harness: same
    store, same plan-apply (harness submit_plan -> upsert_plan_results).
    `count` stays modest because the iterator walk degrades
    quadratically as prefix nodes fill — measuring it small is strictly
    FAVORABLE to the baseline."""
    rates = []
    for i in range(n_evals):
        job = job_proto(i)
        h.store.upsert_job(h.next_index(), job)
        snap = h.store.snapshot()
        sched = IterBaselineScheduler(snap, seed=i)
        t0 = time.perf_counter()
        plan, placed = sched.process(job, count)
        h.submit_plan(plan)
        el = time.perf_counter() - t0
        rates.append(placed / el if el > 0 else 0.0)
    return {"iter_rate": max(rates), "iter_rates": rates,
            "iter_count": count}
