"""C2M steady-state soak under the GC-safepoint regime, with the
governor engaged and a pass/fail flatness verdict.

VERDICT r4 item 7 created this soak; the round-5 artifact
(SOAK_r05.json) then showed the system does NOT hold its numbers:
p99 drifted 69.5 -> 208 ms, throughput decayed ~3.4x, RSS grew
~875 MB/hour. Round 6 adds the steady-state governor (governor/) and
this soak now (a) runs the leak-closing regime the agent runs —
bounded harness history, eval/alloc reaping of dead waves (the
core_sched GC analog; the bare Harness has no GC loop), periodic
governor sampling with store layer compaction — and (b) emits a
machine-checkable flatness verdict: max p99 drift ratio and max RSS
slope, recorded in the JSON artifact so the driver (and
tests/test_soak_smoke.py) can fail a regression instead of an
operator eyeballing windows.

Usage: python -m nomad_tpu.bench.soak [minutes] [n_nodes] [seed_allocs]
Env:   NOMAD_TPU_SOAK_OUT overrides the artifact path
       (default <repo>/SOAK_r06.json).
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time
from statistics import median
from typing import Dict, List

# acceptance thresholds (ISSUE r6): the soak passes when p99 in the
# last window-half stays within this ratio of the first half and RSS
# grows no faster than this slope
MAX_P99_DRIFT_RATIO = 1.5
MAX_RSS_SLOPE_MB_PER_HOUR = 100.0


# one RSS reader and one regression: shared with the governor
from ..governor.drift import least_squares_slope
from ..governor.governor import rss_mb as _rss_mb


def _slope_per_hour(ts_min: List[float], values: List[float]) -> float:
    """Least-squares slope in units/hour over (minutes, value) points —
    robust to one noisy endpoint, unlike last-minus-first."""
    return least_squares_slope(list(zip(ts_min, values))) * 60.0


def flatness_verdict(windows: List[Dict],
                     max_p99_ratio: float = MAX_P99_DRIFT_RATIO,
                     max_rss_slope: float = MAX_RSS_SLOPE_MB_PER_HOUR,
                     warmup_windows: int = 1) -> Dict:
    """The machine-checkable steady-state verdict over per-window
    samples. p99 drift is median-of-last-half over median-of-first-half
    (single-window spikes don't flip the verdict); RSS slope is the
    least-squares fit across the measured windows.

    The first `warmup_windows` are excluded when enough windows remain
    (>=3 measured): the run's BOUNDED structures (identity memos,
    changelog ring, harness history, JIT caches) legitimately fill to
    their plateau during the first window, and a steady-state verdict
    judges the plateau, not the fill — the r6 6-min run measured
    +29 MB in window 1-2 and then three windows of RSS flat to 0.1 MB.
    The exclusion is recorded in the verdict."""
    out: Dict = {"max_p99_drift_ratio": max_p99_ratio,
                 "max_rss_slope_mb_per_hour": max_rss_slope}
    if len(windows) - warmup_windows >= 3:
        windows = windows[warmup_windows:]
        out["warmup_windows_excluded"] = warmup_windows
    else:
        out["warmup_windows_excluded"] = 0
    if len(windows) < 2:
        out.update({"pass": False, "reason": "fewer than 2 windows"})
        return out
    p99 = [w["p99_ms"] for w in windows]
    half = max(1, len(p99) // 2)
    # median of each half: real drift raises every late window (and
    # the median with it); one noisy-neighbor window must not flip a
    # steady-state verdict the other five windows contradict
    first = median(p99[:half])
    last = median(p99[len(p99) - half:])
    ratio = (last / first) if first > 0 else 1.0
    rss_slope = _slope_per_hour([w["t_min"] for w in windows],
                                [w["rss_mb"] for w in windows])
    out["p99_drift_ratio"] = round(ratio, 3)
    out["p99_first_half_ms"] = round(first, 1)
    out["p99_last_half_ms"] = round(last, 1)
    out["rss_slope_mb_per_hour"] = round(rss_slope, 1)
    out["pass"] = bool(ratio <= max_p99_ratio
                       and rss_slope <= max_rss_slope)
    if not out["pass"]:
        reasons = []
        if ratio > max_p99_ratio:
            reasons.append(f"p99 drift {ratio:.2f}x > {max_p99_ratio}x")
        if rss_slope > max_rss_slope:
            reasons.append(f"rss slope {rss_slope:.0f} MB/h > "
                           f"{max_rss_slope:.0f} MB/h")
        out["reason"] = "; ".join(reasons)
    return out


def run_soak(minutes: float = 25.0, n_nodes: int = 50000,
             seed_allocs: int = 2_000_000,
             window_s: float = 60.0, wave_depth: int = 50) -> Dict:
    from ..bench.ladder import _eval_for, _seed_nodes, seed_c2m_allocs
    from ..governor import Governor, WatermarkPolicy
    from ..mock import fixtures as mock
    from ..models import Affinity, Spread, SpreadTarget
    from ..scheduler.harness import Harness
    from ..utils import gcsafe

    out: Dict = {"minutes": minutes, "n_nodes": n_nodes,
                 "seed_allocs": seed_allocs, "window_s": window_s,
                 "windows": []}
    gcsafe.enter()
    gov = Governor()
    try:
        h = Harness()
        nodes = _seed_nodes(h, n_nodes)
        seed_c2m_allocs(h, nodes, seed_allocs)
        h.store.snapshot().node_table()
        gcsafe.freeze_steady_state()
        out["rss_after_seed_mb"] = round(_rss_mb(), 1)
        out["frozen_objects"] = gc.get_freeze_count()

        # the governor's accounting half, driven synchronously (no
        # thread — deterministic sampling between evals): store layer
        # debt with fold compaction, table cardinality, event history
        # (none here — harness has no broker), kernel caches
        from ..ops.select import (clear_kernel_caches,
                                  kernel_cache_entries)
        gov.register("state.version_debt", h.store.version_debt,
                     WatermarkPolicy(100_000, min_reclaim_interval_s=1.0),
                     reclaim=lambda: h.store.compact(min_tip=1024))
        gov.register("state.allocs",
                     lambda: len(h.store._root.table("allocs")))
        gov.register("state.evals",
                     lambda: len(h.store._root.table("evals")))
        gov.register("state.changelog", h.store.changelog_len)
        gov.register("kernel_cache.entries", kernel_cache_entries,
                     WatermarkPolicy(256), reclaim=clear_kernel_caches)
        from ..ops.tables import resource_memo_len
        gov.register("node_table.resource_memo", resource_memo_len)

        dcs = [f"dc{d}" for d in (1, 2, 3, 4)]

        def make_svc(i):
            svc = mock.job()
            svc.id = f"soak-svc-{i}"
            svc.datacenters = dcs
            tg = svc.task_groups[0]
            tg.count = 10
            for t in tg.tasks:
                t.resources.networks = []
            tg.networks = []
            tg.spreads = [Spread(attribute="${node.datacenter}",
                                 weight=50,
                                 spread_target=[SpreadTarget("dc1", 40),
                                                SpreadTarget("dc2", 30)])]
            tg.affinities = [Affinity(ltarget="${meta.rack}",
                                      rtarget="r3", operand="=",
                                      weight=50)]
            return svc

        def reap_job(job_id: str) -> None:
            """The core_sched eval/alloc GC analog for a stopped wave:
            delete the wave's evals AND its allocs so the substrate
            holds steady state instead of accreting dead rows (one of
            the r5 soak leaks — delete_job removed the job but left
            its allocs resident forever)."""
            snap = h.store.snapshot()
            eval_ids = [e.id for e in
                        snap.evals_by_job("default", job_id)]
            alloc_ids = [a.id for a in
                         snap.allocs_by_job("default", job_id)]
            if eval_ids or alloc_ids:
                h.store.delete_evals(h.next_index(), eval_ids,
                                     alloc_ids)

        # warm compiles outside the measured windows
        for w in range(3):
            warm = make_svc(10**6 + w)
            h.store.upsert_job(h.next_index(), warm)
            h.process("service", _eval_for(warm))
        for w in range(3):
            wid = f"soak-svc-{10**6 + w}"
            reap_job(wid)
            h.store.delete_job(h.next_index(), "default", wid)

        end = time.time() + minutes * 60.0
        i = 0
        t_start = time.time()
        window_end = time.time() + window_s
        lat: List[float] = []
        evals_total = 0
        cpu_mark = time.process_time()
        while time.time() < end:
            svc = make_svc(i)
            # stop the previous wave's job so the substrate stays at
            # steady state instead of monotonically accumulating
            if i >= wave_depth:
                old = f"soak-svc-{i - wave_depth}"
                reap_job(old)
                h.store.delete_job(h.next_index(), "default", old)
            h.store.upsert_job(h.next_index(), svc)
            t0 = time.perf_counter()
            h.process("service", _eval_for(svc))
            dt = time.perf_counter() - t0
            lat.append(dt)
            gov.observe_eval_latency(dt)
            gcsafe.safepoint()
            i += 1
            evals_total += 1
            if i % 25 == 0:
                gov.sample_once()
            if time.time() >= window_end:
                import numpy as np
                arr = np.array(lat) * 1e3
                counts = gc.get_count()
                gov.sample_once()
                cpu_now = time.process_time()
                out["windows"].append({
                    "t_min": round((time.time() - t_start) / 60.0, 2),
                    "evals": len(lat),
                    # process CPU seconds consumed this window: if wall
                    # p99 rises while cpu-per-eval stays flat, the
                    # drift is the host's, not ours
                    "cpu_s": round(cpu_now - cpu_mark, 1),
                    "cpu_ms_per_eval": round(
                        1000.0 * (cpu_now - cpu_mark)
                        / max(len(lat), 1), 2),
                    "p50_ms": round(float(np.percentile(arr, 50)), 1),
                    "p99_ms": round(float(np.percentile(arr, 99)), 1),
                    "rss_mb": round(_rss_mb(), 1),
                    "gc_counts": list(counts),
                    "tracked_objects": len(gc.get_objects()),
                    "version_debt": h.store.version_debt(),
                    "store_allocs": len(
                        h.store._root.table("allocs")),
                    "governor_reclaims": sum(
                        g["reclaims"] for g in gov.registry.rows()),
                })
                print(json.dumps(out["windows"][-1]), flush=True)
                lat = []
                cpu_mark = time.process_time()
                window_end = time.time() + window_s
        out["evals_total"] = evals_total
        rss = [w["rss_mb"] for w in out["windows"]]
        objs = [w["tracked_objects"] for w in out["windows"]]
        if len(rss) >= 2:
            out["rss_growth_mb"] = round(rss[-1] - rss[0], 1)
            out["rss_growth_mb_per_hour"] = round(
                _slope_per_hour([w["t_min"] for w in out["windows"]],
                                rss), 1)
            out["tracked_growth"] = objs[-1] - objs[0]
        out["p99_ms_first_window"] = out["windows"][0]["p99_ms"] \
            if out["windows"] else None
        out["p99_ms_last_window"] = out["windows"][-1]["p99_ms"] \
            if out["windows"] else None
        out["flatness"] = flatness_verdict(out["windows"])
        out["governor"] = {
            "gauges": gov.registry.rows(),
            "events": gov.events(20),
            "backpressure": gov.backpressure(),
        }
    finally:
        gcsafe.exit_()
        gcsafe.unfreeze_steady_state()
    return out


def main() -> int:
    minutes = float(sys.argv[1]) if len(sys.argv) > 1 else 25.0
    n_nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 50000
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 2_000_000
    out = run_soak(minutes, n_nodes, seed)
    path = os.environ.get("NOMAD_TPU_SOAK_OUT") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), "SOAK_r06.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items()
                      if k not in ("windows", "governor")}))
    return 0 if out.get("flatness", {}).get("pass") else 1


if __name__ == "__main__":
    sys.exit(main())
