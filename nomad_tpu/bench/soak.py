"""C2M steady-state soak under the GC-safepoint regime.

VERDICT r4 item 7: the latency numbers are conditioned on the
safepoint regime (automatic collection off), and nothing demonstrated
a long C2M run keeps RSS bounded while full collections are deferred.
This soak runs continuous service scheduling against the 2M-alloc
substrate for `minutes`, with the regime exactly as the agent runs it
(gcsafe enter + steady-state freeze + the gen-2 full-collect budget),
and records per-minute windows of eval latency, RSS, tracked-object
count, and collection counters. The driver-committed artifact is
SOAK_r05.json.

Usage: python -m nomad_tpu.bench.soak [minutes] [n_nodes] [seed_allocs]
"""

from __future__ import annotations

import gc
import json
import os
import sys
import time
from typing import Dict, List


def _rss_mb() -> float:
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS:"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def run_soak(minutes: float = 25.0, n_nodes: int = 50000,
             seed_allocs: int = 2_000_000) -> Dict:
    from ..bench.ladder import _eval_for, _seed_nodes, seed_c2m_allocs
    from ..mock import fixtures as mock
    from ..models import Affinity, Spread, SpreadTarget
    from ..scheduler.harness import Harness
    from ..utils import gcsafe

    out: Dict = {"minutes": minutes, "n_nodes": n_nodes,
                 "seed_allocs": seed_allocs, "windows": []}
    gcsafe.enter()
    try:
        h = Harness()
        nodes = _seed_nodes(h, n_nodes)
        seed_c2m_allocs(h, nodes, seed_allocs)
        h.store.snapshot().node_table()
        gcsafe.freeze_steady_state()
        out["rss_after_seed_mb"] = round(_rss_mb(), 1)
        out["frozen_objects"] = gc.get_freeze_count()

        dcs = [f"dc{d}" for d in (1, 2, 3, 4)]

        def make_svc(i):
            svc = mock.job()
            svc.id = f"soak-svc-{i}"
            svc.datacenters = dcs
            tg = svc.task_groups[0]
            tg.count = 10
            for t in tg.tasks:
                t.resources.networks = []
            tg.networks = []
            tg.spreads = [Spread(attribute="${node.datacenter}",
                                 weight=50,
                                 spread_target=[SpreadTarget("dc1", 40),
                                                SpreadTarget("dc2", 30)])]
            tg.affinities = [Affinity(ltarget="${meta.rack}",
                                      rtarget="r3", operand="=",
                                      weight=50)]
            return svc

        # warm compiles outside the measured windows
        for w in range(3):
            warm = make_svc(10**6 + w)
            h.store.upsert_job(h.next_index(), warm)
            h.process("service", _eval_for(warm))

        end = time.time() + minutes * 60.0
        i = 0
        window_end = time.time() + 60.0
        lat: List[float] = []
        evals_total = 0
        while time.time() < end:
            svc = make_svc(i)
            # stop the previous wave's job so the substrate stays at
            # steady state instead of monotonically accumulating
            if i >= 50:
                old = f"soak-svc-{i - 50}"
                h.store.delete_job(h.next_index(), "default", old)
            h.store.upsert_job(h.next_index(), svc)
            t0 = time.perf_counter()
            h.process("service", _eval_for(svc))
            lat.append(time.perf_counter() - t0)
            gcsafe.safepoint()
            i += 1
            evals_total += 1
            if time.time() >= window_end:
                import numpy as np
                arr = np.array(lat) * 1e3
                counts = gc.get_count()
                out["windows"].append({
                    "t_min": round((time.time() - (end - minutes * 60))
                                   / 60.0, 1),
                    "evals": len(lat),
                    "p50_ms": round(float(np.percentile(arr, 50)), 1),
                    "p99_ms": round(float(np.percentile(arr, 99)), 1),
                    "rss_mb": round(_rss_mb(), 1),
                    "gc_counts": list(counts),
                    "tracked_objects": len(gc.get_objects()),
                })
                print(json.dumps(out["windows"][-1]), flush=True)
                lat = []
                window_end = time.time() + 60.0
        out["evals_total"] = evals_total
        rss = [w["rss_mb"] for w in out["windows"]]
        objs = [w["tracked_objects"] for w in out["windows"]]
        if len(rss) >= 2:
            out["rss_growth_mb"] = round(rss[-1] - rss[0], 1)
            out["rss_growth_mb_per_hour"] = round(
                (rss[-1] - rss[0]) / max(minutes / 60.0, 1e-9), 1)
            out["tracked_growth"] = objs[-1] - objs[0]
        out["p99_ms_first_window"] = out["windows"][0]["p99_ms"] \
            if out["windows"] else None
        out["p99_ms_last_window"] = out["windows"][-1]["p99_ms"] \
            if out["windows"] else None
    finally:
        gcsafe.exit_()
        gcsafe.unfreeze_steady_state()
    return out


def main() -> int:
    minutes = float(sys.argv[1]) if len(sys.argv) > 1 else 25.0
    n_nodes = int(sys.argv[2]) if len(sys.argv) > 2 else 50000
    seed = int(sys.argv[3]) if len(sys.argv) > 3 else 2_000_000
    out = run_soak(minutes, n_nodes, seed)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))), "SOAK_r05.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps({k: v for k, v in out.items()
                      if k != "windows"}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
