"""End-to-end scheduler benchmarks over the BASELINE.json ladder.

Unlike the raw-kernel benchmark (bench.py run_kernel_bench), every
number here drives the REAL control plane path: state store snapshot →
GenericScheduler.process → reconciler → placement kernel → plan →
plan application back into the store — the same work the reference's
`nomad.worker.invoke_scheduler_service` metric times
(/root/reference/nomad/worker.go:199).

Ladder configs (BASELINE.md):
  #2  batch job count=10k over 1k nodes        -> placements/sec e2e
  #3  service job w/ spread+affinity, 10k nodes -> p99 Process() latency
  #4  mixed-priority preemption, 1k nodes       -> preemption evals/sec
      (run twice in-process: batched columnar victim selection vs the
      NOMAD_TPU_COLUMNAR_PREEMPT=0 reference path — ISSUE 10)
"""

from __future__ import annotations

import time
from typing import Dict, List

import numpy as np


def _seed_nodes(h, n: int, dcs: int = 4):
    from ..mock import fixtures as mock
    nodes = []
    for i in range(n):
        node = mock.node()
        node.name = f"node-{i}"
        node.datacenter = f"dc{(i % dcs) + 1}"
        node.meta["rack"] = f"r{i % 16}"
        node.compute_class()
        nodes.append(node)
        h.store.upsert_node(h.next_index(), node)
    return nodes


def _eval_for(job):
    from ..models import (Evaluation, EVAL_STATUS_PENDING,
                          TRIGGER_JOB_REGISTER)
    from ..utils.ids import generate_uuid
    return Evaluation(
        id=generate_uuid(), namespace=job.namespace, priority=job.priority,
        triggered_by=TRIGGER_JOB_REGISTER, job_id=job.id,
        status=EVAL_STATUS_PENDING, type=job.type)


def bench_batch_e2e(n_nodes: int = 1000, count: int = 10000,
                    warm: bool = True) -> Dict:
    """Ladder #2: one batch job, count instances, through the full
    scheduler. Returns {rate, process_s, placed}."""
    from ..mock import fixtures as mock
    from ..scheduler.harness import Harness

    def once() -> Dict:
        h = Harness()
        _seed_nodes(h, n_nodes, dcs=1)
        job = mock.batch_job()
        job.datacenters = ["dc1"]
        job.task_groups[0].count = count
        h.store.upsert_job(h.next_index(), job)
        t0 = time.perf_counter()
        h.process("batch", _eval_for(job))
        elapsed = time.perf_counter() - t0
        placed = sum(len(a) for a in h.plans[0].node_allocation.values()) \
            if h.plans else 0
        return {"rate": placed / elapsed, "process_s": elapsed,
                "placed": placed}

    if warm:
        once()  # compile + caches
    return once()


def bench_service_p99(n_nodes: int = 10000, n_evals: int = 50,
                      count: int = 10) -> Dict:
    """Ladder #3: service jobs with spread{} + affinity{} over a 10k-node
    table; p99 of full Process() latency across n_evals evals (the
    BASELINE target is p99 <= 100 ms)."""
    from ..mock import fixtures as mock
    from ..models import Affinity, Spread, SpreadTarget
    from ..scheduler.harness import Harness

    h = Harness()
    _seed_nodes(h, n_nodes)

    def make_job(i: int):
        job = mock.job()
        job.id = f"svc-{i}"
        job.datacenters = [f"dc{d}" for d in (1, 2, 3, 4)]
        tg = job.task_groups[0]
        tg.count = count
        # drop the dynamic-port ask so the bench isolates scheduling,
        # not port bookkeeping; ladder #3 is about spread/affinity
        for t in tg.tasks:
            t.resources.networks = []
        tg.networks = []
        tg.spreads = [Spread(attribute="${node.datacenter}", weight=50,
                             spread_target=[SpreadTarget("dc1", 40),
                                            SpreadTarget("dc2", 30)]),
                      Spread(attribute="${meta.rack}", weight=30)]
        tg.affinities = [Affinity(ltarget="${meta.rack}", rtarget="r3",
                                  operand="=", weight=50)]
        return job

    # warm compile for this table shape
    wjob = make_job(10**6)
    h.store.upsert_job(h.next_index(), wjob)
    h.process("service", _eval_for(wjob))

    # the production worker's GC regime (utils/gcsafe.py; on in the
    # CLI agent): collector pauses land between evals, not inside the
    # timed Process() calls
    from ..utils import gcsafe
    times: List[float] = []
    placed = 0
    t_all = time.perf_counter()
    with gcsafe.safepoints():
        for i in range(n_evals):
            job = make_job(i)
            h.store.upsert_job(h.next_index(), job)
            t0 = time.perf_counter()
            h.process("service", _eval_for(job))
            times.append(time.perf_counter() - t0)
            gcsafe.safepoint()
    wall = time.perf_counter() - t_all
    for plan in h.plans[1:]:  # skip warm-up plan
        placed += sum(len(a) for a in plan.node_allocation.values())
    arr = np.array(times)
    return {
        "p50_ms": float(np.percentile(arr, 50) * 1e3),
        "p99_ms": float(np.percentile(arr, 99) * 1e3),
        "rate": placed / wall,
        "placed": placed,
    }


def bench_broker_service(n_nodes: int = 10000, n_jobs: int = 64,
                         count: int = 10, batch: int = 8,
                         schedulers: int = 2) -> Dict:
    """Service throughput through the PRODUCTION control plane: a real
    Server — eval broker -> workers -> micro-batch gateway/select_many
    -> plan queue -> pipelined applier -> store. Jobs are registered
    while workers are paused so the broker's queue depth exists (the
    C1M shape: a deployment wave, not a drip), then the wall clock runs
    until every job is fully placed.

    Three runs, all against a dispatch cost model SEEDED by the
    startup calibration probe (ISSUE 7 — the 1-in-16 organic probe
    never fires inside a scenario this short, which is exactly how
    BENCH_r05 shipped service_broker_batches=0):
      1. micro-batching ON (the headline service_broker_* keys +
         service_microbatch_* occupancy/window/latency keys)
      2. the SAME run with NOMAD_TPU_MICROBATCH=0 (the legacy
         rendezvous path; service_microbatch_*_off keys)
      3. eval_batch_size=1, micro-batching off (the sequential
         baseline behind service_batching_speedup)
    so both the micro-batch win and the legacy batching win are
    measured, not asserted."""
    import os

    from ..mock import fixtures as mock
    from ..models import Affinity
    from ..server import Server, ServerConfig

    def run(batch_size: int, micro: bool) -> Dict:
        prev = os.environ.get("NOMAD_TPU_MICROBATCH")
        os.environ["NOMAD_TPU_MICROBATCH"] = "1" if micro else "0"
        try:
            s = Server(ServerConfig(num_schedulers=schedulers,
                                    eval_batch_size=batch_size,
                                    heartbeat_ttl_s=3600.0))
        finally:
            if prev is None:
                os.environ.pop("NOMAD_TPU_MICROBATCH", None)
            else:
                os.environ["NOMAD_TPU_MICROBATCH"] = prev
        s.start()
        try:
            for w in s.workers:
                w.set_pause(True)
            idx = s._raft_index
            for i in range(n_nodes):
                node = mock.node()
                node.name = f"node-{i}"
                node.datacenter = f"dc{(i % 4) + 1}"
                node.meta["rack"] = f"r{i % 16}"
                node.compute_class()
                idx += 1
                s.store.upsert_node(idx, node)
            s._raft_index = idx

            def make_job(i):
                job = mock.job()
                job.id = f"bsvc-{i}"
                job.datacenters = [f"dc{d}" for d in (1, 2, 3, 4)]
                tg = job.task_groups[0]
                tg.count = count
                for t in tg.tasks:
                    t.resources.networks = []
                tg.networks = []
                tg.affinities = [Affinity(ltarget="${meta.rack}",
                                          rtarget="r3", operand="=",
                                          weight=50)]
                return job

            # warm compile at this table shape for every batch width the
            # measured run can hit: the vmapped K-way kernel compiles per
            # power-of-2 lane bucket, and paying a 20-40s XLA compile
            # inside the timed window would measure the compiler
            widths = {batch_size}
            w_ = batch_size
            while w_ > 1:
                w_ //= 2
                widths.add(max(w_, 1))
            warm_done = 0
            for wave in sorted(widths, reverse=True):
                warm = [make_job(10**6 + warm_done + k)
                        for k in range(wave)]
                warm_done += wave
                for j in warm:
                    s.register_job(j)
                for w in s.workers:
                    w.set_pause(False)
                deadline = time.perf_counter() + 180
                while time.perf_counter() < deadline:
                    if all(len(s.store.allocs_by_job(
                            "default", j.id)) == count for j in warm):
                        break
                    time.sleep(0.01)
                for w in s.workers:
                    w.set_pause(True)

            jobs = [make_job(i) for i in range(n_jobs)]
            for j in jobs:
                s.register_job(j)
            t0 = time.perf_counter()
            for w in s.workers:
                w.set_pause(False)
            deadline = time.perf_counter() + 300
            while time.perf_counter() < deadline:
                if all(len(s.store.allocs_by_job("default", j.id)) == count
                       for j in jobs):
                    break
                time.sleep(0.005)
            wall = time.perf_counter() - t0
            placed = sum(len(s.store.allocs_by_job("default", j.id))
                         for j in jobs)
            ga = s.plan_applier.stats
            gw = s.gateway
            out = {"rate": placed / wall, "placed": placed,
                   "wall_s": wall,
                   # legacy rendezvous batches + gateway multi-lane
                   # dispatches: either one is "evals shared a device
                   # dispatch"
                   "batches": sum(w.stats["batches"] for w in s.workers)
                   + (gw.stats["batches"] if gw is not None else 0),
                   "occupancy": (gw.occupancy_mean()
                                 if gw is not None else 1.0),
                   "window_us": (gw.window_us() if gw is not None
                                 else 0.0),
                   "plan_groups": ga["groups"],
                   "plan_group_plans": ga["plans"],
                   "plan_group_conflicts": ga["conflict_retries"]}
            # worker-observed eval latency (queue wait INCLUDED — the
            # ISSUE 7 attribution fix), read from the governor's
            # reservoir
            if s.governor is not None:
                out["p50_ms"] = s.governor.latency_percentile_ms(50)
                out["p99_ms"] = s.governor.latency_percentile_ms(99)
            return out
        finally:
            s.shutdown()

    # deterministic width warm: rendezvous widths depend on queue
    # timing, so job-based warm can miss a lane bucket and leak its
    # XLA compile into the timed window — compile every power-of-2
    # bucket at the measured (n, count) shape up front
    import numpy as np
    from ..ops.select import SelectKernel, SelectRequest
    wcap = np.tile(np.array([[4000.0, 8192.0, 102400.0, 1000.0]],
                            np.float32), (n_nodes, 1))

    def _warm_req():
        return SelectRequest(
            ask=np.array([500.0, 256.0, 150.0, 0.0], np.float32),
            count=count, feasible=np.ones(n_nodes, bool),
            capacity=wcap, used=np.zeros_like(wcap),
            desired_count=float(count),
            tg_collisions=np.zeros(n_nodes, np.int32),
            job_count=np.zeros(n_nodes, np.int32))

    wk = SelectKernel()
    width = 2
    while width <= max(2, batch):
        wk.select_many([_warm_req() for _ in range(width)])
        width *= 2

    # startup calibration probe (ISSUE 7): seed the cost model with
    # measured solo + batched per-lane costs at THIS table shape so
    # batched lanes are cost-favored (or correctly demoted) from the
    # first dispatch — the 1-in-16 organic probe never fires inside a
    # scenario this short (BENCH_r05: service_broker_batches=0)
    from ..ops.select import calibrate_cost_model
    calibrate_cost_model(n_nodes, count=count, lanes=min(batch, 8),
                         kernel=wk)

    batched = run(batch, micro=True)
    legacy = run(batch, micro=False)
    solo = run(1, micro=False)
    # CPU-CI regression fence (ISSUE 7 satellite): with the cost model
    # seeded, the burst scenario MUST engage batching — evals sharing
    # device dispatches is the entire point of the gateway
    assert batched["batches"] > 0, (
        f"broker scenario never batched: {batched}")
    # flight-recorder engagement for the service workload (ISSUE 9):
    # this burst is where tail exemplars are born on CPU CI — record
    # how many the recorder holds after the three runs so the
    # artifact shows the soak story will have its evidence
    from ..trace import tracer as _flight
    return {
        "service_trace_exemplars": _flight.exemplar_count(),
        "service_broker_placements_per_sec": round(batched["rate"], 1),
        "service_broker_wall_s": round(batched["wall_s"], 3),
        "service_broker_batches": batched["batches"],
        "service_broker_seq_placements_per_sec": round(solo["rate"], 1),
        "service_batching_speedup": round(
            batched["rate"] / max(solo["rate"], 1e-9), 2),
        # micro-batch gateway engagement + win (ISSUE 7): occupancy,
        # live window, and the on/off rate + latency comparison the
        # TPU re-run verifies
        "service_microbatch_occupancy_mean": round(
            batched["occupancy"], 2),
        "service_microbatch_window_us": round(batched["window_us"], 1),
        "service_microbatch_placements_per_sec": round(
            batched["rate"], 1),
        "service_microbatch_placements_per_sec_off": round(
            legacy["rate"], 1),
        "service_microbatch_speedup": round(
            batched["rate"] / max(legacy["rate"], 1e-9), 2),
        "service_microbatch_p50_ms": round(
            batched.get("p50_ms", 0.0), 1),
        "service_microbatch_p99_ms": round(
            batched.get("p99_ms", 0.0), 1),
        "service_microbatch_p50_ms_off": round(
            legacy.get("p50_ms", 0.0), 1),
        "service_microbatch_p99_ms_off": round(
            legacy.get("p99_ms", 0.0), 1),
        # group-commit visibility for THIS burst scenario (the queue
        # depth a deployment wave builds is exactly the grouping
        # opportunity): mean plans per commit over the on/off/seq runs
        "service_broker_plan_group_mean_size": round(
            (batched["plan_group_plans"] + legacy["plan_group_plans"]
             + solo["plan_group_plans"])
            / max(batched["plan_groups"] + legacy["plan_groups"]
                  + solo["plan_groups"], 1), 2),
        "service_broker_plan_group_conflicts":
            batched["plan_group_conflicts"]
            + legacy["plan_group_conflicts"]
            + solo["plan_group_conflicts"],
    }


def bench_preemption(n_nodes: int = 1000, n_evals: int = 10,
                     count: int = 50) -> Dict:
    """Ladder #4: nodes saturated by low-priority batch allocs; a
    high-priority service job must preempt to place. Runs the scenario
    twice in-process — batched columnar victim selection vs the
    NOMAD_TPU_COLUMNAR_PREEMPT=0 per-node reference path (ISSUE 10) —
    and reports the victim-selection speedup from the accumulated
    preempt-phase seconds (the e2e rate also rides along for both, but
    at CI scale the eval's kernel/plan/commit overhead would mask the
    selector win the acceptance floor is about)."""
    import os

    # both arms force their switch explicitly (the bench_reconcile
    # idiom) — an ambient kill switch in the environment must not
    # silently turn the "on" arm into a second reference run
    prev = os.environ.get("NOMAD_TPU_COLUMNAR_PREEMPT")
    try:
        os.environ["NOMAD_TPU_COLUMNAR_PREEMPT"] = "1"
        # a throwaway run at the REAL shape absorbs process-global
        # warmup (imports, allocator growth, fresh XLA traces for this
        # node/count bucket) that would otherwise land entirely on
        # whichever arm runs first and skew rate and speedup alike
        _preemption_run(n_nodes, 1, count)
        on = _preemption_run(n_nodes, n_evals, count)
        os.environ["NOMAD_TPU_COLUMNAR_PREEMPT"] = "0"
        off = _preemption_run(n_nodes, n_evals, count)
    finally:
        if prev is None:
            os.environ.pop("NOMAD_TPU_COLUMNAR_PREEMPT", None)
        else:
            os.environ["NOMAD_TPU_COLUMNAR_PREEMPT"] = prev
    out = dict(on)
    out["rate_off"] = off["rate"]
    out["speedup"] = (off["select_s"] / on["select_s"]
                      if on["select_s"] > 0 else 0.0)
    return out


def _preemption_run(n_nodes: int, n_evals: int, count: int) -> Dict:
    from ..mock import fixtures as mock
    from ..scheduler import preemption as pmod
    from ..scheduler.harness import Harness

    h = Harness()
    h.store.set_scheduler_config(
        h.next_index(),
        _preemption_config())
    _seed_nodes(h, n_nodes, dcs=1)
    # fill: one low-prio batch job consuming most of each node
    filler = mock.batch_job()
    filler.datacenters = ["dc1"]
    filler.priority = 20
    filler.task_groups[0].count = n_nodes
    filler.task_groups[0].tasks[0].resources.cpu = 3300
    filler.task_groups[0].tasks[0].resources.memory_mb = 6000
    h.store.upsert_job(h.next_index(), filler)
    h.process("batch", _eval_for(filler))

    def make_hi(i: int):
        hi = mock.job()
        hi.id = f"hi-{i}"
        hi.priority = 80
        hi.datacenters = ["dc1"]
        tg = hi.task_groups[0]
        tg.count = count
        for t in tg.tasks:
            t.resources.networks = []
            t.resources.cpu = 2000
            t.resources.memory_mb = 4000
        tg.networks = []
        return hi

    # warm the kernel at this exact (table, count-bucket) shape so the
    # timed evals measure scheduling, not XLA compilation
    warm = make_hi(10**6)
    h.store.upsert_job(h.next_index(), warm)
    h.process("service", _eval_for(warm))
    n_warm_plans = len(h.plans)
    stats0 = pmod.preempt_stats()       # baseline AFTER the warm eval

    # same GC regime as the agent's workers (utils/gcsafe.py)
    from ..utils import gcsafe
    times: List[float] = []
    placed = 0
    t_all = time.perf_counter()
    with gcsafe.safepoints():
        for i in range(n_evals):
            hi = make_hi(i)
            h.store.upsert_job(h.next_index(), hi)
            t0 = time.perf_counter()
            h.process("service", _eval_for(hi))
            times.append(time.perf_counter() - t0)
            gcsafe.safepoint()
    wall = time.perf_counter() - t_all
    stats1 = pmod.preempt_stats()
    preempted = 0
    for plan in h.plans[n_warm_plans:]:
        placed += sum(len(a) for a in plan.node_allocation.values())
        preempted += sum(len(a) for a in plan.node_preemptions.values())
    hits = stats1["cache_hits"] - stats0["cache_hits"]
    misses = stats1["cache_misses"] - stats0["cache_misses"]
    arr = np.array(times)
    return {
        "rate": placed / wall,
        "placed": placed,
        "preempted": preempted,
        "p50_ms": float(np.percentile(arr, 50) * 1e3),
        "p99_ms": float(np.percentile(arr, 99) * 1e3),
        "select_s": stats1["select_s"] - stats0["select_s"],
        "nodes_scanned": int(stats1["nodes_scanned"]
                             - stats0["nodes_scanned"]),
        "cache_hit_rate": hits / max(hits + misses, 1),
    }


def _preemption_config():
    from ..models import PreemptionConfig, SchedulerConfiguration
    return SchedulerConfiguration(
        preemption_config=PreemptionConfig(
            system_scheduler_enabled=True,
            batch_scheduler_enabled=True,
            service_scheduler_enabled=True))


def bench_feasibility(n_nodes: int = 5000, n_rounds: int = 20) -> Dict:
    """Ladder cell: constraint-heavy service jobs (=, version, regexp,
    set_contains_any, is_set, and an attr-vs-attr pair) over a large
    node fleet, compiled feasibility engine vs the
    NOMAD_TPU_COLUMNAR_FEAS=0 per-node scalar checks in-process
    (ISSUE 17). Each timed round updates ONE node (journaling a single
    attr-index row) and registers a fresh job with the same constraint
    shape, so the on-arm's steady state is the mask-patch path: the
    speedup is the accumulated feasibility-stage seconds ratio, and
    the warm window must show ZERO full attribute-column rebuilds
    (feas_column_rebuilds) with a mask-cache hit rate near 1."""
    import os

    # both arms force their switch explicitly (the bench_preemption
    # idiom) — an ambient kill switch must not silently turn the "on"
    # arm into a second reference run
    prev = os.environ.get("NOMAD_TPU_COLUMNAR_FEAS")
    try:
        os.environ["NOMAD_TPU_COLUMNAR_FEAS"] = "1"
        on = _feasibility_run(n_nodes, n_rounds)
        os.environ["NOMAD_TPU_COLUMNAR_FEAS"] = "0"
        off = _feasibility_run(n_nodes, n_rounds)
    finally:
        if prev is None:
            os.environ.pop("NOMAD_TPU_COLUMNAR_FEAS", None)
        else:
            os.environ["NOMAD_TPU_COLUMNAR_FEAS"] = prev
    return {
        "feas_mask_build_ms": round(on["feas_ms"], 3),
        "feas_mask_build_ms_off": round(off["feas_ms"], 3),
        "feas_speedup": round(off["feas_s"] / on["feas_s"]
                              if on["feas_s"] > 0 else 0.0, 2),
        "feas_intern_values": on["intern_values"],
        "feas_mask_cache_hit_rate": round(on["hit_rate"], 4),
        "feas_column_rebuilds": on["column_rebuilds"],
        "feas_rows_patched": on["rows_patched"],
    }


def _feasibility_run(n_nodes: int, n_rounds: int) -> Dict:
    import copy

    from ..mock import fixtures as mock
    from ..models import Constraint
    from ..scheduler import feasible_compiler as fc
    from ..scheduler.harness import Harness
    from ..utils import gcsafe, stages

    h = Harness()
    nodes = []
    for i in range(n_nodes):
        node = mock.node()
        node.name = f"node-{i}"
        node.datacenter = f"dc{(i % 4) + 1}"
        node.meta["rack"] = f"r{i % 16}"
        node.meta["tier"] = ("gold", "silver", "bronze")[i % 3]
        node.attributes["cpu.arch"] = "amd64" if i % 8 else "arm64"
        node.attributes["kernel.version"] = f"5.{10 + (i % 4)}.0"
        node.attributes["driver.docker.version"] = f"24.0.{i % 5}"
        node.compute_class()
        nodes.append(node)
        h.store.upsert_node(h.next_index(), node)

    def make_job(i: int):
        job = mock.job()
        job.id = f"feas-{i}"
        job.datacenters = ["dc1", "dc2", "dc3", "dc4"]
        tg = job.task_groups[0]
        tg.count = 2
        for t in tg.tasks:
            t.resources.networks = []
            t.resources.cpu = 20
            t.resources.memory_mb = 32
        tg.networks = []
        tg.constraints.extend([
            Constraint(ltarget="${attr.cpu.arch}",
                       rtarget="amd64", operand="="),
            Constraint(ltarget="${attr.kernel.version}",
                       rtarget=">= 5.10.0", operand="version"),
            Constraint(ltarget="${meta.rack}",
                       rtarget="r([0-9]|1[0-3])$", operand="regexp"),
            Constraint(ltarget="${meta.tier}",
                       rtarget="gold,silver",
                       operand="set_contains_any"),
            Constraint(ltarget="${attr.driver.docker.version}",
                       rtarget="", operand="is_set"),
            Constraint(ltarget="${node.class}",
                       rtarget="${node.class}", operand="="),
        ])
        return job

    # warm throwaway evals at the REAL shape absorb process-global
    # warmup AND the one-time engine costs (column interning, program
    # compile, first full mask build, XLA traces for this table/count
    # bucket); the node update between them walks the mask-PATCH path
    # once too (incl. the device scatter's compile) — the timed warm
    # window then measures the steady state
    for i in (10**6, 10**6 + 1):
        w = make_job(i)
        h.store.upsert_job(h.next_index(), w)
        h.process("service", _eval_for(w))
        node = copy.deepcopy(h.store.node_by_id(nodes[0].id))
        node.meta["canary"] = f"w{i}"
        h.store.upsert_node(h.next_index(), node)

    fc.reset_stats()
    g0 = h.store.attr_index.gauge_stats()
    # delta-read the global accumulators (bench_preemption idiom): in a
    # bench.py run stages are already collecting for the whole e2e
    # phase, and a reset here would wipe the plan_verify/commit counts
    # the artifact's stage_breakdown reports
    was_collecting = getattr(stages, "_collecting", False)
    if not was_collecting:
        stages.enable(reset=False)
    pre = stages.snapshot().get("feasibility",
                                {"seconds": 0.0, "calls": 0})
    with gcsafe.safepoints():
        for r in range(n_rounds):
            # one node update per round: a benign meta write journals
            # exactly one index row without moving any verdict
            node = copy.deepcopy(
                h.store.node_by_id(nodes[r % n_nodes].id))
            node.meta["canary"] = f"c{r}"
            h.store.upsert_node(h.next_index(), node)
            job = make_job(r)
            h.store.upsert_job(h.next_index(), job)
            h.process("service", _eval_for(job))
            gcsafe.safepoint()
    snap = stages.snapshot()
    if not was_collecting:
        stages.disable()
    post = snap.get("feasibility", {"seconds": 0.0, "calls": 0})
    feas = {"seconds": post["seconds"] - pre["seconds"],
            "calls": post["calls"] - pre["calls"]}
    st = fc.stats()
    g1 = h.store.attr_index.gauge_stats()
    return {
        "feas_s": feas["seconds"],
        "feas_ms": feas["seconds"] * 1e3 / max(feas["calls"], 1),
        "feas_calls": feas["calls"],
        "intern_values": g1["intern_values"],
        "hit_rate": fc.hit_rate(),
        "column_rebuilds": (g1.get("idx_column_builds", 0)
                            - g0.get("idx_column_builds", 0)),
        "rows_patched": st["rows_patched"],
    }


def bench_feas_residue(n_nodes: int = 5000, n_rounds: int = 20) -> Dict:
    """Ladder cell (ISSUE 20): spread/distinct/CSI-heavy service jobs,
    residue-compiled feasibility on vs NOMAD_TPU_FEAS_RESIDUE=0
    in-process (both arms keep the compiled engine on — this cell
    measures the RESIDUE layer, not ISSUE 17's mask compile). Each
    timed round updates ONE node (full table rebuild, which drops the
    per-table attr_codes cache) and registers a fresh CSI job with two
    spreads and a distinct_property constraint, so the off-arm pays
    the O(N) Python dictionary re-encode per spread attribute per eval
    while the on-arm derives codes from the write-through interned
    columns; spread_score_speedup is the accumulated input-build
    seconds ratio. The CSI topology subset mutates the combined mask
    every eval: the on-arm must keep the device token alive via sparse
    residue scatters (survival rate ~1, warm mask uploads ~0)."""
    import os

    prev_r = os.environ.get("NOMAD_TPU_FEAS_RESIDUE")
    prev_c = os.environ.get("NOMAD_TPU_COLUMNAR_FEAS")
    try:
        os.environ["NOMAD_TPU_COLUMNAR_FEAS"] = "1"
        os.environ["NOMAD_TPU_FEAS_RESIDUE"] = "1"
        on = _feas_residue_run(n_nodes, n_rounds)
        os.environ["NOMAD_TPU_FEAS_RESIDUE"] = "0"
        off = _feas_residue_run(n_nodes, n_rounds)
    finally:
        for var, prev in (("NOMAD_TPU_FEAS_RESIDUE", prev_r),
                          ("NOMAD_TPU_COLUMNAR_FEAS", prev_c)):
            if prev is None:
                os.environ.pop(var, None)
            else:
                os.environ[var] = prev
    decided = on["token_survivals"] + on["token_invalidations"]
    return {
        "feas_resident_token_survival_rate": round(
            on["token_survivals"] / max(decided, 1), 4),
        "feas_residue_rows": on["residue_rows"],
        "feas_residue_scatters": on["residue_scatters"],
        # warm-window full mask re-uploads on the on-arm: the token
        # survives CSI residue, so this must stay ~0
        "feas_warm_mask_uploads": on["warm_uploads"],
        "spread_build_ms": round(on["build_ms"], 3),
        "spread_build_ms_off": round(off["build_ms"], 3),
        "spread_score_speedup": round(
            off["build_s"] / on["build_s"]
            if on["build_s"] > 0 else 0.0, 2),
        "spread_score_evals": on["spread_score_evals"],
    }


def _feas_residue_run(n_nodes: int, n_rounds: int) -> Dict:
    import copy

    from ..mock import fixtures as mock
    from ..models import Constraint, Spread, SpreadTarget
    from ..models.csi import ACCESS_MULTI_NODE_MULTI_WRITER, CSIVolume
    from ..models.job import VolumeRequest
    from ..ops import spread as spread_ops
    from ..scheduler import feasible_compiler as fc
    from ..scheduler.harness import Harness
    from ..utils import gcsafe

    h = Harness()
    nodes = []
    for i in range(n_nodes):
        node = mock.node()
        node.name = f"node-{i}"
        node.datacenter = f"dc{(i % 4) + 1}"
        node.meta["rack"] = f"r{i % 16}"
        node.meta["tier"] = f"t{i % 8}"
        node.attributes["csi.plugin.p1"] = "1"
        node.compute_class()
        nodes.append(node)
        h.store.upsert_node(h.next_index(), node)

    # multi-writer volume whose topology admits 3 of 4 nodes: every
    # eval mutates the combined mask (the residue diff the on-arm
    # ships as a sparse scatter) without ever exhausting claims
    vol = CSIVolume(id="data-vol", plugin_id="p1",
                    access_mode=ACCESS_MULTI_NODE_MULTI_WRITER,
                    topology_node_ids=[n.id for i, n in enumerate(nodes)
                                       if i % 4 != 3])
    h.store.upsert_csi_volumes(h.next_index(), [vol])

    def make_job(i: int):
        job = mock.job()
        job.id = f"residue-{i}"
        job.datacenters = ["dc1", "dc2", "dc3", "dc4"]
        job.spreads = [Spread(
            attribute="${node.datacenter}", weight=70,
            spread_target=[SpreadTarget(value="dc1", percent=40),
                           SpreadTarget(value="dc2", percent=30)])]
        tg = job.task_groups[0]
        tg.count = 2
        for t in tg.tasks:
            t.resources.networks = []
            t.resources.cpu = 20
            t.resources.memory_mb = 32
        tg.networks = []
        # host-balancing spread over the full node axis plus a
        # low-cardinality tier: each attribute the off-arm re-encodes
        # O(N) in Python per rebuilt table, the on-arm reads off the
        # interned columns — the spread set mirrors a real placement
        # policy (dc targets, rack balance, host anti-affinity)
        tg.spreads = [Spread(attribute="${meta.rack}", weight=30),
                      Spread(attribute="${node.unique.name}", weight=10),
                      Spread(attribute="${meta.tier}", weight=20)]
        tg.constraints.append(Constraint(
            ltarget="${meta.rack}", rtarget="8",
            operand="distinct_property"))
        tg.volumes = {"vol": VolumeRequest(
            name="vol", type="csi", source="data-vol")}
        return job

    # warm throwaway evals: engine compile, first mask park, device
    # scatter traces, and the feas token the timed rounds dispatch on
    for i in (10**6, 10**6 + 1):
        w = make_job(i)
        h.store.upsert_job(h.next_index(), w)
        h.process("service", _eval_for(w))
        node = copy.deepcopy(h.store.node_by_id(nodes[0].id))
        node.meta["canary"] = f"w{i}"
        h.store.upsert_node(h.next_index(), node)

    fc.reset_stats()
    spread_ops.reset_stats()
    feas_store = h.store.table_cache.device.feas
    up0 = feas_store.stats["uploads"]
    t0 = time.perf_counter()
    with gcsafe.safepoints():
        for r in range(n_rounds):
            # one benign node meta write per round: a full table
            # rebuild that drops the per-table attr_codes cache — the
            # off-arm re-encodes every spread attribute O(N) in Python
            node = copy.deepcopy(
                h.store.node_by_id(nodes[r % n_nodes].id))
            node.meta["canary"] = f"c{r}"
            h.store.upsert_node(h.next_index(), node)
            job = make_job(r)
            h.store.upsert_job(h.next_index(), job)
            h.process("service", _eval_for(job))
            gcsafe.safepoint()
    wall_s = time.perf_counter() - t0
    st = fc.stats()
    sp = spread_ops.stats()
    on_arm = fc.residue_enabled()
    build_s = sp["vector_s"] if on_arm else sp["scalar_s"]
    builds = sp["vector_builds"] if on_arm else sp["scalar_builds"]
    return {
        "token_survivals": st["token_survivals"],
        "token_invalidations": st["token_invalidations"],
        "residue_rows": st["residue_rows"],
        "residue_scatters": feas_store.stats["residue_scatters"],
        "warm_uploads": feas_store.stats["uploads"] - up0,
        "spread_score_evals": sp["spread_score_evals"],
        "build_s": build_s,
        "build_ms": build_s * 1e3 / max(builds, 1),
        "wall_s": wall_s,
    }


def seed_c2m_allocs(h, nodes, seed_allocs: int,
                    sched_allocs: int = 40000) -> Dict:
    """Load the C2M substrate: `sched_allocs` go through the REAL
    scheduler/plan path (proving that machinery at depth), the rest
    through the replay loader (store.bulk_load_allocs — the snapshot-
    restore analog; seeding 2M rows one eval at a time would measure
    nothing new for half an hour). Every seeded alloc carries real
    resources so the resident table's used columns are non-trivial.
    Returns {"seed_s", "sched_s"}."""
    from ..mock import fixtures as mock
    from ..models import Allocation
    from ..models.resources import (AllocatedCpuResources,
                                    AllocatedMemoryResources,
                                    AllocatedResources,
                                    AllocatedSharedResources,
                                    AllocatedTaskResources)

    dcs = [f"dc{d}" for d in (1, 2, 3, 4)]
    t0 = time.perf_counter()
    remaining = min(sched_allocs, seed_allocs)
    chunk = 20000
    while remaining > 0:
        filler_chunk = mock.batch_job()
        filler_chunk.id = f"filler-{remaining}"
        filler_chunk.priority = 20
        filler_chunk.datacenters = dcs
        tg = filler_chunk.task_groups[0]
        tg.count = min(chunk, remaining)
        tg.tasks[0].resources.cpu = 50
        tg.tasks[0].resources.memory_mb = 64
        tg.tasks[0].resources.networks = []
        tg.networks = []
        h.store.upsert_job(h.next_index(), filler_chunk)
        h.process("batch", _eval_for(filler_chunk))
        remaining -= tg.count
    sched_s = time.perf_counter() - t0

    bulk_n = seed_allocs - min(sched_allocs, seed_allocs)
    if bulk_n > 0:
        seed_job = mock.batch_job()
        seed_job.id = "c2m-seed"
        seed_job.priority = 20
        seed_job.datacenters = dcs
        tg = seed_job.task_groups[0]
        tg.tasks[0].resources.cpu = 50
        tg.tasks[0].resources.memory_mb = 64
        tg.tasks[0].resources.networks = []
        tg.networks = []
        tg.count = bulk_n
        tgn = tg.name
        task_name = tg.tasks[0].name
        h.store.upsert_job(h.next_index(), seed_job)
        # one shared flyweight resource row: the table builder only
        # reads it, and 2M private copies would cost GBs for nothing
        res = AllocatedResources(
            tasks={task_name: AllocatedTaskResources(
                cpu=AllocatedCpuResources(cpu_shares=50),
                memory=AllocatedMemoryResources(memory_mb=64))},
            shared=AllocatedSharedResources(disk_mb=10))
        n_nodes = len(nodes)
        allocs = []
        eval_id = "c2m-seed-eval"
        for i in range(bulk_n):
            allocs.append(Allocation(
                id=f"c2m-{i:08d}", namespace="default",
                job_id=seed_job.id, task_group=tgn,
                name=f"c2m-seed.{tgn}[{i}]",
                node_id=nodes[i % n_nodes].id, eval_id=eval_id,
                client_status="running", desired_status="run",
                allocated_resources=res))
            if len(allocs) >= 250_000:
                h.store.bulk_load_allocs(h.next_index(), allocs)
                allocs = []
        if allocs:
            h.store.bulk_load_allocs(h.next_index(), allocs)
    return {"seed_s": time.perf_counter() - t0, "sched_s": sched_s}


def bench_c2m_scale(n_nodes: int = 50000, seed_allocs: int = 2_000_000,
                    batch_count: int = 10000, n_service: int = 10,
                    n_stream: int = 5) -> Dict:
    """See _bench_c2m_scale_impl; this wrapper guarantees the process-
    wide GC regime (disable + freeze) is unwound and the server torn
    down even when a step raises — a bench failure must not leave the
    collector off or worker threads running against the 2M-row store."""
    from ..server import Server, ServerConfig
    from ..utils import gcsafe
    srv = Server(ServerConfig(num_schedulers=2, eval_batch_size=1,
                              heartbeat_ttl_s=3600.0,
                              gc_safepoints=True))
    srv.start()
    gcsafe.enter()
    try:
        return _bench_c2m_scale_impl(srv, n_nodes, seed_allocs,
                                     batch_count, n_service, n_stream)
    finally:
        gcsafe.exit_()
        gcsafe.unfreeze_steady_state()
        srv.shutdown()


def _bench_c2m_scale_impl(srv, n_nodes: int, seed_allocs: int,
                          batch_count: int, n_service: int,
                          n_stream: int) -> Dict:
    """Ladder #5 (C2M replay scale): a 50k-node cluster pre-loaded with
    2M running allocs (BASELINE config #5), then (a) a 10k-instance
    batch job e2e, (a') the stock iterator baseline on the same store,
    (b) service-eval p99, and (c) a STREAM of `n_stream` 10k-instance
    batch jobs through the production control plane (eval broker ->
    two workers -> plan queue -> pipelined applier), where one
    worker's device wait overlaps the other's host work — compute
    overlapping apply end-to-end, the plan_apply.go:44-70 shape."""
    from ..mock import fixtures as mock
    from ..scheduler.harness import Harness

    # the store lives inside the wrapper-owned Server; the single-eval
    # measures below drive it through a store-sharing harness while
    # workers are paused, then the stream runs through the workers
    for w in srv.workers:
        w.set_pause(True)

    # the whole C2M ladder runs under the agent's GC-safepoint regime
    # (entered by the wrapper): automatic collection off, young-gen
    # collects + a gen-2 budget at safepoints, and — once the 2M-alloc
    # substrate is loaded — the steady state frozen out of future
    # collections (utils/gcsafe.py). Without this, CPython's automatic
    # collector walks the multi-million-object heap mid-measurement.
    from ..utils import gcsafe

    h = Harness(store=srv.store)
    h._next_index = srv.store.latest_index() + 1000
    nodes = _seed_nodes(h, n_nodes)
    dcs = [f"dc{d}" for d in (1, 2, 3, 4)]

    seed_stats = seed_c2m_allocs(h, nodes, seed_allocs)
    seed_s = seed_stats["seed_s"]
    total_allocs = sum(1 for _ in h.store.allocs())

    # the one-time post-seed resident-table build (a full 2M-row scan)
    # is reported as its own metric; the batch/service numbers below
    # measure steady state against the delta-maintained table
    t0 = time.perf_counter()
    h.store.snapshot().node_table()
    table_build_s = time.perf_counter() - t0
    gcsafe.freeze_steady_state()

    # (a) batch throughput at scale — three timed evals, best rate:
    # a single sample rides tunnel round-trip variance (~70-250 ms
    # swings) that has nothing to do with the scheduler under test
    batch_s = float("inf")
    placed = 0
    for bi in range(3):
        job = mock.batch_job()
        job.id = f"c2m-batch-{bi}"
        job.datacenters = dcs
        tg = job.task_groups[0]
        tg.count = batch_count
        tg.tasks[0].resources.networks = []
        tg.networks = []
        h.store.upsert_job(h.next_index(), job)
        t0 = time.perf_counter()
        h.process("batch", _eval_for(job))
        el = time.perf_counter() - t0
        p = sum(len(a) for a in h.plans[-1].node_allocation.values())
        if el < batch_s:
            batch_s, placed = el, p

    # (a') the stock pull-iterator scheduler on the SAME store, same
    # plan-apply path — the same-host baseline the kernel path is
    # proven against (bench/iterbaseline.py; measured at a smaller
    # count, which favors the baseline: its walk degrades as prefix
    # nodes fill)
    from .iterbaseline import bench_iter_baseline

    def _iter_proto(i):
        j = mock.batch_job()
        j.id = f"c2m-iterbase-{i}"
        j.datacenters = dcs
        tgp = j.task_groups[0]
        tgp.count = 1000
        tgp.tasks[0].resources.networks = []
        tgp.networks = []
        return j

    iter_stats = bench_iter_baseline(h, _iter_proto, count=1000,
                                     n_evals=2)

    # (b) service p99 at scale (spread + affinity live)
    from ..models import Affinity, Spread, SpreadTarget

    def make_svc(i):
        svc = mock.job()
        svc.id = f"c2m-svc-{i}"
        svc.datacenters = dcs
        tg = svc.task_groups[0]
        tg.count = 10
        for t in tg.tasks:
            t.resources.networks = []
        tg.networks = []
        tg.spreads = [Spread(attribute="${node.datacenter}", weight=50,
                             spread_target=[SpreadTarget("dc1", 40),
                                            SpreadTarget("dc2", 30)])]
        tg.affinities = [Affinity(ltarget="${meta.rack}", rtarget="r3",
                                  operand="=", weight=50)]
        return svc

    # three warm evals: the first compiles at this table shape, the
    # rest settle the per-table-version caches and the allocator so
    # the timed window measures steady state, not residual warm-up
    # (instrumented runs show eval latency decaying over the first
    # few evals at the 2M scale)
    for w in range(3):
        warm = make_svc(10**6 + w)
        h.store.upsert_job(h.next_index(), warm)
        h.process("service", _eval_for(warm))

    # the SAME GC-safepoint protocol the production worker runs
    # (utils/gcsafe.py via ServerConfig.gc_safepoints, on in the CLI
    # agent): collector pauses happen between evals, so the timed
    # window measures the latency an eval experiences in an agent
    from ..utils import gcsafe
    times: List[float] = []
    with gcsafe.safepoints():
        for i in range(n_service):
            svc = make_svc(i)
            h.store.upsert_job(h.next_index(), svc)
            t0 = time.perf_counter()
            h.process("service", _eval_for(svc))
            times.append(time.perf_counter() - t0)
            gcsafe.safepoint()
    arr = np.array(times)

    # (c) streamed batch throughput through the production workers:
    # two schedulers dequeue from the broker concurrently, so one's
    # device dispatch wait (the tunnel RTT + kernel) overlaps the
    # other's host-side reconcile/expand/plan work, and the plan queue
    # + applier pipeline the commits (plan_apply.go:44-70 overlap).
    srv._raft_index = h.store.latest_index()
    stream_jobs = []
    for i in range(n_stream):
        sj = mock.batch_job()
        sj.id = f"c2m-stream-{i}"
        sj.datacenters = dcs
        tgj = sj.task_groups[0]
        tgj.count = batch_count
        tgj.tasks[0].resources.networks = []
        tgj.networks = []
        stream_jobs.append(sj)
    tg_names = {sj.id: sj.task_groups[0].name for sj in stream_jobs}

    def _stream_placed() -> int:
        total = 0
        for sj in stream_jobs:
            summ = srv.store.job_summary("default", sj.id)
            if summ is None:
                continue
            total += sum(summ.summary.get(tg_names[sj.id], {}).values())
        return total

    for sj in stream_jobs:
        srv.register_job(sj)
    want = n_stream * batch_count
    t0 = time.perf_counter()
    for w in srv.workers:
        w.set_pause(False)
    deadline = time.perf_counter() + 600
    while time.perf_counter() < deadline:
        if _stream_placed() >= want:
            break
        time.sleep(0.05)
    stream_wall = time.perf_counter() - t0
    stream_placed = _stream_placed()

    # (c') the same stream with multi-eval batching: workers drain two
    # READY evals into BatchGateway lanes whose dispatches coalesce
    # into one vmapped kernel call — half the device round trips per
    # eval pair. One warm wave compiles the B=2 shape outside the
    # timed window.
    for w in srv.workers:
        w.set_pause(True)
        w.batch_size = 2

    def _stream_jobs(tag, count_jobs):
        out = []
        for i in range(count_jobs):
            sj = mock.batch_job()
            sj.id = f"c2m-{tag}-{i}"
            sj.datacenters = dcs
            tgj = sj.task_groups[0]
            tgj.count = batch_count
            tgj.tasks[0].resources.networks = []
            tgj.networks = []
            out.append(sj)
        return out

    def _placed_of(jobs_):
        total = 0
        for sj in jobs_:
            summ = srv.store.job_summary("default", sj.id)
            if summ is not None:
                total += sum(
                    summ.summary.get(sj.task_groups[0].name, {})
                    .values())
        return total

    def _run_stream(jobs_):
        for sj in jobs_:
            srv.register_job(sj)
        want_ = len(jobs_) * batch_count
        t0_ = time.perf_counter()
        for w in srv.workers:
            w.set_pause(False)
        deadline_ = time.perf_counter() + 600
        while time.perf_counter() < deadline_:
            if _placed_of(jobs_) >= want_:
                break
            time.sleep(0.05)
        wall_ = time.perf_counter() - t0_
        for w in srv.workers:
            w.set_pause(True)
        return wall_

    _run_stream(_stream_jobs("stream-warm", 2))      # B=2 compile
    batches_before = sum(w.stats["batches"] for w in srv.workers)
    bjobs = _stream_jobs("bstream", n_stream)
    bwall = _run_stream(bjobs)
    bplaced = _placed_of(bjobs)
    stream_batches = sum(w.stats["batches"]
                         for w in srv.workers) - batches_before

    return {
        "c2m_nodes": n_nodes,
        "c2m_allocs": total_allocs,
        "c2m_seed_rate": round(seed_allocs / max(seed_s, 1e-9), 1),
        "c2m_seed_sched_s": round(seed_stats["sched_s"], 1),
        "c2m_table_build_s": round(table_build_s, 2),
        "c2m_batch_placements_per_sec": round(placed / batch_s, 1),
        "c2m_batch_placed": placed,
        "c2m_iter_baseline_placements_per_sec": round(
            iter_stats["iter_rate"], 1),
        "c2m_vs_iter_baseline": round(
            (placed / batch_s) / max(iter_stats["iter_rate"], 1e-9), 1),
        "c2m_service_p99_ms": round(float(np.percentile(arr, 99) * 1e3), 1),
        "c2m_service_p50_ms": round(float(np.percentile(arr, 50) * 1e3), 1),
        "c2m_stream_placements_per_sec": round(
            stream_placed / max(stream_wall, 1e-9), 1),
        "c2m_stream_placed": stream_placed,
        "c2m_stream_wall_s": round(stream_wall, 2),
        "c2m_stream_batched_placements_per_sec": round(
            bplaced / max(bwall, 1e-9), 1),
        "c2m_stream_batches": stream_batches,
        "c2m_stream_batching_speedup": round(
            (bplaced / max(bwall, 1e-9))
            / max(stream_placed / max(stream_wall, 1e-9), 1e-9), 2),
    }


def bench_deployment_wave(n_nodes: int = 1000, count: int = 10000,
                          versions: int = 3,
                          evals_per_version: int = 8) -> Dict:
    """Deployment-wave reconcile cost (ISSUE 6): a count-N service job
    with a rolling update stanza takes `versions` spec bumps; every
    eval of a wave re-reconciles ALL N allocs but places at most
    max_parallel — the reference path pays O(N) per-alloc Python plus
    one deep `tasks_updated` diff PER ALLOC per eval, the columnar
    engine pays numpy masks plus ONE memoized diff per version pair.
    Runs the same workload with the engine on and off
    (NOMAD_TPU_COLUMNAR_RECONCILE) and reports evals/s for both, the
    memo hit rate, and the `reconcile` stage seconds for the engine-on
    run."""
    import os

    from ..mock import fixtures as mock
    from ..models.job import UpdateStrategy
    from ..scheduler.harness import Harness
    from ..scheduler.stack import TASKS_UPDATED_STATS
    from ..utils import stages

    def run() -> Dict:
        h = Harness()
        _seed_nodes(h, n_nodes, dcs=1)
        job = mock.job()
        job.datacenters = ["dc1"]
        tg = job.task_groups[0]
        tg.count = count
        # rolling stanza: wave evals reconcile everything, place little
        tg.update = UpdateStrategy(max_parallel=2, canary=0)
        for t in tg.tasks:
            t.resources.networks = []
        tg.networks = []
        h.store.upsert_job(h.next_index(), job)
        h.process("service", _eval_for(job))        # seed placement
        # warm wave OUTSIDE the timer: the first spec bump compiles the
        # max_parallel-sized kernel shape, and whichever run goes first
        # must not donate that compile to the other
        job = job.copy()
        job.task_groups[0].tasks[0].env = {"WAVE": "warm"}
        h.store.upsert_job(h.next_index(), job)
        h.process("service", _eval_for(job))

        tu0 = dict(TASKS_UPDATED_STATS)
        rec0 = (stages.snapshot().get("reconcile", {})
                .get("seconds", 0.0) if stages.enabled else 0.0)
        evals = 0
        t0 = time.perf_counter()
        for v in range(versions):
            job = job.copy()
            job.task_groups[0].tasks[0].env = {"WAVE": str(v)}
            h.store.upsert_job(h.next_index(), job)
            for _ in range(evals_per_version):
                h.process("service", _eval_for(job))
                evals += 1
        elapsed = time.perf_counter() - t0
        tu1 = dict(TASKS_UPDATED_STATS)
        rec1 = (stages.snapshot().get("reconcile", {})
                .get("seconds", 0.0) if stages.enabled else 0.0)
        hits = tu1["hits"] - tu0["hits"]
        misses = tu1["misses"] - tu0["misses"]
        return {"rate": evals / elapsed, "evals": evals,
                "hit_rate": hits / max(hits + misses, 1),
                "reconcile_s": rec1 - rec0}

    prev = os.environ.get("NOMAD_TPU_COLUMNAR_RECONCILE")
    try:
        os.environ["NOMAD_TPU_COLUMNAR_RECONCILE"] = "1"
        on = run()
        os.environ["NOMAD_TPU_COLUMNAR_RECONCILE"] = "0"
        off = run()
    finally:
        if prev is None:
            os.environ.pop("NOMAD_TPU_COLUMNAR_RECONCILE", None)
        else:
            os.environ["NOMAD_TPU_COLUMNAR_RECONCILE"] = prev
    return {
        "deploy_wave_evals_per_sec": round(on["rate"], 2),
        "deploy_wave_evals_per_sec_off": round(off["rate"], 2),
        "deploy_wave_speedup": round(on["rate"] / max(off["rate"], 1e-9),
                                     2),
        "deploy_wave_tasks_updated_hit_rate": round(on["hit_rate"], 4),
        "deploy_wave_reconcile_stage_s": round(on["reconcile_s"], 4),
    }


def bench_cold_start(n_nodes: int = 1000, seed_allocs: int = 30000,
                     n_jobs: int = 8, wal_tail: int = 48) -> Dict:
    """Cold-start recovery (ISSUE 8): seed a C2M-CI-scale store, write
    BOTH snapshot formats of the same state plus a shared WAL tail,
    then time a fresh boot from each — snapshot restore, cold
    resident-table build, batched WAL replay. The columnar pipeline
    (state/columnar.py + the primed NodeTable + eager alloc index)
    must beat the legacy object snapshot ≥ 3× on the summed recovery
    stages (asserted in tests/test_bench_smoke.py), and after the
    columnar boot the recovery invariants hold: the first columnar
    read per job pays ZERO dense index rebuilds and the first
    node_table() read pays ZERO full NodeTable builds."""
    import os
    import shutil
    import tempfile

    from ..mock import fixtures as mock
    from ..models import Allocation
    from ..models.resources import (AllocatedCpuResources,
                                    AllocatedMemoryResources,
                                    AllocatedResources,
                                    AllocatedSharedResources,
                                    AllocatedTaskResources)
    from ..server import Server, ServerConfig
    from ..server.persistence import Persistence

    base = tempfile.mkdtemp(prefix="nomad-tpu-cold-")
    col_dir = os.path.join(base, "columnar")
    leg_dir = os.path.join(base, "legacy")
    try:
        srv = Server(ServerConfig(num_schedulers=0, data_dir=col_dir,
                                  snapshot_background=False,
                                  heartbeat_ttl_s=3600.0))
        idx = srv._raft_index
        nodes = []
        for i in range(n_nodes):
            node = mock.node()
            node.name = f"cold-{i}"
            node.datacenter = f"dc{(i % 4) + 1}"
            node.compute_class()
            idx += 1
            srv.store.upsert_node(idx, node)
            nodes.append(node)
        jobs = []
        per_job = max(seed_allocs // n_jobs, 1)
        for jn in range(n_jobs):
            job = mock.batch_job()
            job.id = f"cold-job-{jn}"
            tg = job.task_groups[0]
            tg.count = per_job
            tg.tasks[0].resources.networks = []
            tg.networks = []
            idx += 1
            srv.store.upsert_job(idx, job)
            jobs.append(job)
            # one shared flyweight resources row per job (the C2M seed
            # shape — the columnar pool collapses it to one entry)
            res = AllocatedResources(
                tasks={tg.tasks[0].name: AllocatedTaskResources(
                    cpu=AllocatedCpuResources(cpu_shares=50),
                    memory=AllocatedMemoryResources(memory_mb=64))},
                shared=AllocatedSharedResources(disk_mb=10))
            allocs = [Allocation(
                id=f"cold-{jn}-{i:07d}", namespace="default",
                job_id=job.id, task_group=tg.name,
                name=f"{job.id}.{tg.name}[{i}]",
                node_id=nodes[(jn * per_job + i) % n_nodes].id,
                eval_id=f"cold-seed-eval-{jn}",
                client_status="running", desired_status="run",
                allocated_resources=res) for i in range(per_job)]
            idx += 1
            srv.store.bulk_load_allocs(idx, allocs)
        srv._raft_index = srv.store.latest_index()
        # legacy (object) snapshot of the SAME state, columnar
        # snapshot via the server's own persistence, one shared WAL
        # tail appended after both
        leg = Persistence(leg_dir, columnar=False, background=False)
        leg.snapshot(srv.store)
        srv.persistence.snapshot(srv.store)
        for k in range(wal_tail):
            srv.raft_apply("eval_update",
                           dict(evals=[_eval_for(jobs[k % n_jobs])]))
        srv.shutdown()
        shutil.copyfile(os.path.join(col_dir, "raft.log"),
                        os.path.join(leg_dir, "raft.log"))

        def boot(data_dir: str):
            s2 = Server(ServerConfig(num_schedulers=0,
                                     data_dir=data_dir,
                                     heartbeat_ttl_s=3600.0))
            st = dict(s2.cold_start_stats)
            st["total_s"] = (st["restore_s"] + st["table_build_s"]
                             + st["wal_replay_s"])
            return s2, st

        s2, cst = boot(col_dir)
        assert cst["snapshot_format"] == 2.0, cst
        # recovery invariants (acceptance): the first columnar read
        # per job finds the eagerly rebuilt index (zero dense
        # rebuilds), the first table read finds the primed resident
        # table (zero full builds)
        snap = s2.store.snapshot()
        for job in jobs:
            snap.job_alloc_columns("default", job.id)
        assert s2.store.alloc_index.stats["rebuilds"] == 0, \
            s2.store.alloc_index.stats
        snap.node_table()
        assert s2.store.table_cache.stats["full_builds"] == 0, \
            s2.store.table_cache.stats
        n_allocs = sum(1 for _ in s2.store.allocs())
        s2.shutdown()

        s3, lst = boot(leg_dir)
        assert lst["snapshot_format"] == 1.0, lst
        assert sum(1 for _ in s3.store.allocs()) == n_allocs
        s3.shutdown()
        return {
            "cold_nodes": n_nodes,
            "cold_allocs": n_allocs,
            "cold_restore_s": round(cst["restore_s"], 4),
            "cold_table_build_s": round(cst["table_build_s"], 4),
            "cold_wal_replay_s": round(cst["wal_replay_s"], 4),
            "cold_start_s": round(cst["total_s"], 4),
            "cold_start_legacy_s": round(lst["total_s"], 4),
            "cold_start_speedup": round(
                lst["total_s"] / max(cst["total_s"], 1e-9), 2),
        }
    finally:
        shutil.rmtree(base, ignore_errors=True)


def bench_cluster_stats(n_clients: int = 4, n_allocs: int = 8) -> Dict:
    """Fleet observability rollup (ISSUE 13): a real server + client
    agents with the stats sampler on, a running job, and the folded
    cluster economics — the artifact records nodes reporting and the
    fleet used-vs-allocated ratios so a TPU soak's bin-packing truth
    is a first-class number next to the device truth (pad_waste)."""
    import time as _time

    from ..client import Client, ClientConfig
    from ..mock import fixtures as mock
    from ..server import Server, ServerConfig

    srv = Server(ServerConfig(num_schedulers=2, heartbeat_ttl_s=30.0,
                              telemetry_sample_interval_s=3600.0))
    srv.start()
    clients = [Client(srv, ClientConfig(node_name=f"stats-{i}",
                                        heartbeat_interval_s=0.2,
                                        stats_sample_interval_s=0.1))
               for i in range(n_clients)]
    out: Dict = {}
    try:
        for c in clients:
            c.start()
        job = mock.job()
        tg = job.task_groups[0]
        tg.count = n_allocs
        tg.networks = []
        for t in tg.tasks:
            t.resources.networks = []
            t.driver = "mock_driver"
            t.config = {"run_for": "10s"}
        srv.register_job(job)
        deadline = _time.time() + 30.0
        while _time.time() < deadline:
            allocs = srv.store.allocs_by_job(job.namespace, job.id)
            if len(allocs) >= n_allocs and any(
                    a.client_status == "running" for a in allocs):
                break
            _time.sleep(0.05)
        # wait for every client's heartbeat to land a stats payload
        deadline = _time.time() + 10.0
        cs = srv.cluster_stats()
        while _time.time() < deadline and \
                cs["nodes_reporting"] < n_clients:
            _time.sleep(0.1)
            cs = srv.cluster_stats()
        if srv.telemetry is not None:
            # the cluster.* family lands in the retained ring too
            srv.telemetry.sample_once()
        out["cluster_nodes"] = int(cs["nodes_total"])
        out["cluster_nodes_reporting"] = int(cs["nodes_reporting"])
        out["cluster_stale_heartbeats"] = int(cs["stale_heartbeats"])
        out["fleet_cpu_used_ratio"] = cs["fleet_cpu_used_ratio"]
        out["fleet_mem_used_ratio"] = cs["fleet_mem_used_ratio"]
        out["fleet_cpu_allocated_ratio"] = \
            cs["fleet_cpu_allocated_ratio"]
        out["fleet_mem_allocated_ratio"] = \
            cs["fleet_mem_allocated_ratio"]
    finally:
        for c in clients:
            c.shutdown()
        srv.shutdown()
    return out


def bench_multiserver(n_nodes: int = 100, n_jobs: int = 32,
                      count: int = 6, waves: int = 3,
                      rtt_ms: float = 80.0) -> Dict:
    """Distributed scheduler plane (ISSUE 16): a real 3-server raft
    ring where followers dequeue evals from the leader's broker over
    RPC, schedule against their fenced local snapshots, and stream
    plans back through Plan.Submit into the leader's group-commit
    applier. The control arm is the SAME ring with
    NOMAD_TPU_FOLLOWER_SCHED=0 — only the leader schedules, i.e.
    single-server scheduling as every pre-r20 cluster ran it.

    The ring is geo-stretched: the fault injector's wire_latency arm
    stretches every AppendEntries round trip by `rtt_ms` in BOTH arms,
    standing in for real inter-server network distance on a loopback
    CI box. That is the regime the plane exists for — the control
    arm's single worker already hides commit latency behind its own
    depth-limited pipeline (r7), so on a co-located loopback ring the
    two arms mostly measure Python overhead. Once the commit RTT
    exceeds per-eval CPU, the control arm goes latency-bound while the
    plane keeps a cluster-wide window of plans in flight and the r9
    applier amortizes them into shared group commits (watch
    multiserver groups < plans). Placement rate is the best of
    `waves` identical deployment waves per arm — wave 0 pays JIT and
    cache warmup, and on a 1-core CI box any wave can lose the host
    to a neighbour, so per-wave best-of is the stable statistic.

    Per-server num_schedulers=1 in both arms: the plane's claim is
    that it turns the standby servers' otherwise-idle worker pools
    into schedulers, so the arms differ only in whether those pools
    may dequeue remotely (follower_max_remote=4)."""
    import os

    from ..chaos.faults import FaultInjector
    from ..mock import fixtures as mock
    from ..rpc import RpcServer
    from ..server import Server, ServerConfig

    def make_job(i: int) -> object:
        job = mock.job()
        job.id = f"msvc-{i}"
        job.datacenters = ["dc1"]
        tg = job.task_groups[0]
        tg.count = count
        tg.networks = []
        for t in tg.tasks:
            t.resources.networks = []
            t.resources.cpu = 50
            t.resources.memory_mb = 32
        return job

    def pause(servers, p: bool) -> None:
        for s in servers:
            for w in s.workers:
                w.set_pause(p)
            if s.follower_sched is not None:
                s.follower_sched.set_pause(p)

    def wait(pred, timeout_s: float) -> bool:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if pred():
                return True
            time.sleep(0.01)
        return False

    def run_arm(follower_on: bool) -> Dict:
        prev = os.environ.get("NOMAD_TPU_FOLLOWER_SCHED")
        os.environ["NOMAD_TPU_FOLLOWER_SCHED"] = \
            "1" if follower_on else "0"
        inj = FaultInjector(seed=0xB16).install()
        if rtt_ms > 0:
            inj.wire_latency(rtt_ms / 1000.0)
        servers, rpcs = [], []
        try:
            for _ in range(3):
                s = Server(ServerConfig(
                    num_schedulers=1, heartbeat_ttl_s=3600.0,
                    telemetry_sample_interval_s=0,
                    governor_interval_s=3600.0,
                    follower_max_remote=4))
                r = RpcServer(s, port=0)
                servers.append(s)
                rpcs.append(r)
            addrs = [r.addr for r in rpcs]
            for s, r in zip(servers, rpcs):
                s.attach_raft(r, addrs)
                r.start()
                s.start()
            assert wait(lambda: sum(
                s.raft.is_leader() for s in servers) == 1, 30.0), \
                "multiserver ring never elected a leader"
            lead = next(s for s in servers if s.raft.is_leader())
            pause(servers, True)
            time.sleep(1.0)     # park in-flight dequeues
            # pipelined node seeding: one raft entry per node, wait
            # only the last waiter (a sync register per node would pay
            # the stretched RTT n_nodes times)
            last_waiter = None
            for i in range(n_nodes):
                node = mock.node()
                node.name = f"mnode-{i}"
                node.datacenter = "dc1"
                node.compute_class()
                _idx, w = lead.raft_apply_async(
                    "node_register", dict(node=node))
                if w is not None:
                    last_waiter = w
            if last_waiter is not None:
                last_waiter()
            # warm wave outside the timed window: JIT compiles, device
            # table upload, select-kernel caches
            warm = [make_job(10 ** 6 + k) for k in range(2)]
            for j in warm:
                lead.register_job(j)
            pause(servers, False)
            assert wait(lambda: all(
                len(lead.store.allocs_by_job("default", j.id)) == count
                for j in warm), 120.0), "multiserver warm wave stuck"
            best_rate = 0.0
            placed_ok = True
            for wave in range(waves):
                pause(servers, True)
                time.sleep(1.0)
                jobs = [make_job(wave * 1000 + i)
                        for i in range(n_jobs)]
                for j in jobs:
                    lead.register_job(j)
                t0 = time.perf_counter()
                pause(servers, False)
                placed_ok = wait(lambda: all(
                    len(lead.store.allocs_by_job("default", j.id))
                    == count for j in jobs), 180.0) and placed_ok
                wall = time.perf_counter() - t0
                placed = sum(
                    len(lead.store.allocs_by_job("default", j.id))
                    for j in jobs)
                best_rate = max(best_rate, placed / wall)
            leases = dict(lead.eval_leases.snapshot_stats())
            fence = max((s.follower_sched.fence_wait_p99_ms()
                         for s in servers
                         if s.follower_sched is not None),
                        default=0.0)
            applier = dict(lead.plan_applier.stats)
            return {"rate": best_rate, "ok": placed_ok,
                    "leases": leases, "fence_p99_ms": fence,
                    "groups": applier.get("groups", 0),
                    "plans": applier.get("plans", 0)}
        finally:
            inj.uninstall()
            for s, r in zip(servers, rpcs):
                r.shutdown()
                s.shutdown()
            if prev is None:
                os.environ.pop("NOMAD_TPU_FOLLOWER_SCHED", None)
            else:
                os.environ["NOMAD_TPU_FOLLOWER_SCHED"] = prev

    on = run_arm(True)
    off = run_arm(False)
    # structural engagement fence (same spirit as the broker-batches
    # assert above): the plane must actually have scheduled remotely,
    # else the headline ratio is two copies of the control arm
    assert on["leases"].get("remote_plans", 0) > 0, (
        f"follower plane never submitted a remote plan: {on}")
    assert on["ok"] and off["ok"], (
        f"multiserver wave never fully placed: on={on} off={off}")
    return {
        "multiserver_placements_per_sec": round(on["rate"], 1),
        "multiserver_placements_per_sec_off": round(off["rate"], 1),
        "multiserver_speedup": round(
            on["rate"] / max(off["rate"], 1e-9), 2),
        "multiserver_fence_wait_p99_ms": round(
            on["fence_p99_ms"], 2),
        "multiserver_remote_demotions": int(
            on["leases"].get("remote_demotions", 0)),
        "multiserver_remote_dequeues": int(
            on["leases"].get("remote_dequeues", 0)),
        "multiserver_plan_groups": int(on["groups"]),
        "multiserver_plans": int(on["plans"]),
        "multiserver_rtt_ms": rtt_ms,
    }


def bench_ingest(n_nodes: int = 100, n_writers: int = 12,
                 regs_per_writer: int = 16,
                 updates_per_writer: int = 16,
                 warm_jobs: int = 4, warm_count: int = 4) -> Dict:
    """Columnar admission path (ISSUE 19): a register storm + client
    status flood from `n_writers` concurrent submitters, mixed with
    the service reads those registers trigger (the workers keep
    scheduling the storm's jobs while it runs). The batched arm runs
    the IngestGateway; the control arm is the SAME storm with
    `NOMAD_TPU_INGEST_BATCH=0` in-process — one raft entry, one store
    transaction, one event flush per write, as every pre-r22 server
    ingested. Registers go through the bulk array-body path in chunks
    (the designed storm client); status updates push one group per
    call so coalescing across submitters is the gateway's doing, not
    the workload's. Both arms run with a DURABLE WAL (wal_fsync, the
    r12 group-fsync discipline): the per-write cost a real server
    pays is the durability boundary, and amortizing it is precisely
    what write group-commit exists for — the control arm fsyncs once
    per raft entry, the batched arm once per coalesced batch. Keys:
    writes/s on vs off + speedup, the full write p99 each submitter
    saw, mean coalesced group size, shed count, and placements/s of
    the concurrent service reads (the not-regressing guard)."""
    import os
    import shutil
    import tempfile
    import threading

    from ..mock import fixtures as mock
    from ..models import Allocation
    from ..server import Server, ServerConfig
    from ..server.ingest import INGEST_ENV
    from ..utils.codec import from_wire, to_wire

    def make_job(tag: str, i: int, count: int) -> object:
        job = mock.job()
        job.id = f"ing-{tag}-{i}"
        job.datacenters = ["dc1"]
        tg = job.task_groups[0]
        tg.count = count
        tg.networks = []
        for t in tg.tasks:
            t.resources.networks = []
            t.resources.cpu = 20
            t.resources.memory_mb = 16
        return job

    def wait(pred, timeout_s: float) -> bool:
        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if pred():
                return True
            time.sleep(0.01)
        return False

    def run_arm(batch_on: bool) -> Dict:
        prev = os.environ.get(INGEST_ENV)
        os.environ[INGEST_ENV] = "1" if batch_on else "0"
        data_dir = tempfile.mkdtemp(prefix="nomad-tpu-bench-ingest-")
        srv = Server(ServerConfig(
            num_schedulers=2, heartbeat_ttl_s=3600.0,
            telemetry_sample_interval_s=0,
            governor_interval_s=3600.0,
            data_dir=data_dir, wal_fsync=True,
            snapshot_every=1 << 20))
        try:
            srv.start()
            for i in range(n_nodes):
                node = mock.node()
                node.name = f"ingnode-{i}"
                node.datacenter = "dc1"
                node.compute_class()
                srv.raft_apply("node_register", dict(node=node))
            # warm wave: real placed allocs for the status flood to
            # target, plus JIT/cache warmup outside the timed window
            warm = [make_job("warm", i, warm_count)
                    for i in range(warm_jobs)]
            for j in warm:
                srv.register_job(j)
            assert wait(lambda: all(
                len(srv.store.allocs_by_job("default", j.id))
                == warm_count for j in warm), 60.0), \
                "ingest warm wave stuck"
            warm_allocs = [a for j in warm
                           for a in srv.store.allocs_by_job(
                               "default", j.id)]
            # update payloads prepared OUTSIDE the timed window: the
            # client-side copy a real agent would push
            updates = []
            for k in range(n_writers * updates_per_writer):
                a = warm_allocs[k % len(warm_allocs)]
                cp = from_wire(Allocation, to_wire(a))
                cp.client_status = "running"
                updates.append([cp])
            storm = [[make_job("storm", w * regs_per_writer + i, 1)
                      for i in range(regs_per_writer)]
                     for w in range(n_writers)]

            def writer(w: int) -> None:
                regs, chunk = storm[w], 8
                ups = updates[w * updates_per_writer:
                              (w + 1) * updates_per_writer]
                ri = ui = 0
                while ri < len(regs) or ui < len(ups):
                    if ri < len(regs):
                        res = srv.register_jobs_bulk(
                            regs[ri:ri + chunk])
                        for r in res:
                            if isinstance(r, Exception):
                                raise r
                        ri += chunk
                    if ui < len(ups):
                        srv.update_alloc_status_from_client(ups[ui])
                        ui += 1

            threads = [threading.Thread(target=writer, args=(w,),
                                        daemon=True)
                       for w in range(n_writers)]
            t0 = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            write_wall = time.perf_counter() - t0
            all_storm = [j for regs in storm for j in regs]
            placed_ok = wait(lambda: all(
                len(srv.store.allocs_by_job("default", j.id)) == 1
                for j in all_storm), 120.0)
            place_wall = time.perf_counter() - t0
            placed = sum(len(srv.store.allocs_by_job("default", j.id))
                         for j in all_storm)
            writes = n_writers * (regs_per_writer + updates_per_writer)
            ing = srv.ingest
            return {
                "writes_per_sec": writes / write_wall,
                "placements_per_sec": placed / place_wall,
                "ok": placed_ok,
                "p99_ms": ing.write_p99_ms() if ing else 0.0,
                "group_mean": ing.mean_batch_size() if ing else 0.0,
                "shed": int(ing.stats["shed"]) if ing else 0,
                "coalesced": int(ing.stats["coalesced_writes"])
                if ing else 0,
            }
        finally:
            srv.shutdown()
            shutil.rmtree(data_dir, ignore_errors=True)
            if prev is None:
                os.environ.pop(INGEST_ENV, None)
            else:
                os.environ[INGEST_ENV] = prev

    on = run_arm(True)
    off = run_arm(False)
    # structural engagement fence: the gateway must actually have
    # coalesced concurrent writes, else the headline ratio compares
    # two copies of the sequential path
    assert on["group_mean"] > 1.0, (
        f"ingest gateway never coalesced a batch: {on}")
    assert on["ok"] and off["ok"], (
        f"ingest storm never fully placed: on={on} off={off}")
    return {
        "ingest_writes_per_sec": round(on["writes_per_sec"], 1),
        "ingest_writes_per_sec_off": round(off["writes_per_sec"], 1),
        "ingest_speedup": round(
            on["writes_per_sec"] / max(off["writes_per_sec"], 1e-9), 2),
        "ingest_write_p99_ms": round(on["p99_ms"], 2),
        "ingest_group_mean_size": round(on["group_mean"], 2),
        "ingest_coalesced_writes": int(on["coalesced"]),
        "ingest_shed": int(on["shed"]),
        "ingest_read_placements_per_sec": round(
            on["placements_per_sec"], 1),
        "ingest_read_placements_per_sec_off": round(
            off["placements_per_sec"], 1),
    }


def bench_scenario_matrix(quick: bool = True,
                          write: bool = False) -> Dict:
    """Scenario matrix under chaos (ISSUE 15): seeded workloads +
    injected faults + invariant checks against a real in-process
    server per cell (nomad_tpu/chaos/). Quick mode runs the three
    fastest cells — including the two acceptance-critical ones (a
    worker killed mid-commit, a corrupted WAL tail) — the full bench
    runs every single-process cell and writes the CHAOS_rNN.json
    artifact next to the bench's own."""
    from ..chaos.matrix import run_matrix, write_artifact
    names = (["batch_backfill", "drain_storm", "blocked_herd"]
             if quick else None)
    result = run_matrix(names=names, quick=quick)
    if write:
        write_artifact(result)
    s = result["summary"]
    by_name = {c["name"]: c for c in result["cells"]}
    out: Dict = {
        "chaos_cells": s["cells"],
        "chaos_cells_passed": s["passed"],
        "chaos_invariants_checked": s["invariants_checked"],
        "chaos_invariants_failed": s["invariants_failed"],
        "chaos_race_findings": s["race_findings"],
        "chaos_race": result["race"],
    }
    # the two acceptance cells get first-class pass/fail keys: no
    # lost/duplicated alloc across a worker kill mid-commit and
    # across a WAL-tail-corruption recovery
    if "batch_backfill" in by_name:
        out["chaos_worker_kill_pass"] = by_name["batch_backfill"]["pass"]
    if "drain_storm" in by_name:
        out["chaos_wal_corruption_pass"] = by_name["drain_storm"]["pass"]
    return out


def run_ladder(quick: bool = False) -> Dict:
    """Run the full ladder; returns a flat dict of results."""
    out: Dict = {}
    r2 = bench_batch_e2e()
    out["e2e_placements_per_sec"] = round(r2["rate"], 1)
    out["e2e_batch10k_process_s"] = round(r2["process_s"], 3)
    out["e2e_batch10k_placed"] = r2["placed"]
    r3 = bench_service_p99(n_nodes=2000 if quick else 10000,
                           n_evals=10 if quick else 50)
    out["service_p99_ms"] = round(r3["p99_ms"], 1)
    out["service_p50_ms"] = round(r3["p50_ms"], 1)
    # same measurement + key as prior rounds (harness-sequential rate)
    out["service_placements_per_sec"] = round(r3["rate"], 1)
    # production-path service throughput: broker -> batched workers ->
    # select_many -> pipelined applier (VERDICT r3 item 1), reported
    # under its own keys
    out.update(bench_broker_service(
        n_nodes=2000 if quick else 10000,
        n_jobs=16 if quick else 64))
    r4 = bench_preemption(n_nodes=200 if quick else 1000,
                          n_evals=3 if quick else 10)
    out["preemption_placements_per_sec"] = round(r4["rate"], 1)
    out["preemption_placements_per_sec_off"] = round(r4["rate_off"], 1)
    out["preemption_preempted"] = r4["preempted"]
    out["preemption_p99_ms"] = round(r4["p99_ms"], 1)
    # batched columnar victim selection vs the per-node reference
    # path, same seeded scenario in-process (ISSUE 10): speedup is the
    # accumulated preempt-stage (victim-selection) seconds ratio
    out["preemption_speedup"] = round(r4["speedup"], 2)
    out["preemption_p50_ms"] = round(r4["p50_ms"], 2)
    out["preemption_nodes_scanned"] = r4["nodes_scanned"]
    out["preemption_victim_cache_hit_rate"] = round(
        r4["cache_hit_rate"], 4)
    # compiled feasibility engine vs the per-node scalar checks over
    # the same seeded constraint-heavy scenario in-process (ISSUE 17):
    # speedup is the accumulated feasibility-stage seconds ratio; the
    # warm window must run entirely on the mask patch path (zero
    # column rebuilds, hit rate ~1)
    out.update(bench_feasibility(
        n_nodes=512 if quick else 5000,
        n_rounds=8 if quick else 20))
    # residue layer atop the compiled engine (ISSUE 20): CSI/spread/
    # distinct-heavy rounds where the device mask token must outlive
    # per-eval mask mutations via sparse residue scatters, and
    # spread/distinct scoring inputs build vectorized off the interned
    # columns vs the O(N) Python re-encode
    out.update(bench_feas_residue(
        n_nodes=512 if quick else 5000,
        n_rounds=8 if quick else 20))
    # columnar reconcile engine on vs off over a rolling deployment
    # wave (ISSUE 6 satellite: 10k-alloc job, 3 rolling versions)
    # quick mode keeps 8 evals/version: the on-vs-off ratio is asserted
    # >= 2x in CI (measured ~3.6x) and more timed evals smooth
    # wall-clock noise on loaded runners
    out.update(bench_deployment_wave(
        n_nodes=300 if quick else 1000,
        count=2000 if quick else 10000,
        versions=2 if quick else 3,
        evals_per_version=8))
    # cold-start recovery: columnar vs legacy snapshot restore on the
    # same seeded store (ISSUE 8; speedup floor asserted in
    # tests/test_bench_smoke.py)
    out.update(bench_cold_start(
        n_nodes=300 if quick else 1000,
        seed_allocs=8000 if quick else 30000,
        n_jobs=6 if quick else 8))
    # fleet observability rollup (ISSUE 13): real client agents with
    # the stats sampler on; records the used-vs-allocated economics
    out.update(bench_cluster_stats(
        n_clients=2 if quick else 4,
        n_allocs=4 if quick else 8))
    # distributed scheduler plane over a geo-stretched 3-server ring
    # (ISSUE 16): follower scheduling on vs the leader-only control
    out.update(bench_multiserver(
        n_jobs=24 if quick else 32,
        waves=2 if quick else 3))
    # columnar admission path (ISSUE 19): batched write ingest on vs
    # the one-entry-per-write control, same in-process storm
    out.update(bench_ingest(
        regs_per_writer=16 if quick else 32,
        updates_per_writer=16 if quick else 32))
    # scenario matrix under chaos (ISSUE 15): quick runs the three
    # fastest cells (incl. worker-kill + WAL-corruption); the full
    # bench runs every single-process cell and emits CHAOS_rNN.json
    out.update(bench_scenario_matrix(quick=quick, write=not quick))
    return out
