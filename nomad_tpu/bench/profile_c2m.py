"""Profile the C2M batch-eval path wall-to-wall (round-5 perf work).

Usage: python profile_c2m.py [n_nodes] [seed_allocs]
Env: NOMAD_TPU_PROFILE_CPU=1 to force CPU backend.
"""
import cProfile
import io
import os
import pstats
import sys
import time

import numpy as np


def main():
    n_nodes = int(sys.argv[1]) if len(sys.argv) > 1 else 50000
    seed_allocs = int(sys.argv[2]) if len(sys.argv) > 2 else 0

    if os.environ.get("NOMAD_TPU_PROFILE_CPU"):
        from nomad_tpu.utils.platform import force_cpu_platform
        force_cpu_platform(1)
    else:
        from nomad_tpu.utils.platform import force_cpu_platform, probe_accelerator
        platform = probe_accelerator(timeout_s=120.0)
        if platform is None or platform == "cpu":
            force_cpu_platform(1)
    from nomad_tpu.bench.ladder import (_eval_for, _seed_nodes,
                                        seed_c2m_allocs)
    from nomad_tpu.mock import fixtures as mock
    from nomad_tpu.scheduler.harness import Harness

    h = Harness()
    t0 = time.perf_counter()
    nodes = _seed_nodes(h, n_nodes)
    print(f"seed_nodes: {time.perf_counter()-t0:.2f}s", flush=True)

    if seed_allocs:
        t0 = time.perf_counter()
        seed_c2m_allocs(h, nodes, seed_allocs, sched_allocs=0)
        print(f"seed_allocs({seed_allocs}): {time.perf_counter()-t0:.2f}s",
              flush=True)

    t0 = time.perf_counter()
    h.store.snapshot().node_table()
    print(f"table_build: {time.perf_counter()-t0:.2f}s", flush=True)

    dcs = [f"dc{d}" for d in (1, 2, 3, 4)]

    def make_batch(i, count=10000):
        job = mock.batch_job()
        job.id = f"pb-{i}"
        job.datacenters = dcs
        tg = job.task_groups[0]
        tg.count = count
        tg.tasks[0].resources.networks = []
        tg.networks = []
        return job

    # warm (compile + caches)
    for i in range(2):
        job = make_batch(10**6 + i)
        h.store.upsert_job(h.next_index(), job)
        t0 = time.perf_counter()
        h.process("batch", _eval_for(job))
        print(f"warm eval {i}: {time.perf_counter()-t0:.2f}s", flush=True)

    # timed, no profiler (clean number)
    for i in range(3):
        job = make_batch(i)
        h.store.upsert_job(h.next_index(), job)
        t0 = time.perf_counter()
        h.process("batch", _eval_for(job))
        el = time.perf_counter() - t0
        placed = sum(len(a) for a in h.plans[-1].node_allocation.values())
        print(f"timed eval {i}: {el:.3f}s placed={placed} "
              f"rate={placed/el:.0f}/s", flush=True)

    # profiled
    job = make_batch(999)
    h.store.upsert_job(h.next_index(), job)
    pr = cProfile.Profile()
    pr.enable()
    h.process("batch", _eval_for(job))
    pr.disable()
    s = io.StringIO()
    ps = pstats.Stats(pr, stream=s).sort_stats("cumulative")
    ps.print_stats(45)
    print(s.getvalue())


if __name__ == "__main__":
    main()
