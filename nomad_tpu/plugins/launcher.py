"""Plugin process entry: `python -m nomad_tpu.plugins.launcher <driver>`
(the re-exec'd plugin binary pattern of go-plugin / `nomad logmon`)."""

import sys

from ..client.drivers import DRIVER_CATALOG
from .base import serve_plugin


def main() -> int:
    if len(sys.argv) != 2 or sys.argv[1] not in DRIVER_CATALOG:
        print(f"usage: launcher <{'|'.join(DRIVER_CATALOG)}>",
              file=sys.stderr)
        return 1
    serve_plugin(DRIVER_CATALOG[sys.argv[1]]())
    return 0


if __name__ == "__main__":
    sys.exit(main())
