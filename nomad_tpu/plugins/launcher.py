"""Plugin process entry: `python -m nomad_tpu.plugins.launcher <driver>`
or `... --device <device-plugin>` (the re-exec'd plugin binary pattern
of go-plugin / `nomad logmon`)."""

import sys

from ..client.drivers import DRIVER_CATALOG
from .base import serve_plugin


def main() -> int:
    args = sys.argv[1:]
    if len(args) == 2 and args[0] == "--device":
        from .device_client import (DEVICE_PLUGIN_CATALOG,
                                    build_device_methods)
        if args[1] not in DEVICE_PLUGIN_CATALOG:
            print(f"usage: launcher --device "
                  f"<{'|'.join(DEVICE_PLUGIN_CATALOG)}>",
                  file=sys.stderr)
            return 1
        plugin = DEVICE_PLUGIN_CATALOG[args[1]]()
        serve_plugin(plugin, methods=build_device_methods(plugin))
        return 0
    if len(args) == 2 and args[0] == "--csi":
        from .csi_client import CSI_PLUGIN_CATALOG, build_csi_methods
        if args[1] not in CSI_PLUGIN_CATALOG:
            print(f"usage: launcher --csi "
                  f"<{'|'.join(CSI_PLUGIN_CATALOG)}>", file=sys.stderr)
            return 1
        plugin = CSI_PLUGIN_CATALOG[args[1]]()
        serve_plugin(plugin, methods=build_csi_methods(plugin))
        return 0
    if len(args) != 1 or args[0] not in DRIVER_CATALOG:
        print(f"usage: launcher <{'|'.join(DRIVER_CATALOG)}> | "
              f"--device <plugin>", file=sys.stderr)
        return 1
    serve_plugin(DRIVER_CATALOG[args[0]]())
    return 0


if __name__ == "__main__":
    sys.exit(main())
