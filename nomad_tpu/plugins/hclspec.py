"""Typed plugin/driver config schemas — the hclspec analog.

Reference: plugins/shared/hclspec (hcl_spec.proto) — plugins declare a
schema for their config block; the client decodes the user's raw config
against it, applying defaults and failing loudly on unknown keys or
type mismatches, instead of passing raw dicts around. The reference
expresses specs as protobuf-encoded HCL decoding instructions; here a
spec is a small tree of Attr/Block nodes with the same semantics
(typed attributes, defaults, required, nested blocks, lists).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional


class SpecError(ValueError):
    """Config did not match the declared spec."""


@dataclasses.dataclass
class Attr:
    """One typed attribute (hclspec.Attr): type is one of 'string',
    'number', 'bool', 'list(string)', 'list(number)', 'any'."""
    type: str = "string"
    required: bool = False
    default: Any = None


@dataclasses.dataclass
class Block:
    """A nested object with its own spec (hclspec.Block)."""
    spec: Dict[str, Any] = dataclasses.field(default_factory=dict)
    required: bool = False


def _coerce(path: str, typ: str, value: Any) -> Any:
    if typ == "any":
        return value
    if typ == "string":
        if isinstance(value, str):
            return value
        if isinstance(value, (int, float, bool)):
            return str(value)
        raise SpecError(f"{path}: expected string, got "
                        f"{type(value).__name__}")
    if typ == "number":
        if isinstance(value, bool):
            raise SpecError(f"{path}: expected number, got bool")
        if isinstance(value, (int, float)):
            return value
        if isinstance(value, str):
            try:
                return float(value) if "." in value else int(value)
            except ValueError:
                raise SpecError(f"{path}: expected number, got {value!r}")
        raise SpecError(f"{path}: expected number, got "
                        f"{type(value).__name__}")
    if typ == "bool":
        if isinstance(value, bool):
            return value
        if isinstance(value, str) and value.lower() in ("true", "false"):
            return value.lower() == "true"
        raise SpecError(f"{path}: expected bool, got "
                        f"{type(value).__name__}")
    if typ.startswith("list(") and typ.endswith(")"):
        inner = typ[5:-1]
        if not isinstance(value, (list, tuple)):
            raise SpecError(f"{path}: expected {typ}, got "
                            f"{type(value).__name__}")
        return [_coerce(f"{path}[{i}]", inner, v)
                for i, v in enumerate(value)]
    if typ.startswith("map(") and typ.endswith(")"):
        # hclspec map(T): string keys, T values (e.g. qemu port_map)
        inner = typ[4:-1]
        if isinstance(value, (list, tuple)):
            # HCL's repeated-block shape: [{k: v}, ...] flattens
            merged: Dict[str, Any] = {}
            for entry in value:
                if not isinstance(entry, dict):
                    raise SpecError(f"{path}: expected {typ}, got list "
                                    f"of {type(entry).__name__}")
                merged.update(entry)
            value = merged
        if not isinstance(value, dict):
            raise SpecError(f"{path}: expected {typ}, got "
                            f"{type(value).__name__}")
        return {str(k): _coerce(f"{path}[{k}]", inner, v)
                for k, v in value.items()}
    raise SpecError(f"{path}: unknown spec type {typ!r}")


def decode(spec: Dict[str, Any], raw: Optional[Dict],
           path: str = "config") -> Dict[str, Any]:
    """Validate `raw` against `spec`: unknown keys fail, required keys
    must be present, defaults fill in, values coerce to their declared
    types. Returns the decoded config."""
    raw = dict(raw or {})
    out: Dict[str, Any] = {}
    for key, node in spec.items():
        present = key in raw
        value = raw.pop(key, None)
        if isinstance(node, Attr):
            if not present:
                if node.required:
                    raise SpecError(f"{path}.{key}: required")
                if node.default is not None:
                    # copy: handing out the spec's own default object
                    # would let one task's in-place mutation poison
                    # every later decode
                    import copy
                    out[key] = copy.deepcopy(node.default)
                continue
            out[key] = _coerce(f"{path}.{key}", node.type, value)
        elif isinstance(node, Block):
            if not present:
                if node.required:
                    raise SpecError(f"{path}.{key}: required block")
                continue
            if not isinstance(value, dict):
                raise SpecError(f"{path}.{key}: expected block, got "
                                f"{type(value).__name__}")
            out[key] = decode(node.spec, value, f"{path}.{key}")
        else:
            raise SpecError(f"{path}.{key}: bad spec node "
                            f"{type(node).__name__}")
    if raw:
        unknown = ", ".join(sorted(raw))
        raise SpecError(f"{path}: unknown keys: {unknown}")
    return out


def describe(spec: Dict[str, Any]) -> Dict[str, Any]:
    """Wire-friendly description of a spec (the plugin boundary ships
    this as the ConfigSchema answer, plugins/base/plugin.go
    ConfigSchema)."""
    out = {}
    for key, node in spec.items():
        if isinstance(node, Attr):
            out[key] = {"type": node.type, "required": node.required,
                        "default": node.default}
        else:
            out[key] = {"block": describe(node.spec),
                        "required": node.required}
    return out


def spec_from_wire(data: Dict[str, Any]) -> Dict[str, Any]:
    """Inverse of describe() — rebuilds a spec tree shipped over the
    plugin boundary."""
    out: Dict[str, Any] = {}
    for key, node in (data or {}).items():
        if "block" in node:
            out[key] = Block(spec=spec_from_wire(node["block"]),
                             required=bool(node.get("required")))
        else:
            out[key] = Attr(type=node.get("type", "string"),
                            required=bool(node.get("required")),
                            default=node.get("default"))
    return out
