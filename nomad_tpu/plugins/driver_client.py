"""Host side of the driver plugin boundary.

ExternalDriver presents the exact in-proc driver interface
(start_task/stop_task/recover_task + TaskHandle semantics) while the
work happens in a supervised subprocess — the drivermanager role
(client/pluginmanager/drivermanager): launch with the handshake cookie,
parse the handshake line, reconnect-and-relaunch on crash.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

from ..rpc.client import RpcClient, RpcError
from ..utils.locks import make_lock
from .base import (HANDSHAKE_COOKIE_KEY, HANDSHAKE_COOKIE_VALUE,
                   HANDSHAKE_PREFIX)

LOG = logging.getLogger("nomad_tpu.plugins")


class ProxyHandle:
    """Client-side stand-in for a plugin-held TaskHandle."""

    def __init__(self, driver: "ExternalDriver", handle_id: str,
                 task_name: str, config: dict, started_at: float):
        self.id = handle_id
        self.driver_name = driver.name
        self._driver = driver
        self.task_name = task_name
        self.config = config
        self.started_at = started_at
        self.finished_at = 0.0
        self.exit_code: Optional[int] = None
        self._done = threading.Event()

    def wait(self, timeout: Optional[float] = None) -> bool:
        if self._done.is_set():
            return True
        deadline = None if timeout is None else time.time() + timeout
        while deadline is None or time.time() < deadline:
            chunk = 30.0 if deadline is None \
                else min(30.0, deadline - time.time())
            if chunk <= 0:
                break
            try:
                res = self._driver.call(
                    "Driver.WaitTask",
                    {"handle_id": self.id, "timeout_s": chunk},
                    timeout_s=chunk + 15.0)
            except RpcError:
                # plugin died: the task is gone; report a failure exit
                self.exit_code = 137
                self.finished_at = time.time()
                self._done.set()
                return True
            if res.get("done"):
                self.exit_code = res.get("exit_code")
                self.finished_at = res.get("finished_at") or time.time()
                self._done.set()
                return True
        return False

    def done(self) -> bool:
        return self._done.is_set()

    def recoverable_state(self) -> dict:
        return {"id": self.id, "task_name": self.task_name,
                "driver": self.driver_name, "config": dict(self.config),
                "pid": None, "started_at": self.started_at,
                "plugin": True}


class ExternalDriver:
    """Driver running behind the plugin process boundary."""

    def __init__(self, driver_name: str, python: str = sys.executable):
        self.name = driver_name
        self.python = python
        self._lock = make_lock()
        self._proc: Optional[subprocess.Popen] = None
        self._rpc: Optional[RpcClient] = None

    # -- process supervision ------------------------------------------
    def _ensure_running(self) -> RpcClient:
        with self._lock:
            if self._rpc is not None and self._proc is not None \
                    and self._proc.poll() is None:
                return self._rpc
            if self._proc is not None:
                LOG.warning("driver plugin %s died (rc=%s); relaunching",
                            self.name, self._proc.poll())
            env = dict(os.environ)
            env[HANDSHAKE_COOKIE_KEY] = HANDSHAKE_COOKIE_VALUE
            self._proc = subprocess.Popen(
                [self.python, "-m", "nomad_tpu.plugins.launcher",
                 self.name],
                env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))))
            line = self._proc.stdout.readline().strip()
            if not line.startswith(HANDSHAKE_PREFIX):
                # kill the half-started process or every retry leaks a
                # live orphan
                self._proc.kill()
                self._proc.wait()
                self._proc = None
                raise RuntimeError(
                    f"driver plugin {self.name} bad handshake: {line!r}")
            addr = line[len(HANDSHAKE_PREFIX):]
            self._rpc = RpcClient(addr)
            return self._rpc

    def call(self, method: str, args: dict, timeout_s: float = 30.0):
        try:
            return self._ensure_running().call(method, args,
                                               timeout_s=timeout_s)
        except RpcError:
            # retry once: a killed plugin may not show in poll() for a
            # moment — after the reap window _ensure_running relaunches
            # it (operations on lost handles then fail unknown-handle,
            # which callers map to task-lost); a transient connection
            # drop to a live plugin just redials
            time.sleep(0.1)
            with self._lock:
                if self._proc is not None and self._proc.poll() is not None \
                        and self._rpc is not None:
                    self._rpc.close()
                    self._rpc = None
            return self._ensure_running().call(method, args,
                                               timeout_s=timeout_s)

    def shutdown(self) -> None:
        with self._lock:
            if self._rpc is not None:
                self._rpc.close()
                self._rpc = None
            if self._proc is not None and self._proc.poll() is None:
                self._proc.terminate()
                try:
                    self._proc.wait(timeout=3)
                except subprocess.TimeoutExpired:
                    self._proc.kill()
            self._proc = None

    # -- driver interface ---------------------------------------------
    def fingerprint(self) -> Dict[str, str]:
        return self.call("Driver.Fingerprint", {})["attributes"]

    def config_spec(self):
        """The plugin's declared config schema, fetched once over the
        boundary (plugins/base ConfigSchema) and cached."""
        cached = getattr(self, "_config_spec", None)
        if cached is not None:
            return cached
        from .hclspec import spec_from_wire
        wire = self.call("Driver.ConfigSchema", {}).get("schema")
        self._config_spec = spec_from_wire(wire) if wire else {}
        return self._config_spec

    def start_task(self, task_name: str, config: dict, env: dict,
                   ctx: Optional[dict] = None):
        try:
            res = self.call("Driver.StartTask",
                            {"task_name": task_name, "config": config,
                             "env": env, "ctx": ctx})
        except RpcError as e:
            raise RuntimeError(str(e))
        h = ProxyHandle(self, res["handle_id"], task_name, config,
                        res.get("started_at") or time.time())
        return h

    def stop_task(self, handle, timeout_s: float = 5.0) -> None:
        try:
            self.call("Driver.StopTask",
                      {"handle_id": handle.id, "timeout_s": timeout_s},
                      timeout_s=timeout_s + 10.0)
        except RpcError:
            pass
        handle.wait(timeout_s)

    def recover_task(self, state: dict):
        try:
            res = self.call("Driver.RecoverTask", {"state": state})
        except RpcError:
            return None
        if not res.get("handle_id"):
            return None
        return ProxyHandle(self, res["handle_id"], state.get("task_name", ""),
                           state.get("config") or {},
                           res.get("started_at") or time.time())
