from .base import HANDSHAKE_COOKIE_KEY, HANDSHAKE_COOKIE_VALUE, serve_plugin
from .driver_client import ExternalDriver

__all__ = ["HANDSHAKE_COOKIE_KEY", "HANDSHAKE_COOKIE_VALUE",
           "serve_plugin", "ExternalDriver"]
