"""The plugin process boundary.

Reference: go-plugin as used by plugins/base/plugin.go:26-35 — the host
launches the plugin as a subprocess with a magic-cookie env var (so a
plugin binary run by hand exits with an explanation), the plugin prints
a one-line handshake (protocol version + listen address) on stdout, and
the two sides speak RPC from then on. Here the transport is the same
length-prefixed msgpack framing as the cluster RPC layer (rpc/codec).

Driver plugin surface (plugins/drivers/driver.go DriverPlugin):
    Driver.Fingerprint              -> attribute map
    Driver.StartTask                -> handle id + start time
    Driver.WaitTask  {id, timeout}  -> {done, exit_code} (blocking;
                                       concurrent waits ride the seq
                                       demultiplexing)
    Driver.StopTask  {id, timeout}
    Driver.RecoverTask {state}      -> handle id (re-attach)
    Driver.InspectTask {id}         -> handle state
"""

from __future__ import annotations

import os
import sys
from typing import Dict, Optional

HANDSHAKE_COOKIE_KEY = "NOMAD_TPU_PLUGIN_COOKIE"
HANDSHAKE_COOKIE_VALUE = "nomad-tpu-driver-plugin-v1"
HANDSHAKE_PREFIX = "NOMAD_TPU_PLUGIN|1|"


def build_driver_methods(driver) -> Dict:
    """RPC method table wrapping an in-proc driver instance."""
    handles: Dict[str, object] = {}

    def fingerprint(_args):
        return {"attributes": driver.fingerprint()}

    def start_task(args):
        h = driver.start_task(args["task_name"], args.get("config") or {},
                              args.get("env") or {},
                              ctx=args.get("ctx") or None)
        handles[h.id] = h
        return {"handle_id": h.id, "started_at": h.started_at}

    def wait_task(args):
        h = handles.get(args["handle_id"])
        if h is None:
            raise KeyError(f"unknown handle {args['handle_id']}")
        done = h.wait(float(args.get("timeout_s") or 0) or None)
        return {"done": bool(done), "exit_code": h.exit_code,
                "finished_at": h.finished_at}

    def stop_task(args):
        h = handles.get(args["handle_id"])
        if h is None:
            return {}
        driver.stop_task(h, float(args.get("timeout_s", 5.0)))
        return {"exit_code": h.exit_code}

    def recover_task(args):
        recover = getattr(driver, "recover_task", None)
        h = recover(args["state"]) if recover else None
        if h is None:
            return {"handle_id": None}
        handles[h.id] = h
        return {"handle_id": h.id, "started_at": h.started_at}

    def inspect_task(args):
        h = handles.get(args["handle_id"])
        if h is None:
            return {"exists": False}
        return {"exists": True, "done": h.done(), "exit_code": h.exit_code,
                "state": h.recoverable_state()}

    def destroy_task(args):
        handles.pop(args["handle_id"], None)
        return {}

    def config_schema(_args):
        # hclspec over the boundary (plugins/base/plugin.go
        # ConfigSchema): the host decodes user config against the
        # plugin's declared schema
        from .hclspec import describe
        spec = getattr(driver, "CONFIG_SPEC", None)
        return {"schema": describe(spec) if spec else None}

    return {
        "Driver.ConfigSchema": config_schema,
        "Driver.Fingerprint": fingerprint,
        "Driver.StartTask": start_task,
        "Driver.WaitTask": wait_task,
        "Driver.StopTask": stop_task,
        "Driver.RecoverTask": recover_task,
        "Driver.InspectTask": inspect_task,
        "Driver.DestroyTask": destroy_task,
    }


def serve_plugin(driver, out=None, methods: Optional[Dict] = None) -> None:
    """Plugin-side main: verify the handshake cookie, listen, print the
    handshake line, serve until stdin closes (the host's death closes
    our stdin, so orphaned plugins exit — go-plugin's supervision
    contract). `methods` overrides the driver method table (device
    plugins serve Device.* instead)."""
    if os.environ.get(HANDSHAKE_COOKIE_KEY) != HANDSHAKE_COOKIE_VALUE:
        print("This binary is a plugin and must be launched by the "
              "nomad-tpu client agent", file=sys.stderr)
        sys.exit(1)
    from ..rpc.server import RpcServer
    rpc = RpcServer(methods=methods if methods is not None
                    else build_driver_methods(driver))
    rpc.start()
    out = out or sys.stdout
    out.write(HANDSHAKE_PREFIX + rpc.addr + "\n")
    out.flush()
    # serve until the host goes away
    try:
        while True:
            line = sys.stdin.readline()
            if not line:
                break
    except KeyboardInterrupt:
        pass
    rpc.shutdown()
