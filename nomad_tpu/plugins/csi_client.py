"""CSI plugins behind the process boundary.

Reference: plugins/csi/client.go — Nomad speaks the CSI gRPC spec
(Identity/Controller/Node services) to external storage plugin
processes; client/pluginmanager/csimanager/volume.go drives the
stage → publish mount lifecycle per volume per node. Here the same
verb surface rides the repo's plugin RPC boundary (plugins/base.py
handshake + msgpack framing), and the built-in `hostpath` plugin is
the in-tree reference implementation (the analog of
kubernetes-csi/csi-driver-host-path): volumes are directories under a
configurable root, staging records the volume on the node, publishing
materializes the per-alloc target path.

Verbs (csi spec names, client.go:
  CSI.Probe                 -> {ready}
  CSI.PluginInfo            -> {name, version}
  CSI.ControllerPublishVolume / ControllerUnpublishVolume
  CSI.NodeStageVolume   {volume_id, staging_path}
  CSI.NodeUnstageVolume {volume_id, staging_path}
  CSI.NodePublishVolume {volume_id, staging_path, target_path, readonly}
  CSI.NodeUnpublishVolume {volume_id, target_path}
"""

from __future__ import annotations

import json
import logging
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ..rpc.client import RpcClient, RpcError
from ..utils.locks import make_lock
from .base import (HANDSHAKE_COOKIE_KEY, HANDSHAKE_COOKIE_VALUE,
                   HANDSHAKE_PREFIX)

LOG = logging.getLogger("nomad_tpu.plugins.csi")


class HostPathCSIPlugin:
    """In-proc implementation served by the plugin process: a hostpath
    storage backend. Every call is journaled to `NOMAD_TPU_CSI_JOURNAL`
    (JSONL) when set, so tests and `operator debug` can audit the exact
    RPC sequence the lifecycle produced."""

    name = "hostpath"

    def __init__(self, root: Optional[str] = None):
        self.root = root or os.environ.get(
            "NOMAD_TPU_CSI_ROOT", "/tmp/nomad-tpu-csi")
        self.journal = os.environ.get("NOMAD_TPU_CSI_JOURNAL", "")

    def _log(self, verb: str, args: Dict) -> None:
        if not self.journal:
            return
        try:
            with open(self.journal, "a") as f:
                f.write(json.dumps({"verb": verb, **args}) + "\n")
        except OSError:
            pass

    def _vol_dir(self, volume_id: str) -> str:
        d = os.path.join(self.root, volume_id)
        os.makedirs(d, exist_ok=True)
        return d

    # -- identity ------------------------------------------------------
    def probe(self) -> bool:
        return True

    def plugin_info(self) -> Dict:
        return {"name": "hostpath.csi.nomad-tpu", "version": "1.0"}

    # -- controller ----------------------------------------------------
    def controller_publish(self, volume_id: str, node_id: str) -> Dict:
        """Attach the volume to a node (no-op for hostpath; returns the
        publish context the node calls receive, client.go
        ControllerPublishVolume)."""
        self._log("ControllerPublishVolume",
                  {"volume_id": volume_id, "node_id": node_id})
        return {"publish_context": {"path": self._vol_dir(volume_id)}}

    def controller_unpublish(self, volume_id: str, node_id: str) -> None:
        self._log("ControllerUnpublishVolume",
                  {"volume_id": volume_id, "node_id": node_id})

    # -- node ----------------------------------------------------------
    def node_stage(self, volume_id: str, staging_path: str) -> None:
        """Make the volume available at the node-wide staging path
        (volume.go stageVolume). For hostpath: a symlink to the backing
        directory."""
        self._log("NodeStageVolume",
                  {"volume_id": volume_id, "staging_path": staging_path})
        os.makedirs(os.path.dirname(staging_path), exist_ok=True)
        src = self._vol_dir(volume_id)
        if not os.path.islink(staging_path):
            try:
                os.symlink(src, staging_path)
            except FileExistsError:
                pass

    def node_unstage(self, volume_id: str, staging_path: str) -> None:
        self._log("NodeUnstageVolume",
                  {"volume_id": volume_id, "staging_path": staging_path})
        try:
            os.unlink(staging_path)
        except OSError:
            pass

    def node_publish(self, volume_id: str, staging_path: str,
                     target_path: str, readonly: bool) -> None:
        """Expose the staged volume at the per-alloc target path
        (volume.go publishVolume)."""
        self._log("NodePublishVolume",
                  {"volume_id": volume_id, "staging_path": staging_path,
                   "target_path": target_path, "readonly": readonly})
        os.makedirs(os.path.dirname(target_path), exist_ok=True)
        src = os.path.realpath(staging_path) if os.path.exists(
            staging_path) else self._vol_dir(volume_id)
        if not os.path.islink(target_path):
            try:
                os.symlink(src, target_path)
            except FileExistsError:
                pass

    def node_unpublish(self, volume_id: str, target_path: str) -> None:
        self._log("NodeUnpublishVolume",
                  {"volume_id": volume_id, "target_path": target_path})
        try:
            os.unlink(target_path)
        except OSError:
            pass


CSI_PLUGIN_CATALOG = {
    "hostpath": HostPathCSIPlugin,
}


def build_csi_methods(plugin) -> Dict:
    """RPC method table for a CSI plugin process."""
    return {
        "CSI.Probe": lambda _a: {"ready": bool(plugin.probe())},
        "CSI.PluginInfo": lambda _a: plugin.plugin_info(),
        "CSI.ControllerPublishVolume": lambda a: plugin.controller_publish(
            a["volume_id"], a.get("node_id", "")),
        "CSI.ControllerUnpublishVolume": lambda a: (
            plugin.controller_unpublish(a["volume_id"],
                                        a.get("node_id", "")) or {}),
        "CSI.NodeStageVolume": lambda a: (
            plugin.node_stage(a["volume_id"], a["staging_path"]) or {}),
        "CSI.NodeUnstageVolume": lambda a: (
            plugin.node_unstage(a["volume_id"], a["staging_path"]) or {}),
        "CSI.NodePublishVolume": lambda a: (
            plugin.node_publish(a["volume_id"], a["staging_path"],
                                a["target_path"],
                                bool(a.get("readonly"))) or {}),
        "CSI.NodeUnpublishVolume": lambda a: (
            plugin.node_unpublish(a["volume_id"], a["target_path"]) or {}),
    }


class ExternalCSIPlugin:
    """Host side: launch + supervise one CSI plugin process and proxy
    the verb surface (the csimanager's plugin client role)."""

    def __init__(self, plugin_name: str = "hostpath",
                 python: str = sys.executable,
                 env_extra: Optional[Dict[str, str]] = None):
        self.name = plugin_name
        self.python = python
        self.env_extra = dict(env_extra or {})
        self._lock = make_lock()
        self._proc: Optional[subprocess.Popen] = None
        self._rpc: Optional[RpcClient] = None

    def _ensure_running(self) -> RpcClient:
        with self._lock:
            if self._rpc is not None and self._proc is not None \
                    and self._proc.poll() is None:
                return self._rpc
            if self._proc is not None:
                LOG.warning("csi plugin %s died (rc=%s); relaunching",
                            self.name, self._proc.poll())
            env = dict(os.environ)
            env[HANDSHAKE_COOKIE_KEY] = HANDSHAKE_COOKIE_VALUE
            env.update(self.env_extra)
            self._proc = subprocess.Popen(
                [self.python, "-m", "nomad_tpu.plugins.launcher",
                 "--csi", self.name],
                env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))))
            line = self._proc.stdout.readline().strip()
            if not line.startswith(HANDSHAKE_PREFIX):
                self._proc.kill()
                self._proc.wait()
                self._proc = None
                raise RuntimeError(
                    f"csi plugin {self.name} bad handshake: {line!r}")
            self._rpc = RpcClient(line[len(HANDSHAKE_PREFIX):])
            return self._rpc

    def call(self, method: str, args: dict, timeout_s: float = 30.0):
        try:
            return self._ensure_running().call(method, args,
                                               timeout_s=timeout_s)
        except RpcError:
            time.sleep(0.1)
            with self._lock:
                if self._proc is not None and \
                        self._proc.poll() is not None and \
                        self._rpc is not None:
                    self._rpc.close()
                    self._rpc = None
            return self._ensure_running().call(method, args,
                                               timeout_s=timeout_s)

    def shutdown(self) -> None:
        with self._lock:
            if self._rpc is not None:
                self._rpc.close()
                self._rpc = None
            if self._proc is not None and self._proc.poll() is None:
                self._proc.terminate()
                try:
                    self._proc.wait(timeout=3)
                except subprocess.TimeoutExpired:
                    self._proc.kill()
            self._proc = None

    # -- verb surface ---------------------------------------------------
    def probe(self) -> bool:
        return bool(self.call("CSI.Probe", {}).get("ready"))

    def plugin_info(self) -> Dict:
        return self.call("CSI.PluginInfo", {})

    def controller_publish(self, volume_id: str, node_id: str) -> Dict:
        return self.call("CSI.ControllerPublishVolume",
                         {"volume_id": volume_id, "node_id": node_id})

    def controller_unpublish(self, volume_id: str, node_id: str) -> None:
        self.call("CSI.ControllerUnpublishVolume",
                  {"volume_id": volume_id, "node_id": node_id})

    def node_stage(self, volume_id: str, staging_path: str) -> None:
        self.call("CSI.NodeStageVolume",
                  {"volume_id": volume_id, "staging_path": staging_path})

    def node_unstage(self, volume_id: str, staging_path: str) -> None:
        self.call("CSI.NodeUnstageVolume",
                  {"volume_id": volume_id, "staging_path": staging_path})

    def node_publish(self, volume_id: str, staging_path: str,
                     target_path: str, readonly: bool) -> None:
        self.call("CSI.NodePublishVolume",
                  {"volume_id": volume_id, "staging_path": staging_path,
                   "target_path": target_path, "readonly": readonly})

    def node_unpublish(self, volume_id: str, target_path: str) -> None:
        self.call("CSI.NodeUnpublishVolume",
                  {"volume_id": volume_id, "target_path": target_path})
