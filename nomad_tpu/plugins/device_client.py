"""Device plugins behind the process boundary.

Reference: plugins/device/device.go — DevicePlugin exposes
Fingerprint (device groups + attributes), Reserve (a container
reservation: env vars / mounts for the chosen instance ids), and
Stats; devices/gpu/nvidia runs behind go-plugin. Here the accelerator
fingerprint (the TPU-native analog of the NVML plugin) moves behind
the same RPC boundary the driver plugins use.
"""

from __future__ import annotations

import logging
import os
import subprocess
import sys
import time
from typing import Dict, List, Optional

from ..rpc.client import RpcClient, RpcError
from ..utils.locks import make_lock
from .base import (HANDSHAKE_COOKIE_KEY, HANDSHAKE_COOKIE_VALUE,
                   HANDSHAKE_PREFIX)

LOG = logging.getLogger("nomad_tpu.plugins.device")


class AcceleratorDevicePlugin:
    """In-proc implementation served by the plugin process: JAX
    accelerator fingerprint + reservation env + runtime stats
    (devices/gpu/nvidia/device.go re-aimed at TPUs)."""

    name = "accelerator"
    CONFIG_SPEC: Dict = {}

    def fingerprint(self) -> List[Dict]:
        from ..client.agent import fingerprint_accelerator_devices
        from ..utils.codec import to_wire
        return [to_wire(g) for g in fingerprint_accelerator_devices()]

    def reserve(self, device_ids: List[str]) -> Dict:
        """ContainerReservation (plugins/device/device.go Reserve): the
        env var that scopes the task to its reserved instances — the
        accelerator analog of NVIDIA_VISIBLE_DEVICES."""
        return {"envs": {
            "JAX_VISIBLE_DEVICES": ",".join(device_ids),
            "TPU_VISIBLE_CHIPS": ",".join(device_ids),
        }}

    def stats(self) -> List[Dict]:
        try:
            import jax
            if jax.default_backend() == "cpu":
                return []
            out = []
            for d in jax.devices():
                entry = {"id": f"{d.platform}-{d.id}", "healthy": True}
                try:
                    ms = d.memory_stats()
                    entry["memory_used_bytes"] = \
                        int(ms.get("bytes_in_use", 0))
                    entry["memory_limit_bytes"] = \
                        int(ms.get("bytes_limit", 0))
                except Exception:
                    pass
                out.append(entry)
            return out
        except Exception:
            return []


DEVICE_PLUGIN_CATALOG = {
    "accelerator": AcceleratorDevicePlugin,
}


def build_device_methods(plugin) -> Dict:
    """RPC method table for a device plugin (Fingerprint/Reserve/Stats
    + ConfigSchema, plugins/device/device.go)."""
    def fingerprint(_args):
        return {"groups": plugin.fingerprint()}

    def reserve(args):
        return plugin.reserve(list(args.get("device_ids") or []))

    def stats(_args):
        return {"devices": plugin.stats()}

    def config_schema(_args):
        from .hclspec import describe
        spec = getattr(plugin, "CONFIG_SPEC", None)
        return {"schema": describe(spec) if spec else None}

    return {
        "Device.Fingerprint": fingerprint,
        "Device.Reserve": reserve,
        "Device.Stats": stats,
        "Device.ConfigSchema": config_schema,
    }


class ExternalDevicePlugin:
    """Host side: launch + supervise the device plugin process and
    proxy the DevicePlugin interface (the devicemanager role,
    client/pluginmanager/devicemanager)."""

    def __init__(self, plugin_name: str = "accelerator",
                 python: str = sys.executable):
        self.name = plugin_name
        self.python = python
        self._lock = make_lock()
        self._proc: Optional[subprocess.Popen] = None
        self._rpc: Optional[RpcClient] = None

    def _ensure_running(self) -> RpcClient:
        with self._lock:
            if self._rpc is not None and self._proc is not None \
                    and self._proc.poll() is None:
                return self._rpc
            if self._proc is not None:
                LOG.warning("device plugin %s died (rc=%s); relaunching",
                            self.name, self._proc.poll())
            env = dict(os.environ)
            env[HANDSHAKE_COOKIE_KEY] = HANDSHAKE_COOKIE_VALUE
            self._proc = subprocess.Popen(
                [self.python, "-m", "nomad_tpu.plugins.launcher",
                 "--device", self.name],
                env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=subprocess.DEVNULL, text=True,
                cwd=os.path.dirname(os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__)))))
            line = self._proc.stdout.readline().strip()
            if not line.startswith(HANDSHAKE_PREFIX):
                # kill the half-started process or every retry leaks a
                # live orphan
                self._proc.kill()
                self._proc.wait()
                self._proc = None
                raise RuntimeError(
                    f"device plugin {self.name} bad handshake: {line!r}")
            self._rpc = RpcClient(line[len(HANDSHAKE_PREFIX):])
            return self._rpc

    def call(self, method: str, args: dict, timeout_s: float = 60.0):
        try:
            return self._ensure_running().call(method, args,
                                               timeout_s=timeout_s)
        except RpcError:
            time.sleep(0.1)
            with self._lock:
                if self._proc is not None and \
                        self._proc.poll() is not None and \
                        self._rpc is not None:
                    self._rpc.close()
                    self._rpc = None
            return self._ensure_running().call(method, args,
                                               timeout_s=timeout_s)

    def shutdown(self) -> None:
        with self._lock:
            if self._rpc is not None:
                self._rpc.close()
                self._rpc = None
            if self._proc is not None and self._proc.poll() is None:
                self._proc.terminate()
                try:
                    self._proc.wait(timeout=3)
                except subprocess.TimeoutExpired:
                    self._proc.kill()
            self._proc = None

    # -- DevicePlugin interface ---------------------------------------
    def fingerprint(self) -> List:
        """Device groups as model objects (NodeDeviceResource)."""
        from ..models import NodeDeviceResource
        from ..utils.codec import from_wire
        groups = self.call("Device.Fingerprint", {},
                           timeout_s=180.0)["groups"]
        return [from_wire(NodeDeviceResource, g) for g in groups]

    def reserve(self, device_ids: List[str]) -> Dict:
        return self.call("Device.Reserve", {"device_ids": device_ids})

    def stats(self) -> List[Dict]:
        return self.call("Device.Stats", {})["devices"]
