"""Typed attributes with units (psstructs).

Re-derivation of the reference's plugin attribute algebra
(plugins/shared/structs/attribute.go:58, units.go): device fingerprints
and device-constraint operands parse into typed Attributes — int, float,
bool, or string, with an optional unit suffix on numbers ("500 MiB",
"1.250 GHz", "250 mW"). Two attributes compare only when their units
share a base dimension (bytes, byte-rates, hertz, watts — or both
unitless); comparison converts both sides to the base unit. Python's
Fraction gives the exact arithmetic the reference gets from big.Float
at 512-bit precision (attribute.go:400) without a precision knob.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from fractions import Fraction
from typing import Optional, Tuple, Union

# Base dimensions (units.go BaseUnit).
SCALAR = "scalar"
BYTE = "byte"
BYTE_RATE = "byte/s"
HERTZ = "hertz"
WATT = "watt"


@dataclass(frozen=True)
class Unit:
    """A named unit: `multiplier` over the dimension's base unit;
    `inverse` means base = value / multiplier (e.g. mW = W/1000)."""
    name: str
    base: str
    multiplier: int
    inverse: bool = False

    def comparable(self, other: "Unit") -> bool:
        return self.base == other.base


def _build_units() -> dict:
    units = []
    # Binary SI bytes / byte rates.
    for i, p in enumerate(("Ki", "Mi", "Gi", "Ti", "Pi", "Ei"), start=1):
        units.append(Unit(p + "B", BYTE, 1 << (10 * i)))
        units.append(Unit(p + "B/s", BYTE_RATE, 1 << (10 * i)))
    # Decimal SI bytes / byte rates ("kB" and "KB" are synonyms).
    for i, p in enumerate(("k", "M", "G", "T", "P", "E"), start=1):
        units.append(Unit(p + "B", BYTE, 1000 ** i))
        units.append(Unit(p + "B/s", BYTE_RATE, 1000 ** i))
    units.append(Unit("KB", BYTE, 1000))
    units.append(Unit("KB/s", BYTE_RATE, 1000))
    # Hertz.
    units.append(Unit("MHz", HERTZ, 1000 ** 2))
    units.append(Unit("GHz", HERTZ, 1000 ** 3))
    # Watts.
    units.append(Unit("mW", WATT, 1000, inverse=True))
    units.append(Unit("W", WATT, 1))
    units.append(Unit("kW", WATT, 10 ** 3))
    units.append(Unit("MW", WATT, 10 ** 6))
    units.append(Unit("GW", WATT, 10 ** 9))
    return {u.name: u for u in units}


UNIT_INDEX = _build_units()
# Longest-first so "MiB/s" wins over "B/s" in suffix matching.
_LENGTH_SORTED_UNITS = sorted(UNIT_INDEX, key=len, reverse=True)

# strconv.ParseBool's accepted spellings.
_BOOL_WORDS = {"1": True, "t": True, "T": True, "true": True,
               "TRUE": True, "True": True,
               "0": False, "f": False, "F": False, "false": False,
               "FALSE": False, "False": False}


@dataclass(frozen=True)
class Attribute:
    """One typed value. Exactly one of int_val/float_val/bool_val/
    str_val is set; unit applies to the numeric variants only."""
    int_val: Optional[int] = None
    float_val: Optional[float] = None
    bool_val: Optional[bool] = None
    str_val: Optional[str] = None
    unit: str = ""

    # -- construction ------------------------------------------------

    @staticmethod
    def of(value: Union[int, float, bool, str, "Attribute", None],
           unit: str = "") -> Optional["Attribute"]:
        """Coerce a raw fingerprint value into an Attribute. Strings
        run through parse(); numbers/bools wrap directly."""
        if value is None:
            return None
        if isinstance(value, Attribute):
            return value
        if isinstance(value, bool):
            return Attribute(bool_val=value)
        if isinstance(value, int):
            return Attribute(int_val=value, unit=unit)
        if isinstance(value, float):
            return Attribute(float_val=value, unit=unit)
        return parse_attribute(str(value))

    # -- algebra -----------------------------------------------------

    def _typed_unit(self) -> Optional[Unit]:
        return UNIT_INDEX.get(self.unit)

    def comparable(self, other: "Attribute") -> bool:
        au, bu = self._typed_unit(), other._typed_unit()
        if au is not None or bu is not None:
            return au is not None and bu is not None \
                and au.comparable(bu)
        if self.str_val is not None:
            return other.str_val is not None
        if self.bool_val is not None:
            return other.bool_val is not None
        return other.str_val is None and other.bool_val is None

    def _base_value(self) -> Optional[Fraction]:
        """Numeric value converted to the unit's base dimension."""
        if self.int_val is not None:
            v = Fraction(self.int_val)
        elif self.float_val is not None:
            # exact decimal semantics: "1.1 GHz" must equal "1100 MHz",
            # so parse the decimal string, not the binary float;
            # directly-constructed inf/nan attributes are incomparable
            try:
                v = Fraction(str(self.float_val))
            except (ValueError, OverflowError):
                return None
        else:
            return None
        u = self._typed_unit()
        if u is None:
            return v
        return v / u.multiplier if u.inverse else v * u.multiplier

    def compare(self, other: "Attribute") -> Tuple[int, bool]:
        """(-1|0|1, comparable). Bools compare only for (in)equality:
        0 when equal, 1 when not (attribute.go:343)."""
        if not self.comparable(other):
            return 0, False
        if self.bool_val is not None:
            return (0 if self.bool_val == other.bool_val else 1), True
        if self.str_val is not None:
            a, b = self.str_val, other.str_val
            return (a > b) - (a < b), True
        av, bv = self._base_value(), other._base_value()
        if av is None or bv is None:
            return 0, False
        return (av > bv) - (av < bv), True

    def __str__(self) -> str:
        if self.bool_val is not None:
            return str(self.bool_val).lower()
        if self.str_val is not None:
            return self.str_val
        num = self.int_val if self.int_val is not None else self.float_val
        return f"{num}{self.unit}" if self.unit else str(num)


def parse_attribute(input_str: str) -> Attribute:
    """Parse "500 MiB" / "1.25GHz" / "true" / arbitrary strings into a
    typed Attribute (attribute.go:58 ParseAttribute): longest-suffix
    unit match when the string ends in a letter, then int → float →
    bool → string."""
    s = input_str
    if not s:
        return Attribute(str_val=s)
    unit = ""
    numeric = s
    if s[-1].isalpha():
        for u in _LENGTH_SORTED_UNITS:
            if s.endswith(u):
                unit = u
                break
        if unit:
            numeric = s[: -len(unit)].strip()
    try:
        return Attribute(int_val=int(numeric, 10), unit=unit)
    except ValueError:
        pass
    try:
        f = float(numeric)
        # inf/nan have no place in the comparison algebra — keep the
        # raw string so they compare (only) as strings
        if math.isfinite(f):
            return Attribute(float_val=f, unit=unit)
    except ValueError:
        pass
    b = _BOOL_WORDS.get(s)
    if b is not None:
        return Attribute(bool_val=b)
    return Attribute(str_val=s)


def compare_values(lval, rval) -> Tuple[int, bool]:
    """Compare two raw values through the typed-attribute algebra."""
    a, b = Attribute.of(lval), Attribute.of(rval)
    if a is None or b is None:
        return 0, False
    return a.compare(b)
