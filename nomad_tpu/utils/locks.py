"""Lock factory: the ONE place nomad_tpu constructs its mutexes.

Every `threading.Lock` / `RLock` / `Condition` in the package is born
here (the `raw-lock` lint pass enforces it), so a single env switch —
`NOMAD_TPU_RACE=1` — swaps the whole process onto the instrumented
shims in `analysis/race.py`: acquisition-order graph with
potential-deadlock detection, hold-time / contention accounting behind
the governor's `lock.*` gauges, and guarded-structure mutation
checks. With the switch off (the default) the factory returns the raw
threading primitives — zero wrapping, zero overhead.

Locks are named by CONSTRUCTION SITE (`eval_broker.py:97`) unless the
caller passes an explicit name: every instance born at one site is a
single node in the order graph, which is the lockdep convention — the
discipline is per lock CLASS, not per instance.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Optional

_RACE_ENV = "NOMAD_TPU_RACE"


def _race_on() -> bool:
    """THE switch predicate — analysis/race.enabled() delegates here
    so the env name and falsy set live in exactly one place (the
    factory and the monitor must never disagree about whether the
    shims exist)."""
    return os.environ.get(_RACE_ENV, "") not in ("", "0", "off", "no")


def _site_name(depth: int = 2) -> str:
    f = sys._getframe(depth)
    return (f"{os.path.basename(f.f_code.co_filename)}"
            f":{f.f_lineno}")


def make_lock(name: Optional[str] = None):
    """A mutex (threading.Lock contract)."""
    if not _race_on():
        return threading.Lock()
    from ..analysis import race
    return race.InstrumentedLock(name or _site_name(), rlock=False)


def make_rlock(name: Optional[str] = None):
    """A re-entrant mutex (threading.RLock contract)."""
    if not _race_on():
        return threading.RLock()
    from ..analysis import race
    return race.InstrumentedLock(name or _site_name(), rlock=True)


def make_condition(lock=None, name: Optional[str] = None):
    """A condition variable (threading.Condition contract), optionally
    sharing a lock previously built by this factory — the raft idiom
    `make_condition(self._lock)` keeps cv and mutex one bookkeeping
    node."""
    if not _race_on():
        return threading.Condition(lock)
    from ..analysis import race
    if lock is None or isinstance(lock, race.InstrumentedLock):
        return race.InstrumentedCondition(
            lock=lock, name=name or _site_name())
    # a raw lock slipped in (constructed before the switch flipped):
    # stay uninstrumented rather than split the bookkeeping
    return threading.Condition(lock)
