from .ids import generate_uuid, short_id
from .hamt import Hamt

__all__ = ["generate_uuid", "short_id", "Hamt"]
