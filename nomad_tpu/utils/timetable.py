"""TimeTable: sparse mapping between wall-clock time and raft index.

Reference semantics: nomad/timetable.go — the leader witnesses
(index, time) pairs at a bounded granularity; GC converts "older than
threshold duration" into "index <= NearestIndex(now - threshold)" so all
GC decisions are pure functions of raft indexes.
"""

from __future__ import annotations

import bisect
import time
from typing import List, Tuple
from .locks import make_lock


class TimeTable:
    def __init__(self, granularity_s: float = 1.0, limit: int = 72 * 3600):
        self._granularity = granularity_s
        self._limit = limit           # max entries retained
        self._lock = make_lock()
        self._times: List[float] = []
        self._indexes: List[int] = []

    def witness(self, index: int, when: float = 0.0) -> None:
        when = when or time.time()
        with self._lock:
            if self._times and when - self._times[-1] < self._granularity:
                return
            self._times.append(when)
            self._indexes.append(index)
            if len(self._times) > self._limit:
                self._times = self._times[-self._limit:]
                self._indexes = self._indexes[-self._limit:]

    def nearest_index(self, when: float) -> int:
        """Largest witnessed index at-or-before `when` (0 if none)."""
        with self._lock:
            i = bisect.bisect_right(self._times, when)
            if i == 0:
                return 0
            return self._indexes[i - 1]

    def nearest_time(self, index: int) -> float:
        with self._lock:
            i = bisect.bisect_right(self._indexes, index)
            if i == 0:
                return 0.0
            return self._times[i - 1]

    # -- persistence (nomad persists the timetable in FSM snapshots so
    #    GC cutoffs survive restarts, fsm.go persistTimeTable) ---------
    def dump(self) -> List[Tuple[float, int]]:
        with self._lock:
            return list(zip(self._times, self._indexes))

    def restore(self, entries) -> None:
        with self._lock:
            self._times = [float(t) for t, _ in entries]
            self._indexes = [int(i) for _, i in entries]
