"""ID helpers (reference: helper/uuid/uuid.go)."""

import os


def generate_uuid() -> str:
    """Random UUIDv4-format string. Formats os.urandom bytes directly:
    ~5x faster than uuid.UUID construction, which matters when a plan
    apply mints tens of thousands of alloc IDs."""
    h = os.urandom(16).hex()
    return f"{h[:8]}-{h[8:12]}-4{h[13:16]}-{h[16:20]}-{h[20:]}"


def short_id(full: str) -> str:
    return full[:8]
