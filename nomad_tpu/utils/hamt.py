"""A persistent (immutable, structurally shared) hash map.

This is the substrate for the MVCC state store (state/store.py): every
write transaction produces a new root while old snapshots keep reading
their own roots — the equivalent of go-memdb's immutable radix trees
(reference: nomad/state/state_store.go uses github.com/hashicorp/go-memdb).

Implementation: 32-way hash array mapped trie with path copying.
O(log32 n) per get/set/delete; snapshots are O(1) (root pointer copy).
"""

from __future__ import annotations

from typing import Any, Iterator, Optional, Tuple

_BITS = 5
_WIDTH = 1 << _BITS  # 32
_MASK = _WIDTH - 1


class _Node:
    __slots__ = ("bitmap", "entries")

    def __init__(self, bitmap: int, entries: tuple):
        self.bitmap = bitmap
        # entries[i] is either (key, value) leaf, a _Node, or a _Collision
        self.entries = entries


class _Collision:
    __slots__ = ("hash", "pairs")

    def __init__(self, h: int, pairs: tuple):
        self.hash = h
        self.pairs = pairs  # tuple of (key, value)


_EMPTY = _Node(0, ())
_SENTINEL = object()


def _index(bitmap: int, bit: int) -> int:
    return (bitmap & (bit - 1)).bit_count()


class EditContext:
    """Transient edit session (the clojure/immer "transient" trick).

    Nodes created while an edit context is active are tagged as owned by
    it; subsequent writes through the same context mutate them in place
    instead of path-copying again, so a transaction of k writes allocates
    O(k·log n) nodes once instead of re-copying the path per write.
    Owned nodes are only ever reachable from unpublished roots, so
    published snapshots stay immutable. `keepalive` pins created nodes so
    an id() is never recycled into a false ownership claim."""

    __slots__ = ("owned", "keepalive")

    def __init__(self):
        self.owned = set()
        self.keepalive = []

    def adopt(self, node):
        self.owned.add(id(node))
        self.keepalive.append(node)
        return node


class Hamt:
    """Immutable hash map. set/delete return new maps sharing structure.

    `with_ctx(ctx)` returns a view whose writes run transiently through
    the given EditContext (see EditContext); reads are identical."""

    __slots__ = ("_root", "_size", "_ctx")

    def __init__(self, _root: _Node = _EMPTY, _size: int = 0,
                 _ctx: "EditContext" = None):
        self._root = _root
        self._size = _size
        self._ctx = _ctx

    def with_ctx(self, ctx: "EditContext") -> "Hamt":
        if ctx is self._ctx:
            return self
        return Hamt(self._root, self._size, ctx)

    def frozen(self) -> "Hamt":
        """Drop the edit context: further writes are fully persistent."""
        if self._ctx is None:
            return self
        return Hamt(self._root, self._size, None)

    # -- reads ---------------------------------------------------------
    def __len__(self) -> int:
        return self._size

    def __contains__(self, key) -> bool:
        return self.get(key, _SENTINEL) is not _SENTINEL

    def __getitem__(self, key):
        v = self.get(key, _SENTINEL)
        if v is _SENTINEL:
            raise KeyError(key)
        return v

    def get(self, key, default=None):
        h = hash(key)
        node = self._root
        shift = 0
        while True:
            if isinstance(node, _Collision):
                if node.hash == h:
                    for k, v in node.pairs:
                        if k == key:
                            return v
                return default
            bit = 1 << ((h >> shift) & _MASK)
            if not (node.bitmap & bit):
                return default
            entry = node.entries[_index(node.bitmap, bit)]
            if isinstance(entry, (_Node, _Collision)):
                node = entry
                shift += _BITS
            else:
                k, v = entry
                return v if k == key else default

    def items(self) -> Iterator[Tuple[Any, Any]]:
        stack = [self._root]
        while stack:
            node = stack.pop()
            if isinstance(node, _Collision):
                yield from node.pairs
            else:
                for entry in node.entries:
                    if isinstance(entry, (_Node, _Collision)):
                        stack.append(entry)
                    else:
                        yield entry

    def keys(self) -> Iterator[Any]:
        for k, _ in self.items():
            yield k

    def values(self) -> Iterator[Any]:
        # direct walk (not via items()): at C2M scale the resident
        # table build iterates 2M entries, and the extra generator
        # frame + tuple unpack per entry is measurable
        stack = [self._root]
        while stack:
            node = stack.pop()
            if isinstance(node, _Collision):
                for _k, v in node.pairs:
                    yield v
            else:
                for entry in node.entries:
                    if isinstance(entry, (_Node, _Collision)):
                        stack.append(entry)
                    else:
                        yield entry[1]

    def __iter__(self) -> Iterator[Any]:
        return self.keys()

    # -- writes (persistent; transient when a ctx is attached) ---------
    def set(self, key, value) -> "Hamt":
        h = hash(key)
        if self._ctx is not None:
            new_root, added = _set_t(self._root, 0, h, key, value, self._ctx)
            return Hamt(new_root, self._size + (1 if added else 0), self._ctx)
        new_root, added = _set(self._root, 0, h, key, value)
        return Hamt(new_root, self._size + (1 if added else 0))

    def delete(self, key) -> "Hamt":
        h = hash(key)
        result = _delete(self._root, 0, h, key)
        if result is _SENTINEL:
            return self  # key absent
        new_root = result if result is not None else _EMPTY
        if isinstance(new_root, tuple):  # collapsed to single leaf
            node = _Node(1 << ((h := hash(new_root[0])) & _MASK), (new_root,))
            new_root = node
        return Hamt(new_root, self._size - 1, self._ctx)

    def update(self, pairs) -> "Hamt":
        """Batch set; runs through one EditContext so the whole batch
        path-copies each trie node at most once. Updating an EMPTY map
        takes the bottom-up radix builder instead — one bucket pass per
        trie level beats per-insert path traversal ~5x, which is what
        makes a 2M-row bulk load (store.bulk_load_allocs) tractable."""
        if self._size == 0:
            items = pairs if isinstance(pairs, dict) else dict(pairs)
            if not items:
                return self
            hkv = [(hash(k), k, v) for k, v in items.items()]
            return Hamt(_build_node(hkv, 0), len(items), self._ctx)
        items = pairs.items() if isinstance(pairs, dict) else pairs
        ctx = self._ctx or EditContext()
        root = self._root
        size = self._size
        for k, v in items:
            root, added = _set_t(root, 0, hash(k), k, v, ctx)
            size += 1 if added else 0
        return Hamt(root, size, self._ctx)


def _build_node(hkv, shift: int):
    """Bottom-up construction of a trie node from [(hash, key, value)]
    with DISTINCT keys: radix-bucket on this level's 5-bit slice, recurse
    only into multi-entry buckets. O(n · levels) with one dict pass per
    level instead of per-insert path walks."""
    buckets = {}
    for item in hkv:
        idx = (item[0] >> shift) & _MASK
        b = buckets.get(idx)
        if b is None:
            buckets[idx] = [item]
        else:
            b.append(item)
    bitmap = 0
    entries = []
    for idx in sorted(buckets):
        bitmap |= 1 << idx
        b = buckets[idx]
        if len(b) == 1:
            _h, k, v = b[0]
            entries.append((k, v))
        else:
            h0 = b[0][0]
            if all(it[0] == h0 for it in b):
                entries.append(_Collision(
                    h0, tuple((k, v) for _h, k, v in b)))
            else:
                entries.append(_build_node(b, shift + _BITS))
    return _Node(bitmap, tuple(entries))


def _set(node, shift: int, h: int, key, value):
    """Returns (new_node, added_bool)."""
    if isinstance(node, _Collision):
        if node.hash == h:
            for i, (k, _) in enumerate(node.pairs):
                if k == key:
                    pairs = node.pairs[:i] + ((key, value),) + node.pairs[i + 1:]
                    return _Collision(h, pairs), False
            return _Collision(h, node.pairs + ((key, value),)), True
        # different hash: push collision node down a level
        bit = 1 << ((node.hash >> shift) & _MASK)
        wrapped = _Node(bit, (node,))
        return _set(wrapped, shift, h, key, value)

    bit = 1 << ((h >> shift) & _MASK)
    idx = _index(node.bitmap, bit)
    if not (node.bitmap & bit):
        entries = node.entries[:idx] + ((key, value),) + node.entries[idx:]
        return _Node(node.bitmap | bit, entries), True

    entry = node.entries[idx]
    if isinstance(entry, (_Node, _Collision)):
        child, added = _set(entry, shift + _BITS, h, key, value)
        return _Node(node.bitmap, node.entries[:idx] + (child,) + node.entries[idx + 1:]), added

    k, v = entry
    if k == key:
        return _Node(node.bitmap, node.entries[:idx] + ((key, value),) + node.entries[idx + 1:]), False

    # split: both leaves descend
    kh = hash(k)
    if kh == h:
        child = _Collision(h, ((k, v), (key, value)))
    else:
        child = _merge_leaves(shift + _BITS, kh, (k, v), h, (key, value))
    return _Node(node.bitmap, node.entries[:idx] + (child,) + node.entries[idx + 1:]), True


def _set_t(node, shift: int, h: int, key, value, ctx):
    """Transient _set: nodes owned by ctx are mutated in place; anything
    else is path-copied once and adopted. Returns (node, added_bool)."""
    if isinstance(node, _Collision):
        if node.hash == h:
            for i, (k, _) in enumerate(node.pairs):
                if k == key:
                    pairs = (node.pairs[:i] + ((key, value),)
                             + node.pairs[i + 1:])
                    return _Collision(h, pairs), False
            return _Collision(h, node.pairs + ((key, value),)), True
        bit = 1 << ((node.hash >> shift) & _MASK)
        wrapped = ctx.adopt(_Node(bit, (node,)))
        return _set_t(wrapped, shift, h, key, value, ctx)

    owned = id(node) in ctx.owned
    bit = 1 << ((h >> shift) & _MASK)
    idx = _index(node.bitmap, bit)
    if not (node.bitmap & bit):
        entries = node.entries[:idx] + ((key, value),) + node.entries[idx:]
        if owned:
            node.bitmap |= bit
            node.entries = entries
            return node, True
        return ctx.adopt(_Node(node.bitmap | bit, entries)), True

    entry = node.entries[idx]
    if isinstance(entry, (_Node, _Collision)):
        child, added = _set_t(entry, shift + _BITS, h, key, value, ctx)
        if child is entry:
            return node, added  # child mutated in place
        entries = node.entries[:idx] + (child,) + node.entries[idx + 1:]
        if owned:
            node.entries = entries
            return node, added
        return ctx.adopt(_Node(node.bitmap, entries)), added

    k, v = entry
    if k == key:
        entries = (node.entries[:idx] + ((key, value),)
                   + node.entries[idx + 1:])
        if owned:
            node.entries = entries
            return node, False
        return ctx.adopt(_Node(node.bitmap, entries)), False

    kh = hash(k)
    if kh == h:
        child = _Collision(h, ((k, v), (key, value)))
    else:
        child = ctx.adopt(_merge_leaves(shift + _BITS, kh, (k, v),
                                        h, (key, value)))
    entries = node.entries[:idx] + (child,) + node.entries[idx + 1:]
    if owned:
        node.entries = entries
        return node, True
    return ctx.adopt(_Node(node.bitmap, entries)), True


def _merge_leaves(shift: int, h1: int, leaf1, h2: int, leaf2) -> _Node:
    i1 = (h1 >> shift) & _MASK
    i2 = (h2 >> shift) & _MASK
    if i1 == i2:
        child = _merge_leaves(shift + _BITS, h1, leaf1, h2, leaf2)
        return _Node(1 << i1, (child,))
    if i1 < i2:
        return _Node((1 << i1) | (1 << i2), (leaf1, leaf2))
    return _Node((1 << i1) | (1 << i2), (leaf2, leaf1))


def _delete(node, shift: int, h: int, key):
    """Returns _SENTINEL if absent; None if node becomes empty; a (k,v)
    tuple if node collapses to a single leaf; else a new node."""
    if isinstance(node, _Collision):
        for i, (k, _) in enumerate(node.pairs):
            if k == key:
                pairs = node.pairs[:i] + node.pairs[i + 1:]
                if len(pairs) == 1:
                    return pairs[0]
                return _Collision(node.hash, pairs)
        return _SENTINEL

    bit = 1 << ((h >> shift) & _MASK)
    if not (node.bitmap & bit):
        return _SENTINEL
    idx = _index(node.bitmap, bit)
    entry = node.entries[idx]

    if isinstance(entry, (_Node, _Collision)):
        result = _delete(entry, shift + _BITS, h, key)
        if result is _SENTINEL:
            return _SENTINEL
        if result is None:
            entries = node.entries[:idx] + node.entries[idx + 1:]
            if not entries:
                return None
            if len(entries) == 1 and not isinstance(entries[0], (_Node, _Collision)):
                return entries[0]
            return _Node(node.bitmap & ~bit, entries)
        if isinstance(result, tuple):  # child collapsed to leaf
            if len(node.entries) == 1:
                return result
            return _Node(node.bitmap, node.entries[:idx] + (result,) + node.entries[idx + 1:])
        return _Node(node.bitmap, node.entries[:idx] + (result,) + node.entries[idx + 1:])

    k, _ = entry
    if k != key:
        return _SENTINEL
    entries = node.entries[:idx] + node.entries[idx + 1:]
    if not entries:
        return None
    if len(entries) == 1 and not isinstance(entries[0], (_Node, _Collision)):
        return entries[0]
    return _Node(node.bitmap & ~bit, entries)
