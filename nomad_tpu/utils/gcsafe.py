"""GC safepoints: keep CPython collector pauses out of eval latency.

With a multi-million-object resident state (C2M: 2M allocs), automatic
collections land mid-eval and put 30-60 ms pauses into scheduling
latency. This controller moves them to explicit safe points (between
evals in the worker loop): automatic collection is disabled while any
participant is registered, and participants call `safepoint()` after
each unit of work — a young-generation collect that is process-level
coordinated (one collector at a time, rate-limited) so N workers don't
run N collections per eval. A collect still holds the GIL while
sibling threads run — inherent to CPython — but rare, rate-limited
collections of the young generations are tens of microseconds against
the tens of milliseconds the automatic collector costs when it decides
to walk a C2M-sized heap mid-eval.

Used by server/worker.py (ServerConfig.gc_safepoints, on in the CLI
agent) and mirrored by the C2M benchmark so it measures the regime the
agent actually runs.
"""

from __future__ import annotations

import gc
import threading
import time

_lock = threading.Lock()
_participants = 0
_was_enabled = True
_last_collect = 0.0

# floor between coordinated young-gen collects; more frequent adds no
# latency benefit and multiplies GIL stalls across workers
MIN_COLLECT_INTERVAL_S = 0.05


def enter() -> None:
    """Register a participant; disables automatic collection on the
    first one (remembering whether it was enabled)."""
    global _participants, _was_enabled
    with _lock:
        _participants += 1
        if _participants == 1:
            _was_enabled = gc.isenabled()
            gc.disable()


def exit_() -> None:
    """Deregister; the last one out restores the collector state."""
    global _participants
    with _lock:
        if _participants > 0:
            _participants -= 1
            if _participants == 0 and _was_enabled:
                gc.enable()


def safepoint() -> None:
    """Young-generation collect at a safe point — at most one
    collector at a time, rate-limited process-wide. Callers that lose
    the race simply skip (a sibling just collected)."""
    global _last_collect
    now = time.monotonic()
    if now - _last_collect < MIN_COLLECT_INTERVAL_S:
        return
    if not _lock.acquire(blocking=False):
        return
    try:
        if now - _last_collect < MIN_COLLECT_INTERVAL_S:
            return
        _last_collect = now
        gc.collect(1)
    finally:
        _lock.release()


class safepoints:
    """Context manager: `with gcsafe.safepoints(): ... gcsafe.safepoint()`"""

    def __enter__(self):
        enter()
        return self

    def __exit__(self, *exc):
        exit_()
        return False
