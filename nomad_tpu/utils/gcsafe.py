"""GC safepoints: keep CPython collector pauses out of eval latency.

With a multi-million-object resident state (C2M: 2M allocs), automatic
collections land mid-eval and put 30-60 ms pauses into scheduling
latency. This controller moves them to explicit safe points (between
evals in the worker loop): automatic collection is disabled while any
participant is registered, and participants call `safepoint()` after
each unit of work — a young-generation collect that is process-level
coordinated (one collector at a time, rate-limited) so N workers don't
run N collections per eval. A collect still holds the GIL while
sibling threads run — inherent to CPython — but rare, rate-limited
collections of the young generations are tens of microseconds against
the tens of milliseconds the automatic collector costs when it decides
to walk a C2M-sized heap mid-eval.

Used by server/worker.py (ServerConfig.gc_safepoints, on in the CLI
agent) and mirrored by the C2M benchmark so it measures the regime the
agent actually runs.
"""

from __future__ import annotations

import gc
import time
from .locks import make_lock

_lock = make_lock()
_participants = 0
_was_enabled = True
_last_collect = 0.0
_last_full_collect = 0.0

# floor between coordinated young-gen collects; more frequent adds no
# latency benefit and multiplies GIL stalls across workers
MIN_COLLECT_INTERVAL_S = 0.05

# gen-2 budget: a FULL collection runs at a safepoint at least this
# often, so unreachable cycles can't accumulate for the lifetime of
# the regime (the young-gen-only policy deferred gen-2 indefinitely
# while workers were busy). After freeze_steady_state() the full pass
# skips the frozen substrate, so it stays cheap even at C2M scale.
FULL_COLLECT_INTERVAL_S = 10.0


def enter() -> None:
    """Register a participant; disables automatic collection on the
    first one (remembering whether it was enabled)."""
    global _participants, _was_enabled
    with _lock:
        _participants += 1
        if _participants == 1:
            _was_enabled = gc.isenabled()
            gc.disable()


def exit_() -> None:
    """Deregister; the last one out restores the collector state."""
    global _participants
    with _lock:
        if _participants > 0:
            _participants -= 1
            if _participants == 0 and _was_enabled:
                gc.enable()


def safepoint() -> None:
    """Collect at a safe point — at most one collector at a time,
    rate-limited process-wide. Young generations collect on the fast
    cadence; a FULL collection runs on the FULL_COLLECT_INTERVAL_S
    budget so gen-2 garbage stays bounded over long runs. Callers that
    lose the race simply skip (a sibling just collected)."""
    global _last_collect, _last_full_collect
    now = time.monotonic()
    if now - _last_collect < MIN_COLLECT_INTERVAL_S:
        return
    if not _lock.acquire(blocking=False):
        return
    try:
        if now - _last_collect < MIN_COLLECT_INTERVAL_S:
            return
        _last_collect = now
        if now - _last_full_collect >= FULL_COLLECT_INTERVAL_S:
            _last_full_collect = now
            gc.collect()
        else:
            gc.collect(1)
    finally:
        _lock.release()


def unfreeze_steady_state() -> None:
    """Return the frozen substrate to the collectable heap (gc.unfreeze)
    — pair with freeze_steady_state when the substrate's lifetime ends
    (e.g. a benchmark tearing down its server)."""
    gc.unfreeze()


def freeze_steady_state() -> None:
    """Move the current live heap to the permanent generation
    (gc.freeze) after reclaiming what's already dead. For a process
    whose resident state is large and long-lived (a C2M server: 2M
    alloc objects), this takes the substrate out of every future
    collection — the gen-2 budget above then costs microseconds, not
    seconds. Call once the steady-state substrate is loaded."""
    gc.collect()
    gc.freeze()


class safepoints:
    """Context manager: `with gcsafe.safepoints(): ... gcsafe.safepoint()`"""

    def __enter__(self):
        enter()
        return self

    def __exit__(self, *exc):
        exit_()
        return False
