"""Generic dataclass <-> plain-dict codec used for JSON/msgpack wire
formats and state persistence (reference: nomad/structs/structs.generated.go
msgpack codegen; we derive codecs from dataclass type hints instead)."""

from __future__ import annotations

import dataclasses
import enum
import types
import typing
from typing import Any, get_args, get_origin, get_type_hints

_HINTS_CACHE: dict = {}


def to_wire(obj: Any) -> Any:
    """Recursively convert dataclasses/enums/containers to plain data.
    bytes become tagged base64 dicts so the output is JSON-safe AND
    round-trips losslessly even inside Any-typed containers."""
    if isinstance(obj, bytes):
        import base64
        return {"__b64__": base64.b64encode(obj).decode("ascii")}
    if obj is None or isinstance(obj, (str, int, float, bool)):
        return obj
    if isinstance(obj, enum.Enum):
        return obj.value
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        out = {}
        for f in dataclasses.fields(obj):
            v = getattr(obj, f.name)
            out[f.name] = to_wire(v)
        return out
    if isinstance(obj, dict):
        return {k: to_wire(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set, frozenset)):
        return [to_wire(v) for v in obj]
    return obj


def from_wire(cls: Any, data: Any) -> Any:
    """Recursively build an instance of `cls` from plain data."""
    if data is None:
        return None
    # tagged bytes decode regardless of the declared type, so bytes
    # survive Any-typed containers (e.g. Task.config values)
    if isinstance(data, dict) and len(data) == 1 and "__b64__" in data:
        import base64
        return base64.b64decode(data["__b64__"])
    origin = get_origin(cls)
    if origin in (typing.Union, types.UnionType):
        args = [a for a in get_args(cls) if a is not type(None)]
        if not args:
            return data
        return from_wire(args[0], data)
    if cls is Any or cls is None:
        return data
    if isinstance(cls, type) and issubclass(cls, enum.Enum):
        return cls(data)
    if dataclasses.is_dataclass(cls):
        hints = _HINTS_CACHE.get(cls)
        if hints is None:
            hints = get_type_hints(cls)
            _HINTS_CACHE[cls] = hints
        kwargs = {}
        names = {f.name for f in dataclasses.fields(cls)}
        for k, v in data.items():
            if k in names:
                kwargs[k] = from_wire(hints.get(k, Any), v)
        return cls(**kwargs)
    if cls in (list, tuple, set, frozenset):
        return cls(data)
    if cls is dict:
        return dict(data)
    if origin in (list, tuple, set, frozenset):
        args = get_args(cls)
        elem = args[0] if args else Any
        seq = [from_wire(elem, v) for v in data]
        if origin is list:
            return seq
        return origin(seq)
    if origin is dict:
        args = get_args(cls)
        vt = args[1] if len(args) == 2 else Any
        return {k: from_wire(vt, v) for k, v in data.items()}
    if cls is bytes:
        if isinstance(data, str):
            import base64
            return base64.b64decode(data)
        return bytes(data) if not isinstance(data, bytes) else data
    if cls in (int, float, str, bool):
        return cls(data) if data is not None else None
    return data
