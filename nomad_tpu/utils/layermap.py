"""A persistent map built from layered CPython dicts.

Same contract as utils/hamt.Hamt (the MVCC substrate contract the state
store needs: immutable values, O(1) snapshots, transient edit sessions),
but tuned for how CPython actually performs: plain dicts are C-speed for
get/set/iterate, so a copy-on-write *overlay* over an immutable base
dict beats a pure-Python trie by 1-3 orders of magnitude on the store's
real workloads (10k-alloc plan applies, 2M-row table scans — see
round-5 profile: Hamt.update of 10k pairs into a 2M-row trie costs
~150 ms and a full build ~13 s; the dict equivalents are ~0.1 ms and
~5 s).

Layout: `_base` (immutable-by-convention dict, structurally shared
between versions) + `_tip` (small overlay dict; deletions are
tombstones). Reads check tip then base. Writes produce a new LayerMap
sharing `_base`; inside one EditContext transaction the tip is mutated
in place (the transient trick — the tip is only reachable from the
unpublished root). When the tip outgrows `max(1024, len(base)/8)` it is
folded into a fresh base dict — O(n) amortized over at least n/8
writes.

Concurrency: published maps are frozen (no ctx), so tips of shared
instances are never mutated; `_materialize()` may swap `_base`/`_tip`
on a shared instance, but only to an equivalent mapping (merged base +
empty tip), which concurrent readers tolerate: they hold local refs to
the old dicts or see new-base+old-tip, whose overlay entries equal the
merged values.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional, Tuple

from .hamt import EditContext  # shared transaction-context type

_TOMB = object()     # deletion marker in the tip overlay
_SENTINEL = object()


class LayerMap:
    """Immutable hash map with the Hamt API. set/delete/update return
    new maps sharing structure; `with_ctx(ctx)` enables transient
    in-place tip writes for the duration of one store transaction."""

    __slots__ = ("_base", "_tip", "_size", "_ctx", "_own")

    def __init__(self, _base: Optional[dict] = None,
                 _tip: Optional[dict] = None, _size: int = 0,
                 _ctx: Optional[EditContext] = None,
                 _own: Optional[EditContext] = None):
        self._base = _base if _base is not None else {}
        self._tip = _tip if _tip is not None else {}
        self._size = _size
        self._ctx = _ctx
        self._own = _own        # ctx that may mutate _tip in place

    def with_ctx(self, ctx: Optional[EditContext]) -> "LayerMap":
        if ctx is self._ctx:
            return self
        # never inherit tip ownership: the tip may be shared
        return LayerMap(self._base, self._tip, self._size, ctx, None)

    def frozen(self) -> "LayerMap":
        if self._ctx is None and self._own is None:
            return self
        return LayerMap(self._base, self._tip, self._size, None, None)

    # -- reads ---------------------------------------------------------
    def __len__(self) -> int:
        return self._size

    def __contains__(self, key) -> bool:
        return self.get(key, _SENTINEL) is not _SENTINEL

    def __getitem__(self, key):
        v = self.get(key, _SENTINEL)
        if v is _SENTINEL:
            raise KeyError(key)
        return v

    def get(self, key, default=None):
        v = self._tip.get(key, _SENTINEL)
        if v is not _SENTINEL:
            return default if v is _TOMB else v
        return self._base.get(key, default)

    def _materialize(self) -> dict:
        """The effective mapping as ONE dict; folds the tip into a fresh
        base and caches it on this instance (safe: the merged mapping is
        equivalent, and tips of shared instances are never mutated)."""
        tip = self._tip
        if not tip:
            return self._base
        merged = dict(self._base)
        for k, v in tip.items():
            if v is _TOMB:
                merged.pop(k, None)
            else:
                merged[k] = v
        # swap order matters for racing readers: new base + old tip is
        # an equivalent mapping; old base + empty tip would not be
        self._base = merged
        self._tip = {}
        self._own = None
        return merged

    def items(self) -> Iterator[Tuple[Any, Any]]:
        return iter(self._materialize().items())

    def keys(self) -> Iterator[Any]:
        return iter(self._materialize().keys())

    def values(self) -> Iterator[Any]:
        return iter(self._materialize().values())

    def __iter__(self) -> Iterator[Any]:
        return self.keys()

    # -- governance accounting (governor/registry.py) -------------------
    def overlay_len(self) -> int:
        """Entries in the uncompacted tip overlay (live + tombstones) —
        the per-table "version debt" the governor bounds."""
        return len(self._tip)

    def layer_stats(self) -> dict:
        tip = self._tip
        return {"size": self._size, "base": len(self._base),
                "tip": len(tip),
                "tombs": sum(1 for v in tip.values() if v is _TOMB)}

    def fold(self) -> "LayerMap":
        """Compact the tip into the base (dropping tombstones). Safe on
        published shared instances — _materialize swaps in an
        equivalent mapping (see the concurrency note above). Called by
        the state store's governor-driven compaction so overlay debt
        can't accumulate between the automatic fold thresholds."""
        self._materialize()
        return self

    # -- writes --------------------------------------------------------
    def set(self, key, value) -> "LayerMap":
        ctx = self._ctx
        existed = self.get(key, _SENTINEL) is not _SENTINEL
        size = self._size + (0 if existed else 1)
        if ctx is not None and self._own is ctx:
            tip = self._tip
            if len(tip) > 1024 and len(tip) > (len(self._base) >> 3):
                self._materialize()
                tip = self._tip = {}
                self._own = ctx
            tip[key] = value
            self._size = size
            return self
        tip = dict(self._tip)
        tip[key] = value
        out = LayerMap(self._base, tip, size, ctx, ctx)
        if len(tip) > 1024 and len(tip) > (len(out._base) >> 3):
            out._materialize()
            out._own = ctx
        return out

    def delete(self, key) -> "LayerMap":
        if self.get(key, _SENTINEL) is _SENTINEL:
            return self
        ctx = self._ctx
        size = self._size - 1
        in_base = key in self._base
        if ctx is not None and self._own is ctx:
            if in_base:
                self._tip[key] = _TOMB
            else:
                self._tip.pop(key, None)
            self._size = size
            return self
        tip = dict(self._tip)
        if in_base:
            tip[key] = _TOMB
        else:
            tip.pop(key, None)
        return LayerMap(self._base, tip, size, ctx, ctx)

    def update(self, pairs) -> "LayerMap":
        items = pairs.items() if isinstance(pairs, dict) else pairs
        ctx = self._ctx
        if self._size == 0 and not self._tip:
            base = dict(items)
            return LayerMap(base, None, len(base), ctx, None)
        if ctx is not None and self._own is ctx:
            tip = self._tip
            size = self._size
            get = self.get
            for k, v in items:
                if get(k, _SENTINEL) is _SENTINEL:
                    size += 1
                tip[k] = v
            self._size = size
            if len(tip) > 1024 and len(tip) > (len(self._base) >> 3):
                self._materialize()
                self._own = ctx
            return self
        tip = dict(self._tip)
        size = self._size
        base_get = self._base.get
        tip_get = tip.get
        for k, v in items:
            # check the accumulating tip (covers the old tip AND keys
            # already inserted by this batch, so duplicate keys in
            # `pairs` don't double-count)
            prior = tip_get(k, _SENTINEL)
            if prior is _SENTINEL:
                if base_get(k, _SENTINEL) is _SENTINEL:
                    size += 1
            elif prior is _TOMB:
                size += 1
            tip[k] = v
        out = LayerMap(self._base, tip, size, ctx, ctx)
        if len(tip) > 1024 and len(tip) > (len(out._base) >> 3):
            out._materialize()
            out._own = ctx
        return out
