"""Minimal 5-field cron evaluator for periodic jobs.

Reference semantics: nomad/periodic.go uses gorhill/cronexpr to compute
`Next(fromTime)` for a PeriodicConfig spec (periodic.go Next / structs.go
PeriodicConfig.Next). This is a dependency-free equivalent supporting the
standard minute hour day-of-month month day-of-week fields with
`*`, lists, ranges, and `*/step`, plus the `@hourly/@daily/@weekly`
shorthands. Times are UTC (PeriodicConfig.timezone other than UTC is
rejected at validate time in round 1).
"""

from __future__ import annotations

import calendar
from datetime import datetime, timedelta, timezone
from typing import List, Sequence

_SHORTHAND = {
    "@minutely": "* * * * *",
    "@hourly": "0 * * * *",
    "@daily": "0 0 * * *",
    "@midnight": "0 0 * * *",
    "@weekly": "0 0 * * 0",
    "@monthly": "0 0 1 * *",
    "@yearly": "0 0 1 1 *",
    "@annually": "0 0 1 1 *",
}

_RANGES = ((0, 59), (0, 23), (1, 31), (1, 12), (0, 6))

_MONTH_NAMES = {name.lower(): i for i, name in
                enumerate(calendar.month_abbr) if name}
_DAY_NAMES = {name.lower(): (i + 1) % 7 for i, name in
              enumerate(calendar.day_abbr)}  # mon=1 .. sun=0


class CronParseError(ValueError):
    pass


def _parse_field(field: str, lo: int, hi: int, names: dict) -> List[int]:
    out = set()
    for part in field.split(","):
        step = 1
        if "/" in part:
            part, step_s = part.split("/", 1)
            try:
                step = int(step_s)
            except ValueError:
                raise CronParseError(f"bad step {step_s!r}")
            if step <= 0:
                raise CronParseError(f"bad step {step}")
        if part in ("*", ""):
            start, end = lo, hi
        elif "-" in part:
            a, b = part.split("-", 1)
            start, end = _num(a, names), _num(b, names)
        else:
            start = end = _num(part, names)
            if step > 1:
                end = hi
        if start < lo or end > hi or start > end:
            raise CronParseError(f"field {field!r} out of range [{lo},{hi}]")
        out.update(range(start, end + 1, step))
    return sorted(out)


def _num(tok: str, names: dict) -> int:
    t = tok.strip().lower()
    if t in names:
        return names[t]
    try:
        n = int(t)
    except ValueError:
        raise CronParseError(f"bad value {tok!r}")
    # cron allows 7 for sunday in day-of-week
    if names is _DAY_NAMES and n == 7:
        return 0
    return n


class Cron:
    def __init__(self, spec: str):
        spec = spec.strip()
        spec = _SHORTHAND.get(spec, spec)
        fields = spec.split()
        if len(fields) != 5:
            raise CronParseError(
                f"cron spec needs 5 fields, got {len(fields)}: {spec!r}")
        self.minutes = _parse_field(fields[0], 0, 59, {})
        self.hours = _parse_field(fields[1], 0, 23, {})
        self.doms = _parse_field(fields[2], 1, 31, {})
        self.months = _parse_field(fields[3], 1, 12, _MONTH_NAMES)
        self.dows = _parse_field(fields[4], 0, 6, _DAY_NAMES)
        self._dom_star = fields[2] == "*"
        self._dow_star = fields[4] == "*"

    def _day_match(self, dt: datetime) -> bool:
        dom_ok = dt.day in self.doms
        dow_ok = ((dt.weekday() + 1) % 7) in self.dows  # python mon=0
        # standard cron: if both dom and dow are restricted, match either
        if not self._dom_star and not self._dow_star:
            return dom_ok or dow_ok
        return dom_ok and dow_ok

    def next_after(self, after_unix: float) -> float:
        """Smallest fire time strictly greater than after_unix (UTC).
        Returns 0.0 if none within ~5 years (mirrors PeriodicConfig.Next
        returning the zero time on no-match)."""
        dt = datetime.fromtimestamp(int(after_unix), tz=timezone.utc)
        dt = dt.replace(second=0, microsecond=0) + timedelta(minutes=1)
        limit = dt + timedelta(days=5 * 366)
        while dt < limit:
            if dt.month not in self.months:
                # jump to the 1st of the next month
                y, m = dt.year, dt.month + 1
                if m > 12:
                    y, m = y + 1, 1
                dt = dt.replace(year=y, month=m, day=1, hour=0, minute=0)
                continue
            if not self._day_match(dt):
                dt = (dt + timedelta(days=1)).replace(hour=0, minute=0)
                continue
            if dt.hour not in self.hours:
                dt = (dt + timedelta(hours=1)).replace(minute=0)
                continue
            if dt.minute not in self.minutes:
                dt = dt + timedelta(minutes=1)
                continue
            return dt.timestamp()
        return 0.0


def next_launch(spec: str, after_unix: float) -> float:
    return Cron(spec).next_after(after_unix)
