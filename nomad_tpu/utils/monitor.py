"""Live log streaming support (reference: command/agent/monitor —
/v1/agent/monitor attaches a sink to the agent's logger and streams
records to the caller).
"""

from __future__ import annotations

import collections
import logging
import time
from typing import List, Optional, Tuple
from .locks import make_condition, make_lock

_LEVELS = {"trace": 5, "debug": logging.DEBUG, "info": logging.INFO,
           "warn": logging.WARNING, "error": logging.ERROR}


class MonitorBuffer(logging.Handler):
    """Ring buffer of formatted log records with blocking reads."""

    def __init__(self, capacity: int = 2048):
        super().__init__(level=logging.DEBUG)
        self.setFormatter(logging.Formatter(
            "%(asctime)s [%(levelname)s] %(name)s: %(message)s"))
        self._buf: collections.deque = collections.deque(maxlen=capacity)
        self._seq = 0
        self._cond = make_condition()

    def emit(self, record: logging.LogRecord) -> None:
        try:
            line = self.format(record)
        except Exception:       # pragma: no cover
            return
        with self._cond:
            self._seq += 1
            self._buf.append((self._seq, record.levelno, line))
            self._cond.notify_all()

    def read_since(self, seq: int, min_level: int,
                   timeout_s: float) -> Tuple[int, List[str]]:
        """Lines newer than seq at >= min_level; blocks up to timeout."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            while True:
                out = [(s, line) for s, lvl, line in self._buf
                       if s > seq and lvl >= min_level]
                if out:
                    return out[-1][0], [line for _s, line in out]
                last = self._buf[-1][0] if self._buf else seq
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return max(seq, last), []
                self._cond.wait(remaining)


_buffer: Optional[MonitorBuffer] = None
_lock = make_lock()


def get_buffer() -> MonitorBuffer:
    """Attach (once) to the package logger tree and return the buffer."""
    global _buffer
    with _lock:
        if _buffer is None:
            _buffer = MonitorBuffer()
            logging.getLogger("nomad_tpu").addHandler(_buffer)
            logging.getLogger("nomad_tpu").setLevel(logging.DEBUG)
        return _buffer


def parse_level(name: str) -> int:
    return _LEVELS.get((name or "info").lower(), logging.INFO)
