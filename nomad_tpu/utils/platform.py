"""JAX platform selection helpers.

The image's sitecustomize imports jax at interpreter startup with
JAX_PLATFORMS=axon already in the environment, so the platform default
is baked before any application code runs. Two consequences drive the
shape of these helpers:

  - env-var edits are too late, but ``jax.config.update`` works as long
    as no backend has been *initialized* yet (backends init lazily at
    first device use). After initialization the update is silently
    ignored (verified on jax 0.9.0).
  - an unusable accelerator backend may HANG on ``jax.devices()`` (a
    dead tunnel blocks >120s) rather than raise, so any probe of the
    ambient platform must happen in a subprocess with a timeout — never
    in the process that needs to survive the answer.

Shared by bench.py, __graft_entry__.py, the CLI agent, and the test
conftest (VERDICT round 1: items 1a/1b).
"""

from __future__ import annotations

import os
import re
import subprocess
import sys
from typing import Optional


def requested_cpu_devices(default: int = 1) -> int:
    """The virtual CPU device count the operator already configured via
    XLA_FLAGS (xla_force_host_platform_device_count=N). Callers that
    re-pin the platform defensively (the CLI agent) pass this instead
    of a literal 1 so they don't clobber a multi-device setup — the
    mesh-routed CPU agent (NOMAD_TPU_MESH=1) depends on it."""
    m = re.search(r"--xla_force_host_platform_device_count=(\d+)",
                  os.environ.get("XLA_FLAGS", ""))
    return int(m.group(1)) if m else default


def force_cpu_platform(n_devices: int = 1) -> None:
    """Point JAX at an n-device virtual CPU platform. Must run before the
    process initializes any backend; raises via assert_cpu_devices if you
    want verification."""
    flags = os.environ.get("XLA_FLAGS", "")
    new_flag = f"--xla_force_host_platform_device_count={n_devices}"
    if "xla_force_host_platform_device_count" in flags:
        # replace a stale count rather than keeping it (a smaller value
        # left in the env would win and break assert_cpu_devices)
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       new_flag, flags)
        os.environ["XLA_FLAGS"] = flags
    else:
        os.environ["XLA_FLAGS"] = (flags + " " + new_flag).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", n_devices)
    except Exception:
        pass  # older jax: the XLA_FLAGS fallback above covers it


def assert_cpu_devices(n_devices: int) -> None:
    """Verify force_cpu_platform took effect. It silently does not when a
    backend was already initialized in this process (e.g. something ran a
    computation on the ambient accelerator first) — fail loudly instead
    of quietly running on the wrong platform."""
    import jax

    devs = jax.devices()
    if not devs or devs[0].platform != "cpu" or len(devs) < n_devices:
        plat = devs[0].platform if devs else "none"
        raise RuntimeError(
            f"expected >= {n_devices} cpu devices but found {len(devs)} "
            f"{plat!r} devices — a JAX backend was already initialized "
            f"before force_cpu_platform(); call it first in a fresh "
            f"process")


_PROBE_CODE = (
    "import jax, jax.numpy as jnp\n"
    "jax.jit(lambda x: x + 1)(jnp.float32(1)).block_until_ready()\n"
    "print(jax.devices()[0].platform)\n"
)


def probe_accelerator(timeout_s: float = 120.0) -> Optional[str]:
    """Check the ambient JAX platform actually works by running a tiny
    jitted dispatch in a SUBPROCESS (first accelerator compile can take
    20-40s; a dead tunnel hangs, hence the timeout). Returns the platform
    name on success, None if the backend raised or hung."""
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE_CODE],
            timeout=timeout_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return None
    if out.returncode != 0:
        return None
    platform = out.stdout.strip().splitlines()[-1] if out.stdout.strip() else ""
    return platform or None
