"""Per-stage wall-clock accounting for the eval hot path.

BENCH_r05 showed a 13x gap between in-kernel placement rate (163.8k/s)
and end-to-end (12.3k/s) with no way to say WHERE the host time went —
the gap had to be inferred from side channels. This module gives every
stage of the pipeline a named accumulator:

    restore       cold start: snapshot load + store rebuild
                  (server/persistence.py restore_into — ISSUE 8)
    wal_replay    cold start: batched WAL tail replay into the FSM
    table_build   host-side NodeTable full builds + delta refreshes
    h2d           host->device transfers (uploads, scatters, arg ships)
    kernel        device dispatch through result availability
    d2h           device->host result transfers (device_get)
    reconcile     alloc-diff host phase: alloc fetch + tainted split +
                  AllocReconciler.compute + result staging (ISSUE 6:
                  this cost was previously invisible — it had to be
                  inferred as "the rest of the host share")
    gateway_wait  time an eval's kernel request spent parked in the
                  micro-batch gateway's dispatch window before its
                  batch fired (ISSUE 7: queue/coalescing wait was
                  invisible in the latency attribution; nests inside
                  sched_host like the device stages do)
    sched_host    one whole scheduler Process() call as seen by the
                  worker (reconcile + placement + plan build; overlaps
                  kernel/h2d/d2h by design — see the note below)
    plan_verify   plan verification against the freshest snapshot +
                  group overlay (the serialization point's read half)
    plan_commit   raft append/apply + quorum wait + store transaction
                  (the serialization point's write half)
    broker_ack    eval broker ack bookkeeping

r8 lumped verify, raft apply, and ack bookkeeping into one
`plan_apply` bucket; the group-commit applier splits it so the bench
artifact can show whether batched commit actually shrank the commit
half (one raft entry / store transaction / event flush per GROUP).

`bench.py` enables collection around a run and emits the snapshot in
the JSON artifact (`stage_breakdown`), so the kernel-vs-e2e gap is
attributable per round instead of inferred. Collection is off by
default: the hot path pays one module-global bool check per report
site when disabled.

The same stage can be reported from overlapping layers (a kernel
dispatch inside a plan-apply verify); accumulators are independent
sums, not a strict partition of wall clock — shares are computed over
the sum of stages, and the interesting signal is the RATIO moving
between rounds, not the absolute seconds.
"""

from __future__ import annotations

import threading
from typing import Dict

STAGES = ("restore", "wal_replay", "table_build", "h2d", "kernel",
          "d2h", "reconcile", "gateway_wait", "sched_host",
          "plan_verify", "plan_commit", "broker_ack")

# superset accumulators: wholly contain other stages' time (sched_host
# wraps reconcile + table_build + h2d + kernel + d2h per dispatch), so
# they are EXCLUDED from the share denominator — otherwise adding one
# would halve every other stage's share and break the cross-round
# share comparisons the bench artifacts exist for. Their own `share`
# is still reported relative to that same denominator (it can
# legitimately exceed other stages' combined share).
SHARE_SUPERSETS = frozenset({"sched_host"})

enabled = False

_l = threading.Lock()
_acc: Dict[str, list] = {s: [0.0, 0] for s in STAGES}


def enable(reset: bool = True) -> None:
    global enabled
    with _l:
        if reset:
            for v in _acc.values():
                v[0] = 0.0
                v[1] = 0
        enabled = True


def disable() -> None:
    global enabled
    enabled = False


def add(stage: str, seconds: float) -> None:
    """Report `seconds` of wall clock spent in `stage`. Callers guard
    with `if stages.enabled:` so the disabled cost is one bool read."""
    with _l:
        ent = _acc.get(stage)
        if ent is None:                 # unknown stage: count it anyway
            ent = _acc.setdefault(stage, [0.0, 0])
        ent[0] += seconds
        ent[1] += 1


def snapshot() -> Dict[str, dict]:
    """{stage: {seconds, calls, share}} over all stages reported since
    enable(). `share` is each stage's fraction of the summed stage
    time — the attribution number the bench artifact records."""
    with _l:
        total = sum(v[0] for s, v in _acc.items()
                    if s not in SHARE_SUPERSETS)
        return {
            s: {"seconds": round(v[0], 4), "calls": v[1],
                "share": round(v[0] / total, 4) if total > 0 else 0.0}
            for s, v in _acc.items() if v[1] > 0 or s in STAGES
        }
