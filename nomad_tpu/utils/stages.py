"""Per-stage wall-clock accounting for the eval hot path.

BENCH_r05 showed a 13x gap between in-kernel placement rate (163.8k/s)
and end-to-end (12.3k/s) with no way to say WHERE the host time went —
the gap had to be inferred from side channels. This module gives every
stage of the pipeline a named accumulator:

    restore       cold start: snapshot load + store rebuild
                  (server/persistence.py restore_into — ISSUE 8)
    wal_replay    cold start: batched WAL tail replay into the FSM
    table_build   host-side NodeTable full builds + delta refreshes
    h2d           host->device transfers (uploads, scatters, arg ships)
    kernel        device dispatch through result availability
    d2h           device->host result transfers (device_get)
    reconcile     alloc-diff host phase: alloc fetch + tainted split +
                  AllocReconciler.compute + result staging (ISSUE 6:
                  this cost was previously invisible — it had to be
                  inferred as "the rest of the host share")
    preempt       victim selection across candidate nodes: the memo
                  sweep + batched columnar matrix pass (or per-node
                  reference Preemptor runs) behind the kernel's
                  pre_score/freed columns and the no-fit fallback
                  (ISSUE 10: BENCH_r05's worst number — 354
                  placements/s — was this phase, previously lumped
                  into sched_host; reported from
                  scheduler/preemption.py _evaluate_pending with
                  nodes-scanned / victim-count attrs for the flight
                  recorder)
    queue_wait    time the eval sat in the broker's READY queue before
                  a worker dequeued it (ISSUE 9: the enqueue->dequeue
                  leg of the flight recorder's span tree; idle time,
                  not attributable work — see SHARE_EXCLUDED)
    gateway_wait  time an eval's kernel request spent parked in the
                  micro-batch gateway's dispatch window before its
                  batch fired (ISSUE 7: queue/coalescing wait was
                  invisible in the latency attribution; nests inside
                  sched_host like the device stages do)
    sched_host    one whole scheduler Process() call as seen by the
                  worker (reconcile + placement + plan build; overlaps
                  kernel/h2d/d2h by design — see the note below)
    plan_verify   plan verification against the freshest snapshot +
                  group overlay (the serialization point's read half)
    plan_commit   raft append/apply + quorum wait + store transaction
                  (the serialization point's write half)
    broker_ack    eval broker ack bookkeeping

r8 lumped verify, raft apply, and ack bookkeeping into one
`plan_apply` bucket; the group-commit applier splits it so the bench
artifact can show whether batched commit actually shrank the commit
half (one raft entry / store transaction / event flush per GROUP).

`bench.py` enables collection around a run and emits the snapshot in
the JSON artifact (`stage_breakdown`), so the kernel-vs-e2e gap is
attributable per round instead of inferred.

The eval flight recorder (nomad_tpu/trace/, ISSUE 9) taps the same
report sites: every add() forwards (stage, seconds, attrs) through the
registered trace hook, which feeds the per-stage percentile reservoirs
and — for stages reported on the eval's own thread — emits a span onto
the thread-local current trace. The aggregate sums are untouched.
`enabled` is therefore True whenever EITHER consumer wants reports
(accumulation via enable()/disable(), tracing via set_trace_hook);
with both off the hot path pays one module-global bool check per
report site, exactly as before.

The same stage can be reported from overlapping layers (a kernel
dispatch inside a plan-apply verify); accumulators are independent
sums, not a strict partition of wall clock — shares are computed over
the sum of stages, and the interesting signal is the RATIO moving
between rounds, not the absolute seconds.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional
from .locks import make_lock

STAGES = ("restore", "wal_replay", "table_build", "feasibility", "h2d",
          "kernel", "d2h", "reconcile", "preempt", "queue_wait",
          "fence_wait", "gateway_wait", "sched_host", "plan_verify",
          "plan_commit", "broker_ack")

# superset accumulators: wholly contain other stages' time (sched_host
# wraps reconcile + table_build + h2d + kernel + d2h per dispatch), so
# they are EXCLUDED from the share denominator — otherwise adding one
# would halve every other stage's share and break the cross-round
# share comparisons the bench artifacts exist for. Their own `share`
# is still reported relative to that same denominator (it can
# legitimately exceed other stages' combined share).
SHARE_SUPERSETS = frozenset({"sched_host"})

# queue_wait is dead time on the broker heap, not attributable work: a
# paused-worker burst would let it dwarf every real stage and wreck
# the cross-round share ratios, so it too stays out of the denominator
# (its own share is still reported against it, like the supersets).
# fence_wait (ISSUE 16) is the same kind of dead time — replication
# lag observed at the snapshot fence, ~0 on a leader and bounded by
# follower_fence_timeout_s on a lagging follower
SHARE_EXCLUDED = SHARE_SUPERSETS | frozenset({"queue_wait",
                                              "fence_wait"})

# cold-start stages dilute steady-state shares when a run cold-boots
# mid-round (ISSUE 9 satellite): snapshot() reports `steady_share`
# over a denominator that excludes them, so cross-round ratio
# comparisons survive a cold boot in the same run. The cold stages'
# own steady_share is 0.0 by definition.
COLD_STAGES = frozenset({"restore", "wal_replay"})

enabled = False

_l = make_lock()
_acc: Dict[str, list] = {s: [0.0, 0] for s in STAGES}

# the flight recorder's tap (nomad_tpu/trace/ installs it at import):
# called as hook(stage, seconds, attrs) AFTER the accumulator update
_collecting = False
_trace_hook: Optional[Callable] = None
_trace_on = False


def set_trace_hook(hook: Optional[Callable], on: bool = True) -> None:
    """Register (or disarm) the flight recorder's report tap. Arms the
    module-global `enabled` flag so the `if stages.enabled:` guards at
    every report site fire for the tracer even while bench
    accumulation is off."""
    global _trace_hook, _trace_on, enabled
    _trace_hook = hook
    _trace_on = bool(on and hook is not None)
    enabled = _collecting or _trace_on


def enable(reset: bool = True) -> None:
    global _collecting, enabled
    with _l:
        if reset:
            for v in _acc.values():
                v[0] = 0.0
                v[1] = 0
        _collecting = True
        enabled = True


def disable() -> None:
    global _collecting, enabled
    _collecting = False
    enabled = _collecting or _trace_on


def add(stage: str, seconds: float,
        attrs: Optional[dict] = None) -> None:
    """Report `seconds` of wall clock spent in `stage`. Callers guard
    with `if stages.enabled:` so the disabled cost is one bool read.
    `attrs` ride through to the flight recorder's span (never into the
    aggregate sums)."""
    if _collecting:
        with _l:
            ent = _acc.get(stage)
            if ent is None:             # unknown stage: count it anyway
                ent = _acc.setdefault(stage, [0.0, 0])
            ent[0] += seconds
            ent[1] += 1
    hook = _trace_hook
    if _trace_on and hook is not None:
        try:
            hook(stage, seconds, attrs)
        except Exception:       # pragma: no cover — defensive
            pass


def snapshot() -> Dict[str, dict]:
    """{stage: {seconds, calls, share, steady_share}} over all stages
    reported since enable(). `share` is each stage's fraction of the
    summed stage time — the attribution number the bench artifact
    records; `steady_share` excludes the cold-start stages from the
    denominator (and reports 0.0 for them) so steady-state ratios
    compare across rounds regardless of whether a round cold-booted."""
    with _l:
        total = sum(v[0] for s, v in _acc.items()
                    if s not in SHARE_EXCLUDED)
        steady = sum(v[0] for s, v in _acc.items()
                     if s not in SHARE_EXCLUDED and s not in COLD_STAGES)
        return {
            s: {"seconds": round(v[0], 4), "calls": v[1],
                "share": round(v[0] / total, 4) if total > 0 else 0.0,
                "steady_share": (
                    0.0 if s in COLD_STAGES or steady <= 0
                    else round(v[0] / steady, 4))}
            for s, v in _acc.items() if v[1] > 0 or s in STAGES
        }
