"""In-memory telemetry registry (reference: armon/go-metrics as wired
by command/agent/command.go setupTelemetry — counters, gauges, and
timer samples with aggregate statistics, served by /v1/metrics in the
InmemSink's shape, plus PrometheusSink-style text exposition behind
/v1/metrics?format=prometheus).

ISSUE 11 parity fixes vs the pre-r15 registry:

* `Timestamp` is interval-ANCHORED, not call time: the reference's
  InmemSink aggregates into fixed intervals (DefaultInmemInterval) and
  DisplayMetrics returns the interval's boundary timestamp, so two
  scrapes inside one interval agree on the window they describe.
* Empty-sample `Min` is explicit: `_Sample.min` is None until the
  first ingest and the display layer states the no-samples case,
  instead of carrying a float('inf') sentinel that snapshot() had to
  special-case (and that would leak as literal Infinity through any
  other reader of the raw sample).
* Timer samples additionally feed fixed-bucket HISTOGRAMS (the
  go-metrics PrometheusSink analog) whose consumer is the Prometheus
  exposition: cumulative `<name>_bucket{le="..."}` rows a scraper
  aggregates across instances. `Histogram.quantile()` documents the
  exposition's resolution contract — the same linear interpolation
  `histogram_quantile()` applies server-side, pinned against numpy
  percentiles in tests/test_telemetry.py.
"""

from __future__ import annotations

import re
import time
from typing import Dict, List, Optional, Tuple
from .locks import make_lock

# the InmemSink aggregation interval (go-metrics DefaultInmemInterval
# is 10s; command.go passes 10s): Timestamp anchors to multiples of it
INTERVAL_S = 10.0

# histogram bucket upper bounds in MILLISECONDS (timer samples are
# ms): roughly log-spaced from sub-ms dispatches to multi-second
# compile walls, + the implicit +Inf bucket. Chosen once, process-wide
# — Prometheus histograms only aggregate across scrapes/instances when
# the bounds agree.
HIST_BUCKETS_MS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0,
    250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)


class _Sample:
    __slots__ = ("count", "sum", "min", "max", "last")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        # None until the first ingest: "no samples yet" is a distinct
        # state the display layer reports explicitly, not an inf
        # sentinel for snapshot() to special-case
        self.min: Optional[float] = None
        self.max = 0.0
        self.last = 0.0

    def add(self, v: float) -> None:
        self.count += 1
        self.sum += v
        self.min = v if self.min is None else min(self.min, v)
        self.max = max(self.max, v)
        self.last = v


class Histogram:
    """Fixed-bucket histogram over timer samples (ms). Buckets hold
    NON-cumulative counts internally; the exposition and quantile
    reads cumulate. Bounded by construction: len(bounds)+1 ints."""

    __slots__ = ("bounds", "counts", "count", "sum")

    def __init__(self, bounds: Tuple[float, ...] = HIST_BUCKETS_MS):
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)   # +1: the +Inf bucket
        self.count = 0
        self.sum = 0.0

    def add(self, v: float) -> None:
        # linear scan beats bisect at 16 buckets for the common small
        # values, and this is the hot-path cost of histogram support
        for i, b in enumerate(self.bounds):
            if v <= b:
                self.counts[i] += 1
                break
        else:
            self.counts[len(self.bounds)] += 1
        self.count += 1
        self.sum += v

    def cumulative(self) -> List[int]:
        out, acc = [], 0
        for c in self.counts:
            acc += c
            out.append(acc)
        return out

    def quantile(self, q: float) -> float:
        """Prometheus histogram_quantile math: find the bucket holding
        rank q*count, linearly interpolate inside it (bucket start ->
        bound). The +Inf bucket reports the largest finite bound, as
        histogram_quantile does."""
        if self.count == 0:
            return 0.0
        q = min(max(q, 0.0), 1.0)
        rank = q * self.count
        acc = 0
        for i, c in enumerate(self.counts):
            prev_acc = acc
            acc += c
            if acc >= rank and c > 0:
                if i >= len(self.bounds):
                    return self.bounds[-1]
                lo = self.bounds[i - 1] if i > 0 else 0.0
                hi = self.bounds[i]
                return lo + (hi - lo) * ((rank - prev_acc) / c)
        return self.bounds[-1]

    def as_dict(self) -> dict:
        return {"bounds": list(self.bounds),
                "counts": list(self.counts),
                "count": self.count, "sum": self.sum}


def _interval_anchor(now: Optional[float] = None) -> float:
    """The current interval's START boundary (epoch seconds): the
    reference InmemSink keys aggregates by interval and reports the
    boundary, so a scrape's Timestamp names the window, not the call."""
    now = time.time() if now is None else now
    return (now // INTERVAL_S) * INTERVAL_S


_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def prom_name(name: str) -> str:
    """`nomad.worker.invoke_scheduler.service` ->
    `nomad_worker_invoke_scheduler_service` (exposition charset)."""
    out = _PROM_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


class MetricsRegistry:
    def __init__(self):
        self._l = make_lock()
        self._gauges: Dict[str, float] = {}
        self._counters: Dict[str, _Sample] = {}
        self._samples: Dict[str, _Sample] = {}
        self._hists: Dict[str, Histogram] = {}

    def set_gauge(self, name: str, value: float) -> None:
        with self._l:
            self._gauges[name] = float(value)

    def incr_counter(self, name: str, n: float = 1.0) -> None:
        with self._l:
            self._counters.setdefault(name, _Sample()).add(n)

    def add_sample_ms(self, name: str, ms: float) -> None:
        with self._l:
            self._samples.setdefault(name, _Sample()).add(ms)
            self._hists.setdefault(name, Histogram()).add(ms)

    def measure_since(self, name: str, start_monotonic: float) -> None:
        """go-metrics MeasureSince: record elapsed milliseconds."""
        self.add_sample_ms(name, (time.monotonic() - start_monotonic)
                           * 1000.0)

    # -- raw reads (telemetry collector + tests) -----------------------
    def counter_totals(self) -> Dict[str, float]:
        """{name: cumulative sum} for every counter — the telemetry
        collector samples these per slot and derives rates from slot
        deltas."""
        with self._l:
            return {k: s.sum for k, s in self._counters.items()}

    def histogram(self, name: str) -> Optional[Histogram]:
        with self._l:
            return self._hists.get(name)

    def snapshot(self) -> dict:
        """The /v1/metrics InmemSink display shape."""
        with self._l:
            def agg(d):
                return [{"Name": k, "Count": s.count, "Sum": s.sum,
                         # explicit empty-sample contract: a sample set
                         # with no ingests reports Min 0.0 BECAUSE it
                         # is empty (Count 0 says so), never an inf
                         # sentinel escaping the aggregate
                         "Min": (s.min if s.min is not None else 0.0),
                         "Max": s.max,
                         "Mean": (s.sum / s.count) if s.count else 0.0}
                        for k, s in sorted(d.items())]
            return {
                # interval-anchored (reference InmemSink parity): two
                # scrapes in the same interval carry the same stamp
                "Timestamp": time.strftime(
                    "%Y-%m-%d %H:%M:%S +0000",
                    time.gmtime(_interval_anchor())),
                "Gauges": [{"Name": k, "Value": v}
                           for k, v in sorted(self._gauges.items())],
                "Counters": agg(self._counters),
                "Samples": agg(self._samples),
            }

    def prometheus(self) -> str:
        """Prometheus text exposition (format version 0.0.4): gauges as
        `gauge`, counters as `<name>_total` `counter`, timer samples as
        full `histogram` families (buckets + _sum + _count). Served at
        /v1/metrics?format=prometheus; one scrape body, text/plain."""
        with self._l:
            gauges = sorted(self._gauges.items())
            counters = sorted((k, s.sum) for k, s in
                              self._counters.items())
            # copy histogram state BY VALUE under the lock: reading
            # cumulative()/sum/count off live objects after release
            # could tear (a sample landing between the bucket read and
            # the count read makes +Inf != _count, which breaks the
            # Prometheus histogram invariant on that scrape)
            hists = sorted(
                (k, (h.bounds, h.cumulative(), h.sum, h.count))
                for k, h in self._hists.items())
        lines: List[str] = []
        for name, value in gauges:
            pn = prom_name(name)
            lines.append(f"# TYPE {pn} gauge")
            lines.append(f"{pn} {value:.10g}")
        for name, total in counters:
            pn = prom_name(name) + "_total"
            lines.append(f"# TYPE {pn} counter")
            lines.append(f"{pn} {total:.10g}")
        for name, (bounds, cum, hsum, hcount) in hists:
            pn = prom_name(name)
            lines.append(f"# TYPE {pn} histogram")
            for bound, c in zip(bounds, cum):
                lines.append(f'{pn}_bucket{{le="{bound:.10g}"}} {c}')
            lines.append(f'{pn}_bucket{{le="+Inf"}} {cum[-1]}')
            lines.append(f"{pn}_sum {hsum:.10g}")
            lines.append(f"{pn}_count {hcount}")
        return "\n".join(lines) + "\n"


GLOBAL = MetricsRegistry()


def set_gauge(name: str, value: float) -> None:
    GLOBAL.set_gauge(name, value)


def incr_counter(name: str, n: float = 1.0) -> None:
    GLOBAL.incr_counter(name, n)


def measure_since(name: str, start_monotonic: float) -> None:
    GLOBAL.measure_since(name, start_monotonic)


def snapshot() -> dict:
    return GLOBAL.snapshot()


def prometheus() -> str:
    return GLOBAL.prometheus()


def counter_totals() -> Dict[str, float]:
    return GLOBAL.counter_totals()
