"""In-memory telemetry registry (reference: armon/go-metrics as wired
by command/agent/command.go setupTelemetry — counters, gauges, and
timer samples with aggregate statistics, served by /v1/metrics in the
InmemSink's shape).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional


class _Sample:
    __slots__ = ("count", "sum", "min", "max", "last")

    def __init__(self):
        self.count = 0
        self.sum = 0.0
        self.min = float("inf")
        self.max = 0.0
        self.last = 0.0

    def add(self, v: float) -> None:
        self.count += 1
        self.sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        self.last = v


class MetricsRegistry:
    def __init__(self):
        self._l = threading.Lock()
        self._gauges: Dict[str, float] = {}
        self._counters: Dict[str, _Sample] = {}
        self._samples: Dict[str, _Sample] = {}

    def set_gauge(self, name: str, value: float) -> None:
        with self._l:
            self._gauges[name] = float(value)

    def incr_counter(self, name: str, n: float = 1.0) -> None:
        with self._l:
            self._counters.setdefault(name, _Sample()).add(n)

    def add_sample_ms(self, name: str, ms: float) -> None:
        with self._l:
            self._samples.setdefault(name, _Sample()).add(ms)

    def measure_since(self, name: str, start_monotonic: float) -> None:
        """go-metrics MeasureSince: record elapsed milliseconds."""
        self.add_sample_ms(name, (time.monotonic() - start_monotonic)
                           * 1000.0)

    def snapshot(self) -> dict:
        """The /v1/metrics InmemSink display shape."""
        with self._l:
            def agg(d):
                return [{"Name": k, "Count": s.count, "Sum": s.sum,
                         "Min": (0.0 if s.count == 0 else s.min),
                         "Max": s.max,
                         "Mean": (s.sum / s.count) if s.count else 0.0}
                        for k, s in sorted(d.items())]
            return {
                "Timestamp": time.strftime("%Y-%m-%d %H:%M:%S +0000",
                                           time.gmtime()),
                "Gauges": [{"Name": k, "Value": v}
                           for k, v in sorted(self._gauges.items())],
                "Counters": agg(self._counters),
                "Samples": agg(self._samples),
            }


GLOBAL = MetricsRegistry()


def set_gauge(name: str, value: float) -> None:
    GLOBAL.set_gauge(name, value)


def incr_counter(name: str, n: float = 1.0) -> None:
    GLOBAL.incr_counter(name, n)


def measure_since(name: str, start_monotonic: float) -> None:
    GLOBAL.measure_since(name, start_monotonic)


def snapshot() -> dict:
    return GLOBAL.snapshot()
