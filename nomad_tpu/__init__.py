"""nomad_tpu: a TPU-native distributed workload orchestrator.

A ground-up rebuild of the capabilities of HashiCorp Nomad (reference:
/root/reference, pure Go) with the per-evaluation scheduler ranking
pipeline re-expressed as batched JAX/XLA tensor kernels and the control
plane designed for a TPU-resident node/alloc table.

Package layout:
  models/    -- the domain model (Job/TaskGroup/Task/Node/Alloc/Eval/Plan),
                mirroring the semantics of nomad/structs/structs.go
  state/     -- MVCC in-memory state store with snapshots and watches
                (go-memdb equivalent, persistent HAMT based)
  ops/       -- the JAX kernels: feasibility masks, bin-pack scoring,
                spread/affinity/anti-affinity, preemption, argmax select
  scheduler/ -- host-side schedulers (generic/system/core), reconciler,
                device-backed placement stack, factory registry, harness
  server/    -- eval broker, blocked evals, plan queue, plan applier,
                worker, leader duties
  client/    -- node agent: fingerprint, heartbeat, alloc/task runners,
                drivers (mock, exec)
  parallel/  -- mesh/sharding for the node axis (pjit/shard_map), ICI/DCN
                collective layout
  api/, cli/ -- north-bound HTTP API + command line
  jobspec/   -- jobspec parsing (JSON + HCL-subset)
  mock/      -- test fixtures (nomad/mock equivalent)
"""

__version__ = "0.1.0"
