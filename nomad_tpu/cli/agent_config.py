"""Agent configuration files (reference: command/agent/config.go —
HCL config files merged under CLI flags; flags win).

Shape:

    data_dir   = "/var/lib/nomad-tpu"
    datacenter = "dc1"
    ports { http = 4646  rpc = 4647 }
    server {
      enabled        = true
      num_schedulers = 4
      acl_enabled    = true
      server_peers   = ["10.0.0.1:4647", "10.0.0.2:4647"]
    }
    client {
      enabled   = true
      servers   = ["10.0.0.1:4647"]
      node_name = "worker-1"
      alloc_dir = "/var/lib/nomad-tpu/allocs"
      state_dir = "/var/lib/nomad-tpu/client"
      meta { rack = "r1" }
    }
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional


@dataclass
class AgentFileConfig:
    data_dir: str = ""
    datacenter: str = ""
    region: str = ""
    region_peers: dict = field(default_factory=dict)
    http_port: Optional[int] = None
    rpc_port: Optional[int] = None
    server_enabled: bool = False
    client_enabled: bool = False
    num_schedulers: Optional[int] = None
    acl_enabled: Optional[bool] = None
    server_peers: List[str] = field(default_factory=list)
    authoritative_region: str = ""
    replication_token: str = ""
    servers: List[str] = field(default_factory=list)
    node_name: str = ""
    alloc_dir: str = ""
    state_dir: str = ""
    meta: dict = field(default_factory=dict)
    cloud_fingerprint: Optional[bool] = None


def load_agent_config(path: str) -> AgentFileConfig:
    from ..jobspec.hcl import parse_hcl
    with open(path) as f:
        data = parse_hcl(f.read())
    cfg = AgentFileConfig()
    cfg.data_dir = data.get("data_dir", "")
    cfg.datacenter = data.get("datacenter", "")
    cfg.region = data.get("region", "")
    # federation peers (the reference discovers these via WAN serf;
    # here they're configured): region_peers { west = "host:4646" }
    peers = data.get("region_peers") or {}
    if isinstance(peers, list):
        peers = peers[0]
    cfg.region_peers = {str(k): str(v) for k, v in peers.items()}
    ports = data.get("ports") or {}
    if isinstance(ports, list):
        ports = ports[0]
    if "http" in ports:
        cfg.http_port = int(ports["http"])
    if "rpc" in ports:
        cfg.rpc_port = int(ports["rpc"])
    srv = data.get("server") or {}
    if isinstance(srv, list):
        srv = srv[0]
    if srv:
        cfg.server_enabled = bool(srv.get("enabled", True))
        if "num_schedulers" in srv:
            cfg.num_schedulers = int(srv["num_schedulers"])
        if "acl_enabled" in srv:
            cfg.acl_enabled = bool(srv["acl_enabled"])
        cfg.server_peers = list(srv.get("server_peers", []))
        cfg.authoritative_region = srv.get("authoritative_region", "")
        cfg.replication_token = srv.get("replication_token", "")
    cli = data.get("client") or {}
    if isinstance(cli, list):
        cli = cli[0]
    if cli:
        cfg.client_enabled = bool(cli.get("enabled", True))
        cfg.servers = list(cli.get("servers", []))
        cfg.node_name = cli.get("node_name", "")
        cfg.alloc_dir = cli.get("alloc_dir", "")
        cfg.state_dir = cli.get("state_dir", "")
        cfg.meta = dict(cli.get("meta", {}))
        if "cloud_fingerprint" in cli:
            cfg.cloud_fingerprint = bool(cli["cloud_fingerprint"])
    return cfg


def apply_to_args(cfg: AgentFileConfig, args) -> None:
    """File values fill in; explicit CLI flags win (config.go Merge —
    argparse defaults are recognizable, so only defaults get
    overridden)."""
    if cfg.server_enabled and not (args.dev or args.server):
        args.server = True
    if cfg.client_enabled and not (args.dev or args.client):
        args.client = True
    if cfg.http_port is not None and args.http_port == 4646:
        args.http_port = cfg.http_port
    if cfg.rpc_port is not None and args.rpc_port == 4647:
        args.rpc_port = cfg.rpc_port
    if cfg.num_schedulers is not None and args.num_schedulers == 2:
        args.num_schedulers = cfg.num_schedulers
    if cfg.acl_enabled is not None and not args.acl_enabled:
        args.acl_enabled = cfg.acl_enabled
    if cfg.server_peers and not args.server_peers:
        args.server_peers = ",".join(cfg.server_peers)
    if cfg.servers and not args.servers:
        args.servers = ",".join(cfg.servers)
    if cfg.node_name and not args.node_name:
        args.node_name = cfg.node_name
    if cfg.alloc_dir and not args.alloc_dir_base:
        args.alloc_dir_base = cfg.alloc_dir
    if cfg.state_dir and not getattr(args, "state_dir", ""):
        args.state_dir = cfg.state_dir
    if cfg.data_dir and not getattr(args, "data_dir", ""):
        args.data_dir = cfg.data_dir
    if cfg.datacenter and not getattr(args, "datacenter", ""):
        args.datacenter = cfg.datacenter
    if cfg.region and not getattr(args, "region", ""):
        args.region = cfg.region
    if cfg.region_peers and not getattr(args, "region_peers", None):
        args.region_peers = [f"{k}={v}" for k, v in
                             cfg.region_peers.items()]
    if cfg.authoritative_region and \
            not getattr(args, "authoritative_region", ""):
        args.authoritative_region = cfg.authoritative_region
    if cfg.replication_token and \
            not getattr(args, "replication_token", ""):
        args.replication_token = cfg.replication_token
    if cfg.meta:
        args.client_meta = cfg.meta
    if cfg.cloud_fingerprint is not None and \
            not getattr(args, "cloud_fingerprint", False):
        args.cloud_fingerprint = cfg.cloud_fingerprint
