"""The command line interface.

Reference semantics: command/ (~170 commands via mitchellh/cli; the core
operator surface is implemented here: agent, job run/status/stop/init,
node status/eligibility/drain, alloc status, eval status, server info).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import time
from typing import List, Optional

from ..api.client import ApiClient, ApiError
from ..utils.ids import short_id


def _client(args) -> ApiClient:
    return ApiClient(args.address, token=getattr(args, "token", ""),
                     region=getattr(args, "region", "") or "")


def _print_rows(rows: List[List[str]], header: List[str]) -> None:
    table = [header] + rows
    widths = [max(len(str(r[i])) for r in table) for i in range(len(header))]
    for r in table:
        print("  ".join(str(c).ljust(w) for c, w in zip(r, widths)).rstrip())


# -- agent -------------------------------------------------------------
def parse_region_peers(specs) -> dict:
    """-region-peer west=10.0.0.5:4646 (repeatable) -> {name: addr}."""
    peers = {}
    for spec in specs:
        name, _, addr = spec.partition("=")
        if not name or not addr:
            raise ValueError(
                f"bad -region-peer {spec!r} (want name=host:port)")
        peers[name] = addr
    return peers


def cmd_agent(args) -> int:
    from ..client import Client, ClientConfig

    if args.config:
        from .agent_config import apply_to_args, load_agent_config
        try:
            apply_to_args(load_agent_config(args.config), args)
        except (OSError, ValueError) as e:
            print(f"Error loading config {args.config}: {e}",
                  file=sys.stderr)
            return 1

    is_server = args.dev or args.server
    is_client = args.dev or args.client
    if not is_server and not is_client:
        print("specify -dev, -server and/or -client", file=sys.stderr)
        return 1
    if is_client and not is_server and not args.servers:
        print("-client requires -servers host:port", file=sys.stderr)
        return 1

    server = None
    rpc = None
    api = None
    clients = []

    if is_server:
        from ..server import Server, ServerConfig
        from ..api import HTTPApiServer
        from ..rpc import RpcServer
        # The scheduler kernels need a working JAX backend. A dead TPU
        # tunnel can hang (not raise) on first device use, so probe it
        # in a subprocess with a timeout and fall back to CPU so the
        # agent still serves. NOTE: JAX_PLATFORMS=cpu in the env is NOT
        # sufficient — the image's sitecustomize registers the
        # accelerator plugin at interpreter startup, so the in-process
        # config update in force_cpu_platform is required
        # (utils/platform.py).
        from ..utils.platform import (force_cpu_platform,
                                      probe_accelerator,
                                      requested_cpu_devices)
        if os.environ.get("JAX_PLATFORMS", "") == "cpu":
            # keep an operator-configured virtual device count (the
            # mesh-routed CPU agent sets 8 via XLA_FLAGS) instead of
            # clobbering it to 1
            force_cpu_platform(requested_cpu_devices())
        elif probe_accelerator(timeout_s=60.0) is None:
            force_cpu_platform(1)
            print("    WARNING: TPU backend unavailable; scheduling on CPU")
        try:
            region_peers = parse_region_peers(
                getattr(args, "region_peers", None) or [])
        except ValueError as e:
            print(f"Error: {e}", file=sys.stderr)
            return 1
        server = Server(ServerConfig(num_schedulers=args.num_schedulers,
                                     acl_enabled=args.acl_enabled,
                                     gc_safepoints=True,
                                     region=getattr(args, "region", "")
                                     or "global",
                                     region_peers=region_peers,
                                     authoritative_region=getattr(
                                         args, "authoritative_region",
                                         "") or "",
                                     replication_token=getattr(
                                         args, "replication_token",
                                         "") or "",
                                     data_dir=getattr(args, "data_dir",
                                                      "")))
        rpc = RpcServer(server, port=args.rpc_port)
        server.rpc_server = rpc
        if args.server_peers:
            peers = [p.strip() for p in args.server_peers.split(",")
                     if p.strip()]
            server.attach_raft(rpc, peers)
        server.start()
        rpc.start()
        # region_peers defaults from server.config inside HTTPApiServer
        api = HTTPApiServer(server, port=args.http_port,
                            alloc_dir_bases=[args.alloc_dir_base]
                            if args.alloc_dir_base else None)
        api.start()

    n_local_clients = args.clients if is_client else 0
    client_kw = dict(
        alloc_dir=args.alloc_dir_base,
        state_dir=getattr(args, "state_dir", None) or None,
        datacenter=getattr(args, "datacenter", "") or "dc1",
        meta=getattr(args, "client_meta", None) or {},
        cloud_fingerprint=getattr(args, "cloud_fingerprint", False))
    for i in range(n_local_clients):
        if server is not None:
            c = Client(server, ClientConfig(
                node_name=args.node_name or f"dev-client-{i}",
                **client_kw))
        else:
            from ..rpc import RemoteTransport
            c = Client(RemoteTransport(args.servers),
                       ClientConfig(node_name=args.node_name or
                                    f"client-{i}",
                                    **client_kw))
        c.start()
        clients.append(c)

    mode = "dev" if args.dev else \
        "+".join(m for m, on in (("server", is_server),
                                 ("client", is_client)) if on)
    print(f"==> nomad-tpu agent started ({mode} mode)")
    if api is not None:
        print(f"    HTTP API: http://127.0.0.1:{api.port}")
    if rpc is not None:
        print(f"    RPC:      {rpc.addr}")
    if clients:
        print(f"    Nodes:    {len(clients)}")
    if server is not None:
        print(f"    Workers:  {args.num_schedulers}")
    sys.stdout.flush()

    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            time.sleep(0.2)
    finally:
        print("==> shutting down")
        if api is not None:
            api.shutdown()
        for c in clients:
            c.shutdown()
        if rpc is not None:
            rpc.shutdown()
        if server is not None:
            server.shutdown()
    return 0


# -- job ---------------------------------------------------------------
def cmd_job_init(args) -> int:
    from .example_job import EXAMPLE_JOB
    path = args.filename
    try:
        with open(path, "x") as f:
            f.write(EXAMPLE_JOB)
    except FileExistsError:
        print(f"Job file {path} already exists", file=sys.stderr)
        return 1
    print(f"Example job file written to {path}")
    return 0


def _collect_vars(args) -> dict:
    """-var k=v flags + NOMAD_VAR_* env (jobspec2 variable inputs)."""
    out = {}
    for k, v in os.environ.items():
        if k.startswith("NOMAD_VAR_"):
            out[k[len("NOMAD_VAR_"):]] = v
    for kv in getattr(args, "var", None) or []:
        if "=" not in kv:
            raise ValueError(f"-var expects key=value, got {kv!r}")
        k, v = kv.split("=", 1)
        out[k] = v
    return out


def cmd_job_run(args) -> int:
    from ..jobspec import parse_job, job_to_spec
    try:
        with open(args.jobfile) as f:
            job = parse_job(f.read(), variables=_collect_vars(args))
    except OSError as e:
        print(f"Error reading job file: {e}", file=sys.stderr)
        return 1
    except ValueError as e:
        print(f"Error parsing job file {args.jobfile}: {e}", file=sys.stderr)
        return 1
    c = _client(args)
    try:
        resp = c.register_job(job_to_spec(job),
                              check_index=getattr(args, "check_index",
                                                  None))
    except ApiError as e:
        print(f"Error submitting job: {e}", file=sys.stderr)
        return 1
    if not resp.get("EvalID"):
        # periodic/parameterized jobs register without an eval
        print(f"Job registration successful (no evaluation: "
              f"\"{job.id}\" is periodic or parameterized)")
        return 0
    print(f"==> Evaluation {short_id(resp['EvalID'])} triggered by job "
          f"\"{job.id}\"")
    if args.detach:
        return 0
    return _monitor_eval(c, resp["EvalID"])


def _monitor_eval(c: ApiClient, eval_id: str, timeout: float = 30.0) -> int:
    deadline = time.time() + timeout
    last_status = ""
    while time.time() < deadline:
        try:
            ev = c.get_evaluation(eval_id)
        except ApiError:
            time.sleep(0.2)
            continue
        if ev["status"] != last_status:
            last_status = ev["status"]
            print(f"    Evaluation status: {last_status}")
        if last_status in ("complete", "failed", "canceled"):
            if ev.get("blocked_eval"):
                print(f"    Blocked eval {short_id(ev['blocked_eval'])} "
                      f"created (insufficient capacity)")
            if ev.get("failed_tg_allocs"):
                for tg, metric in ev["failed_tg_allocs"].items():
                    print(f"    Task group {tg!r} failed to place: "
                          f"{metric.get('constraint_filtered') or metric.get('dimension_exhausted')}")
            print(f"==> Evaluation \"{short_id(eval_id)}\" finished with "
                  f"status \"{last_status}\"")
            return 0 if last_status == "complete" else 1
        time.sleep(0.2)
    print("timed out waiting for evaluation", file=sys.stderr)
    return 1


def cmd_job_status(args) -> int:
    c = _client(args)
    if not args.job_id:
        jobs = c.list_jobs()
        if not jobs:
            print("No running jobs")
            return 0
        _print_rows([[j["ID"], j["Type"], str(j["Priority"]), j["Status"]]
                     for j in jobs], ["ID", "Type", "Priority", "Status"])
        return 0
    try:
        job = c.get_job(args.job_id)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"ID            = {job['id']}")
    print(f"Name          = {job['name']}")
    print(f"Type          = {job['type']}")
    print(f"Priority      = {job['priority']}")
    print(f"Datacenters   = {','.join(job['datacenters'])}")
    print(f"Status        = {job['status']}")
    summary = c.job_summary(args.job_id)
    if summary:
        print("\nSummary")
        rows = []
        for tg, counts in sorted(summary.get("summary", {}).items()):
            rows.append([tg] + [str(counts.get(k, 0)) for k in
                                ("starting", "running", "complete", "failed",
                                 "lost")])
        _print_rows(rows, ["Task Group", "Starting", "Running", "Complete",
                           "Failed", "Lost"])
    allocs = c.job_allocations(args.job_id)
    if allocs:
        print("\nAllocations")
        _print_rows(
            [[short_id(a["id"]), short_id(a["node_id"] or "--------"),
              a["task_group"], a["desired_status"], a["client_status"]]
             for a in allocs],
            ["ID", "Node ID", "Task Group", "Desired", "Status"])
    return 0


def cmd_job_stop(args) -> int:
    c = _client(args)
    try:
        resp = c.deregister_job(args.job_id, purge=args.purge)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"==> Evaluation {short_id(resp['EvalID'])} triggered by job "
          f"deregister")
    if args.detach:
        return 0
    return _monitor_eval(c, resp["EvalID"])


def cmd_job_plan(args) -> int:
    from ..jobspec import parse_job, job_to_spec
    try:
        with open(args.jobfile) as f:
            job = parse_job(f.read())
    except (OSError, ValueError) as e:
        print(f"Error reading job file: {e}", file=sys.stderr)
        return 1
    c = _client(args)
    try:
        result = c.plan_job(job.id, job_to_spec(job))
    except ApiError as e:
        print(f"Error during plan: {e}", file=sys.stderr)
        return 1
    _print_job_diff(result.get("diff") or {})
    print("\nScheduler dry-run:")
    failed = result.get("failed_tg_allocs") or {}
    if not failed:
        print("- All tasks successfully allocated.")
    else:
        for tg, metric in failed.items():
            print(f"- WARNING: Failed to place allocations for task group "
                  f"{tg!r}.")
            for k in ("constraint_filtered", "dimension_exhausted"):
                if metric.get(k):
                    print(f"    {k}: {metric[k]}")
    ann = result.get("annotations") or {}
    for tg, upd in (ann.get("desired_tg_updates") or {}).items():
        parts = [f"{k}: {v}" for k, v in sorted(upd.items()) if v]
        if parts:
            print(f"  Task group {tg!r}: " + ", ".join(parts))
    return 1 if failed else 0


_DIFF_MARK = {"Added": "+", "Deleted": "-", "Edited": "~", "None": " "}


def _print_job_diff(diff: dict, indent: str = "") -> None:
    if not diff:
        return
    mark = _DIFF_MARK.get(diff.get("Type", "None"), " ")
    print(f"{indent}{mark} Job: {diff.get('ID', '')!r}")
    for f in diff.get("Fields", []):
        print(f"{indent}  {_DIFF_MARK[f['Type']]} {f['Name']}: "
              f"{f['Old']!r} => {f['New']!r}")
    for tg in diff.get("TaskGroups", []):
        updates = tg.get("Updates") or {}
        counts = " (" + ", ".join(
            f"{v} {k}" for k, v in sorted(updates.items())) + ")" \
            if updates else ""
        print(f"{indent}{_DIFF_MARK[tg['Type']]} Task Group: "
              f"{tg.get('Name', '')!r}{counts}")
        _print_object_diff(tg, indent + "  ")
        for task in tg.get("Tasks", []):
            ann = task.get("Annotations") or []
            suffix = f" ({', '.join(ann)})" if ann else ""
            print(f"{indent}  {_DIFF_MARK[task['Type']]} Task: "
                  f"{task.get('Name', '')!r}{suffix}")
            _print_object_diff(task, indent + "    ")


def _print_object_diff(obj: dict, indent: str) -> None:
    for f in obj.get("Fields", []):
        ann = f.get("Annotations") or []
        suffix = f" ({', '.join(ann)})" if ann else ""
        print(f"{indent}{_DIFF_MARK[f['Type']]} {f['Name']}: "
              f"{f['Old']!r} => {f['New']!r}{suffix}")
    for o in obj.get("Objects", []):
        print(f"{indent}{_DIFF_MARK[o['Type']]} {o.get('Name', '')}")
        _print_object_diff(o, indent + "  ")


def cmd_job_scale(args) -> int:
    c = _client(args)
    try:
        resp = c.scale_job(args.job_id, args.group, args.count)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"==> Evaluation {short_id(resp['EvalID'])} triggered by job "
          f"scale")
    if args.detach:
        return 0
    return _monitor_eval(c, resp["EvalID"])


# -- deployment --------------------------------------------------------
def cmd_deployment_list(args) -> int:
    c = _client(args)
    deployments = c.list_deployments()
    if not deployments:
        print("No deployments")
        return 0
    _print_rows(
        [[short_id(d["id"]), d["job_id"], str(d["job_version"]), d["status"],
          d["status_description"]] for d in deployments],
        ["ID", "Job ID", "Job Version", "Status", "Description"])
    return 0


def cmd_deployment_status(args) -> int:
    c = _client(args)
    try:
        d = c.get_deployment(args.deployment_id)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"ID          = {short_id(d['id'])}")
    print(f"Job ID      = {d['job_id']}")
    print(f"Job Version = {d['job_version']}")
    print(f"Status      = {d['status']}")
    print(f"Description = {d['status_description']}")
    if d.get("task_groups"):
        print("\nDeployed")
        rows = []
        for tg, s in sorted(d["task_groups"].items()):
            rows.append([tg, str(s["auto_revert"]), str(s["promoted"])
                         if s["desired_canaries"] else "N/A",
                         str(s["desired_total"]), str(s["placed_allocs"]),
                         str(s["healthy_allocs"]), str(s["unhealthy_allocs"])])
        _print_rows(rows, ["Task Group", "Auto Revert", "Promoted", "Desired",
                           "Placed", "Healthy", "Unhealthy"])
    return 0


def cmd_deployment_promote(args) -> int:
    c = _client(args)
    try:
        resp = c.promote_deployment(args.deployment_id,
                                    args.group or None)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"==> Evaluation {short_id(resp['EvalID'])} triggered by "
          f"deployment promotion")
    if args.detach:
        return 0
    return _monitor_eval(c, resp["EvalID"])


def cmd_deployment_fail(args) -> int:
    c = _client(args)
    try:
        resp = c.fail_deployment(args.deployment_id)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"Deployment {short_id(args.deployment_id)} marked as failed")
    if resp.get("EvalID") and not args.detach:
        return _monitor_eval(c, resp["EvalID"])
    return 0


def cmd_deployment_pause(args) -> int:
    c = _client(args)
    try:
        c.pause_deployment(args.deployment_id, pause=not args.resume)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"Deployment {short_id(args.deployment_id)} "
          f"{'resumed' if args.resume else 'paused'}")
    return 0


def cmd_job_revert(args) -> int:
    c = _client(args)
    try:
        resp = c.revert_job(args.job_id, args.version)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"==> Evaluation {short_id(resp['EvalID'])} triggered by job "
          f"revert")
    if args.detach:
        return 0
    return _monitor_eval(c, resp["EvalID"])


def cmd_job_history(args) -> int:
    c = _client(args)
    try:
        versions = c.job_versions(args.job_id)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    for v in sorted(versions, key=lambda j: -j["version"]):
        print(f"Version     = {v['version']}")
        print(f"Stable      = {v['stable']}")
        print(f"Submit Date = {v.get('submit_time', '')}")
        print("")
    return 0


# -- node --------------------------------------------------------------
def cmd_node_status(args) -> int:
    c = _client(args)
    if not args.node_id:
        nodes = c.list_nodes()
        if not nodes:
            print("No nodes registered")
            return 0
        _print_rows(
            [[short_id(n["id"]), n["name"], n["datacenter"],
              n["scheduling_eligibility"], n["status"]] for n in nodes],
            ["ID", "Name", "DC", "Eligibility", "Status"])
        return 0
    try:
        node = c.get_node(args.node_id)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"ID          = {short_id(node['id'])}")
    print(f"Name        = {node['name']}")
    print(f"Class       = {node['node_class'] or '<none>'}")
    print(f"DC          = {node['datacenter']}")
    print(f"Drain       = {node['drain']}")
    print(f"Eligibility = {node['scheduling_eligibility']}")
    print(f"Status      = {node['status']}")
    res = node["node_resources"]
    print(f"Resources   = cpu: {res['cpu']['cpu_shares']} MHz, "
          f"memory: {res['memory']['memory_mb']} MiB, "
          f"disk: {res['disk']['disk_mb']} MiB")
    if getattr(args, "stats", False):
        _render_host_stats(c, node["id"])
    allocs = c.node_allocations(node["id"])
    if allocs:
        print("\nAllocations")
        _print_rows(
            [[short_id(a["id"]), a["task_group"], a["desired_status"],
              a["client_status"]] for a in allocs],
            ["ID", "Task Group", "Desired", "Status"])
    return 0


def _render_host_stats(c: ApiClient, node_id: str) -> None:
    """`node status -stats`: the node's live HostStats, proxied by the
    server to the owning client (ISSUE 13)."""
    try:
        hs = c.client_host_stats(node_id)
    except ApiError as e:
        print(f"\nHost Resource Utilization\n  unavailable: {e}")
        return
    if not hs.get("enabled", True):
        print("\nHost Resource Utilization\n  stats sampler disabled "
              "on this node (NOMAD_TPU_CLIENT_STATS=0)")
        return
    mem = hs.get("Memory") or {}
    disk = (hs.get("DiskStats") or [{}])[0]
    cpu = (hs.get("CPU") or [{}])[0]
    mib = 1024.0 * 1024.0
    print("\nHost Resource Utilization")
    print(f"  CPU     = {cpu.get('TotalPercent', 0.0):.1f}%")
    print(f"  Memory  = {mem.get('Used', 0) / mib:.0f} MiB / "
          f"{mem.get('Total', 0) / mib:.0f} MiB")
    print(f"  Disk    = {disk.get('Used', 0) / mib:.0f} MiB / "
          f"{disk.get('Size', 0) / mib:.0f} MiB "
          f"({disk.get('UsedPercent', 0.0):.1f}%)")
    print(f"  Uptime  = {hs.get('Uptime', 0.0):.0f} s; allocs "
          f"running = {hs.get('AllocsRunning', 0)} "
          f"(reporting usage = {hs.get('AllocsReporting', 0)})")


def cmd_node_eligibility(args) -> int:
    if args.enable == args.disable:
        print("Exactly one of -enable or -disable is required",
              file=sys.stderr)
        return 1
    c = _client(args)
    try:
        c.set_node_eligibility(args.node_id, args.enable)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"Node {short_id(args.node_id)} scheduling eligibility: "
          f"{'eligible' if args.enable else 'ineligible'}")
    return 0


def cmd_node_drain(args) -> int:
    if args.enable == args.disable:
        print("Exactly one of -enable or -disable is required",
              file=sys.stderr)
        return 1
    c = _client(args)
    try:
        if args.enable:
            c.drain_node(args.node_id, deadline_s=args.deadline)
            print(f"Node {short_id(args.node_id)} drain strategy set")
        else:
            c.drain_node(args.node_id, enable=False, mark_eligible=True)
            print(f"Node {short_id(args.node_id)} drain disabled")
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    if args.enable and getattr(args, "monitor", False):
        return _monitor_drain(c, args.node_id)
    return 0


def _monitor_drain(c: ApiClient, node_id: str,
                   timeout: float = 600.0) -> int:
    """Block until the node finishes draining, reporting alloc
    migrations as they happen (command/node_drain.go -monitor +
    api/nodes.go MonitorDrain)."""
    seen: dict = {}
    deadline = time.time() + timeout
    print(f"{time.strftime('%H:%M:%S')}: Monitoring node "
          f"{short_id(node_id)}: Ctrl-C to detach monitoring")
    while time.time() < deadline:
        try:
            node = c.get_node(node_id)
            allocs = c.node_allocations(node_id)
        except ApiError as e:
            print(f"Error: {e}", file=sys.stderr)
            return 1
        remaining = 0
        for a in allocs:
            status = (a.get("desired_status", ""),
                      a.get("client_status", ""))
            if seen.get(a["id"]) != status:
                seen[a["id"]] = status
                print(f"{time.strftime('%H:%M:%S')}: Alloc "
                      f"{short_id(a['id'])} status {status[1]} "
                      f"(desired {status[0]})")
            if a.get("desired_status") == "run" and \
                    a.get("client_status") in ("running", "pending"):
                remaining += 1
        draining = bool(node.get("drain"))
        if not draining and remaining == 0:
            print(f"{time.strftime('%H:%M:%S')}: Drain complete for "
                  f"node {short_id(node_id)}")
            return 0
        if not draining:
            # drain strategy cleared (deadline hit / canceled) but
            # allocs still present — report and finish
            print(f"{time.strftime('%H:%M:%S')}: Node drain strategy "
                  f"cleared; {remaining} alloc(s) still on node")
            return 0
        time.sleep(1.0)
    print("Error: drain monitor timed out", file=sys.stderr)
    return 1


# -- alloc / eval ------------------------------------------------------
def cmd_alloc_status(args) -> int:
    c = _client(args)
    try:
        a = c.get_allocation(args.alloc_id)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"ID         = {short_id(a['id'])}")
    print(f"Name       = {a['name']}")
    print(f"Node ID    = {short_id(a['node_id'])}")
    print(f"Job ID     = {a['job_id']}")
    print(f"Desired    = {a['desired_status']}")
    print(f"Status     = {a['client_status']}")
    for task, state in (a.get("task_states") or {}).items():
        print(f"\nTask \"{task}\" is \"{state['state']}\"" +
              (" (failed)" if state.get("failed") else ""))
    if getattr(args, "stats", False):
        # live task-level ResourceUsage from the owning client's
        # sampler (ISSUE 13)
        try:
            st = c.alloc_stats(a["id"])
        except ApiError as e:
            print(f"\nResource Utilization\n  unavailable: {e}")
            st = None
        if st is not None and st.get("stats"):
            usage = st["stats"]
            mib = 1024.0 * 1024.0
            rows = []
            for task, tu in sorted((usage.get("Tasks") or {}).items()):
                ru = tu.get("ResourceUsage") or {}
                cpu = (ru.get("CpuStats") or {})
                memst = (ru.get("MemoryStats") or {})
                rows.append([task,
                             f"{cpu.get('Percent', 0.0):.1f}%",
                             f"{memst.get('RSS', 0) / mib:.1f} MiB"])
            print("\nResource Utilization")
            _print_rows(rows, ["Task", "CPU", "Memory (RSS)"])
        elif st is not None:
            print("\nResource Utilization\n  no live usage reported "
                  "(sampler disabled or alloc not running)")
    metrics = a.get("metrics")
    if metrics and metrics.get("score_meta_data"):
        print("\nPlacement Metrics")
        print(f"  Nodes evaluated: {metrics['nodes_evaluated']}; "
              f"filtered: {metrics['nodes_filtered']}; "
              f"exhausted: {metrics['nodes_exhausted']}")
        for sm in metrics["score_meta_data"][:3]:
            print(f"  {short_id(sm['node_id'])}: {sm['norm_score']:.4f}")
    return 0


def cmd_eval_status(args) -> int:
    c = _client(args)
    try:
        ev = c.get_evaluation(args.eval_id)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    for k in ("id", "type", "triggered_by", "job_id", "status",
              "status_description"):
        print(f"{k:<20}= {ev.get(k)}")
    if ev.get("queued_allocations"):
        print(f"{'queued':<20}= {ev['queued_allocations']}")
    return 0


def cmd_server_info(args) -> int:
    c = _client(args)
    info = c.agent_self()
    print(json.dumps(info, indent=2))
    return 0


def cmd_alloc_logs(args) -> int:
    c = _client(args)
    try:
        out = c._request(
            "GET", f"/v1/client/fs/logs/{args.alloc_id}",
            params={"task": args.task,
                    "type": "stderr" if args.stderr else "stdout"})
    except ApiError as e:
        print(f"Error reading logs: {e}", file=sys.stderr)
        return 1
    sys.stdout.write(out.get("Data", ""))
    return 0


def cmd_alloc_fs(args) -> int:
    c = _client(args)
    try:
        out = c._request("GET", f"/v1/client/fs/ls/{args.alloc_id}",
                         params={"path": args.path})
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    rows = [["d" if e["IsDir"] else "-", str(e["Size"]), e["Name"]]
            for e in out]
    _print_rows(rows, ["Mode", "Size", "Name"])
    return 0


def cmd_alloc_exec(args) -> int:
    """`nomad alloc exec` (command/alloc_exec.go): run a command inside
    the task environment, stdin piped through, stdout/stderr relayed
    until exit."""
    c = _client(args)
    cmd = [a for a in args.cmd if a != "--"]
    if not cmd:
        print("Error: a command is required", file=sys.stderr)
        return 1
    try:
        sid = c.alloc_exec_start(args.alloc_id, cmd, task=args.task)
    except ApiError as e:
        print(f"Error starting exec: {e}", file=sys.stderr)
        return 1
    stdin = b""
    if not sys.stdin.isatty():
        stdin = sys.stdin.buffer.read()
    sent = False
    code = 1
    try:
        while True:
            out = c.alloc_exec_io(args.alloc_id, sid,
                                  stdin=stdin if not sent else b"",
                                  close_stdin=not sent, wait_s=1.0)
            sent = True
            if out["stdout"]:
                sys.stdout.buffer.write(out["stdout"])
                sys.stdout.buffer.flush()
            if out["stderr"]:
                sys.stderr.buffer.write(out["stderr"])
                sys.stderr.buffer.flush()
            if out["exited"]:
                code = out["exit_code"]
                break
    except KeyboardInterrupt:
        c.alloc_exec_stop(args.alloc_id, sid)
        return 130
    return code


def cmd_job_dispatch(args) -> int:
    """`nomad job dispatch` (command/job_dispatch.go)."""
    import base64
    c = _client(args)
    meta = {}
    for kv in (args.meta or []):
        if "=" not in kv:
            print(f"Error: -meta expects key=value, got {kv!r}",
                  file=sys.stderr)
            return 1
        k, v = kv.split("=", 1)
        meta[k] = v
    body = {"Meta": meta}
    if args.payload:
        with open(args.payload, "rb") as f:
            body["Payload"] = base64.b64encode(f.read()).decode()
    try:
        out = c._request("POST", f"/v1/job/{args.job_id}/dispatch", body)
    except ApiError as e:
        print(f"Error dispatching: {e}", file=sys.stderr)
        return 1
    print(f"Dispatched Job ID = {out['DispatchedJobID']}")
    print(f"Evaluation ID     = {short_id(out['EvalID'])}")
    return 0


def cmd_job_inspect(args) -> int:
    """`nomad job inspect` — the stored job as JSON."""
    c = _client(args)
    try:
        job = c.get_job(args.job_id)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(json.dumps(job, indent=2, sort_keys=True, default=str))
    return 0


def cmd_job_validate(args) -> int:
    """`nomad job validate` — parse + server-side validation via the
    plan endpoint (command/job_validate.go)."""
    c = _client(args)
    try:
        with open(args.path) as f:
            spec = f.read()
        out = c._request("POST", "/v1/jobs/parse", {"JobHCL": spec})
    except (OSError, ApiError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"Job \"{out.get('id', '?')}\" is valid")
    return 0


def cmd_job_eval(args) -> int:
    """`nomad job eval` — force a new evaluation."""
    c = _client(args)
    try:
        out = c._request("POST", f"/v1/job/{args.job_id}/evaluate", {})
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"Created eval {short_id(out['EvalID'])}")
    return 0


def cmd_job_periodic_force(args) -> int:
    """`nomad job periodic force`."""
    c = _client(args)
    try:
        out = c._request("POST",
                         f"/v1/job/{args.job_id}/periodic/force", {})
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    if out.get("Skipped"):
        print("Launch skipped (overlap prohibited or already launched)")
        return 0
    print(f"Dispatched {out.get('DispatchedJobID', '')} "
          f"(eval {short_id(out.get('EvalID', ''))})")
    return 0


def cmd_job_scaling_events(args) -> int:
    c = _client(args)
    out = c._request("GET", f"/v1/job/{args.job_id}/scaling-events")
    rows = [[str(ev.get("time", ""))[:19], str(ev.get("count", "")),
             ev.get("message", "")]
            for ev in out.get("ScalingEvents", [])]
    _print_rows(rows, ["Time", "Count", "Message"])
    return 0


def cmd_alloc_stop(args) -> int:
    """`nomad alloc stop` — stop and reschedule one allocation."""
    c = _client(args)
    try:
        out = c._request("POST", f"/v1/allocation/{args.alloc_id}/stop",
                         {})
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"Created eval {short_id(out['EvalID'])}")
    return 0


def cmd_alloc_restart(args) -> int:
    # reference surface: `alloc restart [-task <name>] <alloc> [<task>]`
    # — the flag and the positional are alternatives (alloc_restart.go);
    # naming the task both ways must agree
    task = args.task_opt or args.task
    if args.task_opt and args.task and args.task_opt != args.task:
        print("Error: task name given both as -task flag and "
              "positional argument", file=sys.stderr)
        return 1
    c = _client(args)
    try:
        out = c._request(
            "POST", f"/v1/client/allocation/{args.alloc_id}/restart",
            {"Task": task})
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"Restarted {out.get('restarted', 0)} task(s)")
    return 0


def cmd_alloc_signal(args) -> int:
    # reference surface: `alloc signal [-s <sig>] [-task <name>]
    # <alloc> [<task>]` (alloc_signal.go)
    task = args.task_opt or args.task
    if args.task_opt and args.task and args.task_opt != args.task:
        print("Error: task name given both as -task flag and "
              "positional argument", file=sys.stderr)
        return 1
    c = _client(args)
    try:
        out = c._request(
            "POST", f"/v1/client/allocation/{args.alloc_id}/signal",
            {"Task": task, "Signal": args.signal})
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"Signalled {out.get('delivered', 0)} task(s)")
    return 0


def cmd_eval_list(args) -> int:
    c = _client(args)
    evals = c._request("GET", "/v1/evaluations")
    rows = [[short_id(e["id"]), e.get("type", ""),
             e.get("triggered_by", ""), e.get("job_id", "")[:24],
             e.get("status", "")] for e in evals]
    _print_rows(rows, ["ID", "Type", "Triggered By", "Job", "Status"])
    return 0


def cmd_scaling_policy_list(args) -> int:
    c = _client(args)
    pols = c.list_scaling_policies()
    rows = [[short_id(p["ID"]), p["Target"].get("Job", ""),
             p["Target"].get("Group", ""),
             "yes" if p["Enabled"] else "no", p["Type"]]
            for p in pols]
    _print_rows(rows, ["ID", "Job", "Group", "Enabled", "Type"])
    return 0


def cmd_scaling_policy_info(args) -> int:
    c = _client(args)
    try:
        p = c.get_scaling_policy(args.policy_id)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(json.dumps(p, indent=2, sort_keys=True, default=str))
    return 0


def cmd_version(args) -> int:
    from .. import __version__
    print(f"nomad-tpu v{__version__}")
    return 0


def cmd_ui(args) -> int:
    print(f"Web UI: {args.address}/ui")
    return 0


def cmd_status(args) -> int:
    """`nomad status [prefix]` — no-prefix lists jobs; a prefix
    searches every context (command/status.go)."""
    c = _client(args)
    if not args.prefix:
        args.job_id = ""
        return cmd_job_status(args)
    res = c.search(args.prefix)
    hits = [(ctx, m) for ctx, matches in
            (res.get("Matches") or {}).items() for m in matches]
    if not hits:
        print(f"No matches found for {args.prefix!r}")
        return 1
    rows = [[ctx, short_id(m) if len(m) > 30 else m]
            for ctx, m in hits]
    _print_rows(rows, ["Context", "ID"])
    return 0


def cmd_monitor(args) -> int:
    """Stream agent logs (command/agent_monitor.go)."""
    import urllib.request
    url = f"{args.address}/v1/agent/monitor?log_level={args.log_level}"
    req = urllib.request.Request(url)
    if getattr(args, "token", ""):
        req.add_header("X-Nomad-Token", args.token)
    import urllib.error
    try:
        with urllib.request.urlopen(req, timeout=3600) as resp:
            for line in resp:
                line = line.strip()
                if not line or line == b"{}":
                    continue
                print(json.loads(line).get("Data", ""))
    except KeyboardInterrupt:
        pass
    except urllib.error.HTTPError as e:
        try:
            msg = json.loads(e.read()).get("error", str(e))
        except Exception:
            msg = str(e)
        print(f"Error: {msg}", file=sys.stderr)
        return 1
    except urllib.error.URLError as e:
        print(f"Error: unable to reach agent: {e.reason}",
              file=sys.stderr)
        return 1
    return 0


def cmd_volume_status(args) -> int:
    c = _client(args)
    if args.volume_id:
        try:
            v = c.get_volume(args.volume_id, namespace=args.namespace)
        except ApiError as e:
            print(f"Error: {e}", file=sys.stderr)
            return 1
        print(json.dumps(v, indent=2, sort_keys=True, default=str))
        return 0
    rows = [[v.get("id", ""), v.get("plugin_id", ""),
             str(v.get("schedulable", "")),
             v.get("access_mode", "")]
            for v in c.list_volumes(namespace=args.namespace)]
    _print_rows(rows, ["ID", "Plugin", "Schedulable", "Access mode"])
    return 0


def cmd_volume_register(args) -> int:
    c = _client(args)
    from ..jobspec.hcl import parse_hcl
    try:
        with open(args.file) as f:
            raw = f.read()
        spec = json.loads(raw) if raw.strip().startswith("{") \
            else parse_hcl(raw)
        body = spec.get("volume", spec)
        if isinstance(body, dict) and len(body) == 1 and \
                isinstance(next(iter(body.values())), dict):
            vol_id, body = next(iter(body.items()))
            body.setdefault("id", vol_id)
        c.register_volume(body, namespace=args.namespace)
    except (OSError, ValueError, ApiError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"Volume {body.get('id', '')} registered")
    return 0


def cmd_volume_deregister(args) -> int:
    c = _client(args)
    try:
        c.deregister_volume(args.volume_id, force=args.force,
                            namespace=args.namespace)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"Volume {args.volume_id} deregistered")
    return 0


def cmd_operator_debug(args) -> int:
    """Capture a debug archive (command/operator_debug.go): cluster
    state, agent info, metrics sampled over -duration at -interval,
    pprof analogs, and the monitor log — bundled as a .tar.gz the
    operator attaches to a support ticket."""
    import io
    import tarfile
    c = _client(args)
    # a zero/negative interval would busy-loop metrics captures against
    # the very agent being debugged
    args.interval = max(args.interval, 0.2)
    stamp = time.strftime("%Y%m%d-%H%M%S")
    out_path = args.output or f"nomad-debug-{stamp}.tar.gz"
    root = f"nomad-debug-{stamp}"
    captures = 0

    try:
        tar = tarfile.open(out_path, "w:gz")
    except OSError as e:
        print(f"Error opening {out_path}: {e}", file=sys.stderr)
        return 1

    def add(name: str, payload) -> None:
        nonlocal captures
        if not isinstance(payload, (bytes, bytearray)):
            payload = json.dumps(payload, indent=2,
                                 default=str).encode()
        info = tarfile.TarInfo(f"{root}/{name}")
        info.size = len(payload)
        info.mtime = int(time.time())
        tar.addfile(info, io.BytesIO(bytes(payload)))
        captures += 1

    def try_add(name: str, fn) -> None:
        try:
            add(name, fn())
        except Exception as e:
            add(name + ".error", {"error": str(e)})

    # one-shot cluster captures
    try_add("agent-self.json", c.agent_self)
    try_add("members.json",
            lambda: c._request("GET", "/v1/operator/members"))
    # scheduler-plane view (ISSUE 16): per-member role/applied/fence
    # lag + the leader's eval-lease counters ride in the bundle
    try_add("scheduler-plane.json",
            lambda: c._request("GET", "/v1/agent/members"))
    try_add("raft-status.json",
            lambda: c._request("GET", "/v1/operator/raft/configuration"))
    try_add("autopilot.json", c.autopilot_config)
    try_add("governor.json", c.governor)
    # flight recorder: exemplar span trees + stage percentiles ride in
    # the bundle, so a support ticket carries the anatomy of the worst
    # evals, not just gauge values
    try_add("trace.json", c.trace)
    try_add("trace-chrome.json",
            lambda: c.trace({"format": "chrome"}))
    # retained telemetry (ISSUE 11): the whole in-process history ring
    # + the live flatness verdict + a Prometheus-format snapshot ride
    # in the bundle ONE-SHOT — the interval poll below only adds
    # samples taken during the capture window, but the ring carries
    # the minutes BEFORE the operator ran this command, which is
    # where the incident usually lives
    try_add("telemetry.json", c.telemetry)
    try_add("flatness.json", c.flatness)
    try_add("metrics.prom",
            lambda: c.metrics(format="prometheus").encode())
    # latest chaos artifact (ISSUE 15): when an operator has run
    # `nomad dev chaos` on this machine, the newest CHAOS_rNN.json
    # rides in the bundle as chaos.json — a support ticket carries the
    # invariant verdicts the cluster last proved, not just its gauges
    from ..chaos.matrix import latest_artifact
    chaos_path = latest_artifact(".")
    if chaos_path is not None:
        def _read_chaos(p=chaos_path):
            with open(p, "rb") as f:
                return f.read()
        try_add("chaos.json", _read_chaos)
    try_add("scheduler-config.json", c.scheduler_config)
    try_add("nomad/jobs.json", c.list_jobs)
    # per-node live host stats (ISSUE 13): each reachable client's
    # HostStats + its retained client-side ring ride the bundle, so a
    # ticket carries the fleet's host truth, not just server state
    try:
        nodes = c.list_nodes()
        add("nomad/nodes.json", nodes)
        for n in nodes:
            try_add(f"nomad/client-stats/{n['id'][:8]}.json",
                    lambda nid=n["id"]: c.client_host_stats(
                        nid, history=True))
    except Exception as e:
        add("nomad/nodes.json.error", {"error": str(e)})
    try_add("nomad/allocations.json", c.list_allocations)
    try_add("nomad/deployments.json", c.list_deployments)
    try_add("nomad/volumes.json", c.list_volumes)
    try_add("pprof/threads.json", c.agent_threads)
    try_add("pprof/profile.json",
            lambda: c.agent_profile(seconds=min(args.duration, 2.0)))

    # interval captures over the window (metrics time series)
    end = time.time() + max(args.duration, 0.0)
    i = 0
    while True:
        try_add(f"metrics/metrics_{i:03d}.json", c.metrics)
        i += 1
        if time.time() >= end:
            break
        time.sleep(min(args.interval, max(end - time.time(), 0.0)))

    add("index.json", {
        "timestamp": stamp,
        "duration_s": args.duration,
        "interval_s": args.interval,
        "captures": captures,
        "cli": "nomad-tpu operator debug",
    })
    tar.close()
    print(f"Created debug archive: {out_path} ({captures} captures)")
    return 0


def cmd_operator_governor(args) -> int:
    """Steady-state governor status (governor/): every governed
    structure's gauge with watermark state, the backpressure signal,
    and recent structured events (watermark crossings, reclaims, drift
    findings)."""
    c = _client(args)
    try:
        out = c.governor()
    except ApiError as e:
        print(f"Error querying governor: {e}", file=sys.stderr)
        return 1
    if not out.get("enabled", False):
        print("Governor disabled on this agent")
        return 0
    print(f"Backpressure  = {'ENGAGED' if out.get('backpressure') else 'off'}")
    print(f"Service p99   = {out.get('service_p99_ms', 0.0)} ms "
          f"({out.get('latency_samples', 0)} samples)")
    print(f"Process RSS   = {out.get('process_rss_mb', 0.0)} MB")
    print(f"Samples       = {out.get('samples', 0)} "
          f"(every {out.get('interval_s', 0)}s)")
    print()
    rows = []
    for g in out.get("gauges", []):
        high = g.get("high")
        wm = (f"{g['value']:.0f}/{high:.0f}" if high is not None
              else f"{g['value']:.0f}")
        status = g.get("status", "ok") if high is not None else "-"
        if g.get("pressure"):
            status += " (pressure)"
        rows.append([g["name"], wm, g.get("unit", "count"), status,
                     g.get("reclaims", 0)])
    _print_rows(rows, ["Structure", "Value/High", "Unit", "Status",
                       "Reclaims"])
    events = out.get("events", [])[-10:]
    if events:
        print()
        print(f"Recent events ({len(events)}):")
        for e in events:
            ts = time.strftime("%H:%M:%S",
                               time.localtime(e.get("ts", 0)))
            kind = e.get("kind", "event")
            detail = {k: v for k, v in e.items()
                      if k not in ("ts", "kind")}
            print(f"  {ts}  {kind:12s} {json.dumps(detail, default=str)}")
    # runtime race sanitizer (analysis/race.py, NOMAD_TPU_RACE=1):
    # aggregate lock traffic + the worst-holder exemplars
    locks = out.get("locks") or {}
    if locks.get("enabled"):
        print()
        print(f"Lock traffic (NOMAD_TPU_RACE=1): "
              f"{locks.get('tracked', 0)} tracked, "
              f"{locks.get('order_edges', 0)} order edges, "
              f"{locks.get('findings_unsuppressed', 0)} finding(s)")
        rows = [[l["name"], l["instances"], l["acquires"],
                 l["contended"], f"{l['max_hold_ms']:.1f}",
                 l["hold_warns"]]
                for l in locks.get("locks", [])[:8]]
        if rows:
            _print_rows(rows, ["Lock", "Inst", "Acquires",
                               "Contended", "MaxHold(ms)", "Warns"])
        for e in locks.get("worst_holders", [])[:4]:
            print(f"  worst holder {e['lock']}: {e['hold_ms']:.1f} ms "
                  f"in {e['thread']}  {e.get('holder', '')}")
    return 0


def cmd_operator_trace(args) -> int:
    """Eval flight recorder (nomad_tpu/trace/): per-eval span trees,
    tail exemplars with governor-gauge snapshots, per-stage
    percentiles. `-o chrome` emits Chrome trace-event JSON — load it
    in Perfetto (ui.perfetto.dev) or chrome://tracing; one track per
    worker / gateway / applier so cross-thread overlap is visible."""
    c = _client(args)
    params = {"n": str(args.n)}
    if args.exemplars:
        params["exemplars"] = "true"
    if args.o == "chrome":
        params["format"] = "chrome"
    try:
        out = c.trace(params)
    except ApiError as e:
        print(f"Error querying trace: {e}", file=sys.stderr)
        return 1
    if args.o == "chrome":
        payload = json.dumps(out)
        if args.output:
            with open(args.output, "w") as f:
                f.write(payload)
            print(f"Wrote {len(out.get('traceEvents', []))} trace "
                  f"events to {args.output} (load in Perfetto / "
                  f"chrome://tracing)")
        else:
            print(payload)
        return 0
    if not out.get("enabled", False):
        print("Flight recorder disabled on this agent "
              "(NOMAD_TPU_TRACE=0)")
        return 0
    ring = out.get("ring", {})
    st = out.get("stats", {})
    print(f"Traces        = {ring.get('traces', 0)} in ring "
          f"({ring.get('bytes', 0)}/{ring.get('bytes_max', 0)} bytes); "
          f"{st.get('traces', 0)} recorded, {st.get('dropped', 0)} "
          f"aged out")
    print(f"Exemplars     = {len(out.get('exemplars', []))}"
          f"/{out.get('exemplar_slots', 0)} "
          f"(threshold {out.get('threshold_ms', 0.0)} ms, "
          f"{st.get('exemplar_pins', 0)} pinned)")
    print()
    rows = []
    for stage, p in out.get("stage_percentiles", {}).items():
        rows.append([stage, p["p50_ms"], p["p95_ms"], p["p99_ms"],
                     p["count"]])
    if rows:
        _print_rows(rows, ["Stage", "p50 ms", "p95 ms", "p99 ms",
                           "Samples"])
    exemplars = out.get("exemplars", [])
    if exemplars:
        print()
        print(f"Tail exemplars ({len(exemplars)}):")
        for t in exemplars:
            pin = " PINNED " + t.get("reason", "") \
                if t.get("pinned") else ""
            print(f"  {t['eval_id'][:8]}  {t['total_ms']:9.1f} ms  "
                  f"{t.get('type', ''):8s} {t.get('job_id', '')} "
                  f"({len(t.get('spans', []))} spans){pin}")
            for sp in t.get("spans", []):
                attrs = sp.get("attrs")
                extra = f"  {json.dumps(attrs)}" if attrs else ""
                print(f"      {sp['t0_ms']:9.1f} +{sp['dur_ms']:8.2f}"
                      f"  {sp['name']:13s} [{sp.get('track', '')}]"
                      f"{extra}")
    return 0


def cmd_operator_top(args) -> int:
    """Live rates and trends from the retained telemetry ring (ISSUE
    11): evals/s and placements/s from counter deltas, the p99 trend
    over history, recent per-stage latency shares, the device
    economics the TPU validation campaign reads (pad waste, per-arm
    dispatch seconds + compiles, kernel cache, mirror/HBM bytes, lane
    occupancy), the live flatness verdict, and drift annotations from
    the governor's event log — `/v1/metrics` shows a point in time,
    this shows where the numbers are GOING."""
    from statistics import median
    c = _client(args)
    try:
        tel = c.telemetry(last=args.n)
    except ApiError as e:
        print(f"Error querying telemetry: {e}", file=sys.stderr)
        return 1
    if not tel.get("enabled", True) or "series" not in tel:
        print("Telemetry collector disabled on this agent "
              "(NOMAD_TPU_TELEMETRY=0 or telemetry_sample_interval_s=0)")
        return 0
    series = tel.get("series", {})
    rates = tel.get("rates", {})

    def tail_vals(d, name):
        return [v for v in d.get(name, []) if v is not None]

    def rate_now(name, k=5):
        vals = tail_vals(rates, name)
        return (sum(vals[-k:]) / len(vals[-k:])) if vals else 0.0

    def rate_peak(name):
        vals = tail_vals(rates, name)
        return max(vals) if vals else 0.0

    ring_kib = tel.get("ring_bytes", 0) / 1024.0
    print(f"Telemetry     = {tel.get('samples', 0)} samples @ "
          f"{tel.get('interval_s', 0)}s "
          f"({tel.get('series_count', 0)} series, ring "
          f"{ring_kib:.0f} KiB)")
    print(f"Evals/s       = "
          f"{rate_now('counter.nomad.worker.eval_processed'):.1f} now, "
          f"{rate_peak('counter.nomad.worker.eval_processed'):.1f} peak")
    print(f"Placements/s  = "
          f"{rate_now('counter.nomad.plan.placements'):.1f} now, "
          f"{rate_peak('counter.nomad.plan.placements'):.1f} peak")
    p99s = tail_vals(series, "latency.p99_ms")
    if p99s:
        half = max(1, len(p99s) // 2)
        first = median(p99s[:half]) or 0.0
        last = median(p99s[len(p99s) - half:])
        trend = (last / first) if first > 0 else 1.0
        p50s = tail_vals(series, "latency.p50_ms")
        print(f"Latency       = p50 {p50s[-1] if p50s else 0.0:.1f} ms, "
              f"p99 {p99s[-1]:.1f} ms "
              f"(trend {trend:.2f}x first->last half)")
    rss = tail_vals(series, "process.rss_mb")
    if rss:
        print(f"RSS           = {rss[-1]:.1f} MB "
              f"(start of window {rss[0]:.1f} MB)")
    try:
        flat = c.flatness()
        if flat.get("enabled", flat.get("pass") is not None):
            if flat.get("pass") is None:
                verdict = f"n/a ({flat.get('reason', 'no verdict')})"
            elif flat["pass"]:
                verdict = "PASS"
            else:
                verdict = f"FAIL ({flat.get('reason', '?')})"
            print(f"Flatness      = {verdict} "
                  f"(p99 drift {flat.get('p99_drift_ratio', '?')}x, "
                  f"rss {flat.get('rss_slope_mb_per_hour', '?')} MB/h "
                  f"over {flat.get('windows_measured', 0)} windows)")
    except ApiError:
        pass

    # cluster rollup (ISSUE 13): fleet economics folded from the
    # clients' heartbeat host-stats payloads — allocated is what the
    # scheduler bin-packed, used is what the hosts actually burned
    nt = tail_vals(series, "cluster.nodes_total")
    if nt:
        def clast(name):
            vals = tail_vals(series, f"cluster.{name}")
            return vals[-1] if vals else 0.0
        print()
        print("Cluster:")
        print(f"  nodes              = {clast('nodes_total'):.0f} total, "
              f"{clast('nodes_ready'):.0f} ready, "
              f"{clast('nodes_down'):.0f} down "
              f"({clast('nodes_reporting'):.0f} reporting stats, "
              f"{clast('stale_heartbeats'):.0f} stale)")
        print(f"  fleet cpu          = "
              f"{clast('fleet_cpu_allocated_ratio'):.1%} allocated, "
              f"{clast('fleet_cpu_used_ratio'):.1%} used of "
              f"{clast('fleet_cpu_capacity_mhz'):.0f} MHz")
        print(f"  fleet memory       = "
              f"{clast('fleet_mem_allocated_ratio'):.1%} allocated, "
              f"{clast('fleet_mem_used_ratio'):.1%} used of "
              f"{clast('fleet_mem_capacity_mb'):.0f} MiB")
        if tail_vals(series, "cluster.node_cpu_pct_p50"):
            print(f"  node utilization   = cpu p50 "
                  f"{clast('node_cpu_pct_p50'):.1f}% / p99 "
                  f"{clast('node_cpu_pct_p99'):.1f}%, mem p50 "
                  f"{clast('node_mem_ratio_p50'):.1%} / p99 "
                  f"{clast('node_mem_ratio_p99'):.1%}")

    # write ingest block (ISSUE 19): the admission path's economics —
    # coalescing, shed, and the full write latency each submitter saw
    # (gauges land in the ring via the governor snapshot; the rates
    # come from the nomad.ingest.* counter deltas)
    if tail_vals(series, "ingest.batch_size"):
        def ilast(name):
            vals = tail_vals(series, f"ingest.{name}")
            return vals[-1] if vals else 0.0
        print()
        print("Write ingest:")
        print(f"  writes/s           = "
              f"{rate_now('counter.nomad.ingest.writes'):.1f} now, "
              f"{rate_peak('counter.nomad.ingest.writes'):.1f} peak "
              f"({rate_now('counter.nomad.ingest.batches'):.1f} "
              f"batches/s)")
        print(f"  write p99          = {ilast('write_p99_ms'):.2f} ms "
              f"(mean batch {ilast('batch_size'):.2f})")
        print(f"  coalesced          = {ilast('coalesced_writes'):.0f} "
              f"writes shared a raft entry, {ilast('shed'):.0f} shed")
        print(f"  queue              = {ilast('queue_depth'):.0f} deep, "
              f"window {ilast('window_us'):.0f} us")

    # recent per-stage share: p50 x reservoir occupancy approximates
    # each stage's recent seconds (reservoirs hold the last 2048
    # reports); superset/idle stages stay out of the denominator like
    # stages.snapshot()
    excluded = {"sched_host", "queue_wait"}
    stage_rows = []
    weights = {}
    for name in series:
        if name.startswith("stage.") and name.endswith(".p50_ms"):
            stage = name[len("stage."):-len(".p50_ms")]
            p50 = (tail_vals(series, name) or [0.0])[-1]
            p99 = (tail_vals(series, f"stage.{stage}.p99_ms")
                   or [0.0])[-1]
            cnt = (tail_vals(series, f"stage_count.{stage}")
                   or [0.0])[-1]
            weights[stage] = (p50 * cnt, p50, p99, cnt)
    denom = sum(w for s, (w, _p, _q, _c) in weights.items()
                if s not in excluded) or 1.0
    for stage in sorted(weights):
        w, p50, p99, cnt = weights[stage]
        share = 0.0 if stage in excluded else w / denom
        stage_rows.append([stage, f"{p50:.2f}", f"{p99:.2f}",
                           int(cnt), f"{share:.1%}"])
    if stage_rows:
        print()
        _print_rows(stage_rows, ["Stage", "p50 ms", "p99 ms",
                                 "Samples", "Recent share"])

    # device economics (the validation campaign's instruments)
    print()
    print("Device economics:")
    pw = tail_vals(series, "device.pad_waste_ratio")
    if pw:
        shipped = tail_vals(series, "device.pad_rows_shipped")
        print(f"  pad waste ratio    = {pw[-1]:.4f} "
              f"(rows shipped {shipped[-1] if shipped else 0:.0f})")
    arms = sorted({n[len("device.dispatch_s."):]
                   for n in series if n.startswith("device.dispatch_s.")})
    for arm in arms:
        s_ = (tail_vals(series, f"device.dispatch_s.{arm}") or [0.0])[-1]
        d_ = (tail_vals(series, f"device.dispatches.{arm}") or [0.0])[-1]
        c_ = (tail_vals(series, f"device.compiles.{arm}") or [0.0])[-1]
        print(f"  {arm:18s} = {s_:.3f}s over {d_:.0f} dispatches "
              f"({c_:.0f} fresh compiles)")
    kc = tail_vals(series, "device.kernel_cache_entries")
    if kc:
        print(f"  kernel caches      = {kc[-1]:.0f} entries")
    mb = tail_vals(series, "device.mirror_bytes")
    if mb:
        print(f"  device mirror      = {mb[-1] / 1024.0:.0f} KiB")
    # compiled feasibility economics (feas.* gauges, ISSUE 17)
    fi = tail_vals(series, "feas.intern_values")
    if fi:
        fm = (tail_vals(series, "feas.mask_cache_entries") or [0.0])[-1]
        fh = (tail_vals(series, "feas.mask_cache_hit_rate")
              or [0.0])[-1]
        fr = (tail_vals(series, "feas.recompiles") or [0.0])[-1]
        print(f"  feasibility        = {fi[-1]:.0f} interned values, "
              f"{fm:.0f} cached masks")
        print(f"  feas mask cache    = {fh:.1%} hit rate "
              f"({fr:.0f} recompiles)")
        # residue economics (ISSUE 20): device-resident tokens that
        # outlived CSI/preferred-node mutations vs dense re-uploads,
        # plus accumulated scatter debt and vectorized scoring builds
        ts_ = (tail_vals(series, "feas.token_survivals") or [0.0])[-1]
        ti_ = (tail_vals(series, "feas.token_invalidations")
               or [0.0])[-1]
        rr_ = (tail_vals(series, "feas.residue_rows") or [0.0])[-1]
        se_ = (tail_vals(series, "feas.spread_score_evals")
               or [0.0])[-1]
        if ts_ or ti_ or rr_ or se_:
            print(f"  feas residue       = {ts_:.0f} token survivals, "
                  f"{ti_:.0f} invalidations")
            print(f"  residue debt       = {rr_:.0f} scatter rows, "
                  f"{se_:.0f} vector spread evals")
    # mesh block: sharded residency economics (present only when a
    # mesh dispatcher exists — the device.mesh_* family)
    md = tail_vals(series, "device.mesh_devices")
    if md and md[-1] > 0:
        rb = (tail_vals(series,
                        "device.mesh_resident_bytes_per_device")
              or [0.0])[-1]
        ru = (tail_vals(series, "device.mesh_reshard_uploads")
              or [0.0])[-1]
        ds = (tail_vals(series, "device.mesh_delta_scatters")
              or [0.0])[-1]
        rh = (tail_vals(series, "device.mesh_resident_hits")
              or [0.0])[-1]
        sm = (tail_vals(series, "device.mesh_stale_misses")
              or [0.0])[-1]
        print(f"  mesh               = {md[-1]:.0f} devices, "
              f"resident {rb / 1024.0:.0f} KiB/device")
        print(f"  mesh traffic       = {ru:.0f} reshard uploads, "
              f"{ds:.0f} delta scatters, {rh:.0f} resident hits "
              f"({sm:.0f} stale misses)")
    hbm = tail_vals(series, "device.hbm_bytes_in_use")
    if hbm and hbm[-1] > 0:
        print(f"  HBM in use         = {hbm[-1] / (1 << 20):.1f} MiB")
    occ = tail_vals(series, "gateway.batch_occupancy")
    if occ:
        print(f"  lane occupancy     = {occ[-1]:.2f}")

    # drift annotations: the governor's structured findings over the
    # same window the trends cover
    try:
        gov = c.governor()
    except ApiError:
        gov = {}
    drifts = [e for e in gov.get("events", [])
              if e.get("kind") in ("drift", "backpressure", "reclaim")]
    if drifts:
        print()
        print(f"Annotations ({len(drifts[-8:])}):")
        for e in drifts[-8:]:
            ts = time.strftime("%H:%M:%S",
                               time.localtime(e.get("ts", 0)))
            detail = {k: v for k, v in e.items()
                      if k not in ("ts", "kind")}
            print(f"  {ts}  {e.get('kind', ''):12s} "
                  f"{json.dumps(detail, default=str)}")
    return 0


def cmd_operator_snapshot_save(args) -> int:
    c = _client(args)
    try:
        out = c.snapshot_save()
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    with open(args.file, "w") as f:
        json.dump(out, f, default=str)
    print(f"State snapshot written to {args.file} "
          f"(index {out['index']})")
    return 0


def cmd_operator_snapshot_inspect(args) -> int:
    try:
        with open(args.file) as f:
            out = json.load(f)
    except (OSError, ValueError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    tables = out.get("snapshot", {}).get("tables", {})
    rows = [[name, str(len(rows_)) if isinstance(rows_, list) else "1"]
            for name, rows_ in sorted(tables.items()) if rows_]
    print(f"Index: {out.get('index')}")
    _print_rows(rows, ["Table", "Rows"])
    return 0


def cmd_operator_snapshot_restore(args) -> int:
    c = _client(args)
    try:
        with open(args.file) as f:
            out = json.load(f)
        res = c.snapshot_restore(out["snapshot"])
    except (OSError, ValueError, KeyError, ApiError) as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"Snapshot restored (index {res['index']})")
    return 0


def cmd_operator_autopilot_get(args) -> int:
    c = _client(args)
    print(json.dumps(c.autopilot_config(), indent=2, sort_keys=True))
    return 0


def cmd_operator_autopilot_set(args) -> int:
    c = _client(args)
    cfg = {}
    if args.cleanup_dead_servers is not None:
        cfg["CleanupDeadServers"] = \
            args.cleanup_dead_servers.lower() == "true"
    if args.dead_server_cleanup_secs is not None:
        cfg["DeadServerCleanupSecs"] = args.dead_server_cleanup_secs
    c.set_autopilot_config(cfg)
    print("Configuration updated!")
    return 0


def cmd_job_promote(args) -> int:
    """`nomad job promote` — promote the job's latest deployment
    (command/job_promote.go)."""
    c = _client(args)
    try:
        deps = c.job_deployments(args.job_id)
        active = [d for d in deps
                  if d.get("status") in ("running", "paused")]
        if not active:
            print(f"Error: no active deployment for job "
                  f"{args.job_id}", file=sys.stderr)
            return 1
        c.promote_deployment(active[0]["id"])
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f"Deployment {short_id(active[0]['id'])} promoted")
    return 0


def cmd_namespace_list(args) -> int:
    c = _client(args)
    rows = [[n["name"], n["description"]]
            for n in c.list_namespaces()]
    _print_rows(rows, ["Name", "Description"])
    return 0


def cmd_namespace_apply(args) -> int:
    c = _client(args)
    try:
        c.apply_namespace(args.name, description=args.description)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f'Successfully applied namespace "{args.name}"!')
    return 0


def cmd_namespace_delete(args) -> int:
    c = _client(args)
    try:
        c.delete_namespace(args.name)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(f'Successfully deleted namespace "{args.name}"!')
    return 0


def cmd_namespace_status(args) -> int:
    c = _client(args)
    try:
        ns = c.get_namespace(args.name)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    print(json.dumps(ns, indent=2, sort_keys=True, default=str))
    return 0


def cmd_service_list(args) -> int:
    """nomad service list (the built-in catalog's discovery surface)."""
    c = _client(args)
    rows = [[s["ServiceName"], ",".join(s["Tags"]), str(s["Instances"])]
            for s in c.list_services(namespace=args.namespace)]
    _print_rows(rows, ["Service", "Tags", "Instances"])
    return 0


def cmd_service_info(args) -> int:
    c = _client(args)
    try:
        regs = c.get_service(args.service_name, namespace=args.namespace)
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    rows = [[short_id(r["alloc_id"]), r["task_name"] or "(group)",
             f"{r['address']}:{r['port']}", r["status"]]
            for r in regs]
    _print_rows(rows, ["Alloc", "Task", "Address", "Status"])
    return 0


def cmd_event_sink_register(args) -> int:
    c = _client(args)
    out = c.upsert_event_sink(args.sink_address, sink_id=args.id or "")
    print(f"Registered sink {out['ID']}")
    return 0


def cmd_event_sink_list(args) -> int:
    c = _client(args)
    rows = [[s["ID"], s["Type"], s["Address"],
             str(s["LatestIndex"])] for s in c.list_event_sinks()]
    _print_rows(rows, ["ID", "Type", "Address", "Progress"])
    return 0


def cmd_event_sink_deregister(args) -> int:
    _client(args).delete_event_sink(args.id)
    print(f"Deregistered sink {args.id}")
    return 0


def cmd_server_members(args) -> int:
    """`nomad server members` (command/server_members.go shape) plus
    the scheduler-plane columns (ISSUE 16): per-member raft role,
    applied index, fence lag behind the leader's log, and how many
    broker evals the leader has leased to each follower."""
    c = _client(args)
    try:
        out = c._request("GET", "/v1/agent/members")
    except ApiError:
        out = c._request("GET", "/v1/operator/members")
    plane = out.get("SchedulerPlane") or {}
    members = {m["addr"]: m for m in plane.get("members") or []}
    leader = out.get("Leader", "")
    rows = []
    for addr in out.get("Members", []):
        m = members.get(addr)
        if m is None:
            rows.append([addr,
                         "leader" if addr == leader else "follower",
                         "-", "-", "-"])
            continue
        rows.append([addr, str(m.get("role")),
                     "-" if m.get("applied_index") is None
                     else str(m["applied_index"]),
                     "-" if m.get("fence_lag") is None
                     else str(m["fence_lag"]),
                     str(m.get("leased_evals", 0))])
    if not rows:
        print("single-server (dev) agent; no cluster membership")
        return 0
    _print_rows(rows, ["Address", "Role", "Applied", "FenceLag",
                       "LeasedEvals"])
    leases = plane.get("leases") or {}
    print(f"\nScheduler plane: "
          f"{'on' if plane.get('enabled') else 'off'}"
          f"  remote_dequeues={leases.get('remote_dequeues', 0)}"
          f"  remote_plans={leases.get('remote_plans', 0)}"
          f"  remote_demotions={leases.get('remote_demotions', 0)}"
          f"  leases_outstanding={leases.get('outstanding', 0)}")
    return 0


def cmd_metrics(args) -> int:
    c = _client(args)
    print(json.dumps(c.metrics(), indent=2, sort_keys=True))
    return 0


def cmd_agent_info(args) -> int:
    c = _client(args)
    print(json.dumps(c.agent_self(), indent=2, sort_keys=True,
                     default=str))
    return 0


def cmd_acl_token_self(args) -> int:
    c = _client(args)
    try:
        print(json.dumps(c.acl_token_self(), indent=2, default=str))
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    return 0


def cmd_acl_policy_info(args) -> int:
    c = _client(args)
    try:
        print(json.dumps(c.acl_policy(args.name), indent=2, default=str))
    except ApiError as e:
        print(f"Error: {e}", file=sys.stderr)
        return 1
    return 0


def cmd_acl_policy_delete(args) -> int:
    _client(args).acl_delete_policy(args.name)
    print(f"Deleted policy {args.name}")
    return 0


def cmd_acl_token_delete(args) -> int:
    _client(args).acl_delete_token(args.accessor_id)
    print(f"Deleted token {args.accessor_id}")
    return 0


def cmd_operator_raft(args) -> int:
    c = _client(args)
    out = c._request("GET", "/v1/operator/raft/configuration")
    rows = [[s.get("Address", ""), s.get("Role", ""),
             "yes" if s.get("Leader") else "no",
             str(s.get("Term", "")), str(s.get("LastLogIndex", ""))]
            for s in out.get("Servers", [])]
    _print_rows(rows, ["Address", "Role", "Leader", "Term", "LastIndex"])
    return 0


def cmd_system_gc(args) -> int:
    _client(args)._request("PUT", "/v1/system/gc")
    print("GC triggered")
    return 0


# -- acl ---------------------------------------------------------------
def cmd_acl_bootstrap(args) -> int:
    c = _client(args)
    tok = c.acl_bootstrap()
    print(f"Accessor ID = {tok['accessor_id']}")
    print(f"Secret ID   = {tok['secret_id']}")
    print(f"Type        = {tok['type']}")
    return 0


def cmd_acl_policy_apply(args) -> int:
    with open(args.file) as f:
        rules = f.read()
    _client(args).acl_upsert_policy(args.name, rules,
                                    description=args.description)
    print(f"Successfully wrote policy {args.name!r}")
    return 0


def cmd_acl_policy_list(args) -> int:
    rows = [[p["name"], p.get("description", "")]
            for p in _client(args).acl_policies()]
    _print_rows(rows, ["Name", "Description"])
    return 0


def cmd_acl_token_create(args) -> int:
    tok = _client(args).acl_create_token(
        name=args.name, type_=args.type,
        policies=args.policy or [])
    print(f"Accessor ID = {tok['accessor_id']}")
    print(f"Secret ID   = {tok['secret_id']}")
    print(f"Type        = {tok['type']}")
    print(f"Policies    = {tok['policies']}")
    return 0


def cmd_acl_token_list(args) -> int:
    rows = [[t["accessor_id"][:8], t["name"], t["type"],
             ",".join(t.get("policies", []))]
            for t in _client(args).acl_tokens()]
    _print_rows(rows, ["Accessor", "Name", "Type", "Policies"])
    return 0


def cmd_dev_lint(args) -> int:
    """`nomad dev lint` — the TPU-hygiene static analyzer
    (nomad_tpu/analysis/): host-sync / jit / dtype / lock /
    surface-drift passes over the tree, non-zero exit on unsuppressed
    findings. Local tooling: no agent connection involved."""
    from ..analysis.__main__ import main as lint_main
    argv = list(args.paths or [])
    if args.as_json:
        argv.append("--json")
    if args.show_suppressed:
        argv.append("--show-suppressed")
    return lint_main(argv)


def cmd_dev_chaos(args) -> int:
    """`nomad dev chaos [-cell NAME]` — the scenario matrix +
    fault-injection harness (nomad_tpu/chaos/, ISSUE 15): every cell
    is a seeded workload + fault schedule + invariant checks +
    flatness verdict against a real in-process server; the run emits
    a CHAOS_rNN.json artifact and exits non-zero when a cell fails.
    Local tooling: no agent connection involved."""
    from ..chaos.__main__ import main as chaos_main
    argv = []
    if args.cell:
        argv += ["-cell", args.cell]
    if args.full:
        argv.append("-full")
    if args.seed is not None:
        argv += ["-seed", str(args.seed)]
    if args.list_cells:
        argv.append("-list")
    if args.output:
        argv += ["-output", args.output]
    if args.no_artifact:
        argv.append("-no-artifact")
    return chaos_main(argv)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="nomad-tpu",
                                description="TPU-native workload orchestrator")
    p.add_argument("-address", default="http://127.0.0.1:4646")
    p.add_argument("-token", default=os.environ.get("NOMAD_TOKEN", ""),
                   help="ACL token secret (env NOMAD_TOKEN)")
    p.add_argument("-region", default=os.environ.get("NOMAD_REGION", ""),
                   help="target federation region (env NOMAD_REGION)")
    sub = p.add_subparsers(dest="cmd")

    agent = sub.add_parser("agent", help="run the agent")
    agent.add_argument("-dev", action="store_true")
    agent.add_argument("-server", action="store_true")
    agent.add_argument("-client", action="store_true")
    agent.add_argument("-servers", default="",
                       help="server RPC address host:port (client mode)")
    agent.add_argument("-node-name", dest="node_name", default="")
    agent.add_argument("-http-port", dest="http_port", type=int, default=4646)
    agent.add_argument("-rpc-port", dest="rpc_port", type=int, default=4647)
    agent.add_argument("-acl-enabled", dest="acl_enabled",
                       action="store_true")
    agent.add_argument("-server-peers", dest="server_peers", default="",
                       help="comma-separated rpc addrs of ALL servers "
                            "(incl. this one) to form a raft cluster")
    agent.add_argument("-alloc-dir", dest="alloc_dir_base", default="",
                       help="base directory for alloc dirs (fs/logs)")
    agent.add_argument("-cloud-fingerprint", dest="cloud_fingerprint",
                       action="store_true",
                       help="probe AWS/GCE/Azure metadata endpoints "
                            "for platform node attributes")
    # explicit -region on the subparser: without it argparse would
    # abbreviation-match `agent ... -region X` onto -region-peer
    agent.add_argument("-region", default=argparse.SUPPRESS,
                       help="this agent's federation region")
    agent.add_argument("-region-peer", dest="region_peers",
                       action="append", default=None, metavar="NAME=ADDR",
                       help="federation peer agent, repeatable "
                            "(west=10.0.0.5:4646)")
    agent.add_argument("-authoritative-region",
                       dest="authoritative_region", default="",
                       help="region to replicate ACLs/namespaces from")
    agent.add_argument("-replication-token", dest="replication_token",
                       default="", help="ACL token used for replication "
                                        "reads in the authoritative "
                                        "region")
    agent.add_argument("-config", default="",
                       help="HCL agent config file (flags win on merge)")
    agent.add_argument("-clients", type=int, default=1)
    agent.add_argument("-num-schedulers", dest="num_schedulers", type=int,
                       default=2)
    agent.set_defaults(fn=cmd_agent)

    job = sub.add_parser("job", help="job commands").add_subparsers(dest="sub")
    run = job.add_parser("run")
    run.add_argument("jobfile")
    run.add_argument("-detach", action="store_true")
    run.add_argument("-check-index", dest="check_index", type=int,
                     default=None,
                     help="enforce the job's modify index (CAS submit; "
                          "0 = job must not exist)")
    run.add_argument("-var", action="append",
                     help="variable value key=value (repeatable)")
    run.set_defaults(fn=cmd_job_run)
    status = job.add_parser("status")
    status.add_argument("job_id", nargs="?")
    status.set_defaults(fn=cmd_job_status)
    stop = job.add_parser("stop")
    stop.add_argument("job_id")
    stop.add_argument("-purge", action="store_true")
    stop.add_argument("-detach", action="store_true")
    stop.set_defaults(fn=cmd_job_stop)
    init = job.add_parser("init")
    init.add_argument("filename", nargs="?", default="example.nomad")
    init.set_defaults(fn=cmd_job_init)
    revert = job.add_parser("revert")
    revert.add_argument("job_id")
    revert.add_argument("version", type=int)
    revert.add_argument("-detach", action="store_true")
    revert.set_defaults(fn=cmd_job_revert)
    jpromote = job.add_parser("promote")
    jpromote.add_argument("job_id")
    jpromote.set_defaults(fn=cmd_job_promote)
    history = job.add_parser("history")
    history.add_argument("job_id")
    history.set_defaults(fn=cmd_job_history)
    plan = job.add_parser("plan")
    plan.add_argument("jobfile")
    plan.set_defaults(fn=cmd_job_plan)
    jdisp = job.add_parser("dispatch")
    jdisp.add_argument("job_id")
    jdisp.add_argument("-meta", action="append")
    jdisp.add_argument("-payload", default="")
    jdisp.set_defaults(fn=cmd_job_dispatch)
    jinspect = job.add_parser("inspect")
    jinspect.add_argument("job_id")
    jinspect.set_defaults(fn=cmd_job_inspect)
    jvalidate = job.add_parser("validate")
    jvalidate.add_argument("path")
    jvalidate.set_defaults(fn=cmd_job_validate)
    jeval = job.add_parser("eval")
    jeval.add_argument("job_id")
    jeval.set_defaults(fn=cmd_job_eval)
    jpf = job.add_parser("periodic-force")
    jpf.add_argument("job_id")
    jpf.set_defaults(fn=cmd_job_periodic_force)
    jse = job.add_parser("scaling-events")
    jse.add_argument("job_id")
    jse.set_defaults(fn=cmd_job_scaling_events)
    scale = job.add_parser("scale")
    scale.add_argument("job_id")
    scale.add_argument("group")
    scale.add_argument("count", type=int)
    scale.add_argument("-detach", action="store_true")
    scale.set_defaults(fn=cmd_job_scale)

    dep = sub.add_parser("deployment",
                         help="deployment commands").add_subparsers(dest="sub")
    dlist = dep.add_parser("list")
    dlist.set_defaults(fn=cmd_deployment_list)
    dstatus = dep.add_parser("status")
    dstatus.add_argument("deployment_id")
    dstatus.set_defaults(fn=cmd_deployment_status)
    dpromote = dep.add_parser("promote")
    dpromote.add_argument("deployment_id")
    dpromote.add_argument("-group", action="append")
    dpromote.add_argument("-detach", action="store_true")
    dpromote.set_defaults(fn=cmd_deployment_promote)
    dfail = dep.add_parser("fail")
    dfail.add_argument("deployment_id")
    dfail.add_argument("-detach", action="store_true")
    dfail.set_defaults(fn=cmd_deployment_fail)
    dpause = dep.add_parser("pause")
    dpause.add_argument("deployment_id")
    dpause.set_defaults(fn=cmd_deployment_pause, resume=False)
    dresume = dep.add_parser("resume")
    dresume.add_argument("deployment_id")
    dresume.set_defaults(fn=cmd_deployment_pause, resume=True)

    node = sub.add_parser("node", help="node commands").add_subparsers(dest="sub")
    nstatus = node.add_parser("status")
    nstatus.add_argument("node_id", nargs="?")
    nstatus.add_argument("-stats", action="store_true",
                         help="include live host resource usage from "
                              "the client's stats sampler")
    nstatus.set_defaults(fn=cmd_node_status)
    nelig = node.add_parser("eligibility")
    nelig.add_argument("node_id")
    nelig.add_argument("-enable", action="store_true")
    nelig.add_argument("-disable", action="store_true")
    nelig.set_defaults(fn=cmd_node_eligibility)
    ndrain = node.add_parser("drain")
    ndrain.add_argument("node_id")
    ndrain.add_argument("-enable", action="store_true")
    ndrain.add_argument("-disable", action="store_true")
    ndrain.add_argument("-deadline", type=float, default=0.0)
    ndrain.add_argument("-monitor", action="store_true",
                        help="block and report until the drain "
                             "completes")
    ndrain.set_defaults(fn=cmd_node_drain)

    alloc = sub.add_parser("alloc").add_subparsers(dest="sub")
    astatus = alloc.add_parser("status")
    astatus.add_argument("alloc_id")
    astatus.add_argument("-stats", action="store_true",
                         help="include live task-level resource usage")
    astatus.set_defaults(fn=cmd_alloc_status)
    alogs = alloc.add_parser("logs")
    alogs.add_argument("alloc_id")
    alogs.add_argument("task", nargs="?", default="")
    alogs.add_argument("-stderr", action="store_true")
    alogs.set_defaults(fn=cmd_alloc_logs)
    afs = alloc.add_parser("fs")
    afs.add_argument("alloc_id")
    afs.add_argument("path", nargs="?", default="/")
    afs.set_defaults(fn=cmd_alloc_fs)
    aexec = alloc.add_parser("exec")
    aexec.add_argument("-task", default="")
    aexec.add_argument("alloc_id")
    # REMAINDER: flag-bearing commands (`alloc exec <id> ls -l`) must
    # pass through untouched
    aexec.add_argument("cmd", nargs=argparse.REMAINDER)
    aexec.set_defaults(fn=cmd_alloc_exec)
    astop = alloc.add_parser("stop")
    astop.add_argument("alloc_id")
    astop.set_defaults(fn=cmd_alloc_stop)
    arst = alloc.add_parser("restart")
    arst.add_argument("-task", dest="task_opt", default="")
    arst.add_argument("alloc_id")
    arst.add_argument("task", nargs="?", default="")
    arst.set_defaults(fn=cmd_alloc_restart)
    asig = alloc.add_parser("signal")
    asig.add_argument("-s", dest="signal", default="SIGUSR1")
    asig.add_argument("-task", dest="task_opt", default="")
    asig.add_argument("alloc_id")
    asig.add_argument("task", nargs="?", default="")
    asig.set_defaults(fn=cmd_alloc_signal)

    ev = sub.add_parser("eval").add_subparsers(dest="sub")
    estatus = ev.add_parser("status")
    estatus.add_argument("eval_id")
    estatus.set_defaults(fn=cmd_eval_status)
    elist = ev.add_parser("list")
    elist.set_defaults(fn=cmd_eval_list)

    srv = sub.add_parser("server").add_subparsers(dest="sub")
    sinfo = srv.add_parser("info")
    sinfo.set_defaults(fn=cmd_server_info)
    smembers = srv.add_parser("members")
    smembers.set_defaults(fn=cmd_server_members)

    op = sub.add_parser("operator").add_subparsers(dest="sub")
    oraft = op.add_parser("raft-status")
    oraft.set_defaults(fn=cmd_operator_raft)
    odebug = op.add_parser("debug")
    odebug.add_argument("-duration", type=float, default=2.0,
                        help="seconds of interval captures")
    odebug.add_argument("-interval", type=float, default=1.0)
    odebug.add_argument("-output", default="",
                        help="archive path (default "
                             "nomad-debug-<ts>.tar.gz)")
    odebug.set_defaults(fn=cmd_operator_debug)
    ogov = op.add_parser("governor",
                         help="steady-state governor gauges/watermarks")
    ogov.set_defaults(fn=cmd_operator_governor)
    otop = op.add_parser("top",
                         help="live rates/trends from the telemetry "
                              "ring: evals/s, p99 trend, stage "
                              "shares, device economics, flatness")
    otop.add_argument("-n", type=int, default=120,
                      help="history samples to read (default 120)")
    otop.set_defaults(fn=cmd_operator_top)
    otrace = op.add_parser(
        "trace", help="eval flight recorder: span trees, tail "
                      "exemplars, stage percentiles")
    otrace.add_argument("-exemplars", action="store_true",
                        help="only the pinned tail-exemplar set")
    otrace.add_argument("-o", default="", choices=["", "chrome"],
                        help="chrome: trace-event JSON for "
                             "Perfetto/chrome://tracing")
    otrace.add_argument("-n", type=int, default=32,
                        help="recent traces to include (default 32)")
    otrace.add_argument("-output", default="",
                        help="write chrome output to a file instead "
                             "of stdout")
    otrace.set_defaults(fn=cmd_operator_trace)
    osave = op.add_parser("snapshot-save")
    osave.add_argument("file")
    osave.set_defaults(fn=cmd_operator_snapshot_save)
    oinspect = op.add_parser("snapshot-inspect")
    oinspect.add_argument("file")
    oinspect.set_defaults(fn=cmd_operator_snapshot_inspect)
    orestore = op.add_parser("snapshot-restore")
    orestore.add_argument("file")
    orestore.set_defaults(fn=cmd_operator_snapshot_restore)
    oaget = op.add_parser("autopilot-get-config")
    oaget.set_defaults(fn=cmd_operator_autopilot_get)
    oaset = op.add_parser("autopilot-set-config")
    oaset.add_argument("-cleanup-dead-servers",
                       dest="cleanup_dead_servers", default=None)
    oaset.add_argument("-dead-server-cleanup-secs",
                       dest="dead_server_cleanup_secs", type=float,
                       default=None)
    oaset.set_defaults(fn=cmd_operator_autopilot_set)

    scaling = sub.add_parser("scaling").add_subparsers(dest="sub")
    spl = scaling.add_parser("policy-list")
    spl.set_defaults(fn=cmd_scaling_policy_list)
    spi = scaling.add_parser("policy-info")
    spi.add_argument("policy_id")
    spi.set_defaults(fn=cmd_scaling_policy_info)

    ver = sub.add_parser("version")
    ver.set_defaults(fn=cmd_version)

    uip = sub.add_parser("ui", help="print the web UI address")
    uip.set_defaults(fn=cmd_ui)

    st = sub.add_parser("status",
                        help="cross-context id lookup (or job list)")
    st.add_argument("prefix", nargs="?", default="")
    st.set_defaults(fn=cmd_status)

    mon = sub.add_parser("monitor", help="stream agent logs")
    mon.add_argument("-log-level", dest="log_level", default="info")
    mon.set_defaults(fn=cmd_monitor)

    volume = sub.add_parser("volume").add_subparsers(dest="sub")
    vst = volume.add_parser("status")
    vst.add_argument("volume_id", nargs="?", default="")
    vst.add_argument("-namespace", default="default")
    vst.set_defaults(fn=cmd_volume_status)
    vrg = volume.add_parser("register")
    vrg.add_argument("file")
    vrg.add_argument("-namespace", default="default")
    vrg.set_defaults(fn=cmd_volume_register)
    vdr = volume.add_parser("deregister")
    vdr.add_argument("volume_id")
    vdr.add_argument("-force", action="store_true")
    vdr.add_argument("-namespace", default="default")
    vdr.set_defaults(fn=cmd_volume_deregister)

    namespace = sub.add_parser("namespace").add_subparsers(dest="sub")
    nsl = namespace.add_parser("list")
    nsl.set_defaults(fn=cmd_namespace_list)
    nsa = namespace.add_parser("apply")
    nsa.add_argument("name")
    nsa.add_argument("-description", default="")
    nsa.set_defaults(fn=cmd_namespace_apply)
    nsd = namespace.add_parser("delete")
    nsd.add_argument("name")
    nsd.set_defaults(fn=cmd_namespace_delete)
    nss = namespace.add_parser("status")
    nss.add_argument("name")
    nss.set_defaults(fn=cmd_namespace_status)

    service = sub.add_parser("service").add_subparsers(dest="sub")
    svl = service.add_parser("list")
    svl.add_argument("-namespace", default="default")
    svl.set_defaults(fn=cmd_service_list)
    svi = service.add_parser("info")
    svi.add_argument("service_name")
    svi.add_argument("-namespace", default="default")
    svi.set_defaults(fn=cmd_service_info)

    event = sub.add_parser("event").add_subparsers(dest="sub")
    esr = event.add_parser("sink-register")
    esr.add_argument("sink_address")
    esr.add_argument("-id", default="")
    esr.set_defaults(fn=cmd_event_sink_register)
    esl = event.add_parser("sink-list")
    esl.set_defaults(fn=cmd_event_sink_list)
    esd = event.add_parser("sink-deregister")
    esd.add_argument("id")
    esd.set_defaults(fn=cmd_event_sink_deregister)

    metrics_p = sub.add_parser("metrics")
    metrics_p.set_defaults(fn=cmd_metrics)
    ainfo = sub.add_parser("agent-info")
    ainfo.set_defaults(fn=cmd_agent_info)

    system = sub.add_parser("system").add_subparsers(dest="sub")
    sgc = system.add_parser("gc")
    sgc.set_defaults(fn=cmd_system_gc)

    dev = sub.add_parser("dev",
                         help="developer tooling").add_subparsers(
                             dest="sub")
    dlint = dev.add_parser("lint",
                           help="TPU-hygiene static analysis "
                                "(nomad_tpu/analysis)")
    dlint.add_argument("paths", nargs="*",
                       help="files/dirs (default: the package)")
    dlint.add_argument("-json", action="store_true", dest="as_json")
    dlint.add_argument("-show-suppressed", action="store_true",
                       dest="show_suppressed")
    dlint.set_defaults(fn=cmd_dev_lint)
    dchaos = dev.add_parser("chaos",
                            help="scenario matrix + fault injection "
                                 "(nomad_tpu/chaos)")
    dchaos.add_argument("-cell", default="",
                        help="comma-separated cell names (default: "
                             "all quick cells)")
    dchaos.add_argument("-full", action="store_true",
                        help="full-scale cells instead of quick")
    dchaos.add_argument("-seed", type=int, default=None)
    dchaos.add_argument("-list", action="store_true",
                        dest="list_cells")
    dchaos.add_argument("-output", default="",
                        help="artifact path (default CHAOS_rNN.json)")
    dchaos.add_argument("-no-artifact", action="store_true",
                        dest="no_artifact")
    dchaos.set_defaults(fn=cmd_dev_chaos)

    acl = sub.add_parser("acl", help="ACL policies and tokens")
    acl_sub = acl.add_subparsers(dest="acl_cmd", required=True)
    ab = acl_sub.add_parser("bootstrap")
    ab.set_defaults(fn=cmd_acl_bootstrap)
    ap_ = acl_sub.add_parser("policy-apply")
    ap_.add_argument("name")
    ap_.add_argument("file")
    ap_.add_argument("-description", default="")
    ap_.set_defaults(fn=cmd_acl_policy_apply)
    apl = acl_sub.add_parser("policy-list")
    apl.set_defaults(fn=cmd_acl_policy_list)
    atc = acl_sub.add_parser("token-create")
    atc.add_argument("-name", default="")
    atc.add_argument("-type", default="client")
    atc.add_argument("-policy", action="append")
    atc.set_defaults(fn=cmd_acl_token_create)
    atl = acl_sub.add_parser("token-list")
    atl.set_defaults(fn=cmd_acl_token_list)

    return p


def main(argv: Optional[List[str]] = None) -> int:
    parser = build_parser()
    args = parser.parse_args(argv)
    fn = getattr(args, "fn", None)
    if fn is None:
        parser.print_help()
        return 1
    return fn(args)


if __name__ == "__main__":
    sys.exit(main())
