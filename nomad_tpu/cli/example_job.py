"""The `job init` example jobspec (reference: command/assets/example.nomad,
adapted to the drivers available here)."""

EXAMPLE_JOB = '''# An example jobspec. Run it with:
#   python -m nomad_tpu job run example.nomad
job "example" {
  datacenters = ["dc1"]
  type = "service"

  update {
    max_parallel      = 1
    min_healthy_time  = "10s"
    healthy_deadline  = "3m"
    progress_deadline = "10m"
    auto_revert       = false
    canary            = 0
  }

  group "cache" {
    count = 1

    restart {
      attempts = 2
      interval = "30m"
      delay    = "15s"
      mode     = "fail"
    }

    ephemeral_disk {
      size = 300
    }

    task "redis" {
      driver = "mock_driver"

      config {
        run_for = "3600s"
      }

      resources {
        cpu    = 500
        memory = 256

        network {
          mbits = 10
          port "db" {}
        }
      }
    }
  }
}
'''
