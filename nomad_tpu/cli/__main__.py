"""`python -m nomad_tpu.cli` entry point."""
import sys

from .main import main

if __name__ == "__main__":
    sys.exit(main())
