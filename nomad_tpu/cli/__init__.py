from .main import main
