// Native msgpack codec for the RPC wire format.
//
// The reference's hot wire path is a compiled codec (go-msgpack,
// nomad/rpc.go:27 + structs.generated.go codegen); this is the rebuild's
// equivalent: a CPython extension encoding/decoding the msgpack subset
// the RPC layer and WAL use (nil, bool, int, float64, str, bin, array,
// map). Output is standard msgpack, wire-compatible with python-msgpack
// peers in mixed clusters.
//
// Built on demand by nomad_tpu/native/__init__.py (g++ -O2 -shared),
// loaded as the module `nomad_tpu_native_codec`.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

namespace {

// ---------------------------------------------------------------------
// encoder
// ---------------------------------------------------------------------
struct Encoder {
  std::vector<uint8_t> buf;

  void put(uint8_t b) { buf.push_back(b); }
  void put_bytes(const void* p, size_t n) {
    const uint8_t* c = static_cast<const uint8_t*>(p);
    buf.insert(buf.end(), c, c + n);
  }
  void put_be16(uint16_t v) {
    put(v >> 8); put(v & 0xff);
  }
  void put_be32(uint32_t v) {
    put(v >> 24); put((v >> 16) & 0xff); put((v >> 8) & 0xff);
    put(v & 0xff);
  }
  void put_be64(uint64_t v) {
    for (int s = 56; s >= 0; s -= 8) put((v >> s) & 0xff);
  }

  bool encode(PyObject* obj);

  bool encode_long(PyObject* obj) {
    int overflow = 0;
    long long v = PyLong_AsLongLongAndOverflow(obj, &overflow);
    if (overflow > 0) {
      unsigned long long u = PyLong_AsUnsignedLongLong(obj);
      if (PyErr_Occurred()) return false;
      put(0xcf); put_be64(u);
      return true;
    }
    if (overflow < 0) {
      PyErr_SetString(PyExc_OverflowError, "int too small for msgpack");
      return false;
    }
    if (v >= 0) {
      if (v < 0x80) { put(static_cast<uint8_t>(v)); }
      else if (v <= 0xff) { put(0xcc); put(static_cast<uint8_t>(v)); }
      else if (v <= 0xffff) { put(0xcd); put_be16(v); }
      else if (v <= 0xffffffffLL) { put(0xce); put_be32(v); }
      else { put(0xcf); put_be64(v); }
    } else {
      if (v >= -32) { put(static_cast<uint8_t>(v)); }
      else if (v >= -128) { put(0xd0); put(static_cast<uint8_t>(v)); }
      else if (v >= -32768) { put(0xd1); put_be16(static_cast<uint16_t>(v)); }
      else if (v >= -2147483648LL) {
        put(0xd2); put_be32(static_cast<uint32_t>(v));
      } else { put(0xd3); put_be64(static_cast<uint64_t>(v)); }
    }
    return true;
  }

  bool encode_str(PyObject* obj) {
    Py_ssize_t n = 0;
    const char* s = PyUnicode_AsUTF8AndSize(obj, &n);
    if (s == nullptr) return false;
    if (n < 32) put(0xa0 | static_cast<uint8_t>(n));
    else if (n <= 0xff) { put(0xd9); put(static_cast<uint8_t>(n)); }
    else if (n <= 0xffff) { put(0xda); put_be16(n); }
    else { put(0xdb); put_be32(n); }
    put_bytes(s, n);
    return true;
  }

  bool encode_bin(const char* p, Py_ssize_t n) {
    if (n <= 0xff) { put(0xc4); put(static_cast<uint8_t>(n)); }
    else if (n <= 0xffff) { put(0xc5); put_be16(n); }
    else { put(0xc6); put_be32(n); }
    put_bytes(p, n);
    return true;
  }

  bool encode_array_header(Py_ssize_t n) {
    if (n < 16) put(0x90 | static_cast<uint8_t>(n));
    else if (n <= 0xffff) { put(0xdc); put_be16(n); }
    else { put(0xdd); put_be32(n); }
    return true;
  }
};

bool Encoder::encode(PyObject* obj) {
  if (obj == Py_None) { put(0xc0); return true; }
  if (obj == Py_True) { put(0xc3); return true; }
  if (obj == Py_False) { put(0xc2); return true; }
  if (PyLong_CheckExact(obj)) return encode_long(obj);
  if (PyFloat_CheckExact(obj)) {
    double d = PyFloat_AS_DOUBLE(obj);
    uint64_t bits;
    std::memcpy(&bits, &d, 8);
    put(0xcb); put_be64(bits);
    return true;
  }
  if (PyUnicode_CheckExact(obj)) return encode_str(obj);
  if (PyBytes_CheckExact(obj))
    return encode_bin(PyBytes_AS_STRING(obj), PyBytes_GET_SIZE(obj));
  if (PyByteArray_CheckExact(obj))
    return encode_bin(PyByteArray_AS_STRING(obj),
                      PyByteArray_GET_SIZE(obj));
  if (PyList_CheckExact(obj)) {
    Py_ssize_t n = PyList_GET_SIZE(obj);
    encode_array_header(n);
    for (Py_ssize_t i = 0; i < n; i++)
      if (!encode(PyList_GET_ITEM(obj, i))) return false;
    return true;
  }
  if (PyTuple_CheckExact(obj)) {
    Py_ssize_t n = PyTuple_GET_SIZE(obj);
    encode_array_header(n);
    for (Py_ssize_t i = 0; i < n; i++)
      if (!encode(PyTuple_GET_ITEM(obj, i))) return false;
    return true;
  }
  if (PyDict_CheckExact(obj)) {
    Py_ssize_t n = PyDict_GET_SIZE(obj);
    if (n < 16) put(0x80 | static_cast<uint8_t>(n));
    else if (n <= 0xffff) { put(0xde); put_be16(n); }
    else { put(0xdf); put_be32(n); }
    PyObject *key, *value;
    Py_ssize_t pos = 0;
    while (PyDict_Next(obj, &pos, &key, &value)) {
      if (!encode(key)) return false;
      if (!encode(value)) return false;
    }
    return true;
  }
  // fall back: bools subclass int etc.
  if (PyBool_Check(obj)) { put(obj == Py_True ? 0xc3 : 0xc2); return true; }
  if (PyLong_Check(obj)) return encode_long(obj);
  if (PyUnicode_Check(obj)) return encode_str(obj);
  PyErr_Format(PyExc_TypeError, "cannot msgpack-encode %s",
               Py_TYPE(obj)->tp_name);
  return false;
}

// ---------------------------------------------------------------------
// decoder
// ---------------------------------------------------------------------
// wire hardening (python-msgpack enforces the same class of limits):
// bounded recursion so a crafted deeply-nested frame cannot overflow
// the C stack, and container headers validated against the remaining
// bytes before allocation so a 4-byte header cannot force a multi-GB
// PyList_New.
static const int kMaxDepth = 512;

struct Decoder {
  const uint8_t* p;
  const uint8_t* end;
  int depth = 0;

  bool need(size_t n) {
    if (static_cast<size_t>(end - p) < n) {
      PyErr_SetString(PyExc_ValueError, "msgpack: truncated input");
      return false;
    }
    return true;
  }
  // every element needs >=1 encoded byte; reject headers promising
  // more elements than bytes remain (mult = min bytes per element)
  bool plausible(size_t n, size_t mult) {
    if (n > static_cast<size_t>(end - p) / mult + 1) {
      PyErr_SetString(PyExc_ValueError,
                      "msgpack: container length exceeds input");
      return false;
    }
    return true;
  }
  uint64_t be(size_t n) {
    uint64_t v = 0;
    for (size_t i = 0; i < n; i++) v = (v << 8) | p[i];
    p += n;
    return v;
  }

  PyObject* decode();

  PyObject* decode_str(size_t n) {
    if (!need(n)) return nullptr;
    PyObject* s = PyUnicode_DecodeUTF8(
        reinterpret_cast<const char*>(p), n, "replace");
    p += n;
    return s;
  }
  PyObject* decode_bin(size_t n) {
    if (!need(n)) return nullptr;
    PyObject* b = PyBytes_FromStringAndSize(
        reinterpret_cast<const char*>(p), n);
    p += n;
    return b;
  }
  PyObject* decode_array(size_t n) {
    if (!plausible(n, 1)) return nullptr;
    PyObject* lst = PyList_New(n);
    if (!lst) return nullptr;
    for (size_t i = 0; i < n; i++) {
      PyObject* item = decode();
      if (!item) { Py_DECREF(lst); return nullptr; }
      PyList_SET_ITEM(lst, i, item);
    }
    return lst;
  }
  PyObject* decode_map(size_t n) {
    if (!plausible(n, 2)) return nullptr;   // key + value per entry
    PyObject* d = PyDict_New();
    if (!d) return nullptr;
    for (size_t i = 0; i < n; i++) {
      PyObject* k = decode();
      if (!k) { Py_DECREF(d); return nullptr; }
      PyObject* v = decode();
      if (!v) { Py_DECREF(k); Py_DECREF(d); return nullptr; }
      PyDict_SetItem(d, k, v);
      Py_DECREF(k);
      Py_DECREF(v);
    }
    return d;
  }
};

struct DepthGuard {
  int& d;
  explicit DepthGuard(int& depth) : d(depth) { d++; }
  ~DepthGuard() { d--; }
};

PyObject* Decoder::decode() {
  if (depth >= kMaxDepth) {
    PyErr_SetString(PyExc_ValueError, "msgpack: nesting too deep");
    return nullptr;
  }
  DepthGuard guard(depth);
  if (!need(1)) return nullptr;
  uint8_t tag = *p++;
  if (tag < 0x80) return PyLong_FromLong(tag);
  if (tag >= 0xe0) return PyLong_FromLong(static_cast<int8_t>(tag));
  if ((tag & 0xf0) == 0x80) return decode_map(tag & 0x0f);
  if ((tag & 0xf0) == 0x90) return decode_array(tag & 0x0f);
  if ((tag & 0xe0) == 0xa0) return decode_str(tag & 0x1f);
  switch (tag) {
    case 0xc0: Py_RETURN_NONE;
    case 0xc2: Py_RETURN_FALSE;
    case 0xc3: Py_RETURN_TRUE;
    case 0xc4: if (!need(1)) return nullptr; return decode_bin(be(1));
    case 0xc5: if (!need(2)) return nullptr; return decode_bin(be(2));
    case 0xc6: if (!need(4)) return nullptr; return decode_bin(be(4));
    case 0xca: {
      if (!need(4)) return nullptr;
      uint32_t bits = static_cast<uint32_t>(be(4));
      float f;
      std::memcpy(&f, &bits, 4);
      return PyFloat_FromDouble(f);
    }
    case 0xcb: {
      if (!need(8)) return nullptr;
      uint64_t bits = be(8);
      double d;
      std::memcpy(&d, &bits, 8);
      return PyFloat_FromDouble(d);
    }
    case 0xcc: if (!need(1)) return nullptr; return PyLong_FromUnsignedLongLong(be(1));
    case 0xcd: if (!need(2)) return nullptr; return PyLong_FromUnsignedLongLong(be(2));
    case 0xce: if (!need(4)) return nullptr; return PyLong_FromUnsignedLongLong(be(4));
    case 0xcf: if (!need(8)) return nullptr; return PyLong_FromUnsignedLongLong(be(8));
    case 0xd0: if (!need(1)) return nullptr; return PyLong_FromLongLong(static_cast<int8_t>(be(1)));
    case 0xd1: if (!need(2)) return nullptr; return PyLong_FromLongLong(static_cast<int16_t>(be(2)));
    case 0xd2: if (!need(4)) return nullptr; return PyLong_FromLongLong(static_cast<int32_t>(be(4)));
    case 0xd3: if (!need(8)) return nullptr; return PyLong_FromLongLong(static_cast<int64_t>(be(8)));
    case 0xd9: if (!need(1)) return nullptr; return decode_str(be(1));
    case 0xda: if (!need(2)) return nullptr; return decode_str(be(2));
    case 0xdb: if (!need(4)) return nullptr; return decode_str(be(4));
    case 0xdc: if (!need(2)) return nullptr; return decode_array(be(2));
    case 0xdd: if (!need(4)) return nullptr; return decode_array(be(4));
    case 0xde: if (!need(2)) return nullptr; return decode_map(be(2));
    case 0xdf: if (!need(4)) return nullptr; return decode_map(be(4));
  }
  PyErr_Format(PyExc_ValueError, "msgpack: unsupported tag 0x%02x", tag);
  return nullptr;
}

// ---------------------------------------------------------------------
// module
// ---------------------------------------------------------------------
PyObject* py_packb(PyObject*, PyObject* arg) {
  Encoder enc;
  enc.buf.reserve(256);
  if (!enc.encode(arg)) return nullptr;
  return PyBytes_FromStringAndSize(
      reinterpret_cast<const char*>(enc.buf.data()), enc.buf.size());
}

PyObject* py_unpackb(PyObject*, PyObject* arg) {
  Py_buffer view;
  if (PyObject_GetBuffer(arg, &view, PyBUF_SIMPLE) != 0) return nullptr;
  Decoder dec;
  dec.p = static_cast<const uint8_t*>(view.buf);
  dec.end = dec.p + view.len;
  PyObject* out = dec.decode();
  if (out != nullptr && dec.p != dec.end) {
    Py_DECREF(out);
    out = nullptr;
    PyErr_SetString(PyExc_ValueError, "msgpack: trailing bytes");
  }
  PyBuffer_Release(&view);
  return out;
}

PyMethodDef methods[] = {
    {"packb", py_packb, METH_O, "Encode a value tree to msgpack bytes"},
    {"unpackb", py_unpackb, METH_O, "Decode msgpack bytes"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "nomad_tpu_native_codec",
    "Native msgpack codec for the RPC wire format", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

extern "C" PyMODINIT_FUNC PyInit_nomad_tpu_native_codec(void) {
  return PyModule_Create(&moduledef);
}
