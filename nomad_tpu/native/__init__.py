"""Native runtime components.

The reference keeps its wire codec compiled (go-msgpack + generated
encoders); here codec.cpp is a CPython extension built on demand with
g++ and loaded as `nomad_tpu_native_codec`. The build is cached beside
the source keyed by source hash + python ABI; failures fall back to the
pure-python msgpack package transparently (the wire format is
identical, so mixed clusters interoperate).

NOMAD_TPU_NATIVE=0 disables the native path.
"""

from __future__ import annotations

import hashlib
import logging
import os
import subprocess
import sys
import sysconfig
from typing import Optional

LOG = logging.getLogger("nomad_tpu.native")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "codec.cpp")
_loaded = None
_attempted = False


def _cache_path(src: str, name: str) -> str:
    with open(src, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    abi = sysconfig.get_config_var("SOABI") or "abi3"
    cache_dir = os.environ.get(
        "NOMAD_TPU_NATIVE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "nomad-tpu"))
    os.makedirs(cache_dir, exist_ok=True)
    return os.path.join(cache_dir, f"{name}-{digest}.{abi}.so")


def _build(src: str, so_path: str) -> bool:
    include = sysconfig.get_path("include")
    cmd = ["g++", "-O2", "-fPIC", "-shared", "-std=c++17",
           f"-I{include}", src, "-o", so_path + ".tmp"]
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=120)
    except (OSError, subprocess.TimeoutExpired) as e:
        LOG.warning("native build failed to run: %s", e)
        return False
    if out.returncode != 0:
        LOG.warning("native build failed:\n%s", out.stderr[-2000:])
        return False
    os.replace(so_path + ".tmp", so_path)
    return True


def _load_module(src: str, name: str):
    """Build (cached by source hash) and import one native module, or
    None on any failure — callers keep their pure-python fallback."""
    if os.environ.get("NOMAD_TPU_NATIVE", "1") == "0":
        return None
    so = _cache_path(src, name)
    if not os.path.exists(so) and not _build(src, so):
        return None
    import importlib.util
    spec = importlib.util.spec_from_file_location(name, so)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_codec():
    """Returns the native codec module, or None (with msgpack fallback
    left to the caller)."""
    global _loaded, _attempted
    if _loaded is not None or _attempted:
        return _loaded
    _attempted = True
    try:
        mod = _load_module(_SRC, "nomad_tpu_native_codec")
        if mod is None:
            return None
        # self-check before trusting it on the wire
        probe = {"a": [1, -7, 2.5, "x", b"\x00\xff", None, True],
                 "nested": {"k": [list(range(40))]}}
        import msgpack
        if msgpack.unpackb(mod.packb(probe), raw=False) != probe or \
                mod.unpackb(msgpack.packb(probe, use_bin_type=True)) \
                != probe:
            LOG.warning("native codec self-check failed; falling back")
            return None
        _loaded = mod
        return mod
    except Exception as e:       # pragma: no cover — env-dependent
        LOG.warning("native codec unavailable: %s", e)
        return None


_kway_loaded = None
_kway_attempted = False


def load_kway():
    """The native k-way stream merge (kway.cpp) used by the placement
    kernel's host expansion, or None (python-heap fallback)."""
    global _kway_loaded, _kway_attempted
    if _kway_loaded is not None or _kway_attempted:
        return _kway_loaded
    _kway_attempted = True
    try:
        mod = _load_module(os.path.join(_HERE, "kway.cpp"),
                           "nomad_tpu_native_kway")
        if mod is None:
            return None
        # self-check: two streams, scores [3,1] on node 5 and [2,4] on
        # node 9 -> pop order (row,j): (0,0) s=3, (1,0) s=2 ... heads
        # compared, stream 1 advances to 4 -> (1,1), then (0,1)
        import struct
        scores = struct.pack("4f", 3.0, 1.0, 2.0, 4.0)
        nodes = struct.pack("2i", 5, 9)
        lens = struct.pack("2i", 2, 2)
        out = mod.merge(scores, nodes, lens, 2, 100)
        got = struct.unpack("8i", out)
        if got != (0, 1, 1, 0, 0, 0, 1, 1):
            LOG.warning("native kway self-check failed; falling back "
                        "(%r)", got)
            return None
        _kway_loaded = mod
        return mod
    except Exception as e:       # pragma: no cover — env-dependent
        LOG.warning("native kway unavailable: %s", e)
        return None
