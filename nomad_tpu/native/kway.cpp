// Native k-way stream merge for the placement kernel's host expansion.
//
// The K-way device kernel (ops/select.py _select_kway*) returns per-phase
// winner chunks; the host reconstructs the exact greedy per-instance
// order by merging the winners' score streams: pop the stream whose
// CURRENT head has the max score (ties -> lowest node id), then advance
// that stream (streams are not monotonic — binpack scores rise as a node
// fills — so this is a streaming merge, not a sort). In Python this heap
// loop costs ~3-5us per instance and dominates multi-batch expansion;
// here it is a std::priority_queue over raw float32 rows.
//
// merge(scores: buffer f32[W*max_m], nodes: buffer i32[W],
//       lens: buffer i32[W], max_m: int, limit: int) -> bytes
// Returns int32[2*P]: P winner-row indexes then P stream positions,
// P = min(sum(lens), limit).

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <queue>
#include <vector>

namespace {

struct Head {
    float score;
    int32_t node;
    int32_t row;
    int32_t j;
};

struct HeadLess {
    // priority_queue keeps the LARGEST by this order on top:
    // max score first, then lowest node id
    bool operator()(const Head &a, const Head &b) const {
        if (a.score != b.score) return a.score < b.score;
        return a.node > b.node;
    }
};

PyObject *merge(PyObject *, PyObject *args) {
    Py_buffer scores_b, nodes_b, lens_b;
    Py_ssize_t max_m, limit;
    if (!PyArg_ParseTuple(args, "y*y*y*nn", &scores_b, &nodes_b, &lens_b,
                          &max_m, &limit)) {
        return nullptr;
    }
    const float *scores = static_cast<const float *>(scores_b.buf);
    const int32_t *nodes = static_cast<const int32_t *>(nodes_b.buf);
    const int32_t *lens = static_cast<const int32_t *>(lens_b.buf);
    const Py_ssize_t w = nodes_b.len / static_cast<Py_ssize_t>(sizeof(int32_t));

    // mutually-consistent buffers or a clean ValueError — a silent
    // overread would corrupt placement order or crash the scheduler
    if (lens_b.len != nodes_b.len ||
        scores_b.len < static_cast<Py_ssize_t>(w * max_m * sizeof(float))) {
        PyBuffer_Release(&scores_b);
        PyBuffer_Release(&nodes_b);
        PyBuffer_Release(&lens_b);
        PyErr_SetString(PyExc_ValueError, "kway.merge: buffer size mismatch");
        return nullptr;
    }
    Py_ssize_t total = 0;
    for (Py_ssize_t k = 0; k < w; k++) {
        if (lens[k] < 0 || lens[k] > max_m) {
            PyBuffer_Release(&scores_b);
            PyBuffer_Release(&nodes_b);
            PyBuffer_Release(&lens_b);
            PyErr_SetString(PyExc_ValueError, "kway.merge: len out of range");
            return nullptr;
        }
        total += lens[k];
    }
    if (total > limit) total = limit;
    if (total < 0) total = 0;

    PyObject *out = PyBytes_FromStringAndSize(
        nullptr, static_cast<Py_ssize_t>(2 * total * sizeof(int32_t)));
    if (out == nullptr) {
        PyBuffer_Release(&scores_b);
        PyBuffer_Release(&nodes_b);
        PyBuffer_Release(&lens_b);
        return nullptr;
    }
    int32_t *ok = reinterpret_cast<int32_t *>(PyBytes_AS_STRING(out));
    int32_t *oj = ok + total;

    std::priority_queue<Head, std::vector<Head>, HeadLess> heap;
    for (Py_ssize_t k = 0; k < w; k++) {
        if (lens[k] > 0) {
            heap.push(Head{scores[k * max_m], nodes[k],
                           static_cast<int32_t>(k), 0});
        }
    }
    Py_ssize_t pos = 0;
    while (!heap.empty() && pos < total) {
        Head h = heap.top();
        heap.pop();
        ok[pos] = h.row;
        oj[pos] = h.j;
        pos++;
        int32_t nj = h.j + 1;
        if (nj < lens[h.row]) {
            heap.push(Head{scores[h.row * max_m + nj], h.node, h.row, nj});
        }
    }

    PyBuffer_Release(&scores_b);
    PyBuffer_Release(&nodes_b);
    PyBuffer_Release(&lens_b);
    return out;
}

PyMethodDef methods[] = {
    {"merge", merge, METH_VARARGS,
     "k-way greedy stream merge -> int32 (rows, positions) bytes"},
    {nullptr, nullptr, 0, nullptr},
};

PyModuleDef module = {
    PyModuleDef_HEAD_INIT, "nomad_tpu_native_kway",
    "native k-way stream merge", -1, methods,
    nullptr, nullptr, nullptr, nullptr,
};

}  // namespace

PyMODINIT_FUNC PyInit_nomad_tpu_native_kway(void) {
    return PyModule_Create(&module);
}
