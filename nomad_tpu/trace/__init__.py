"""Eval flight recorder (ISSUE 9): always-on, bounded-ring per-eval
span tracing with tail exemplars and Chrome/Perfetto export. See
tracer.py for the design; `tracer` is the process-wide recorder the
server configures and the kernels/gateways report into."""

from .tracer import (AMBIENT_STAGES, STAGE_PARENTS, EvalTrace, Tracer,
                     begin, current, current_all, emit, emit_kernel,
                     finish, to_chrome, tracer, use, use_many)

__all__ = [
    "AMBIENT_STAGES", "STAGE_PARENTS", "EvalTrace", "Tracer", "begin",
    "current", "current_all", "emit", "emit_kernel", "finish",
    "to_chrome", "tracer", "use", "use_many",
]
