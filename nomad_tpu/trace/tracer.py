"""Eval flight recorder: always-on per-eval span tracing (ISSUE 9).

Every ROADMAP validation item is a "re-run on real TPU and confirm X"
task, but the only attribution surfaces were aggregate sums
(`utils/stages.py` stage_breakdown) and governor gauges — neither can
answer *why a specific p99 eval was slow* (gateway park? group-commit
conflict retry? cold table rebuild? fresh XLA trace?). This module is
the Dapper-style answer: one span tree per eval, always on, cheap
enough for the C2M soak.

  EvalTrace   one eval's span tree: broker enqueue -> dequeue
              (queue_wait) -> gateway park/fire (batch id + lanes +
              trigger) -> reconcile -> kernel dispatch (arm, n_pad,
              fresh-trace flag) -> plan verify (group size, conflict /
              demotion) -> group commit -> broker ack. Spans are plain
              dicts (JSON-ready); the tree is encoded by a static
              parent map (sched_host wraps the per-dispatch stages,
              everything else hangs off the eval root).
  Tracer      the per-server recorder: a byte-bounded ring of
              completed traces (`trace_ring_bytes`), a pinned
              tail-exemplar set (`trace_exemplar_slots`, promotion at
              `trace_exemplar_threshold_pct` percent of the
              governor-tracked full-latency p99), and per-stage
              duration reservoirs behind stage_percentiles() — the
              p50/p95/p99 breakdown the bench artifact records.

Collection paths:

  ambient     utils/stages.py report sites forward every (stage,
              seconds) through set_trace_hook — the aggregate sums
              stay identical, and sites that run on the EVAL's own
              thread (reconcile, table_build, h2d, d2h, sched_host,
              broker_ack) land as spans on the thread-local current
              trace(s). The hook also feeds the percentile
              reservoirs for EVERY stage, traced context or not.
  explicit    sites where thread-local attribution is wrong or
              attribute-rich get their own emit calls: the gateway
              records each parked request's wait onto the request's
              CAPTURED trace (the firing thread is some other eval),
              the dispatch cost model fans the kernel span out to
              every lane of a batched fire, and the plan applier /
              committer attach verify/commit spans to the trace the
              submitting worker stamped onto the plan.

Exports three ways: `/v1/operator/trace` (JSON), `nomad operator
trace [-exemplars] [-o chrome]` (Chrome trace-event JSON, loadable in
Perfetto/chrome://tracing — one track per worker / gateway / applier
so overlap is visible), and the `operator debug` capture bundle.

`NOMAD_TPU_TRACE=0` is the kill switch: begin() returns None, the
stages hook disarms, and the report sites degenerate to the pre-trace
one-bool-read cost.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Tuple

from ..utils import stages
from ..utils.locks import make_lock

TRACE_ENV = "NOMAD_TPU_TRACE"

DEFAULT_RING_BYTES = 4 << 20
DEFAULT_EXEMPLAR_SLOTS = 8
DEFAULT_THRESHOLD_PCT = 100.0

# ring accounting is an ESTIMATE (sizing every dict would cost more
# than the spans being sized): per-trace overhead + per-span cost,
# calibrated generously so the configured byte budget is a ceiling
TRACE_EST_BYTES = 256
SPAN_EST_BYTES = 176
# a runaway eval (retry loop) must not grow one trace without bound
MAX_SPANS_PER_TRACE = 512
# per-stage duration reservoir behind stage_percentiles()
STAGE_RESERVOIR = 2048
# the tracer's own full-latency reservoir: the promotion fallback when
# no governor threshold_fn is wired (standalone benches, tests);
# its p99 is re-sorted only every OWN_P99_EVERY completions
OWN_LATENCY_RESERVOIR = 512
OWN_P99_EVERY = 32

# static span-tree encoding: the per-dispatch stages nest inside the
# scheduler's Process() window, everything else hangs off the eval
# root — deterministic (testable) without runtime stack bookkeeping
STAGE_PARENTS: Dict[str, Optional[str]] = {
    "queue_wait": "eval", "gateway_wait": "sched_host",
    "reconcile": "sched_host", "preempt": "sched_host",
    "table_build": "sched_host",
    "h2d": "sched_host", "kernel": "sched_host", "d2h": "sched_host",
    "sched_host": "eval", "plan_verify": "eval", "plan_commit": "eval",
    "broker_ack": "eval", "restore": None, "wal_replay": None,
}

# stages whose report site runs on the eval's OWN thread, so the
# thread-local context attributes them correctly. The rest (kernel,
# gateway_wait, plan_verify, plan_commit, queue_wait) report from
# other threads or need per-request attrs and use the explicit
# emitters below instead — the ambient hook emitting them too would
# double-count or mis-attribute them.
AMBIENT_STAGES = frozenset({
    "restore", "wal_replay", "table_build", "h2d", "d2h",
    "reconcile", "preempt", "sched_host", "broker_ack",
})


def _env_enabled() -> bool:
    return os.environ.get(TRACE_ENV, "1") not in ("0", "off", "no")


# -- thread-local span context ----------------------------------------
# holds (traces tuple, track override): the worker loop installs its
# eval's trace around Process(); the gateway installs the UNION of a
# batched fire's lane traces (with track "gateway") so shared device
# spans fan out to every lane
_tls = threading.local()


def current_all() -> Tuple:
    return getattr(_tls, "ctx", ((), None))[0]


def current():
    traces = current_all()
    return traces[0] if traces else None


def _ctx() -> Tuple[Tuple, Optional[str]]:
    return getattr(_tls, "ctx", ((), None))


@contextmanager
def use(trace, track: Optional[str] = None):
    """Install one trace (or None for a no-op) as this thread's span
    context for the duration of the block."""
    with use_many((trace,) if trace is not None else (), track):
        yield


@contextmanager
def use_many(traces, track: Optional[str] = None):
    prev = getattr(_tls, "ctx", ((), None))
    _tls.ctx = (tuple(traces), track)
    try:
        yield
    finally:
        _tls.ctx = prev


class EvalTrace:
    """One eval's span tree. Span appends are lock-free (CPython list
    append is atomic) because concurrent emitters (worker thread,
    gateway firing thread, applier, committer) only ever append."""

    __slots__ = ("eval_id", "job_id", "namespace", "eval_type", "track",
                 "wall0", "mono0", "spans", "total_ms", "status",
                 "gauges", "truncated")

    def __init__(self, eval_id: str, job_id: str, namespace: str,
                 eval_type: str, track: str, mono0: float, wall0: float):
        self.eval_id = eval_id
        self.job_id = job_id
        self.namespace = namespace
        self.eval_type = eval_type
        self.track = track
        self.mono0 = mono0          # monotonic anchor (broker enqueue)
        self.wall0 = wall0          # wall anchor for export timestamps
        self.spans: List[dict] = []
        self.total_ms = 0.0
        self.status = "open"
        self.gauges: Optional[dict] = None   # set on exemplar promotion
        self.truncated = 0

    def add_span(self, name: str, dur_s: float,
                 end_mono: Optional[float] = None,
                 track: Optional[str] = None,
                 attrs: Optional[dict] = None) -> None:
        if len(self.spans) >= MAX_SPANS_PER_TRACE:
            self.truncated += 1
            return
        end = time.monotonic() if end_mono is None else end_mono
        t0 = max(0.0, (end - max(dur_s, 0.0)) - self.mono0)
        span = {"name": name, "t0_ms": round(t0 * 1000.0, 3),
                "dur_ms": round(max(dur_s, 0.0) * 1000.0, 3),
                "track": track or self.track,
                "parent": STAGE_PARENTS.get(name, "eval")}
        if attrs:
            span["attrs"] = attrs
        self.spans.append(span)
        tracer.stats["spans"] += 1      # racy inc; stats, not billing

    def to_dict(self) -> dict:
        out = {"eval_id": self.eval_id, "job_id": self.job_id,
               "namespace": self.namespace, "type": self.eval_type,
               "track": self.track, "start": round(self.wall0, 6),
               "total_ms": round(self.total_ms, 3),
               "status": self.status, "spans": list(self.spans)}
        if self.gauges is not None:
            out["gauges"] = self.gauges
        if self.truncated:
            out["truncated_spans"] = self.truncated
        return out

    def est_bytes(self) -> int:
        return TRACE_EST_BYTES + SPAN_EST_BYTES * len(self.spans)


class Tracer:
    """The flight recorder: bounded ring + pinned exemplars + stage
    percentile reservoirs. One module-global instance (`tracer`) is
    shared the way stages/GROUP_STATS are — kernels and gateways have
    no server handle — and each Server configures it from its
    ServerConfig knobs and wires threshold_fn/gauge_fn to its
    governor."""

    def __init__(self, ring_bytes: int = DEFAULT_RING_BYTES,
                 exemplar_slots: int = DEFAULT_EXEMPLAR_SLOTS,
                 threshold_pct: float = DEFAULT_THRESHOLD_PCT):
        self._l = make_lock()
        self.ring_bytes = int(ring_bytes)
        self.exemplar_slots = int(exemplar_slots)
        self.threshold_pct = float(threshold_pct)
        # adaptive promotion threshold: the governor's FULL-latency
        # p99 (queue wait included — what the eval experienced); the
        # tracer's own reservoir is the standalone fallback
        self.threshold_fn: Optional[Callable[[], float]] = None
        # compact governor gauge snapshot captured onto each exemplar
        # at completion (the anatomy plus the weather it happened in)
        self.gauge_fn: Optional[Callable[[], dict]] = None
        # tests pin the threshold to a known value (0.0 == promote all)
        self.force_threshold_ms: Optional[float] = None
        self._enabled = _env_enabled()
        self._ring: deque = deque()             # (trace, est_bytes)
        self._ring_used = 0
        # rolling worst-K tail set; a pin MOVES entries to _pinned
        # (bounded) so the rolling slots stay open — one drift event
        # must never blind the recorder to every later tail eval
        self._exemplars: List[dict] = []        # {trace, pinned, reason}
        self._pinned: List[dict] = []
        self._own_lat: deque = deque(maxlen=OWN_LATENCY_RESERVOIR)
        # cached fallback p99 over _own_lat, recomputed every
        # OWN_P99_EVERY completions: sorting the 512-entry reservoir
        # on EVERY finish() was measurable against millisecond evals
        # (the promotion threshold tolerates a slightly stale p99)
        self._own_p99 = 0.0
        self._own_since_p99 = 0
        self._stage_res: Dict[str, deque] = {}
        self._stage_l = make_lock()
        self.stats = {"traces": 0, "spans": 0, "dropped": 0,
                      "exemplar_promotions": 0, "exemplar_pins": 0}

    # -- lifecycle -----------------------------------------------------
    def enabled(self) -> bool:
        return self._enabled

    def set_enabled(self, on: bool) -> None:
        self._enabled = bool(on)
        stages.set_trace_hook(self._on_stage, on=self._enabled)

    def refresh(self) -> None:
        """Re-read the NOMAD_TPU_TRACE kill switch (tests/operators
        toggle the env var; Server construction calls this)."""
        self.set_enabled(_env_enabled())

    def configure(self, ring_bytes: Optional[int] = None,
                  exemplar_slots: Optional[int] = None,
                  threshold_pct: Optional[float] = None) -> None:
        if ring_bytes is not None:
            self.ring_bytes = int(ring_bytes)
        if exemplar_slots is not None:
            self.exemplar_slots = int(exemplar_slots)
        if threshold_pct is not None:
            self.threshold_pct = float(threshold_pct)
        self.refresh()

    def reset(self) -> None:
        """Forget recorded state (tests); configuration survives."""
        with self._l:
            self._ring.clear()
            self._ring_used = 0
            self._exemplars = []
            self._pinned = []
            self._own_lat.clear()
            self._own_p99 = 0.0
            self._own_since_p99 = 0
        with self._stage_l:
            self._stage_res.clear()
        for k in self.stats:
            self.stats[k] = 0
        self.threshold_fn = None
        self.gauge_fn = None
        self.force_threshold_ms = None

    # -- recording -----------------------------------------------------
    def begin(self, ev, track: str) -> Optional[EvalTrace]:
        """Open a trace for a dequeued eval. The anchor is BACKDATED
        to broker enqueue (ev.broker_wait_s covers blocked/delayed
        heap time too, ev.queue_wait_s the READY-queue slice), so the
        root span is the full enqueue->ack latency and the queue_wait
        span is visible even though nothing ran yet."""
        if not self._enabled or not _env_enabled():
            return None
        now = time.monotonic()
        qw = max(float(getattr(ev, "queue_wait_s", 0.0) or 0.0), 0.0)
        bw = max(float(getattr(ev, "broker_wait_s", qw) or 0.0), qw)
        tr = EvalTrace(
            eval_id=getattr(ev, "id", ""),
            job_id=getattr(ev, "job_id", ""),
            namespace=getattr(ev, "namespace", ""),
            eval_type=getattr(ev, "type", ""),
            track=track, mono0=now - bw, wall0=time.time() - bw)
        attrs = {"ready_ms": round(qw * 1000.0, 3)}
        if bw > qw + 1e-9:
            # time parked on the per-job blocked / delayed heaps
            # before the eval even became READY
            attrs["held_ms"] = round((bw - qw) * 1000.0, 3)
        tr.add_span("queue_wait", bw, end_mono=now, track="broker",
                    attrs=attrs)
        return tr

    def finish(self, tr: Optional[EvalTrace],
               status: str = "acked") -> None:
        """Close and record a trace. Defensive end to end: tracing
        runs inside the worker's ack path, and a recorder bug must
        fail a span, never an eval."""
        if tr is None:
            return
        try:
            tr.total_ms = max(time.monotonic() - tr.mono0, 0.0) * 1000.0
            tr.status = status
            with self._l:
                # the reservoir lock matters: list() elsewhere
                # iterates this deque, and CPython raises on
                # iterate-during-append
                self._own_lat.append(tr.total_ms)
                self._own_since_p99 += 1
                if len(self._own_lat) >= 16 and (
                        self._own_since_p99 >= OWN_P99_EVERY
                        or self._own_p99 <= 0.0):
                    self._own_since_p99 = 0
                    lat = sorted(self._own_lat)
                    self._own_p99 = lat[min(len(lat) - 1,
                                            int(0.99 * len(lat)))]
            self._maybe_promote(tr)
            est = tr.est_bytes()
            with self._l:
                self.stats["traces"] += 1
                self._ring.append((tr, est))
                self._ring_used += est
                while self._ring_used > self.ring_bytes \
                        and len(self._ring) > 1:
                    _old, old_est = self._ring.popleft()
                    self._ring_used -= old_est
                    self.stats["dropped"] += 1
        except Exception:       # pragma: no cover — defensive
            pass

    # -- tail exemplars ------------------------------------------------
    def threshold_ms(self) -> float:
        """Promotion threshold: threshold_pct percent of the tracked
        full-latency p99. 0.0 (no signal yet — cold reservoirs) means
        promote-everything: the worst-K retention below still keeps
        only the slowest traces, so early exemplars are exactly the
        cold-start anatomy a first TPU run wants to see."""
        if self.force_threshold_ms is not None:
            return self.force_threshold_ms
        base = 0.0
        fn = self.threshold_fn
        if fn is not None:
            try:
                base = float(fn())
            except Exception:       # pragma: no cover — defensive
                base = 0.0
        if base <= 0.0:
            base = self._own_p99    # cached; recomputed in finish()
        return base * (self.threshold_pct / 100.0)

    def _maybe_promote(self, tr: EvalTrace) -> None:
        if self.exemplar_slots <= 0 or tr.total_ms < self.threshold_ms():
            return
        gauges = None
        fn = self.gauge_fn
        if fn is not None:
            try:
                gauges = fn()
            except Exception:       # pragma: no cover — defensive
                gauges = None
        with self._l:
            if len(self._exemplars) < self.exemplar_slots:
                tr.gauges = gauges
                self._exemplars.append(
                    {"trace": tr, "pinned": False, "reason": "tail"})
                self.stats["exemplar_promotions"] += 1
                return
            # full: displace the FASTEST rolling exemplar, keeping
            # the set "the worst evals seen" (pinned captures live in
            # _pinned and never occupy rolling slots)
            victim = None
            for e in self._exemplars:
                if victim is None or \
                        e["trace"].total_ms < victim["trace"].total_ms:
                    victim = e
            if victim is not None and \
                    tr.total_ms > victim["trace"].total_ms:
                tr.gauges = gauges
                victim["trace"] = tr
                victim["reason"] = "tail"
                self.stats["exemplar_promotions"] += 1

    def pin_exemplars(self, reason: str = "pinned") -> int:
        """Pin the CURRENT exemplar set (drift auto-pin satellite):
        the captures that existed when the drift detector named a
        suspect are MOVED to a bounded pinned store (2x slots; once
        it is full further pins are dropped — the onset-of-drift
        evidence is the interesting capture) so they survive any
        later, slower tail WITHOUT occupying the rolling slots — a
        pin must never blind the recorder to the tails that develop
        after it. Returns how many were pinned."""
        n = 0
        cap = max(2 * self.exemplar_slots, self.exemplar_slots)
        with self._l:
            for e in self._exemplars:
                if len(self._pinned) >= cap:
                    break
                e["pinned"] = True
                e["reason"] = reason
                self._pinned.append(e)
                n += 1
            del self._exemplars[:n]
        if n:
            self.stats["exemplar_pins"] += n
        return n

    def exemplars(self) -> List[dict]:
        with self._l:
            entries = list(self._pinned) + list(self._exemplars)
        out = []
        for e in sorted(entries, key=lambda e: -e["trace"].total_ms):
            d = e["trace"].to_dict()
            d["pinned"] = e["pinned"]
            d["reason"] = e["reason"]
            out.append(d)
        return out

    def exemplar_count(self) -> int:
        return len(self._pinned) + len(self._exemplars)

    def recent(self, limit: int = 32) -> List[dict]:
        with self._l:
            traces = [t for t, _e in self._ring][-max(limit, 0):]
        return [t.to_dict() for t in traces]

    def ring_len(self) -> int:
        return len(self._ring)

    # -- stage percentiles ---------------------------------------------
    def observe_stage(self, stage: str, seconds: float) -> None:
        # append under the lock: stage_percentiles() copies these
        # deques for sorting, and CPython raises on a deque mutated
        # mid-iteration — one short lock per report, the same cost
        # class as the stages accumulator's own lock
        with self._stage_l:
            res = self._stage_res.get(stage)
            if res is None:
                res = self._stage_res.setdefault(
                    stage, deque(maxlen=STAGE_RESERVOIR))
            res.append(seconds * 1000.0)

    def stage_percentiles(self) -> Dict[str, dict]:
        """{stage: {p50_ms, p95_ms, p99_ms, count}} over the most
        recent STAGE_RESERVOIR reports per stage — the distributional
        complement to stage_breakdown's sums (a sum can't say whether
        plan_commit is uniformly slow or bimodal behind group
        conflicts)."""
        with self._stage_l:     # copy while appends are paused
            items = [(stage, list(res))
                     for stage, res in self._stage_res.items()]
        out = {}
        for stage, vals in sorted(items):
            vals.sort()
            if not vals:
                continue

            def pct(p, _v=vals):
                return _v[min(len(_v) - 1, int(p / 100.0 * len(_v)))]

            out[stage] = {"p50_ms": round(pct(50), 4),
                          "p95_ms": round(pct(95), 4),
                          "p99_ms": round(pct(99), 4),
                          "count": len(vals)}
        return out

    # -- the stages.add hook -------------------------------------------
    def _on_stage(self, stage: str, seconds: float,
                  attrs: Optional[dict] = None) -> None:
        self.observe_stage(stage, seconds)
        if stage in AMBIENT_STAGES:
            traces, track = _ctx()
            for tr in traces:
                tr.add_span(stage, seconds, track=track, attrs=attrs)

    # -- status / export -----------------------------------------------
    def status(self, limit: int = 32,
               exemplars_only: bool = False) -> dict:
        out = {
            "enabled": self._enabled,
            "stats": dict(self.stats),
            "ring": {"traces": len(self._ring),
                     "bytes": self._ring_used,
                     "bytes_max": self.ring_bytes},
            "threshold_ms": round(self.threshold_ms(), 3),
            "exemplar_slots": self.exemplar_slots,
            "exemplars": self.exemplars(),
            "stage_percentiles": self.stage_percentiles(),
        }
        if not exemplars_only:
            out["recent"] = self.recent(limit)
        return out

    def export_chrome(self, limit: int = 32,
                      exemplars_only: bool = False) -> dict:
        seen = set()
        traces: List[dict] = []
        for d in self.exemplars():
            seen.add(d["eval_id"])
            traces.append(d)
        if not exemplars_only:
            for d in self.recent(limit):
                if d["eval_id"] not in seen:
                    traces.append(d)
        return to_chrome(traces)


def to_chrome(traces: List[dict]) -> dict:
    """Chrome trace-event JSON (Perfetto / chrome://tracing loadable)
    from trace dicts: one X (complete) event per span plus the eval
    root, one tid per TRACK (worker-N / broker / gateway / applier /
    committer) so cross-thread overlap is visible on the timeline, and
    M metadata events naming the tracks."""
    events: List[dict] = []
    tids: Dict[str, int] = {}

    def tid(track: str) -> int:
        t = tids.get(track)
        if t is None:
            t = len(tids) + 1
            tids[track] = t
            events.append({"ph": "M", "name": "thread_name", "pid": 1,
                           "tid": t, "args": {"name": track}})
        return t

    for tr in traces:
        base_us = tr.get("start", 0.0) * 1e6
        root_args = {"eval_id": tr.get("eval_id", ""),
                     "job_id": tr.get("job_id", ""),
                     "namespace": tr.get("namespace", ""),
                     "type": tr.get("type", ""),
                     "status": tr.get("status", "")}
        if tr.get("pinned") is not None:
            root_args["pinned"] = tr["pinned"]
            root_args["reason"] = tr.get("reason", "")
        events.append({
            "name": f"eval {tr.get('eval_id', '')[:8]}", "ph": "X",
            "cat": "eval", "pid": 1, "tid": tid(tr.get("track", "eval")),
            "ts": round(base_us, 1),
            "dur": round(max(tr.get("total_ms", 0.0), 0.0) * 1000.0, 1),
            "args": root_args})
        for sp in tr.get("spans", ()):
            args = dict(sp.get("attrs") or {})
            args["eval_id"] = tr.get("eval_id", "")
            events.append({
                "name": sp["name"], "ph": "X", "cat": "eval", "pid": 1,
                "tid": tid(sp.get("track") or tr.get("track", "eval")),
                "ts": round(base_us + sp.get("t0_ms", 0.0) * 1000.0, 1),
                "dur": round(max(sp.get("dur_ms", 0.0), 0.0) * 1000.0, 1),
                "args": args})
    return {"traceEvents": events, "displayTimeUnit": "ms"}


# process-wide recorder, same idiom as stages / GROUP_STATS / the
# sanitizer's trace counter: kernels and gateways have no server
# handle; Server.configure()s it and wires its governor in
tracer = Tracer()
stages.set_trace_hook(tracer._on_stage, on=tracer.enabled())


# -- module-level conveniences (the call-site API) ---------------------
def begin(ev, track: str) -> Optional[EvalTrace]:
    return tracer.begin(ev, track)


def finish(tr: Optional[EvalTrace], status: str = "acked") -> None:
    tracer.finish(tr, status)


def emit(tr: Optional[EvalTrace], name: str, dur_s: float,
         end_mono: Optional[float] = None,
         track: Optional[str] = None, **attrs) -> None:
    """Attach one span to an explicit trace (the plan applier path:
    the submitting worker stamped the trace onto the plan, and the
    applier/committer threads attribute through it)."""
    if tr is None:
        return
    tr.add_span(name, dur_s, end_mono=end_mono, track=track,
                attrs=attrs or None)


def emit_kernel(arm: str, n_pad: int, seconds: float, lanes: int = 1,
                fresh: bool = False) -> None:
    """Kernel-dispatch span onto every trace in the thread context —
    the dispatch cost model's choke point calls this, so solo arms
    attribute to the dispatching eval and a batched gateway fire fans
    the one shared device span out to all of its lanes. `fresh` is the
    _note_trace verdict: this dispatch paid an XLA trace+compile."""
    traces, track = _ctx()
    if not traces:
        return
    attrs = {"arm": arm, "n_pad": int(n_pad), "lanes": int(lanes),
             "fresh": bool(fresh)}
    for tr in traces:
        tr.add_span("kernel", seconds, track=track, attrs=attrs)
