from .acl import (ACL, ACL_MANAGEMENT, AclPolicy, AclToken, ParseError,
                  compile_acl, new_token, parse_policy_rules)

__all__ = ["ACL", "ACL_MANAGEMENT", "AclPolicy", "AclToken",
           "ParseError", "compile_acl", "new_token",
           "parse_policy_rules"]
