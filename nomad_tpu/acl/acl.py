"""The ACL policy engine.

Reference: /root/reference/acl/policy.go (policy rules: namespace
blocks with policy levels or capability lists, node/agent/operator/
quota levels, glob namespace matching) and /root/reference/acl/acl.go
(compiled ACL object answering capability questions; exact-match
namespaces take precedence over glob matches, with the longest-prefix
glob winning ties).

Rules are accepted as JSON/dict (the wire form) or HCL text parsed by
the in-tree HCL parser (jobspec/hcl.py).
"""

from __future__ import annotations

import fnmatch
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# policy levels (policy.go:14-18)
POLICY_DENY = "deny"
POLICY_READ = "read"
POLICY_LIST = "list"
POLICY_WRITE = "write"
POLICY_SCALE = "scale"

# namespace capabilities (policy.go:27-47)
CAP_DENY = "deny"
NS_READ_CAPS = [
    "list-jobs", "read-job", "csi-list-volume", "csi-read-volume",
    "read-job-scaling", "list-scaling-policies", "read-scaling-policy",
]
NS_WRITE_CAPS = NS_READ_CAPS + [
    "scale-job", "submit-job", "dispatch-job", "read-logs", "read-fs",
    "alloc-exec", "alloc-lifecycle", "csi-mount-volume",
    "csi-write-volume", "submit-recommendation",
]
NS_SCALE_CAPS = [
    "list-scaling-policies", "read-scaling-policy", "read-job-scaling",
    "scale-job",
]
VALID_NS_CAPS = set(NS_WRITE_CAPS) | {CAP_DENY, "alloc-node-exec",
                                      "csi-register-plugin",
                                      "sentinel-override"}


class ParseError(Exception):
    pass


def expand_namespace_policy(policy: str) -> List[str]:
    """expandNamespacePolicy (policy.go:166)."""
    if policy == POLICY_DENY:
        return [CAP_DENY]
    if policy == POLICY_READ:
        return list(NS_READ_CAPS)
    if policy == POLICY_WRITE:
        return list(NS_WRITE_CAPS)
    if policy == POLICY_SCALE:
        return list(NS_SCALE_CAPS)
    raise ParseError(f"invalid namespace policy: {policy!r}")


@dataclass
class AclPolicy:
    """structs.ACLPolicy: named policy with raw rules."""
    name: str = ""
    description: str = ""
    rules: str = ""                    # HCL or JSON text, as submitted
    create_index: int = 0
    modify_index: int = 0


@dataclass
class AclToken:
    """structs.ACLToken."""
    accessor_id: str = ""
    secret_id: str = ""
    name: str = ""
    type: str = "client"               # "client" | "management"
    policies: List[str] = field(default_factory=list)
    global_: bool = False
    create_time: float = 0.0
    create_index: int = 0
    modify_index: int = 0

    def stub(self) -> dict:
        return {"accessor_id": self.accessor_id, "name": self.name,
                "type": self.type, "policies": list(self.policies),
                "global": self.global_,
                "create_index": self.create_index,
                "modify_index": self.modify_index}


def parse_policy_rules(rules) -> dict:
    """Normalize policy rules into
    {namespaces: {name: set(caps)}, node: level, agent: level,
     operator: level, quota: level, host_volumes: {name: set(caps)}}.
    Accepts a dict (wire form) or HCL text (policy.go Parse)."""
    if isinstance(rules, str):
        rules = rules.strip()
        if not rules:
            return _normalize({})
        if rules.startswith("{"):
            import json
            return _normalize(json.loads(rules))
        from ..jobspec.hcl import parse_hcl
        return _normalize(parse_hcl(rules))
    return _normalize(rules or {})


def _as_blocks(v) -> List[Tuple[str, dict]]:
    """HCL labeled blocks arrive as {label: body} or lists of them."""
    out = []
    if isinstance(v, dict):
        for label, body in v.items():
            if isinstance(body, list):
                for b in body:
                    out.append((label, b or {}))
            else:
                out.append((label, body or {}))
    elif isinstance(v, list):
        for item in v:
            out.extend(_as_blocks(item))
    return out


def _level(body, what: str, allowed) -> Optional[str]:
    lvl = body.get("policy") if isinstance(body, dict) else body
    if lvl is None:
        return None
    if lvl not in allowed:
        raise ParseError(f"invalid {what} policy: {lvl!r}")
    return lvl


def _normalize(data: dict) -> dict:
    out = {"namespaces": {}, "host_volumes": {},
           "node": None, "agent": None, "operator": None, "quota": None,
           "plugin": None}
    for label, body in _as_blocks(data.get("namespace", {})):
        caps = set()
        if isinstance(body, dict) and body.get("capabilities"):
            for c in body["capabilities"]:
                if c not in VALID_NS_CAPS:
                    raise ParseError(f"invalid namespace capability: {c!r}")
                caps.add(c)
        lvl = _level(body, "namespace",
                     (POLICY_DENY, POLICY_READ, POLICY_WRITE, POLICY_SCALE))
        if lvl:
            caps.update(expand_namespace_policy(lvl))
        out["namespaces"][label] = caps
    for label, body in _as_blocks(data.get("host_volume", {})):
        caps = set(body.get("capabilities", [])) if isinstance(body, dict) \
            else set()
        lvl = _level(body, "host_volume",
                     (POLICY_DENY, POLICY_READ, POLICY_WRITE))
        if lvl == POLICY_DENY:
            caps.add(CAP_DENY)
        elif lvl == POLICY_READ:
            caps.add("mount-readonly")
        elif lvl == POLICY_WRITE:
            caps.update(("mount-readonly", "mount-readwrite"))
        out["host_volumes"][label] = caps
    for key, levels in (("node", (POLICY_DENY, POLICY_READ, POLICY_WRITE)),
                        ("agent", (POLICY_DENY, POLICY_READ, POLICY_WRITE)),
                        ("operator", (POLICY_DENY, POLICY_READ,
                                      POLICY_WRITE)),
                        ("quota", (POLICY_DENY, POLICY_READ, POLICY_LIST)),
                        ("plugin", (POLICY_DENY, POLICY_READ,
                                    POLICY_LIST))):
        v = data.get(key)
        if v is None:
            continue
        body = v[0] if isinstance(v, list) else v
        out[key] = _level(body, key, levels)
    return out


_LEVEL_ORDER = {None: 0, POLICY_DENY: -1, POLICY_LIST: 1, POLICY_READ: 2,
                POLICY_WRITE: 3}


class ACL:
    """Compiled capability set over one or more policies (acl/acl.go).
    Exact namespace rules take precedence over glob rules; among glob
    matches the one with the fewest wildcard-expanded characters (the
    most specific pattern) wins."""

    def __init__(self, management: bool = False):
        self.management = management
        self.namespaces: Dict[str, set] = {}
        self.wildcard_namespaces: Dict[str, set] = {}
        self.host_volumes: Dict[str, set] = {}
        self.wildcard_host_volumes: Dict[str, set] = {}
        self.node = None
        self.agent = None
        self.operator = None
        self.quota = None
        self.plugin = None

    # -- compile -------------------------------------------------------
    def merge(self, parsed: dict) -> None:
        for name, caps in parsed["namespaces"].items():
            target = self.wildcard_namespaces if "*" in name \
                else self.namespaces
            cur = target.setdefault(name, set())
            cur.update(caps)
        for name, caps in parsed["host_volumes"].items():
            target = self.wildcard_host_volumes if "*" in name \
                else self.host_volumes
            target.setdefault(name, set()).update(caps)
        for key in ("node", "agent", "operator", "quota", "plugin"):
            new = parsed[key]
            if _LEVEL_ORDER.get(new, 0) == -1:
                setattr(self, key, POLICY_DENY)
            elif getattr(self, key) != POLICY_DENY and \
                    _LEVEL_ORDER.get(new, 0) > \
                    _LEVEL_ORDER.get(getattr(self, key), 0):
                setattr(self, key, new)

    # -- namespace checks ---------------------------------------------
    def _ns_caps(self, ns: str) -> set:
        caps = self.namespaces.get(ns)
        if caps is not None:
            return caps
        best = None
        best_len = -1
        for pattern, caps in self.wildcard_namespaces.items():
            if fnmatch.fnmatchcase(ns, pattern):
                specificity = len(pattern.replace("*", ""))
                if specificity > best_len:
                    best, best_len = caps, specificity
        return best or set()

    def allow_namespace_operation(self, ns: str, cap: str) -> bool:
        if self.management:
            return True
        caps = self._ns_caps(ns)
        if CAP_DENY in caps:
            return False
        return cap in caps

    def allow_namespace(self, ns: str) -> bool:
        """Any capability at all in the namespace."""
        if self.management:
            return True
        caps = self._ns_caps(ns)
        return bool(caps) and CAP_DENY not in caps

    # -- host volumes --------------------------------------------------
    def allow_host_volume_operation(self, vol: str, cap: str) -> bool:
        if self.management:
            return True
        caps = self.host_volumes.get(vol)
        if caps is None:
            best_len = -1
            caps = set()
            for pattern, c in self.wildcard_host_volumes.items():
                if fnmatch.fnmatchcase(vol, pattern):
                    spec = len(pattern.replace("*", ""))
                    if spec > best_len:
                        caps, best_len = c, spec
        if CAP_DENY in caps:
            return False
        return cap in caps

    # -- coarse checks -------------------------------------------------
    def _allow(self, level, want: str) -> bool:
        if self.management:
            return True
        return _LEVEL_ORDER.get(level, 0) >= _LEVEL_ORDER[want] and \
            level != POLICY_DENY

    def allow_node_read(self) -> bool:
        return self._allow(self.node, POLICY_READ)

    def allow_node_write(self) -> bool:
        return self._allow(self.node, POLICY_WRITE)

    def allow_agent_read(self) -> bool:
        return self._allow(self.agent, POLICY_READ)

    def allow_agent_write(self) -> bool:
        return self._allow(self.agent, POLICY_WRITE)

    def allow_operator_read(self) -> bool:
        return self._allow(self.operator, POLICY_READ)

    def allow_operator_write(self) -> bool:
        return self._allow(self.operator, POLICY_WRITE)

    def is_management(self) -> bool:
        return self.management


ACL_MANAGEMENT = ACL(management=True)
ACL_DENY_ALL = ACL()


def compile_acl(policies: List[AclPolicy]) -> ACL:
    """Compile an ACL from policy objects (acl.go NewACL)."""
    acl = ACL()
    for p in policies:
        acl.merge(parse_policy_rules(p.rules))
    return acl


def new_token(name: str = "", type_: str = "client",
              policies: Optional[List[str]] = None,
              global_: bool = False) -> AclToken:
    from ..utils.ids import generate_uuid
    return AclToken(accessor_id=generate_uuid(),
                    secret_id=generate_uuid(),
                    name=name, type=type_,
                    policies=list(policies or []),
                    global_=global_, create_time=time.time())
