"""Retained telemetry core (ISSUE 11): the sampling collector behind
`/v1/operator/telemetry`, `/v1/operator/flatness`, and
`nomad operator top` — history rings over governor gauges, counter
rates, stage percentiles, device economics, and RSS. See collector.py
for the design; `enabled()` is the NOMAD_TPU_TELEMETRY kill switch."""

from .collector import (MAX_SERIES, TelemetryCollector,
                        default_device_fn, enabled)

__all__ = ["TelemetryCollector", "default_device_fn", "enabled",
           "MAX_SERIES"]
