"""Retained telemetry: the server-side sampling collector (ISSUE 11).

`/v1/metrics` is a point-in-time InmemSink snapshot; the soak harness
computes flatness verdicts AFTER a run from windows it assembled
itself; and the device economics the north star turns on (pad waste,
compile counts, dispatch seconds) lived only in process-local structs.
This collector closes all three gaps in-process: a background sampler
snapshots governor gauges, counter totals (rates derived from slot
deltas at read time), stage percentile reservoirs, device-economics
stats, and RSS into bounded struct-of-arrays ring buffers — numpy
float64 columns, one write cursor, wrap-around overwrite — so
`/v1/operator/flatness` can run `bench/soak.flatness_verdict` over the
LIVE ring and `nomad operator top` can render rates and trends from
history instead of a single scrape.

Bounding: `telemetry_ring_slots` slots × MAX_SERIES series × 8 bytes
(defaults: 512 × 256 = 1 MiB hard ceiling); series past the cap are
dropped and counted, never grown. The collector only READS — gauge
closures, counter totals, reservoir percentiles — and every read is
host-side (the device stats it samples are plain dict snapshots), so
sampling can never sync the accelerator.

Kill switch: NOMAD_TPU_TELEMETRY=0 (or telemetry_sample_interval_s=0)
builds no collector at all — /v1/metrics degenerates to today's
snapshot-only behavior and the flatness/telemetry routes report
disabled.
"""

from __future__ import annotations

import math
import os
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from ..governor.governor import rss_mb
from ..utils import metrics
from ..utils.locks import make_lock

# hard series ceiling: a gauge-name churn storm (e.g. per-job counter
# keys) must not grow the ring without bound — excess series are
# dropped and counted in status()
MAX_SERIES = 256

DEFAULT_SLOTS = 512
DEFAULT_INTERVAL_S = 1.0


def enabled() -> bool:
    """The NOMAD_TPU_TELEMETRY kill switch (parallel to
    NOMAD_TPU_TRACE): default on."""
    return os.environ.get("NOMAD_TPU_TELEMETRY", "1") not in ("0", "off")


def default_device_fn() -> Dict[str, float]:
    """The `device.*` metrics family (ISSUE 11): pad-waste ratio and
    per-arm dispatch/compile accounting from the kernel hot path,
    kernel-cache entries, and HBM-in-use where the backend reports it.
    Lazy imports: the collector must be constructible before (or
    without) the ops layer touching jax."""
    out: Dict[str, float] = {}
    try:
        from ..ops.select import (device_hbm_bytes, device_stats_snapshot,
                                  kernel_cache_entries)
        snap = device_stats_snapshot()
        out["device.pad_waste_ratio"] = snap["pad_waste_ratio"]
        out["device.pad_rows_shipped"] = snap["pad_rows_shipped"]
        out["device.packs"] = snap["packs"]
        for arm, s in snap["dispatch_s"].items():
            out[f"device.dispatch_s.{arm}"] = s
        for arm, c in snap["compiles"].items():
            out[f"device.compiles.{arm}"] = c
        for arm, d in snap["dispatches"].items():
            out[f"device.dispatches.{arm}"] = d
        out["device.kernel_cache_entries"] = kernel_cache_entries()
        out["device.hbm_bytes_in_use"] = device_hbm_bytes()
        # mesh-sharded residency economics: present only when a mesh
        # dispatcher exists, so a single-chip run's series stay lean
        from ..ops.select import mesh_stats_snapshot
        ms = mesh_stats_snapshot()
        if ms:
            out["device.mesh_devices"] = ms["devices"]
            out["device.mesh_resident_bytes_per_device"] = \
                ms["resident_bytes_per_device"]
            out["device.mesh_reshard_uploads"] = ms["reshard_uploads"]
            out["device.mesh_reshard_bytes"] = ms["reshard_bytes"]
            out["device.mesh_delta_scatters"] = ms["delta_scatters"]
            out["device.mesh_resident_hits"] = ms["resident_hits"]
            out["device.mesh_stale_misses"] = ms["stale_misses"]
    except Exception:       # pragma: no cover — defensive
        pass
    return out


class TelemetryCollector:
    """Struct-of-arrays history ring. One instance per server (or per
    bench); `sample_once()` is the deterministic entry the thread loop
    and the tests share, exactly like Governor.sample_once."""

    # cumulative series (counters, dispatch seconds/counts): rates
    # derive from slot deltas at READ time, so the ring stores raw
    # totals and a wrap never corrupts a rate. (stage_count.* is NOT
    # here: it is reservoir occupancy, capped at STAGE_RESERVOIR, not
    # a monotone total.)
    RATE_PREFIXES = ("counter.", "device.dispatch_s.",
                     "device.compiles.", "device.dispatches.",
                     "device.packs", "device.mesh_reshard_uploads",
                     "device.mesh_reshard_bytes",
                     "device.mesh_delta_scatters",
                     "device.mesh_resident_hits")

    def __init__(self, interval_s: float = DEFAULT_INTERVAL_S,
                 slots: int = DEFAULT_SLOTS,
                 gauges_fn: Optional[Callable[[], Dict[str, float]]] = None,
                 latency_fn: Optional[Callable[[float], float]] = None,
                 stage_fn: Optional[Callable[[], Dict[str, dict]]] = None,
                 device_fn: Optional[Callable[[], Dict[str, float]]]
                 = default_device_fn,
                 extra_fn: Optional[Callable[[], Dict[str, float]]] = None):
        self.interval_s = max(float(interval_s), 0.05)
        self.slots = max(int(slots), 8)
        self.gauges_fn = gauges_fn
        self.latency_fn = latency_fn
        self.stage_fn = stage_fn
        self.device_fn = device_fn
        self.extra_fn = extra_fn
        self._l = make_lock()
        self._t = np.full(self.slots, np.nan, dtype=np.float64)
        self._series: Dict[str, np.ndarray] = {}
        self._n = 0                     # total samples ever written
        self._dropped_series = 0
        self._started_at = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="telemetry")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:       # pragma: no cover — defensive
                import logging
                logging.getLogger("nomad_tpu.telemetry").exception(
                    "telemetry sample failed")

    # -- the sampling step ---------------------------------------------
    def _collect_row(self) -> Dict[str, float]:
        row: Dict[str, float] = {"process.rss_mb": rss_mb()}
        if self.gauges_fn is not None:
            try:
                row.update(self.gauges_fn())
            except Exception:       # pragma: no cover — defensive
                pass
        if self.latency_fn is not None:
            try:
                # FULL latency (host + queue wait): what an eval
                # experienced — the flatness verdict's p99 series
                row["latency.p50_ms"] = self.latency_fn(50)
                row["latency.p99_ms"] = self.latency_fn(99)
            except Exception:       # pragma: no cover — defensive
                pass
        # counter totals: raw cumulative sums; read-side slot deltas
        # become the rate series `operator top` renders
        for name, total in metrics.counter_totals().items():
            row[f"counter.{name}"] = total
        if self.stage_fn is not None:
            try:
                for stage, pct in self.stage_fn().items():
                    row[f"stage.{stage}.p50_ms"] = pct.get("p50_ms", 0.0)
                    row[f"stage.{stage}.p99_ms"] = pct.get("p99_ms", 0.0)
                    row[f"stage_count.{stage}"] = pct.get("count", 0)
            except Exception:       # pragma: no cover — defensive
                pass
        if self.device_fn is not None:
            try:
                row.update(self.device_fn())
            except Exception:       # pragma: no cover — defensive
                pass
        if self.extra_fn is not None:
            try:
                row.update(self.extra_fn())
            except Exception:       # pragma: no cover — defensive
                pass
        return row

    def sample_once(self, now: Optional[float] = None) -> int:
        """Collect one row into the ring; returns the sample ordinal.
        Series first seen mid-run begin at this slot (earlier slots
        hold NaN); series absent this sample record NaN so a
        wrapped-over stale value can never masquerade as fresh."""
        row = self._collect_row()
        now = time.time() if now is None else now
        with self._l:
            cur = self._n % self.slots
            self._t[cur] = now
            for arr in self._series.values():
                arr[cur] = np.nan
            for name, value in row.items():
                arr = self._series.get(name)
                if arr is None:
                    if len(self._series) >= MAX_SERIES:
                        self._dropped_series += 1
                        continue
                    arr = self._series[name] = np.full(
                        self.slots, np.nan, dtype=np.float64)
                try:
                    arr[cur] = float(value)
                except (TypeError, ValueError):
                    arr[cur] = np.nan
            self._n += 1
            return self._n

    # -- reads ---------------------------------------------------------
    def _order(self) -> np.ndarray:
        """Chronological slot indexes of the valid window."""
        if self._n <= self.slots:
            return np.arange(self._n)
        cur = self._n % self.slots
        return np.concatenate([np.arange(cur, self.slots),
                               np.arange(0, cur)])

    def history(self, last: Optional[int] = None) -> dict:
        """The ring, chronological, JSON-safe (NaN -> None). `last`
        limits to the most recent N samples."""
        with self._l:
            order = self._order()
            if last is not None and last > 0:
                order = order[-last:]
            t = self._t[order]
            series = {name: arr[order].tolist()
                      for name, arr in sorted(self._series.items())}
        def clean(vals):
            return [None if (isinstance(v, float) and math.isnan(v))
                    else v for v in vals]
        return {
            "interval_s": self.interval_s,
            "slots": self.slots,
            "samples": self._n,
            "series_count": len(series),
            "series_dropped": self._dropped_series,
            "t": t.tolist(),
            "series": {k: clean(v) for k, v in series.items()},
            "rates": {k: clean(self._rate(t, np.asarray(v, np.float64)))
                      for k, v in series.items()
                      if k.startswith(self.RATE_PREFIXES)},
        }

    @staticmethod
    def _rate(t: np.ndarray, totals: np.ndarray) -> List[float]:
        """Per-second rates from a cumulative series: delta over dt
        per slot pair (first slot NaN — no left neighbor). A counter
        reset (delta < 0, e.g. a series re-keyed) reads NaN, not a
        negative rate."""
        out = np.full(len(totals), np.nan)
        if len(totals) >= 2:
            dt = np.diff(t)
            dv = np.diff(totals)
            with np.errstate(invalid="ignore", divide="ignore"):
                r = np.where((dt > 0) & (dv >= 0), dv / np.maximum(
                    dt, 1e-9), np.nan)
            out[1:] = r
        return [float(v) for v in out]

    def windows(self) -> List[Dict]:
        """The soak-window shape over the ring — the rows
        `bench/soak.flatness_verdict` consumes: per-slot t_min (from
        the first retained sample), p99_ms (full-latency reservoir),
        rss_mb, and the evals counted between slots."""
        with self._l:
            order = self._order()
            t = self._t[order]
            p99 = self._series.get("latency.p99_ms")
            rss = self._series.get("process.rss_mb")
            ev = self._series.get("counter.nomad.worker.eval_processed")
            p99 = p99[order] if p99 is not None else None
            rss = rss[order] if rss is not None else None
            ev = ev[order] if ev is not None else None
        out: List[Dict] = []
        if len(t) == 0:
            return out
        t0 = t[0]
        for i in range(len(t)):
            w = {"t_min": round((t[i] - t0) / 60.0, 4)}
            w["p99_ms"] = (0.0 if p99 is None or math.isnan(p99[i])
                           else float(p99[i]))
            w["rss_mb"] = (0.0 if rss is None or math.isnan(rss[i])
                           else float(rss[i]))
            if ev is not None and i > 0 and not math.isnan(ev[i]) \
                    and not math.isnan(ev[i - 1]):
                w["evals"] = int(max(ev[i] - ev[i - 1], 0))
            else:
                w["evals"] = 0
            out.append(w)
        return out

    # the live verdict needs this much post-warmup history before a
    # pass/fail is meaningful: an RSS slope fit over a few seconds is
    # noise (the first e2e drive measured -10161 MB/h over 3 slots)
    MIN_VERDICT_SPAN_S = 120.0

    def flatness(self, **kw) -> dict:
        """Live verdict: `bench/soak.flatness_verdict` over the
        in-process ring — the same math the soak artifact records,
        pointed at retained history instead of harness windows.

        The soak calibrates its thresholds for 60-second windows
        (warmup_windows=1 excludes a full minute of legitimate
        bounded-structure fill). The ring samples much faster, so the
        warmup exclusion is rescaled to cover the same ~60 seconds of
        wall clock, and until MIN_VERDICT_SPAN_S of post-warmup
        history exists the verdict reports pass=None ("insufficient
        history") instead of failing a healthy server on a
        noise-dominated slope fit."""
        from ..bench.soak import flatness_verdict
        windows = self.windows()
        kw.setdefault("warmup_windows",
                      max(1, math.ceil(60.0 / self.interval_s)))
        out = flatness_verdict(windows, **kw)
        out["windows_measured"] = len(windows)
        out["interval_s"] = self.interval_s
        warmup = kw["warmup_windows"]
        measured = windows[warmup:] if len(windows) - warmup >= 3 \
            else windows
        span_s = ((measured[-1]["t_min"] - measured[0]["t_min"]) * 60.0
                  if len(measured) >= 2 else 0.0)
        out["span_s"] = round(span_s, 1)
        if span_s < self.MIN_VERDICT_SPAN_S:
            out["pass"] = None
            out["reason"] = (
                f"insufficient history: {span_s:.0f}s of post-warmup "
                f"windows < {self.MIN_VERDICT_SPAN_S:.0f}s — verdict "
                f"needs a longer retained window")
        return out

    def status(self) -> dict:
        with self._l:
            nbytes = self._t.nbytes + sum(
                a.nbytes for a in self._series.values())
            return {
                "enabled": True,
                "running": self._thread is not None,
                "interval_s": self.interval_s,
                "slots": self.slots,
                "samples": self._n,
                "series_count": len(self._series),
                "series_dropped": self._dropped_series,
                "ring_bytes": int(nbytes),
                "started_at": self._started_at,
            }
