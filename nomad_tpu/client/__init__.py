from .agent import Client, ClientConfig
from .drivers import MockDriver, ExecDriver, RawExecDriver, DRIVER_CATALOG
