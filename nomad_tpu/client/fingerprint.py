"""Cloud environment fingerprints: AWS / GCE / Azure metadata probes.

Reference semantics: client/fingerprint/env_aws.go, env_gce.go,
env_azure.go — each probes the platform's link-local metadata service
with a short timeout; a node not on that platform fails the probe fast
and carries no attributes. Attribute names mirror the reference
(`platform.aws.instance-type`, `unique.platform.aws.hostname`, ...)
and the node link (`aws.ec2`, `gce`, `azure`) feeds constraint
targeting just like any other attribute.

The metadata base URLs are overridable (NOMAD_AWS_METADATA_URL etc.)
so tests point them at a fake local HTTP server — the same hook the
reference exposes via AWS_ENV_URL/GCE_ENV_URL (env_aws.go:37).
"""

from __future__ import annotations

import json
import logging
import os
import urllib.error
import urllib.request
from typing import Dict, Optional, Tuple

LOG = logging.getLogger("nomad_tpu.fingerprint")

DEFAULT_TIMEOUT_S = 0.5

AWS_METADATA_URL = "http://169.254.169.254/latest/meta-data/"
GCE_METADATA_URL = "http://169.254.169.254/computeMetadata/v1/"
AZURE_METADATA_URL = ("http://169.254.169.254/metadata/instance/"
                      "compute")
AZURE_API_VERSION = "2019-06-04"


def _get(url: str, headers: Optional[Dict[str, str]] = None,
         timeout_s: float = DEFAULT_TIMEOUT_S,
         method: str = "GET") -> Optional[str]:
    req = urllib.request.Request(url, headers=headers or {},
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as r:
            return r.read().decode("utf-8", "replace")
    except Exception:
        return None


class AwsFingerprint:
    """env_aws.go: EC2 instance metadata v1 paths -> platform.aws.*"""

    name = "env_aws"
    # metadata path -> attribute suffix; unique marks per-node identity
    # attributes (env_aws.go ec2InstanceSpeedMap sibling table)
    PATHS = (
        ("ami-id", "ami-id", False),
        ("hostname", "hostname", True),
        ("instance-id", "instance-id", True),
        ("instance-type", "instance-type", False),
        ("local-hostname", "local-hostname", True),
        ("local-ipv4", "local-ipv4", True),
        ("public-hostname", "public-hostname", True),
        ("public-ipv4", "public-ipv4", True),
        ("placement/availability-zone", "placement.availability-zone",
         False),
    )

    def __init__(self, base_url: Optional[str] = None,
                 timeout_s: float = DEFAULT_TIMEOUT_S):
        self.base_url = (base_url
                         or os.environ.get("NOMAD_AWS_METADATA_URL")
                         or AWS_METADATA_URL)
        self.timeout_s = timeout_s

    def _session_headers(self) -> Dict[str, str]:
        """IMDSv2 session token (PUT /latest/api/token) — required by
        default on new EC2 launches and commonly enforced org-wide;
        without it every metadata GET 401s and the probe would
        silently report 'not on EC2'. A failed token request falls
        back to bare IMDSv1 headers."""
        token_url = self.base_url.replace("/meta-data/", "/api/token")
        if token_url == self.base_url:      # unexpected base: skip v2
            return {}
        token = _get(token_url, method="PUT", headers={
            "X-aws-ec2-metadata-token-ttl-seconds": "21600"},
            timeout_s=self.timeout_s)
        if token:
            return {"X-aws-ec2-metadata-token": token.strip()}
        return {}

    def fingerprint(self) -> Tuple[Dict[str, str], Dict[str, str]]:
        headers = self._session_headers()
        # availability probe first: one fast miss means "not on EC2";
        # a hit doubles as the ami-id value (no second round trip)
        probe = _get(self.base_url + "ami-id", headers=headers,
                     timeout_s=self.timeout_s)
        if probe is None:
            return {}, {}
        attrs: Dict[str, str] = {"platform.aws": "true"}
        for path, suffix, unique in self.PATHS:
            v = probe if path == "ami-id" else \
                _get(self.base_url + path, headers=headers,
                     timeout_s=self.timeout_s)
            if v is None or v == "":
                continue
            key = f"platform.aws.{suffix}"
            if unique:
                key = f"unique.{key}"
            attrs[key] = v.strip()
        links: Dict[str, str] = {}
        instance = attrs.get("unique.platform.aws.instance-id")
        az = attrs.get("platform.aws.placement.availability-zone")
        if instance and az:
            links["aws.ec2"] = f"{az}.{instance}"
        return attrs, links


class GceFingerprint:
    """env_gce.go: GCE metadata (Metadata-Flavor header) ->
    platform.gce.*"""

    name = "env_gce"
    HEADERS = {"Metadata-Flavor": "Google"}
    PATHS = (
        ("instance/id", "id", True),
        ("instance/hostname", "hostname", True),
        ("instance/machine-type", "machine-type", False),
        ("instance/zone", "zone", False),
    )

    def __init__(self, base_url: Optional[str] = None,
                 timeout_s: float = DEFAULT_TIMEOUT_S):
        self.base_url = (base_url
                         or os.environ.get("NOMAD_GCE_METADATA_URL")
                         or GCE_METADATA_URL)
        self.timeout_s = timeout_s

    def fingerprint(self) -> Tuple[Dict[str, str], Dict[str, str]]:
        probe = _get(self.base_url + "instance/id",
                     headers=self.HEADERS, timeout_s=self.timeout_s)
        if probe is None:
            return {}, {}
        attrs: Dict[str, str] = {"platform.gce": "true"}
        for path, suffix, unique in self.PATHS:
            v = probe if path == "instance/id" else \
                _get(self.base_url + path, headers=self.HEADERS,
                     timeout_s=self.timeout_s)
            if v is None or v == "":
                continue
            # machine-type/zone arrive as full resource paths
            # (projects/123/zones/us-central1-a); keep the leaf
            v = v.strip()
            if suffix in ("machine-type", "zone") and "/" in v:
                v = v.rsplit("/", 1)[1]
            key = f"platform.gce.{suffix}"
            if unique:
                key = f"unique.{key}"
            attrs[key] = v
        links: Dict[str, str] = {}
        if "unique.platform.gce.id" in attrs:
            links["gce"] = attrs["unique.platform.gce.id"]
        return attrs, links


class AzureFingerprint:
    """env_azure.go: IMDS compute document (Metadata: true header) ->
    platform.azure.*"""

    name = "env_azure"
    HEADERS = {"Metadata": "true"}
    FIELDS = (
        ("name", "name", True),
        ("vmId", "id", True),
        ("vmSize", "vm-size", False),
        ("location", "location", False),
        ("resourceGroupName", "resource-group", False),
    )

    def __init__(self, base_url: Optional[str] = None,
                 timeout_s: float = DEFAULT_TIMEOUT_S):
        self.base_url = (base_url
                         or os.environ.get("NOMAD_AZURE_METADATA_URL")
                         or AZURE_METADATA_URL)
        self.timeout_s = timeout_s

    def fingerprint(self) -> Tuple[Dict[str, str], Dict[str, str]]:
        raw = _get(f"{self.base_url}?api-version={AZURE_API_VERSION}"
                   "&format=json", headers=self.HEADERS,
                   timeout_s=self.timeout_s)
        if raw is None:
            return {}, {}
        try:
            doc = json.loads(raw)
        except ValueError:
            return {}, {}
        attrs: Dict[str, str] = {"platform.azure": "true"}
        for field, suffix, unique in self.FIELDS:
            v = doc.get(field)
            if not v:
                continue
            key = f"platform.azure.{suffix}"
            if unique:
                key = f"unique.{key}"
            attrs[key] = str(v)
        links: Dict[str, str] = {}
        if "unique.platform.azure.id" in attrs:
            links["azure"] = attrs["unique.platform.azure.id"]
        return attrs, links


CLOUD_FINGERPRINTERS = (AwsFingerprint, GceFingerprint,
                        AzureFingerprint)


def fingerprint_cloud(timeout_s: float = DEFAULT_TIMEOUT_S
                      ) -> Tuple[Dict[str, str], Dict[str, str]]:
    """Run every cloud probe; a node is on at most one platform, so
    misses are cheap (one timed-out request each) and hits merge their
    attributes and links."""
    attrs: Dict[str, str] = {}
    links: Dict[str, str] = {}
    for cls in CLOUD_FINGERPRINTERS:
        try:
            a, l = cls(timeout_s=timeout_s).fingerprint()
        except Exception:       # pragma: no cover — defensive
            LOG.exception("cloud fingerprint %s failed", cls.name)
            continue
        attrs.update(a)
        links.update(l)
    return attrs, links
