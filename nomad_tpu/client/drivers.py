"""Task drivers.

Reference semantics: plugins/drivers/driver.go DriverPlugin (StartTask/
WaitTask/StopTask/DestroyTask/InspectTask); drivers/mock/driver.go
(configurable fake: run_for, exit_code, start_error, kill_after —
:113-226) and drivers/rawexec (fork/exec runner).

In-process classes for now; the plugin process boundary (go-plugin gRPC
in the reference) arrives with the gRPC layer.
"""

from __future__ import annotations

import os as _os
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, Optional

from ..plugins.hclspec import Attr as _SpecAttr


@dataclass
class TaskHandle:
    task_name: str
    driver: str
    config: dict
    proc: Optional[object] = None
    exit_code: Optional[int] = None
    error: str = ""
    started_at: float = 0.0
    finished_at: float = 0.0
    id: str = ""
    _done: threading.Event = field(default_factory=threading.Event)
    _kill: threading.Event = field(default_factory=threading.Event)

    def __post_init__(self):
        if not self.id:
            from ..utils.ids import generate_uuid
            self.id = generate_uuid()

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def done(self) -> bool:
        return self._done.is_set()

    def recoverable_state(self) -> dict:
        """What the client state DB persists so a restarted client can
        re-attach (plugins/drivers TaskHandle / RecoverTask)."""
        pid = None
        if self.proc is not None:
            pid = getattr(self.proc, "pid", None)
        out = {"id": self.id, "task_name": self.task_name,
               "driver": self.driver, "config": dict(self.config),
               "pid": pid, "started_at": self.started_at}
        cg = getattr(self, "cgroup_name", None)
        if cg:
            out["cgroup"] = cg
        ea = getattr(self, "executor_addr", None)
        if ea:
            out["executor_addr"] = ea
            out["executor_pid"] = getattr(self, "executor_pid", None)
            out["executor_auth"] = getattr(self, "executor_auth", "")
        cid = getattr(self, "container_id", None)
        if cid:
            out["container_id"] = cid
            dp = getattr(self, "docklog_pid", None)
            if dp:
                out["docklog_pid"] = dp
                out["log_dir"] = getattr(self, "log_dir", "")
                out["log_max_files"] = getattr(self, "log_max_files", 10)
                out["log_max_file_size_mb"] = getattr(
                    self, "log_max_file_size_mb", 10)
        mon = getattr(self, "monitor_path", None)
        if mon:
            out["monitor_path"] = mon
        return out


def resolve_host_ports(alloc_networks) -> Dict[str, tuple]:
    """label -> (host_port, host_ip) from the alloc's allocated
    networks, which arrive as model objects (in-proc drivers) or wire
    dicts (across the plugin boundary). Shared by the docker and qemu
    port_map paths."""
    def field(obj, name, default=None):
        if isinstance(obj, dict):
            return obj.get(name, default)
        return getattr(obj, name, default)

    host_ports: Dict[str, tuple] = {}
    for nw in alloc_networks or []:
        for p in list(field(nw, "reserved_ports") or []) + \
                list(field(nw, "dynamic_ports") or []):
            host_ports[field(p, "label")] = (
                field(p, "value"), field(nw, "ip", "") or "0.0.0.0")
    return host_ports


def child_process_env(extra: Optional[Dict[str, str]] = None
                      ) -> Dict[str, str]:
    """Minimal env for spawned helper processes (executor, docklog,
    plugin launchers): the repo on PYTHONPATH plus a sane PATH —
    deliberately NOT the agent's env (credentials must not leak into
    task-side processes)."""
    repo_root = _os.path.dirname(_os.path.dirname(
        _os.path.dirname(_os.path.abspath(__file__))))
    env = {"PYTHONPATH": repo_root,
           "PATH": _os.environ.get("PATH", "/usr/bin:/bin")}
    if extra:
        env.update(extra)
    return env


def _parse_duration(val) -> float:
    """'500ms' / '3s' / '2m' / numeric seconds."""
    if isinstance(val, (int, float)):
        return float(val)
    s = str(val).strip()
    for suffix, mult in (("ms", 0.001), ("s", 1.0), ("m", 60.0), ("h", 3600.0)):
        if s.endswith(suffix):
            try:
                return float(s[: -len(suffix)]) * mult
            except ValueError:
                return 0.0
    try:
        return float(s)
    except ValueError:
        return 0.0


class MockDriver:
    """drivers/mock: runs for config['run_for'], exits config['exit_code'];
    config['start_error'] fails the start."""

    name = "mock_driver"
    # typed config schema (plugins/shared/hclspec; drivers/mock
    # driver.go:113-226 declares the same knobs via hclspec)
    CONFIG_SPEC = {
        "run_for": _SpecAttr("string", default="0s"),
        "exit_code": _SpecAttr("number", default=0),
        "start_error": _SpecAttr("string"),
        "recover_error": _SpecAttr("string"),
        "stdout_string": _SpecAttr("string"),
    }

    def fingerprint(self) -> Dict[str, str]:
        return {"driver.mock_driver": "1"}

    def start_task(self, task_name: str, config: dict, env: dict,
                   ctx: Optional[dict] = None) -> TaskHandle:
        if config.get("start_error"):
            raise RuntimeError(str(config["start_error"]))
        h = TaskHandle(task_name=task_name, driver=self.name, config=config,
                       started_at=time.time())
        run_for = _parse_duration(config.get("run_for", 0))
        exit_code = int(config.get("exit_code", 0))

        def run():
            if run_for > 0:
                h._kill.wait(run_for)
            h.exit_code = 137 if h._kill.is_set() else exit_code
            h.finished_at = time.time()
            h._done.set()

        t = threading.Thread(target=run, daemon=True)
        t.start()
        return h

    def stop_task(self, handle: TaskHandle, timeout_s: float = 5.0) -> None:
        handle._kill.set()
        handle.wait(timeout_s)

    def recover_task(self, state: dict) -> Optional[TaskHandle]:
        """Re-attach to a 'live' mock task (drivers/mock recovery
        simulation knobs, driver.go:169-264): fails when the persisted
        config asks for it; otherwise reconstructs a handle whose
        remaining runtime is derived from the persisted start time, so
        a task that should still be running keeps 'running' and one
        past its run_for completes immediately."""
        config = state.get("config", {})
        if config.get("recover_error"):
            return None
        run_for = _parse_duration(config.get("run_for", 0))
        exit_code = int(config.get("exit_code", 0))
        started_at = float(state.get("started_at") or time.time())
        h = TaskHandle(task_name=state["task_name"], driver=self.name,
                       config=config, started_at=started_at,
                       id=state.get("id", ""))
        remaining = started_at + run_for - time.time()

        def run():
            if remaining > 0:
                h._kill.wait(remaining)
            h.exit_code = 137 if h._kill.is_set() else exit_code
            h.finished_at = time.time()
            h._done.set()

        threading.Thread(target=run, daemon=True).start()
        return h


class RawExecDriver:
    """drivers/rawexec: plain fork/exec, no isolation."""

    name = "raw_exec"
    CONFIG_SPEC = {
        "command": _SpecAttr("string", required=True),
        "args": _SpecAttr("list(string)", default=[]),
    }

    def fingerprint(self) -> Dict[str, str]:
        return {"driver.raw_exec": "1"}

    def start_task(self, task_name: str, config: dict, env: dict,
                   ctx: Optional[dict] = None) -> TaskHandle:
        command = config.get("command")
        if not command:
            raise RuntimeError("missing command")
        args = [command] + list(config.get("args", []))
        ctx = ctx or {}
        cwd = ctx.get("task_dir") or None
        # logmon: pump stdout/stderr into size-rotated files under the
        # alloc's log dir (client/logmon); without a log dir, discard
        log_dir = ctx.get("log_dir")
        stdout = stderr = subprocess.DEVNULL
        if log_dir:
            stdout = stderr = subprocess.PIPE
        try:
            proc = subprocess.Popen(
                args, env={**env} if env else None, cwd=cwd,
                stdout=stdout, stderr=stderr)
        except OSError as e:
            raise RuntimeError(f"failed to exec {command}: {e}")
        h = TaskHandle(task_name=task_name, driver=self.name, config=config,
                       proc=proc, started_at=time.time())
        if log_dir:
            from .logmon import RotatingWriter, pump
            max_files = int(ctx.get("log_max_files", 10))
            max_mb = int(ctx.get("log_max_file_size_mb", 10))
            pump(proc.stdout, RotatingWriter(
                log_dir, f"{task_name}.stdout", max_files, max_mb))
            pump(proc.stderr, RotatingWriter(
                log_dir, f"{task_name}.stderr", max_files, max_mb))

        def wait():
            code = proc.wait()
            h.exit_code = code
            h.finished_at = time.time()
            h._done.set()

        threading.Thread(target=wait, daemon=True).start()
        return h

    def stop_task(self, handle: TaskHandle, timeout_s: float = 5.0) -> None:
        proc = handle.proc
        if proc is None:
            pid = getattr(handle, "_recovered_pid", None)
            if pid:
                import os
                import signal as _signal
                try:
                    os.kill(pid, _signal.SIGTERM)
                except ProcessLookupError:
                    pass
            handle._kill.set()
            handle.wait(timeout_s)
            return
        proc.terminate()
        try:
            proc.wait(timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
        handle.wait(1.0)

    def recover_task(self, state: dict) -> Optional[TaskHandle]:
        """Re-attach to a running process by pid (the executor
        re-attach path, task_runner.go:996). A non-child pid can't be
        wait()ed, so liveness is polled."""
        import os
        pid = state.get("pid")
        if not pid:
            return None
        try:
            os.kill(pid, 0)
        except (ProcessLookupError, PermissionError):
            return None
        h = TaskHandle(task_name=state["task_name"], driver=self.name,
                       config=state.get("config", {}),
                       started_at=float(state.get("started_at") or 0),
                       id=state.get("id", ""))
        h._recovered_pid = pid

        def poll():
            while not h._kill.is_set():
                try:
                    os.kill(pid, 0)
                except (ProcessLookupError, PermissionError):
                    break
                time.sleep(0.1)
            # exit status of a non-child is unknowable; treat
            # disappeared-without-kill as clean exit
            h.exit_code = 137 if h._kill.is_set() else 0
            h.finished_at = time.time()
            h._done.set()

        threading.Thread(target=poll, daemon=True).start()
        return h

    def stats(self, handle: TaskHandle) -> Dict[str, float]:
        """Resource usage from /proc/<pid> (the unprivileged analog of
        executor Stats(): raw_exec has no cgroup, so RSS comes from
        statm and cpu from utime+stime). Feeds the client host-stats
        sampler's per-alloc ResourceUsage (ISSUE 13)."""
        proc = handle.proc
        pid = proc.pid if proc is not None \
            else getattr(handle, "_recovered_pid", None)
        if not pid or handle.done():
            return {}
        import os
        try:
            with open(f"/proc/{pid}/statm") as f:
                rss_pages = int(f.read().split()[1])
            with open(f"/proc/{pid}/stat") as f:
                fields = f.read().rsplit(")", 1)[1].split()
            # fields after comm: index 11/12 are utime/stime in ticks
            ticks = float(fields[11]) + float(fields[12])
        except (OSError, IndexError, ValueError):
            return {}
        hz = os.sysconf("SC_CLK_TCK") or 100
        page = os.sysconf("SC_PAGE_SIZE") or 4096
        return {"memory_bytes": float(rss_pages * page),
                "cpu_total_ns": ticks / hz * 1e9}


class ExecDriver(RawExecDriver):
    """drivers/exec: fork/exec with cgroup resource limits and a
    mount-namespace chroot when the host allows it (root + writable
    cgroupfs), per drivers/shared/executor/executor_linux.go. Falls
    back to raw fork/exec otherwise, and advertises which mode the
    fingerprint detected (driver.exec.isolation)."""

    CONFIG_SPEC = {
        "command": _SpecAttr("string", required=True),
        "args": _SpecAttr("list(string)", default=[]),
        "user": _SpecAttr("string"),
        "no_chroot": _SpecAttr("bool", default=False),
        "no_isolation": _SpecAttr("bool", default=False),
    }

    name = "exec"

    def fingerprint(self) -> Dict[str, str]:
        from .executor import IsolatedExecutor
        isolated = IsolatedExecutor.available()
        return {"driver.exec": "1",
                "driver.exec.isolation": "cgroups" if isolated else "none"}

    @staticmethod
    def _spawn_executor():
        """Launch the supervising executor process (executor_plugin.go
        analog) in its own session and dial its RPC handshake. A
        per-executor auth token (handed over via the root-only child
        env) gates every RPC — the listener is a localhost socket and
        Exec/State expose the task's env and isolation."""
        import secrets as _secrets
        import sys as _sys

        from ..plugins.base import (HANDSHAKE_COOKIE_KEY,
                                    HANDSHAKE_COOKIE_VALUE,
                                    HANDSHAKE_PREFIX)
        from ..rpc.client import RpcClient
        token = _secrets.token_hex(16)
        env = child_process_env({
            HANDSHAKE_COOKIE_KEY: HANDSHAKE_COOKIE_VALUE,
            "NOMAD_TPU_EXECUTOR_TOKEN": token})
        eproc = subprocess.Popen(
            [_sys.executable, "-m", "nomad_tpu.client.executor_server"],
            env=env, stdin=subprocess.DEVNULL, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True,
            start_new_session=True)
        import select as _select
        ready, _w, _x = _select.select([eproc.stdout], [], [], 15.0)
        line = eproc.stdout.readline().strip() if ready else ""
        if not line.startswith(HANDSHAKE_PREFIX):
            eproc.kill()
            eproc.wait()
            raise RuntimeError(f"executor bad handshake: {line!r}")
        addr = line[len(HANDSHAKE_PREFIX):]
        return eproc, RpcClient(addr), addr, token

    @staticmethod
    def _ecall(h: TaskHandle, method: str, args: dict,
               timeout_s: float = 30.0):
        """Executor RPC with the handle's auth token injected."""
        return h.executor_rpc.call(
            method, {**args, "auth": getattr(h, "executor_auth", "")},
            timeout_s=timeout_s)

    def start_task(self, task_name: str, config: dict, env: dict,
                   ctx: Optional[dict] = None) -> TaskHandle:
        from .executor import IsolatedExecutor
        ctx = ctx or {}
        resources = ctx.get("resources") or {}
        if not IsolatedExecutor.available() or \
                config.get("no_isolation"):
            return super().start_task(task_name, config, env, ctx=ctx)

        command = config.get("command")
        if not command:
            raise RuntimeError("missing command")
        cwd = ctx.get("task_dir") or None
        cg_name = f"{ctx.get('alloc_id', 'anon')[:8]}-{task_name}"
        chroot_dir = None
        if cwd and not config.get("no_chroot"):
            chroot_dir = cwd
        # the jobspec `user` (Task.user / config user), defaulting to
        # an unprivileged account when the agent runs as root — an
        # isolated task must never silently inherit root
        # (drivers/shared/executor/executor.go user switch)
        run_as = config.get("user") or (ctx.get("user") or "") or "nobody"
        # spec is fully built BEFORE the executor spawns: an exception
        # here must not leak a detached executor process
        spec = {
            "cgroup": cg_name,
            "cpu_shares": int(resources.get("cpu", 0)),
            "memory_mb": int(resources.get("memory_mb", 0)),
            "chroot_dir": chroot_dir,
            "command": command,
            "args": list(config.get("args", [])),
            "env": {**env} if env else {},
            "cwd": cwd,
            "user": run_as,
            "chown_dirs": [cwd] if cwd else [],
            "bind_mounts": list(ctx.get("volume_mounts") or []),
            "log_dir": ctx.get("log_dir"),
            "task_name": task_name,
            "log_max_files": int(ctx.get("log_max_files", 10)),
            "log_max_file_size_mb": int(
                ctx.get("log_max_file_size_mb", 10)),
        }
        # the OUT-OF-PROC executor owns cgroup + containment + logs
        # (drivers/shared/executor/executor_plugin.go): the client
        # holds only an RPC handle, so supervision and log rotation
        # survive a client restart, and `alloc exec` can enter the
        # task's isolation through Executor.Exec
        try:
            eproc, rpc, addr, token = self._spawn_executor()
        except (OSError, subprocess.SubprocessError, RuntimeError) as e:
            raise RuntimeError(f"failed to start executor: {e}")
        try:
            res = rpc.call("Executor.Launch",
                           {"spec": spec, "auth": token},
                           timeout_s=30.0)
        except Exception as e:
            try:
                eproc.kill()
            except OSError:
                pass
            try:
                eproc.wait(timeout=5)
            except Exception:
                pass
            # the executor may have created the cgroup (and even the
            # task) before dying/timing out: reap it so the workload
            # can't keep running unsupervised while the scheduler
            # replaces it
            IsolatedExecutor.recover(cg_name).destroy()
            raise RuntimeError(f"failed to exec {command}: {e}")
        h = TaskHandle(task_name=task_name, driver=self.name,
                       config=config, proc=eproc,
                       started_at=res.get("started_at") or time.time())
        h.executor_rpc = rpc
        h.executor_addr = addr
        h.executor_auth = token
        h.executor_pid = eproc.pid
        h.task_pid = res.get("pid")
        h.cgroup_name = cg_name
        self._watch_executor(h)
        return h

    @classmethod
    def _watch_executor(cls, h: TaskHandle) -> None:
        """Long-poll Executor.Wait until the task exits, then reflect
        the result on the handle (WaitTask over the process boundary)."""

        def wait():
            fails = 0
            while True:
                try:
                    res = cls._ecall(h, "Executor.Wait",
                                     {"timeout_s": 60.0},
                                     timeout_s=90.0)
                    fails = 0
                except Exception:
                    # transient RPC hiccups must not kill a live task:
                    # only give up once the executor PROCESS is gone or
                    # several consecutive calls failed (a dead executor
                    # means the task is unsupervised either way)
                    fails += 1
                    pid = getattr(h, "executor_pid", None)
                    alive = bool(pid) and \
                        _os.path.isdir(f"/proc/{pid}")
                    if alive and fails < 3:
                        time.sleep(1.0)
                        continue
                    h.error = h.error or "executor process lost"
                    h.exit_code = h.exit_code if h.exit_code is not None \
                        else -1
                    h.finished_at = time.time()
                    break
                if res.get("done"):
                    h.exit_code = res.get("exit_code")
                    if res.get("oom"):
                        h.error = "OOM Killed: memory limit exceeded"
                    h.finished_at = res.get("finished_at") or time.time()
                    try:
                        cls._ecall(h, "Executor.Quit", {},
                                   timeout_s=5.0)
                    except Exception:
                        pass
                    break
            # reap the executor child so it doesn't linger as a zombie
            # (recovered handles have no Popen to reap)
            p = h.proc
            if p is not None and hasattr(p, "wait"):
                try:
                    p.wait(timeout=15)
                except Exception:
                    pass
            h._done.set()

        threading.Thread(target=wait, daemon=True,
                         name=f"exec-wait-{h.id[:8]}").start()

    def stop_task(self, handle: TaskHandle, timeout_s: float = 5.0) -> None:
        rpc = getattr(handle, "executor_rpc", None)
        if rpc is None:
            super().stop_task(handle, timeout_s)
            executor = getattr(handle, "executor", None)
            if executor is not None:
                executor.destroy()
            return
        try:
            self._ecall(handle, "Executor.Shutdown",
                        {"grace_s": timeout_s},
                        timeout_s=timeout_s + 30.0)
            self._ecall(handle, "Executor.Quit", {}, timeout_s=5.0)
        except Exception:
            # executor unresponsive: kill it and reap the cgroup (which
            # terminates member processes)
            pid = getattr(handle, "executor_pid", None)
            if pid:
                try:
                    _os.kill(pid, 9)
                except OSError:
                    pass
                p = handle.proc
                if p is not None and hasattr(p, "wait"):
                    try:
                        p.wait(timeout=5)
                    except Exception:
                        pass
            cg = getattr(handle, "cgroup_name", None)
            if cg:
                from .executor import IsolatedExecutor
                IsolatedExecutor.recover(cg).destroy()
            handle.exit_code = handle.exit_code \
                if handle.exit_code is not None else -1
            handle.finished_at = handle.finished_at or time.time()
            handle._done.set()

    def exec_in_task(self, handle: TaskHandle, argv, timeout_s: float
                     = 30.0) -> Dict:
        """Run a command inside the task's isolation (same cgroup +
        chroot) through the executor — the `alloc exec` entry
        (executor_linux.go Exec). Returns {exit_code, output,
        timed_out}."""
        rpc = getattr(handle, "executor_rpc", None)
        if rpc is None:
            raise RuntimeError("task has no out-of-proc executor")
        return self._ecall(handle, "Executor.Exec",
                           {"cmd": list(argv), "timeout_s": timeout_s},
                           timeout_s=timeout_s + 30.0)

    def recover_task(self, state: dict) -> Optional[TaskHandle]:
        """Re-dial the still-running executor process (RecoverTask over
        the executor boundary, executor_plugin.go): supervision, logs,
        and exec keep working after a client restart. Falls back to
        pid adoption + cgroup reap for pre-executor states or a dead
        executor."""
        addr = state.get("executor_addr")
        if addr:
            from ..rpc.client import RpcClient
            rpc = None
            auth = state.get("executor_auth", "")
            try:
                rpc = RpcClient(addr)
                st = rpc.call("Executor.State", {"auth": auth},
                              timeout_s=5.0)
            except Exception:
                if rpc is not None:
                    rpc.close()
                rpc = None
            if rpc is not None:
                h = TaskHandle(task_name=state.get("task_name", ""),
                               driver=self.name,
                               config=state.get("config") or {},
                               proc=None,
                               started_at=st.get("started_at") or
                               state.get("started_at") or 0.0,
                               id=state.get("id") or "")
                h.executor_rpc = rpc
                h.executor_addr = addr
                h.executor_auth = auth
                h.executor_pid = state.get("executor_pid")
                h.task_pid = st.get("pid")
                h.cgroup_name = state.get("cgroup", "")
                if st.get("done"):
                    h.exit_code = st.get("exit_code")
                    if st.get("oom"):
                        h.error = "OOM Killed: memory limit exceeded"
                    h.finished_at = st.get("finished_at") or time.time()
                    h._done.set()
                    try:
                        self._ecall(h, "Executor.Quit", {},
                                    timeout_s=5.0)
                    except Exception:
                        pass
                else:
                    self._watch_executor(h)
                return h
            # executor gone: the task group lives only in the cgroup —
            # reap it so a fresh start doesn't double-run
            cg = state.get("cgroup")
            if cg:
                from .executor import IsolatedExecutor
                IsolatedExecutor.recover(cg).destroy()
            return None
        h = super().recover_task(state)
        cg = state.get("cgroup")
        if cg:
            from .executor import IsolatedExecutor
            executor = IsolatedExecutor.recover(cg)
            if h is None:
                # process already gone: reap the leftover cgroup now
                executor.destroy()
            else:
                h.executor = executor
                h.cgroup_name = cg

                def cleanup():
                    h.wait()
                    executor.destroy()

                threading.Thread(target=cleanup, daemon=True).start()
        return h

    def stats(self, handle: TaskHandle) -> Dict[str, float]:
        """Resource usage for a running task (executor Stats() ->
        client task gauges)."""
        rpc = getattr(handle, "executor_rpc", None)
        if rpc is not None:
            try:
                return self._ecall(handle, "Executor.Stats", {},
                                   timeout_s=10.0).get("stats", {})
            except Exception:
                return {}
        executor = getattr(handle, "executor", None)
        if executor is None:
            return {}
        return executor.stats()


class JavaDriver(RawExecDriver):
    """drivers/java/driver.go: run a jar or class on the host JVM.
    Conditional on a working `java` binary (the availability probe
    drops the driver cleanly on hosts without one, like docker)."""

    name = "java"
    CONFIG_SPEC = {
        "jar_path": _SpecAttr("string"),
        "class": _SpecAttr("string"),
        "class_path": _SpecAttr("string"),
        "args": _SpecAttr("list(string)", default=[]),
        "jvm_options": _SpecAttr("list(string)", default=[]),
    }

    def available(self) -> bool:
        import shutil
        return shutil.which("java") is not None

    def fingerprint(self) -> Dict[str, str]:
        """javaVersionInfo (driver.go:239): `java -version` writes to
        STDERR; parse version/runtime/vm lines."""
        try:
            out = subprocess.run(["java", "-version"],
                                 capture_output=True, text=True,
                                 timeout=10)
        except (OSError, subprocess.TimeoutExpired):
            return {}
        text = out.stderr or out.stdout or ""
        attrs = {"driver.java": "1"}
        import re as _re
        m = _re.search(r'version "([^"]+)"', text)
        if m:
            attrs["driver.java.version"] = m.group(1)
        # JAVA_TOOL_OPTIONS prepends "Picked up ..." lines to stderr;
        # skip them or runtime/vm land one line off
        lines = [line.strip() for line in text.splitlines()
                 if line.strip()
                 and not line.startswith("Picked up ")]
        if len(lines) > 1:
            attrs["driver.java.runtime"] = lines[1]
        if len(lines) > 2:
            attrs["driver.java.vm"] = lines[2]
        return attrs

    def start_task(self, task_name: str, config: dict, env: dict,
                   ctx: Optional[dict] = None) -> TaskHandle:
        """driver.go StartTask:311 — `jar_path or class must be
        specified`; argv = java [jvm_options] [-cp class_path]
        (-jar jar | class) [args]."""
        jar = config.get("jar_path") or ""
        cls = config.get("class") or ""
        if not jar and not cls:
            raise RuntimeError("jar_path or class must be specified")
        # absolute binary path (driver.go GetAbsolutePath): the task's
        # env map usually has no PATH, so exec must not depend on it
        import shutil
        java_bin = shutil.which("java") or "java"
        argv = [java_bin] + list(config.get("jvm_options") or [])
        if config.get("class_path"):
            argv += ["-cp", str(config["class_path"])]
        if jar:
            task_dir = (ctx or {}).get("task_dir") or ""
            if task_dir and not _os.path.isabs(jar):
                jar = _os.path.join(task_dir, jar)
            argv += ["-jar", jar]
        else:
            argv.append(cls)
        argv += [str(a) for a in config.get("args") or []]
        sub = dict(config)
        sub["command"], sub["args"] = argv[0], argv[1:]
        return super().start_task(task_name, sub, env, ctx=ctx)


class QemuDriver(RawExecDriver):
    """drivers/qemu/driver.go: boot a VM image under qemu-system.
    Conditional on the qemu binary; graceful shutdown rides a unix
    monitor socket (system_powerdown) with SIGTERM fallback."""

    name = "qemu"
    BINARY = "qemu-system-x86_64"
    CONFIG_SPEC = {
        "image_path": _SpecAttr("string", required=True),
        "accelerator": _SpecAttr("string", default="tcg"),
        "graceful_shutdown": _SpecAttr("bool", default=False),
        "args": _SpecAttr("list(string)", default=[]),
        "port_map": _SpecAttr("map(number)", default={}),
    }

    def available(self) -> bool:
        import shutil
        return shutil.which(self.BINARY) is not None

    def fingerprint(self) -> Dict[str, str]:
        try:
            out = subprocess.run([self.BINARY, "--version"],
                                 capture_output=True, text=True,
                                 timeout=10)
        except (OSError, subprocess.TimeoutExpired):
            return {}
        attrs = {"driver.qemu": "1"}
        import re as _re
        m = _re.search(r"version ([\d.]+)", out.stdout or "")
        if m:
            attrs["driver.qemu.version"] = m.group(1)
        return attrs

    def start_task(self, task_name: str, config: dict, env: dict,
                   ctx: Optional[dict] = None) -> TaskHandle:
        """driver.go StartTask:402: -machine accel, -m from resources,
        -drive the image, -nographic; port_map becomes user-net
        hostfwd entries mapping scheduler-assigned host ports to guest
        ports."""
        ctx = ctx or {}
        image = str(config.get("image_path") or "")
        if not image:
            raise RuntimeError("image_path is required")
        task_dir = ctx.get("task_dir") or ""
        if task_dir and not _os.path.isabs(image):
            image = _os.path.join(task_dir, image)
        mem_mb = int((ctx.get("resources") or {}).get("memory_mb")
                     or 512)
        import shutil
        qemu_bin = shutil.which(self.BINARY) or self.BINARY
        argv = [qemu_bin,
                "-machine", "type=pc,accel="
                + str(config.get("accelerator") or "tcg"),
                "-name", f"nomad-{task_name}",
                "-m", f"{mem_mb}M",
                "-drive", f"file={image}",
                "-nographic"]
        monitor = ""
        if config.get("graceful_shutdown"):
            import tempfile
            from ..utils.ids import generate_uuid
            monitor = _os.path.join(
                task_dir or tempfile.gettempdir(),
                f"qmon-{generate_uuid()[:8]}.sock")
            # AF_UNIX sun_path limit — the reference rejects over-long
            # monitor paths up front (qemuLegacyMaxMonitorPathLen)
            # instead of letting qemu die with an opaque bind error
            if len(monitor.encode()) > 104:
                raise RuntimeError(
                    f"qemu monitor path {monitor!r} exceeds the unix "
                    "socket path limit; use a shorter alloc dir")
            argv += ["-monitor", f"unix:{monitor},server,nowait"]
        port_map = config.get("port_map") or {}
        if port_map:
            # hostfwd=tcp::<host>-:<guest> per mapped label
            # (driver.go:438-449); host ports come from the
            # scheduler's allocated networks
            host_ports = resolve_host_ports(ctx.get("alloc_networks"))
            fwds = []
            for label, guest in port_map.items():
                hp = host_ports.get(label)
                if not hp or not hp[0]:
                    raise RuntimeError(
                        f"unknown port label {label!r} in port_map")
                fwds.append(f"hostfwd=tcp::{int(hp[0])}-:{int(guest)}")
            argv += ["-netdev",
                     "user,id=user.0," + ",".join(fwds),
                     "-device", "virtio-net,netdev=user.0"]
        argv += [str(a) for a in config.get("args") or []]
        sub = dict(config)
        sub["command"], sub["args"] = argv[0], argv[1:]
        h = super().start_task(task_name, sub, env, ctx=ctx)
        h.monitor_path = monitor
        return h

    def stop_task(self, handle: TaskHandle, timeout_s: float = 5.0
                  ) -> None:
        """Graceful shutdown via the monitor socket
        (qemuGracefulShutdownMsg driver.go:41), then the SIGTERM/kill
        escalation."""
        monitor = getattr(handle, "monitor_path", "")
        if monitor:
            import socket
            try:
                with socket.socket(socket.AF_UNIX,
                                   socket.SOCK_STREAM) as sk:
                    sk.settimeout(2.0)
                    sk.connect(monitor)
                    sk.sendall(b"system_powerdown\n")
                # wait for a clean exit before escalating; works for
                # both child procs and restart-recovered handles
                # (whose liveness poller sets _done)
                if handle.proc is not None:
                    try:
                        handle.proc.wait(timeout_s)
                        handle.wait(1.0)
                        return
                    except subprocess.TimeoutExpired:
                        pass
                elif handle.wait(timeout_s):
                    return
            except OSError:
                pass
        super().stop_task(handle, timeout_s)

    def recover_task(self, state: dict) -> Optional[TaskHandle]:
        """Re-attach keeps the monitor socket path so graceful
        shutdown survives a client restart."""
        h = super().recover_task(state)
        if h is not None and state.get("monitor_path"):
            h.monitor_path = state["monitor_path"]
        return h


def _docker_driver():
    # deferred: docker_driver imports TaskHandle from this module
    from .docker_driver import DockerDriver
    return DockerDriver()


DRIVER_CATALOG = {
    "mock_driver": MockDriver,
    "raw_exec": RawExecDriver,
    "exec": ExecDriver,
    "docker": _docker_driver,
    "java": JavaDriver,
    "qemu": QemuDriver,
}
