"""Per-allocation directory tree (reference: client/allocdir — shared
alloc dir + per-task local/secrets/tmp dirs, log dir under the shared
alloc dir; SharedAllocName/TaskLocal layout).
"""

from __future__ import annotations

import os
import shutil
import stat
import tempfile
from typing import Dict, Tuple


class AllocDir:
    def __init__(self, base_dir: str, alloc_id: str):
        if not base_dir:
            base_dir = os.path.join(tempfile.gettempdir(),
                                    "nomad-tpu-allocs")
        self.base = os.path.join(base_dir, alloc_id)
        self.shared = os.path.join(self.base, "alloc")
        self.logs = os.path.join(self.shared, "logs")
        self._task_dirs: Dict[str, str] = {}

    def build(self, task_names) -> None:
        os.makedirs(self.logs, exist_ok=True)
        os.makedirs(os.path.join(self.shared, "data"), exist_ok=True)
        os.makedirs(os.path.join(self.shared, "tmp"), exist_ok=True)
        for name in task_names:
            td = os.path.join(self.base, name)
            for sub in ("local", "secrets", "tmp"):
                os.makedirs(os.path.join(td, sub), exist_ok=True)
            # secrets dir is owner-only (allocdir secretsDirPerms)
            os.chmod(os.path.join(td, "secrets"),
                     stat.S_IRWXU)
            self._task_dirs[name] = td

    def task_dir(self, task: str) -> str:
        return self._task_dirs.get(task) or os.path.join(self.base, task)

    def task_paths(self, task: str) -> Tuple[str, str, str]:
        """(task_dir, local_dir, secrets_dir)."""
        td = self.task_dir(task)
        return td, os.path.join(td, "local"), os.path.join(td, "secrets")

    def destroy(self) -> None:
        shutil.rmtree(self.base, ignore_errors=True)
