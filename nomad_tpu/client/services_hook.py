"""Client-side service registration + health checking.

Reference: the group/task service hooks push registrations into the
local Consul agent (client/allocrunner/groupservice_hook.go,
taskrunner/service_hook.go via command/agent/consul/service_client.go),
Consul runs the checks, and checkwatcher restarts tasks whose
check_restart budget is exhausted
(command/agent/consul/check_watcher.go). Here registrations go to the
server's built-in catalog over the client transport, and this hook
runs the http/tcp checks itself, reporting status transitions into the
catalog.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from ..utils.locks import make_lock
from ..models.services import (
    SERVICE_STATUS_CRITICAL,
    SERVICE_STATUS_PASSING,
    SERVICE_STATUS_PENDING,
    ServiceRegistration,
    registration_id,
)

LOG = logging.getLogger("nomad_tpu.client.services")


def _resolve_port(networks, label: str) -> int:
    for nw in networks or []:
        got = nw.port_labels().get(label)
        if got:
            return got
    return 0


def _resolve_addr(networks) -> str:
    for nw in networks or []:
        if nw.ip:
            return nw.ip
    return "127.0.0.1"


def run_check(check, address: str, port: int) -> bool:
    """One http/tcp probe (Consul's agent checks; script/grpc checks
    pass vacuously here as the reference delegates them to Consul
    features we don't model)."""
    import socket
    kind = check.type.lower()
    if kind == "tcp":
        try:
            with socket.create_connection((address, port),
                                          timeout=check.timeout_s):
                return True
        except OSError:
            return False
    if kind == "http":
        import urllib.error
        import urllib.request
        proto = check.protocol or "http"
        url = f"{proto}://{address}:{port}{check.path}"
        req = urllib.request.Request(url, method=check.method or "GET")
        try:
            with urllib.request.urlopen(req,
                                        timeout=check.timeout_s) as resp:
                return 200 <= resp.status < 300
        except (urllib.error.URLError, OSError):
            return False
    return True


class AllocServices:
    """Registers one alloc's services, runs their checks, and applies
    check_restart. Owned by the AllocRunner."""

    def __init__(self, runner, transport):
        self.runner = runner
        self.transport = transport
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._regs: Dict[str, ServiceRegistration] = {}
        self._l = make_lock()

    # -- registration --------------------------------------------------
    def _build(self) -> List[ServiceRegistration]:
        alloc = self.runner.alloc
        job = alloc.job
        tg = job.lookup_task_group(alloc.task_group) if job else None
        if tg is None:
            return []
        ar = alloc.allocated_resources
        shared_nw = ar.shared.networks if ar is not None else []
        out = []

        def mk(svc, owner: str, networks, task_name: str = ""):
            port = _resolve_port(networks, svc.port_label) \
                if svc.port_label else 0
            return ServiceRegistration(
                id=registration_id(alloc.id, owner, svc.name),
                service_name=svc.name, namespace=alloc.namespace,
                node_id=alloc.node_id, job_id=alloc.job_id,
                alloc_id=alloc.id, task_name=task_name,
                tags=list(svc.tags), address=_resolve_addr(networks),
                port=port,
                status=(SERVICE_STATUS_PENDING if svc.checks
                        else SERVICE_STATUS_PASSING),
                checks={(c.name or f"{c.type}-{i}"): SERVICE_STATUS_PENDING
                        for i, c in enumerate(svc.checks)})

        for svc in tg.services:
            out.append((svc, mk(svc, tg.name, shared_nw)))
        for task in tg.tasks:
            networks = list(shared_nw)
            if ar is not None:
                tr = ar.tasks.get(task.name)
                if tr is not None:
                    networks = list(tr.networks or []) + networks
            for svc in task.services:
                out.append((svc, mk(svc, task.name, networks, task.name)))
        return out

    def start(self) -> None:
        pairs = self._build()
        if not pairs:
            return
        regs = [r for _svc, r in pairs]
        with self._l:
            for r in regs:
                self._regs[r.id] = r
        try:
            self.transport.update_services(upserts=regs)
        except Exception:
            LOG.exception("service registration for alloc %s",
                          self.runner.alloc.id[:8])
        for svc, reg in pairs:
            for i, check in enumerate(svc.checks):
                th = threading.Thread(
                    target=self._check_loop,
                    args=(svc, check, check.name or f"{check.type}-{i}",
                          reg.id),
                    daemon=True,
                    name=f"check-{reg.service_name}")
                th.start()
                self._threads.append(th)

    def stop(self) -> None:
        """Deregister everything this alloc owns (groupservice_hook
        Postrun)."""
        self._stop.set()
        try:
            self.transport.update_services(
                delete_alloc_ids=[self.runner.alloc.id])
        except Exception:
            LOG.exception("service deregistration for alloc %s",
                          self.runner.alloc.id[:8])

    # -- checks --------------------------------------------------------
    def _check_loop(self, svc, check, check_name: str,
                    reg_id: str) -> None:
        """Poll one check; push status transitions; count consecutive
        failures against check_restart.limit after the grace window
        (check_watcher.go apply)."""
        grace_until = time.time() + (
            check.check_restart.grace_s
            if check.check_restart is not None else 0.0)
        fails = 0
        # test-friendly floor mirrors the restart-policy cap elsewhere
        interval = max(0.2, min(check.interval_s, 10.0))
        while not self._stop.is_set():
            with self._l:
                reg = self._regs.get(reg_id)
            if reg is None:
                return
            port = reg.port
            if check.port_label:
                alloc = self.runner.alloc
                ar = alloc.allocated_resources
                networks = list(ar.shared.networks) if ar else []
                got = _resolve_port(networks, check.port_label)
                if got:
                    port = got
            ok = run_check(check, reg.address, port)
            self._apply_status(reg_id, check_name,
                               SERVICE_STATUS_PASSING if ok
                               else SERVICE_STATUS_CRITICAL)
            cr = check.check_restart
            if ok:
                fails = 0
            elif cr is not None and cr.limit > 0 and \
                    time.time() >= grace_until:
                fails += 1
                if fails >= cr.limit:
                    LOG.warning("check %s unhealthy %dx; restarting "
                                "task", check_name, fails)
                    self._restart_task(svc)
                    fails = 0
                    grace_until = time.time() + cr.grace_s
            if self._stop.wait(interval):
                return

    def _apply_status(self, reg_id: str, check_name: str,
                      status: str) -> None:
        with self._l:
            reg = self._regs.get(reg_id)
            if reg is None:
                return
            if reg.checks.get(check_name) == status:
                return
            reg.checks[check_name] = status
            agg = SERVICE_STATUS_PASSING
            if any(s == SERVICE_STATUS_CRITICAL
                   for s in reg.checks.values()):
                agg = SERVICE_STATUS_CRITICAL
            elif any(s == SERVICE_STATUS_PENDING
                     for s in reg.checks.values()):
                agg = SERVICE_STATUS_PENDING
            reg.status = agg
            from dataclasses import replace
            snapshot = replace(reg, tags=list(reg.tags),
                               checks=dict(reg.checks))
        try:
            self.transport.update_services(upserts=[snapshot])
        except Exception:
            LOG.exception("service status update %s", reg_id[:16])

    def _restart_task(self, svc) -> None:
        """checkRestarter.apply: restart the backing task (group
        services restart the whole alloc's tasks)."""
        targets = [tr for tr in self.runner.task_runners
                   if not svc.task_name or tr.task.name == svc.task_name]
        for tr in targets:
            h = tr.handle
            if h is None:
                continue
            tr._force_restart = True
            try:
                tr.driver.stop_task(h, 5.0)
            except Exception:
                tr._force_restart = False
