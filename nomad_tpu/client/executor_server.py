"""The out-of-proc task executor: a supervising process between the
client agent and the task.

Reference: drivers/shared/executor/executor_plugin.go — the exec driver
launches `nomad executor` as a separate process speaking RPC
(Launch/Wait/Shutdown/Stats/Signal/Exec); the executor owns the task's
cgroup, containment, and log files, so the CLIENT can die and restart
while supervision continues, and RecoverTask re-dials the executor
instead of adopting a bare pid. Exec runs commands INSIDE the task's
isolation (same cgroup + chroot), which is what `alloc exec` needs
(executor_linux.go Exec).

Process shape: the driver spawns
    python -m nomad_tpu.client.executor_server
with the plugin handshake cookie; the executor prints the handshake
line (protocol|addr) on stdout, detaches into its own session (so a
dying client doesn't take it down), and serves until Shutdown. Task
launch re-execs the exec_helper bootstrap exactly as the in-proc path
did — the containment recipe is shared, only its supervisor moved out
of the client.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional


class _ExecutorState:
    def __init__(self):
        self.proc: Optional[subprocess.Popen] = None
        self.spec: Dict = {}
        self.started_at = 0.0
        self.finished_at = 0.0
        self.exit_code: Optional[int] = None
        self.oom = False
        self.executor = None          # IsolatedExecutor (cgroup owner)
        self.done = threading.Event()
        self.log_threads: List[threading.Thread] = []


STATE = _ExecutorState()


def _spawn_helper(spec: Dict, stdout, stderr) -> subprocess.Popen:
    from .drivers import child_process_env
    helper_env = child_process_env()
    proc = subprocess.Popen(
        [sys.executable, "-m", "nomad_tpu.client.exec_helper"],
        env=helper_env, stdin=subprocess.PIPE,
        stdout=stdout, stderr=stderr)
    proc.stdin.write(json.dumps(spec).encode())
    proc.stdin.close()
    # communicate() would flush the (closed) stdin and raise
    proc.stdin = None
    return proc


def _launch(args: Dict) -> Dict:
    """Executor.Launch: create the cgroup, start the contained task,
    own its logs (log rotation runs HERE so task output survives a
    client restart — the docklog stance)."""
    if STATE.proc is not None:
        raise RuntimeError("executor already launched a task")
    spec = dict(args["spec"])
    from .executor import IsolatedExecutor
    cg_name = spec.get("cgroup", "")
    isolated = bool(cg_name) and IsolatedExecutor.available()
    if isolated:
        STATE.executor = IsolatedExecutor(
            cg_name,
            cpu_shares=int(spec.get("cpu_shares", 0)),
            memory_mb=int(spec.get("memory_mb", 0)),
            chroot_dir=spec.get("chroot_dir"))
        spec["procs_files"] = STATE.executor.procs_files
        spec["chroot_dirs"] = list(STATE.executor.chroot_dirs)
    else:
        spec.setdefault("procs_files", [])
        spec["chroot_dir"] = None

    log_dir = spec.pop("log_dir", None)
    task_name = spec.pop("task_name", "task")
    stdout = stderr = subprocess.DEVNULL
    if log_dir:
        stdout = stderr = subprocess.PIPE
    STATE.spec = spec
    STATE.proc = _spawn_helper(spec, stdout, stderr)
    STATE.started_at = time.time()
    if log_dir:
        from .logmon import RotatingWriter, pump
        max_files = int(spec.pop("log_max_files", 10))
        max_mb = int(spec.pop("log_max_file_size_mb", 10))
        pump(STATE.proc.stdout, RotatingWriter(
            log_dir, f"{task_name}.stdout", max_files, max_mb))
        pump(STATE.proc.stderr, RotatingWriter(
            log_dir, f"{task_name}.stderr", max_files, max_mb))

    def waiter():
        code = STATE.proc.wait()
        STATE.exit_code = code
        if code in (-9, 137) and STATE.executor is not None \
                and STATE.executor.oom_killed():
            STATE.oom = True
            STATE.exit_code = 137
        STATE.finished_at = time.time()
        if STATE.executor is not None:
            STATE.executor.destroy()
        STATE.done.set()

    threading.Thread(target=waiter, daemon=True).start()
    return {"pid": STATE.proc.pid, "started_at": STATE.started_at,
            "isolated": isolated}


def _wait(args: Dict) -> Dict:
    timeout = args.get("timeout_s")
    done = STATE.done.wait(float(timeout) if timeout else None)
    return {"done": bool(done), "exit_code": STATE.exit_code,
            "finished_at": STATE.finished_at,
            "oom": STATE.oom}


def _shutdown_task(args: Dict) -> Dict:
    import signal as _signal
    grace = float(args.get("grace_s", 5.0))
    proc = STATE.proc
    if proc is not None and proc.poll() is None:
        try:
            # the helper setsid()s, so signal the whole task group
            os.killpg(proc.pid, _signal.SIGTERM)
        except (ProcessLookupError, PermissionError, OSError):
            proc.terminate()
        if not STATE.done.wait(grace):
            try:
                os.killpg(proc.pid, _signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                proc.kill()
            STATE.done.wait(5.0)
    return {"exit_code": STATE.exit_code}


def _signal_task(args: Dict) -> Dict:
    sig = int(args.get("signal", 15))
    proc = STATE.proc
    if proc is not None and proc.poll() is None:
        try:
            os.killpg(proc.pid, sig)
        except (ProcessLookupError, PermissionError, OSError):
            proc.send_signal(sig)
    return {}


def _stats(_args: Dict) -> Dict:
    if STATE.executor is not None:
        return {"stats": STATE.executor.stats()}
    return {"stats": {}}


def _exec_in_task(args: Dict) -> Dict:
    """Executor.Exec: run a command INSIDE the task's isolation — same
    cgroup, same chroot view — and return its output
    (executor_linux.go Exec; the alloc-exec-into-isolation path)."""
    argv = list(args.get("cmd") or [])
    if not argv:
        raise ValueError("exec requires a command")
    timeout = float(args.get("timeout_s", 30.0))
    spec = {
        "procs_files": list(STATE.spec.get("procs_files", [])),
        "chroot_dir": STATE.spec.get("chroot_dir"),
        "chroot_dirs": list(STATE.spec.get("chroot_dirs", [])),
        # the exec session must see the task's volumes at their
        # destinations, not empty stub dirs
        "bind_mounts": list(STATE.spec.get("bind_mounts", [])),
        "command": argv[0],
        "args": argv[1:],
        "env": dict(args.get("env") or STATE.spec.get("env") or {}),
        "cwd": args.get("cwd") or STATE.spec.get("cwd"),
        "user": STATE.spec.get("user"),
    }
    proc = _spawn_helper(spec, subprocess.PIPE, subprocess.STDOUT)
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        out, _ = proc.communicate()
        return {"exit_code": -1, "output": out or b"",
                "timed_out": True}
    return {"exit_code": proc.returncode, "output": out or b"",
            "timed_out": False}


def _state(_args: Dict) -> Dict:
    return {"pid": STATE.proc.pid if STATE.proc else None,
            "started_at": STATE.started_at,
            "finished_at": STATE.finished_at,
            "done": STATE.done.is_set(),
            "exit_code": STATE.exit_code,
            "oom": STATE.oom,
            "cgroup": getattr(STATE.executor, "name", "")}


def main() -> int:
    from ..plugins.base import (HANDSHAKE_COOKIE_KEY,
                                HANDSHAKE_COOKIE_VALUE, HANDSHAKE_PREFIX)
    if os.environ.get(HANDSHAKE_COOKIE_KEY) != HANDSHAKE_COOKIE_VALUE:
        print("This binary is the task executor and must be launched "
              "by the nomad-tpu client agent", file=sys.stderr)
        return 1
    # detach from the client's session: a dying client must not take
    # the executor (and its task) down with it
    try:
        os.setsid()
    except OSError:
        pass
    from ..rpc.server import RpcServer
    stop = threading.Event()

    def _quit(_args: Dict) -> Dict:
        stop.set()
        return {}

    # every call must carry the per-executor auth token the spawning
    # driver generated (passed via our env — only root can read it):
    # the listener is a localhost TCP socket, and without auth any
    # local user could call Executor.Exec into the task or read its
    # env (VAULT_TOKEN) back out. The stdin-only spec transport this
    # replaced existed exactly to avoid that exposure.
    token = os.environ.get("NOMAD_TPU_EXECUTOR_TOKEN", "")

    def _authed(fn):
        def wrapper(args: Dict) -> Dict:
            import hmac
            supplied = str(args.get("auth", ""))
            if not token or not hmac.compare_digest(supplied, token):
                raise PermissionError("executor auth token mismatch")
            return fn(args)
        return wrapper

    rpc = RpcServer(methods={
        name: _authed(fn) for name, fn in {
            "Executor.Launch": _launch,
            "Executor.Wait": _wait,
            "Executor.Shutdown": _shutdown_task,
            "Executor.Signal": _signal_task,
            "Executor.Stats": _stats,
            "Executor.Exec": _exec_in_task,
            "Executor.State": _state,
            "Executor.Quit": _quit,
        }.items()})
    rpc.start()
    sys.stdout.write(HANDSHAKE_PREFIX + rpc.addr + "\n")
    sys.stdout.flush()
    # serve until told to quit; unlike driver plugins the executor must
    # NOT exit when the client's stdin pipe closes — surviving the
    # client is the whole point. It exits when its task is done AND the
    # client has collected the result (Quit), or after an orphan grace
    # period once the task finished.
    while not stop.is_set():
        if STATE.done.is_set():
            # task finished: linger briefly for a reconnecting client
            # to collect the result, then exit
            if stop.wait(60.0):
                break
            break
        stop.wait(1.0)
    rpc.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
