"""docklog: the external container log streamer.

Reference: drivers/docker/docklog/docklog.go — the docker driver
launches `nomad docklog` as a separate process that follows a
container's log stream and writes the task's rotated log files, so log
capture keeps running while the client agent (or the driver plugin)
restarts. Here the spec arrives as JSON on stdin, the process detaches
into its own session, follows `GET /containers/{id}/logs?follow=1`
over the Docker unix socket (demuxing the stream frames), and exits
when the container stops.

Invoked as: python -m nomad_tpu.client.docklog   (spec on STDIN)
spec: {socket_path, container_id, task_name, log_dir,
       log_max_files, log_max_file_size_mb, since}
"""

from __future__ import annotations

import http.client
import json
import os
import struct
import sys
import time


def _connect(socket_path: str):
    from .docker_driver import _UnixHTTPConnection
    return _UnixHTTPConnection(socket_path, timeout=300.0)


def follow(spec: dict) -> int:
    from .logmon import RotatingWriter
    cid = spec["container_id"]
    task = spec.get("task_name", "task")
    log_dir = spec["log_dir"]
    max_files = int(spec.get("log_max_files", 10))
    max_mb = int(spec.get("log_max_file_size_mb", 10))
    since = int(spec.get("since", 0))
    out_w = RotatingWriter(log_dir, f"{task}.stdout", max_files, max_mb)
    err_w = RotatingWriter(log_dir, f"{task}.stderr", max_files, max_mb)
    writers = {1: out_w, 2: err_w}

    announced = False
    while True:
        conn = None
        # the reconnect cursor is the CONNECT time, not per-frame
        # wall-clock: frames buffered behind a slow reader carry
        # emission timestamps older than "now", and a per-frame cursor
        # would drop them on reconnect. Connect-time resume can
        # re-fetch a frame emitted in the same second — duplicates are
        # the acceptable side; loss is not.
        next_since = int(time.time())
        try:
            conn = _connect(spec["socket_path"])
            conn.request(
                "GET",
                f"/containers/{cid}/logs?follow=1&stdout=1&stderr=1"
                f"&since={since}")
            resp = conn.getresponse()
            if resp.status >= 400:
                return 1
            if not announced:
                # startup handshake for the spawning driver
                sys.stdout.write("OK\n")
                sys.stdout.flush()
                announced = True
            # demux the Engine API stream frames:
            # [stream:1][pad:3][len:4][payload]
            while True:
                header = resp.read(8)
                if len(header) < 8:
                    break               # stream closed
                stream_id = header[0]
                (length,) = struct.unpack(">I", header[4:8])
                payload = resp.read(length) if length else b""
                w = writers.get(stream_id, out_w)
                w.write(payload)
        except (OSError, http.client.HTTPException):
            pass
        finally:
            if conn is not None:
                try:
                    conn.close()
                except Exception:
                    pass
        # stream ended: container stopped, dockerd restarted, or a
        # transient error — exit if the container is gone, else
        # reconnect and resume from `since` (docklog.go retry loop)
        try:
            conn = _connect(spec["socket_path"])
            conn.request("GET", f"/containers/{cid}/json")
            resp = conn.getresponse()
            if resp.status >= 400:
                break
            info = json.loads(resp.read() or b"{}")
            if not (info.get("State") or {}).get("Running"):
                break
        except (OSError, http.client.HTTPException, ValueError):
            break
        finally:
            try:
                conn.close()
            except Exception:
                pass
        since = next_since
        time.sleep(0.5)
    for w in writers.values():
        try:
            w.close()
        except Exception:
            pass
    return 0


def main() -> int:
    spec = json.loads(sys.stdin.read())
    try:
        os.setsid()     # survive the client agent
    except OSError:
        pass
    return follow(spec)


if __name__ == "__main__":
    sys.exit(main())
