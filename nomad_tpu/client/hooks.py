"""Task prestart hooks: artifacts and templates.

Reference: client/allocrunner/taskrunner/artifact_hook.go (go-getter
fetch into the task dir) and template_hook.go (consul-template render).
Artifact sources: local paths, file:// and http(s):// URLs. Template
sources: embedded content or a file, rendered with the same ${...}
interpolation the driver config gets (client/taskenv).
"""

from __future__ import annotations

import os
import shutil
import urllib.request
from typing import Dict

from .taskenv import interpolate


class HookError(Exception):
    pass


def fetch_artifacts(task, task_dir: str, env: Dict[str, str],
                    node=None) -> None:
    """artifact_hook.go Prestart: each artifact lands under the task
    dir (relative_dest defaults to local/)."""
    for art in task.artifacts or []:
        source = interpolate(art.getter_source, env, node)
        rel = art.relative_dest or "local/"
        dest_dir = os.path.join(task_dir, rel)
        os.makedirs(dest_dir, exist_ok=True)
        name = os.path.basename(source.split("?")[0]) or "artifact"
        dest = os.path.join(dest_dir, name)
        try:
            if source.startswith(("http://", "https://")):
                with urllib.request.urlopen(source, timeout=30) as r, \
                        open(dest, "wb") as f:
                    shutil.copyfileobj(r, f)
            else:
                path = source[len("file://"):] \
                    if source.startswith("file://") else source
                if os.path.isdir(path):
                    shutil.copytree(path, dest, dirs_exist_ok=True)
                else:
                    shutil.copy(path, dest)
        except Exception as e:
            raise HookError(
                f"failed to fetch artifact {source!r}: {e}") from e
        mode = art.getter_options.get("mode") if art.getter_options else None
        if mode:
            try:
                os.chmod(dest, int(str(mode), 8))
            except (ValueError, OSError):
                pass


def render_templates(task, task_dir: str, env: Dict[str, str],
                     node=None) -> None:
    """template_hook.go Prestart: render embedded or file templates
    with env/node interpolation into the task dir."""
    for tmpl in task.templates or []:
        if tmpl.embedded_tmpl:
            content = tmpl.embedded_tmpl
        elif tmpl.source_path:
            src = interpolate(tmpl.source_path, env, node)
            try:
                with open(src) as f:
                    content = f.read()
            except OSError as e:
                raise HookError(
                    f"failed to read template {src!r}: {e}") from e
        else:
            continue
        rendered = interpolate(content, env, node)
        dest = tmpl.dest_path or "local/template"
        path = os.path.join(task_dir, dest)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(rendered)
