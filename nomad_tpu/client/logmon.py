"""Task log collection with rotation (reference: client/logmon — a
per-task process pumping stdout/stderr FIFOs into size-rotated files
named <task>.<stream>.N; here a pump thread per stream does the same
in-process).
"""

from __future__ import annotations

import os
import threading
from typing import IO, Optional


class RotatingWriter:
    """Writes <prefix>.0, rotating to .1.. when max_file_size is hit and
    pruning past max_files (logmon/logging rotator.go)."""

    def __init__(self, directory: str, prefix: str,
                 max_files: int = 10, max_file_size_mb: int = 10):
        self.dir = directory
        self.prefix = prefix
        self.max_files = max(max_files, 1)
        self.max_bytes = max_file_size_mb * 1024 * 1024
        self._n = 0
        self._size = 0
        self._f: Optional[IO[bytes]] = None
        os.makedirs(directory, exist_ok=True)
        self._open()

    def _path(self, n: int) -> str:
        return os.path.join(self.dir, f"{self.prefix}.{n}")

    def _open(self) -> None:
        self._f = open(self._path(self._n), "ab")
        self._size = self._f.tell()

    def write(self, data: bytes) -> None:
        if self._f is None:
            return
        self._f.write(data)
        self._f.flush()
        self._size += len(data)
        if self._size >= self.max_bytes:
            self._f.close()
            self._n += 1
            self._open()
            drop = self._n - self.max_files
            if drop >= 0:
                try:
                    os.unlink(self._path(drop))
                except OSError:
                    pass

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


def pump(stream, writer: RotatingWriter) -> threading.Thread:
    """Read a subprocess pipe into the rotating writer until EOF.
    Uses read1 so partial output lands in the log file as the task
    produces it — a buffered read(4096) would sit on a live pipe until
    4KB accumulate or the task exits, making `alloc logs -f` blind to
    everything a long-running task has printed so far."""
    read1 = getattr(stream, "read1", None)

    def run():
        try:
            while True:
                chunk = read1(4096) if read1 is not None \
                    else stream.read(4096)
                if not chunk:
                    break
                writer.write(chunk)
        except (OSError, ValueError):
            pass
        finally:
            writer.close()

    t = threading.Thread(target=run, daemon=True, name="logmon-pump")
    t.start()
    return t
