"""Isolated process executor: cgroup resource limits + mount-namespace
chroot containment for the `exec` driver.

Reference semantics: drivers/shared/executor/executor_linux.go (the
libcontainer-based executor: cgroup cpu/memory limits, chroot built
from a directory allowlist, namespace isolation, resource stats) and
executor_universal_linux.go. The TPU-native runtime keeps the same
contract with direct cgroupfs writes and CLONE_NEWNS bind mounts:

  - limits: memory.max / memory.limit_in_bytes (the kernel OOM-kills
    the task when exceeded — the "task exceeding memory_mb is killed"
    contract), cpu.weight / cpu.shares
  - containment: the child unshares its mount namespace, bind-mounts a
    read-only allowlist of system dirs into the task dir, and chroots;
    the mounts die with the namespace so nothing leaks host-side
  - stats: memory.current / memory.usage_in_bytes and cpu usage flow
    into the client's task gauges (executor Stats())

Everything degrades gracefully: without root or writable cgroupfs the
exec driver falls back to plain fork/exec (and says so in its
fingerprint), matching how the reference's exec driver refuses only
when isolation was explicitly required.
"""

from __future__ import annotations

import ctypes
import ctypes.util
import os
import signal
import time
from typing import Dict, List, Optional, Tuple
from ..utils.locks import make_lock

CG_ROOT = "/sys/fs/cgroup"
CG_PARENT = "nomad_tpu"

# mount(2) / unshare(2) constants
MS_RDONLY = 1
MS_REMOUNT = 32
MS_BIND = 4096
MS_REC = 16384
MS_PRIVATE = 1 << 18
CLONE_NEWNS = 0x00020000

# chroot allowlist (drivers/shared/executor: chrootEnv defaults)
DEFAULT_CHROOT_DIRS = ("/bin", "/usr", "/lib", "/lib64", "/etc", "/sbin")

_libc = None


def _get_libc():
    global _libc
    if _libc is None:
        _libc = ctypes.CDLL(ctypes.util.find_library("c") or "libc.so.6",
                            use_errno=True)
    return _libc


class CgroupBackend:
    """v2 when /sys/fs/cgroup/cgroup.controllers lists controllers,
    else v1 (separate memory/ and cpu,cpuacct hierarchies)."""

    def __init__(self, root: str = CG_ROOT):
        self.root = root
        self.v2 = False
        ctrl = os.path.join(root, "cgroup.controllers")
        try:
            with open(ctrl) as f:
                self.v2 = bool(f.read().strip())
        except OSError:
            self.v2 = False

    # -- probes --------------------------------------------------------
    def writable(self) -> bool:
        try:
            if self.v2:
                probe = os.path.join(self.root, CG_PARENT)
                os.makedirs(probe, exist_ok=True)
                return True
            for sub in ("memory", "cpu"):
                probe = os.path.join(self.root, sub, CG_PARENT)
                os.makedirs(probe, exist_ok=True)
            return True
        except OSError:
            return False

    # -- lifecycle -----------------------------------------------------
    def _enable_v2_controllers(self) -> None:
        """Child cgroups only grow memory.max/cpu.weight files when the
        PARENT's cgroup.subtree_control delegates those controllers —
        enable them down the path root -> nomad_tpu."""
        for base in ("", CG_PARENT):
            ctl = os.path.join(self.root, base, "cgroup.subtree_control")
            for c in ("+memory", "+cpu"):
                _write(ctl, c, ignore_errors=True)

    def create(self, name: str, cpu_shares: int,
               memory_mb: int) -> List[str]:
        """Create the task's cgroup dirs, apply limits, and return the
        cgroup.procs paths the child must join. Cleans up the partial
        cgroup if a limit write fails."""
        try:
            return self._create(name, cpu_shares, memory_mb)
        except OSError:
            self.destroy(name)
            raise

    def _create(self, name: str, cpu_shares: int,
                memory_mb: int) -> List[str]:
        procs: List[str] = []
        if self.v2:
            self._enable_v2_controllers()
            path = os.path.join(self.root, CG_PARENT, name)
            os.makedirs(path, exist_ok=True)
            if memory_mb > 0:
                _write(os.path.join(path, "memory.max"),
                       str(memory_mb * 1024 * 1024))
                # fail fast instead of swapping forever
                _write(os.path.join(path, "memory.swap.max"), "0",
                       ignore_errors=True)
            if cpu_shares > 0:
                # shares (2..262144) -> weight (1..10000), the kernel's
                # own conversion formula
                weight = 1 + ((cpu_shares - 2) * 9999) // 262142
                _write(os.path.join(path, "cpu.weight"),
                       str(max(1, min(10000, weight))),
                       ignore_errors=True)
            procs.append(os.path.join(path, "cgroup.procs"))
            return procs
        mem = os.path.join(self.root, "memory", CG_PARENT, name)
        os.makedirs(mem, exist_ok=True)
        if memory_mb > 0:
            _write(os.path.join(mem, "memory.limit_in_bytes"),
                   str(memory_mb * 1024 * 1024))
            _write(os.path.join(mem, "memory.memsw.limit_in_bytes"),
                   str(memory_mb * 1024 * 1024), ignore_errors=True)
        procs.append(os.path.join(mem, "cgroup.procs"))
        cpu = os.path.join(self.root, "cpu", CG_PARENT, name)
        try:
            os.makedirs(cpu, exist_ok=True)
            if cpu_shares > 0:
                _write(os.path.join(cpu, "cpu.shares"),
                       str(max(2, cpu_shares)), ignore_errors=True)
            procs.append(os.path.join(cpu, "cgroup.procs"))
        except OSError:
            pass
        return procs

    def paths_for(self, name: str) -> List[str]:
        if self.v2:
            return [os.path.join(self.root, CG_PARENT, name)]
        return [os.path.join(self.root, "memory", CG_PARENT, name),
                os.path.join(self.root, "cpu", CG_PARENT, name)]

    def stats(self, name: str) -> Dict[str, float]:
        """Resource usage for the task's cgroup (executor Stats())."""
        out: Dict[str, float] = {}
        try:
            if self.v2:
                base = os.path.join(self.root, CG_PARENT, name)
                out["memory_bytes"] = float(_read(
                    os.path.join(base, "memory.current")) or 0)
                for line in (_read(os.path.join(base, "cpu.stat"))
                             or "").splitlines():
                    if line.startswith("usage_usec"):
                        out["cpu_total_ns"] = float(
                            line.split()[1]) * 1000.0
            else:
                mem = os.path.join(self.root, "memory", CG_PARENT, name)
                out["memory_bytes"] = float(_read(
                    os.path.join(mem, "memory.usage_in_bytes")) or 0)
                cpuacct = os.path.join(self.root, "cpuacct", CG_PARENT,
                                       name, "cpuacct.usage")
                usage = _read(cpuacct)
                if usage is None:
                    usage = _read(os.path.join(self.root, "cpu", CG_PARENT,
                                               name, "cpuacct.usage"))
                if usage is not None:
                    out["cpu_total_ns"] = float(usage)
        except (OSError, ValueError):
            pass
        return out

    def oom_killed(self, name: str) -> bool:
        """Did the kernel OOM-kill inside this cgroup?"""
        try:
            if self.v2:
                events = _read(os.path.join(self.root, CG_PARENT, name,
                                            "memory.events")) or ""
                for line in events.splitlines():
                    if line.startswith("oom_kill"):
                        return int(line.split()[1]) > 0
                return False
            ctl = _read(os.path.join(self.root, "memory", CG_PARENT, name,
                                     "memory.oom_control")) or ""
            for line in ctl.splitlines():
                if line.startswith("oom_kill "):
                    return int(line.split()[1]) > 0
            # older kernels only expose under_oom; fall back to failcnt
            fail = _read(os.path.join(self.root, "memory", CG_PARENT, name,
                                      "memory.failcnt"))
            return bool(fail and int(fail) > 0)
        except (OSError, ValueError):
            return False

    def destroy(self, name: str) -> None:
        """Kill any stragglers in the cgroup and remove it."""
        for base in self.paths_for(name):
            procs_file = os.path.join(base, "cgroup.procs")
            for _ in range(10):
                pids = (_read(procs_file) or "").split()
                if not pids:
                    break
                for pid in pids:
                    try:
                        os.kill(int(pid), signal.SIGKILL)
                    except (ProcessLookupError, ValueError,
                            PermissionError):
                        pass
                time.sleep(0.05)
            try:
                os.rmdir(base)
            except OSError:
                pass


def _write(path: str, value: str, ignore_errors: bool = False) -> None:
    try:
        with open(path, "w") as f:
            f.write(value)
    except OSError:
        if not ignore_errors:
            raise


def _read(path: str) -> Optional[str]:
    try:
        with open(path) as f:
            return f.read().strip()
    except OSError:
        return None


class IsolatedExecutor:
    """Owns one task's cgroup (limits, stats, teardown) and the chroot
    parameters the exec_helper bootstrap applies in the re-exec'd
    child. Used by ExecDriver when available()."""

    _avail: Optional[bool] = None
    _avail_lock = make_lock()

    @classmethod
    def available(cls) -> bool:
        with cls._avail_lock:
            if cls._avail is None:
                cls._avail = (os.name == "posix"
                              and hasattr(os, "geteuid")
                              and os.geteuid() == 0
                              and CgroupBackend().writable())
            return cls._avail

    def __init__(self, name: str, cpu_shares: int, memory_mb: int,
                 chroot_dir: Optional[str] = None,
                 chroot_dirs: Tuple[str, ...] = DEFAULT_CHROOT_DIRS):
        self.name = name
        self.backend = CgroupBackend()
        self.procs_files = self.backend.create(name, cpu_shares,
                                               memory_mb)
        self.chroot_dir = chroot_dir
        self.chroot_dirs = chroot_dirs

    @classmethod
    def recover(cls, name: str) -> "IsolatedExecutor":
        """Reconstruct the executor for a re-attached task from its
        persisted cgroup name so destroy()/stats() keep working after a
        client restart (executor re-attach, task_runner.go:996)."""
        ex = cls.__new__(cls)
        ex.name = name
        ex.backend = CgroupBackend()
        ex.procs_files = []
        ex.chroot_dir = None
        ex.chroot_dirs = DEFAULT_CHROOT_DIRS
        return ex

    def stats(self) -> Dict[str, float]:
        return self.backend.stats(self.name)

    def oom_killed(self) -> bool:
        return self.backend.oom_killed(self.name)

    def destroy(self) -> None:
        self.backend.destroy(self.name)
