"""Previous-allocation watcher: wait for the predecessor to terminate
and migrate its ephemeral disk into the replacement's alloc dir.

Reference: client/allocwatcher/alloc_watcher.go — a replacement alloc
(previous_allocation set) blocks its tasks until the watched alloc is
terminal; with ephemeral_disk {migrate = true} the shared data dir and
each task's local dir move over — locally when the predecessor ran on
this node, remotely via the owning client's fs API otherwise
(migrateRemoteAllocDir). sticky-without-migrate moves local data only.
"""

from __future__ import annotations

import logging
import os
import shutil
import time
from typing import Optional

LOG = logging.getLogger("nomad_tpu.allocwatcher")

WAIT_PREV_TIMEOUT_S = 120.0
POLL_S = 0.5

# the dir set that migrates (allocwatcher: SharedAllocDir data + task
# local dirs)
def _migrate_paths(task_names):
    return ["alloc/data"] + [f"{t}/local" for t in task_names]


def wait_for_previous(get_alloc, prev_id: str,
                      timeout_s: float = WAIT_PREV_TIMEOUT_S):
    """Block until the previous alloc is terminal. Returns
    (status, record) where status is 'terminal' (record carries node
    info), 'gone' (GC'd — nothing to migrate), or 'timeout' (still
    running — migrating now would copy a torn mid-write disk)."""
    deadline = time.monotonic() + timeout_s
    last = None
    while time.monotonic() < deadline:
        try:
            last = get_alloc(prev_id)
        except Exception:
            # transient server unreachability must NOT read as "GC'd"
            # — that would silently skip a migration whose data still
            # exists. Keep retrying until the deadline.
            time.sleep(POLL_S)
            continue
        if last is None:
            return "gone", None             # GC'd: nothing to wait on
        status = (last.get("alloc") or {}).get("client_status", "")
        desired = (last.get("alloc") or {}).get("desired_status", "")
        if status in ("complete", "failed", "lost"):
            return "terminal", last
        if desired not in ("stop", "evict") and status not in (
                "pending", "running"):
            return "terminal", last
        time.sleep(POLL_S)
    LOG.warning("previous alloc %s did not terminate within %.0fs; "
                "proceeding without migration", prev_id[:8], timeout_s)
    return "timeout", last


def _copy_local(src_base: str, dst_base: str, rel_paths) -> int:
    moved = 0
    for rel in rel_paths:
        src = os.path.join(src_base, rel)
        dst = os.path.join(dst_base, rel)
        if not os.path.isdir(src):
            continue
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copytree(src, dst, dirs_exist_ok=True, symlinks=True)
        moved += 1
    return moved


def _fetch_remote_tree(rpc_call, prev_id: str, rel: str,
                       dst: str) -> None:
    """Recursive pull of one dir over the owning client's fs API
    (ClientFS.List/Cat — the remote side of migrateRemoteAllocDir)."""
    entries = rpc_call("ClientFS.List",
                       {"alloc_id": prev_id, "path": rel})["Entries"]
    if entries is None:
        return
    os.makedirs(dst, exist_ok=True)
    for e in entries:
        name = e["Name"]
        sub_rel = f"{rel}/{name}"
        sub_dst = os.path.join(dst, name)
        if e.get("IsDir"):
            _fetch_remote_tree(rpc_call, prev_id, sub_rel, sub_dst)
        else:
            # CHUNKED pull via the frame stream: a whole-file Cat
            # would buffer multi-GB files in RAM on both ends and
            # blow the RPC timeout exactly when migration matters
            offset = 0
            with open(sub_dst, "wb") as f:
                while True:
                    frames = rpc_call(
                        "ClientFS.Stream",
                        {"alloc_id": prev_id, "path": sub_rel,
                         "offset": offset})["Frames"]
                    progressed = False
                    for fr in frames:
                        data = bytes(fr.get("Data") or b"")
                        if data:
                            f.write(data)
                            offset = fr["Offset"] + len(data)
                            progressed = True
                    if not progressed:
                        break
            mode = e.get("FileMode")
            if mode:
                os.chmod(sub_dst, int(mode))


def migrate_previous(client, runner) -> None:
    """The prerun hook: wait on the predecessor, then migrate its
    ephemeral disk when the group asks for it. Failures degrade to a
    fresh disk (logged), never a dead alloc."""
    alloc = runner.alloc
    prev_id = alloc.previous_allocation
    if not prev_id or alloc.job is None:
        return
    tg = alloc.job.lookup_task_group(alloc.task_group)
    if tg is None or tg.ephemeral_disk is None:
        return
    ed = tg.ephemeral_disk
    if not (ed.sticky or ed.migrate):
        return

    get_alloc = getattr(client.transport, "get_alloc", None)
    wait_status, prev_info = "gone", None
    if get_alloc is not None:
        wait_status, prev_info = wait_for_previous(get_alloc, prev_id)
    if wait_status == "timeout":
        # the predecessor is STILL RUNNING: copying its disk now would
        # snapshot files mid-write — start fresh instead
        return

    task_names = [t.name for t in tg.tasks]
    rels = _migrate_paths(task_names)
    dst_base = runner.alloc_dir.base

    # local predecessor: straight copy
    src_base = client.alloc_base(prev_id)
    if src_base is not None:
        moved = _copy_local(src_base, dst_base, rels)
        LOG.info("migrated %d dirs locally from %s", moved, prev_id[:8])
        return

    # remote predecessor: pull over the owning client's fs API
    if not ed.migrate or prev_info is None:
        return                              # sticky-only is node-local
    node_rpc = prev_info.get("node_rpc") or ""
    if not node_rpc:
        LOG.warning("previous alloc %s: owning node has no client RPC "
                    "address; starting with a fresh ephemeral disk",
                    prev_id[:8])
        return
    from ..rpc.client import RpcClient
    c = RpcClient(node_rpc, dial_timeout_s=3.0)
    ok = fail = 0
    try:
        for rel in rels:
            try:
                _fetch_remote_tree(
                    lambda m, a: c.call(m, a, timeout_s=60.0),
                    prev_id, rel, os.path.join(dst_base, rel))
                ok += 1
            except Exception as e:
                fail += 1
                LOG.warning("remote migration of %s from %s failed: %s",
                            rel, prev_id[:8], e)
        if fail:
            LOG.warning("remote migration from %s INCOMPLETE: %d of %d "
                        "dirs failed; the replacement starts with a "
                        "partial disk", prev_id[:8], fail, ok + fail)
        else:
            LOG.info("migrated ephemeral disk remotely from %s via %s",
                     prev_id[:8], node_rpc)
    finally:
        c.close()
