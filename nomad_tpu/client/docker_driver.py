"""Docker task driver over the Engine HTTP API.

Reference surface: drivers/docker/driver.go (4.7k LoC) — image pull,
container create/start/stop/remove, port maps, resource limits,
stats, log collection, RecoverTask re-attach, and the orphan-container
reconciler (drivers/docker/reconciler.go: containers labeled as
nomad-managed whose alloc no longer exists get stopped). This driver
speaks the Engine API directly over the unix socket (no docker SDK in
the image); it registers only when a reachable dockerd advertises a
version, and fingerprints as absent otherwise.
"""

from __future__ import annotations

import http.client
import json
import logging
import os
import socket
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..plugins.hclspec import Attr as _SpecAttr
from .drivers import TaskHandle

LOG = logging.getLogger("nomad_tpu.docker")

DEFAULT_SOCKET = "/var/run/docker.sock"
LABEL_ALLOC = "com.nomad-tpu.alloc_id"
LABEL_TASK = "com.nomad-tpu.task"


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, path: str, timeout: float = 60.0):
        super().__init__("localhost", timeout=timeout)
        self._path = path

    def connect(self):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(self.timeout)
        sock.connect(self._path)
        self.sock = sock


class DockerAPIError(RuntimeError):
    def __init__(self, status: int, message: str):
        super().__init__(f"docker API {status}: {message}")
        self.status = status


class DockerAPI:
    """Minimal Engine API client (one connection per request — the
    engine supports keep-alive but per-request keeps stream handling
    simple)."""

    def __init__(self, socket_path: str = DEFAULT_SOCKET):
        self.socket_path = socket_path

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 timeout: float = 60.0) -> Tuple[int, bytes]:
        conn = _UnixHTTPConnection(self.socket_path, timeout=timeout)
        try:
            data = json.dumps(body).encode() if body is not None else None
            headers = {"Content-Type": "application/json"} if data else {}
            conn.request(method, path, body=data, headers=headers)
            resp = conn.getresponse()
            payload = resp.read()
            return resp.status, payload
        finally:
            conn.close()

    def call(self, method: str, path: str, body: Optional[dict] = None,
             timeout: float = 60.0):
        status, payload = self._request(method, path, body, timeout)
        if status >= 400:
            try:
                msg = json.loads(payload).get("message", payload.decode())
            except Exception:
                msg = payload.decode("utf-8", "replace")
            raise DockerAPIError(status, msg)
        if not payload:
            return None
        try:
            return json.loads(payload)
        except json.JSONDecodeError:
            return payload

    # -- surface -------------------------------------------------------
    def version(self) -> Optional[dict]:
        try:
            return self.call("GET", "/version", timeout=3.0)
        except (OSError, DockerAPIError):
            return None

    @staticmethod
    def normalize_image(image: str) -> str:
        """Tagless references mean :latest (docker's own resolution)."""
        if ":" not in image.rsplit("/", 1)[-1]:
            return image + ":latest"
        return image

    def pull(self, image: str, timeout: float = 600.0) -> None:
        image = self.normalize_image(image)
        # the create-image endpoint answers 200 immediately and streams
        # progress JSON; FAILURES arrive as error messages inside the
        # stream, not as an HTTP status
        status, payload = self._request(
            "POST", f"/images/create?fromImage={image}", timeout=timeout)
        if status >= 400:
            raise DockerAPIError(status, payload.decode("utf-8", "replace"))
        for line in payload.decode("utf-8", "replace").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                msg = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(msg, dict) and ("error" in msg
                                          or "errorDetail" in msg):
                detail = msg.get("error") or \
                    (msg.get("errorDetail") or {}).get("message", "")
                raise DockerAPIError(500, f"pull of {image} failed: "
                                          f"{detail}")

    def image_exists(self, image: str) -> bool:
        try:
            self.call("GET", f"/images/{image}/json", timeout=10.0)
            return True
        except DockerAPIError as e:
            if e.status == 404:
                return False
            raise

    def create_container(self, name: str, spec: dict) -> str:
        out = self.call("POST", f"/containers/create?name={name}", spec)
        return out["Id"]

    def start(self, cid: str) -> None:
        self.call("POST", f"/containers/{cid}/start")

    def stop(self, cid: str, timeout_s: int = 5) -> None:
        self.call("POST", f"/containers/{cid}/stop?t={int(timeout_s)}",
                  timeout=timeout_s + 15.0)

    def kill(self, cid: str) -> None:
        self.call("POST", f"/containers/{cid}/kill")

    def remove(self, cid: str, force: bool = True) -> None:
        self.call("DELETE",
                  f"/containers/{cid}?force={'true' if force else 'false'}")

    def inspect(self, cid: str) -> dict:
        return self.call("GET", f"/containers/{cid}/json")

    def wait(self, cid: str, timeout: float = 86400.0) -> int:
        out = self.call("POST", f"/containers/{cid}/wait",
                        timeout=timeout)
        return int(out.get("StatusCode", -1))

    def stats(self, cid: str) -> dict:
        return self.call("GET", f"/containers/{cid}/stats?stream=false",
                         timeout=20.0) or {}

    def list_containers(self, label: Optional[str] = None,
                        all_: bool = True) -> List[dict]:
        path = f"/containers/json?all={'true' if all_ else 'false'}"
        if label:
            filters = json.dumps({"label": [label]})
            from urllib.parse import quote
            path += f"&filters={quote(filters)}"
        return self.call("GET", path) or []

    def logs(self, cid: str, since: int = 0) -> Tuple[bytes, bytes]:
        """(stdout, stderr) since the unix timestamp — demuxes the
        engine's 8-byte-header stream framing."""
        status, payload = self._request(
            "GET",
            f"/containers/{cid}/logs?stdout=true&stderr=true&since={since}",
            timeout=30.0)
        if status >= 400:
            raise DockerAPIError(status,
                                 payload.decode("utf-8", "replace"))
        out = [b"", b""]
        i = 0
        while i + 8 <= len(payload):
            stream, size = struct.unpack(">BxxxL", payload[i:i + 8])
            chunk = payload[i + 8:i + 8 + size]
            if stream == 2:
                out[1] += chunk
            else:
                out[0] += chunk
            i += 8 + size
        if i == 0 and payload:          # tty containers: raw stream
            out[0] = payload
        return out[0], out[1]


def _pid_is_docklog(pid, cid: str = "") -> bool:
    """A recycled pid must not masquerade as a live docklog: verify
    the process runs the docklog module FOR THIS CONTAINER (the
    container id rides argv precisely so this check can tell two
    docklogs apart after pid reuse)."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmdline = f.read()
        if b"nomad_tpu.client.docklog" not in cmdline:
            return False
        return (cid[:12].encode() in cmdline) if cid else True
    except OSError:
        return False


class DockerDriver:
    """drivers/docker as a nomad_tpu task driver. Registers only when
    dockerd answers /version (fingerprint absent otherwise — the
    scheduler's DriverChecker then filters such nodes)."""

    name = "docker"
    CONFIG_SPEC = {
        "image": _SpecAttr("string", required=True),
        "command": _SpecAttr("string"),
        "args": _SpecAttr("list(string)", default=[]),
        # open maps: user-chosen keys (a Block would reject them all)
        "port_map": _SpecAttr("any"),
        "network_mode": _SpecAttr("string"),
        "force_pull": _SpecAttr("bool", default=False),
        "labels": _SpecAttr("any"),
        # "host:container[:ro]" bind specs (drivers/docker volumes)
        "volumes": _SpecAttr("list(string)", default=[]),
    }

    def __init__(self, socket_path: str = DEFAULT_SOCKET):
        self.api = DockerAPI(socket_path)
        self._version = self.api.version()
        self._reconciler: Optional[threading.Thread] = None
        self._reconcile_stop = threading.Event()

    def available(self) -> bool:
        return self._version is not None

    def fingerprint(self) -> Dict[str, str]:
        if not self.available():
            return {}
        return {"driver.docker": "1",
                "driver.docker.version":
                    str(self._version.get("Version", "unknown"))}

    # -- port maps -----------------------------------------------------
    @staticmethod
    def _port_bindings(port_map: Dict, alloc_networks: List) -> Tuple[Dict, Dict]:
        """(ExposedPorts, PortBindings): container port label->host port
        from the alloc's reserved/dynamic port offers
        (drivers/docker port_map semantics: port_map maps LABEL ->
        container port; the alloc network supplies the host port for
        that label)."""
        from .drivers import resolve_host_ports
        exposed: Dict[str, dict] = {}
        bindings: Dict[str, list] = {}
        host_ports = resolve_host_ports(alloc_networks)
        for label, container_port in (port_map or {}).items():
            hp = host_ports.get(label)
            if hp is None:
                continue
            key = f"{int(container_port)}/tcp"
            exposed[key] = {}
            bindings[key] = [{"HostIp": hp[1],
                              "HostPort": str(hp[0])}]
        return exposed, bindings

    # -- lifecycle -----------------------------------------------------
    def start_task(self, task_name: str, config: dict, env: dict,
                   ctx: Optional[dict] = None) -> TaskHandle:
        if not self.available():
            raise RuntimeError("dockerd is not reachable")
        ctx = ctx or {}
        image = self.api.normalize_image(config["image"])
        if config.get("force_pull") or not self.api.image_exists(image):
            self.api.pull(image)
        resources = ctx.get("resources") or {}
        alloc_id = ctx.get("alloc_id", "anon")
        alloc_networks = ctx.get("alloc_networks") or []
        # network modes (drivers/docker/network.go): bridge (default)
        # gets the label->container port bindings; host and
        # container:<name> share another namespace's stack, where
        # Docker rejects port bindings — ports ride the joined
        # namespace instead
        net_mode = (config.get("network_mode") or "").strip()
        shares_netns = net_mode == "host" or \
            net_mode.startswith("container:")
        if shares_netns:
            exposed, bindings = {}, {}
        else:
            exposed, bindings = self._port_bindings(
                config.get("port_map") or {}, alloc_networks)
        # volumes: jobspec "host:container[:ro]" specs plus the group's
        # volume_mount stanzas resolved by the alloc runner (CSI publish
        # targets / host volumes) — drivers/docker volumes + mounts
        binds = [str(v) for v in (config.get("volumes") or [])]
        for vm in (ctx.get("volume_mounts") or []):
            mode = ":ro" if vm.get("read_only") else ""
            binds.append(f"{vm['source']}:{vm['destination']}{mode}")
        spec = {
            "Image": image,
            "Env": [f"{k}={v}" for k, v in (env or {}).items()],
            "Labels": {LABEL_ALLOC: alloc_id, LABEL_TASK: task_name,
                       **(config.get("labels") or {})},
            "ExposedPorts": exposed,
            "HostConfig": {
                "Memory": int(resources.get("memory_mb", 0)) * 1024 * 1024,
                "CPUShares": int(resources.get("cpu", 0)),
                "PortBindings": bindings,
                "Binds": binds,
            },
        }
        if config.get("command"):
            spec["Cmd"] = [config["command"]] + \
                list(config.get("args") or [])
        if net_mode:
            spec["HostConfig"]["NetworkMode"] = net_mode
        cname = f"nomad-{alloc_id[:8]}-{task_name}-{int(time.time())}"
        cid = self.api.create_container(cname, spec)
        try:
            self.api.start(cid)
        except DockerAPIError:
            try:
                self.api.remove(cid)
            except Exception:
                pass
            raise
        h = TaskHandle(task_name=task_name, driver=self.name,
                       config=config, started_at=time.time())
        h.container_id = cid

        log_dir = ctx.get("log_dir")
        docklog_ok = False
        if log_dir:
            # external docklog process (drivers/docker/docklog): log
            # streaming keeps running across client/driver restarts
            try:
                h.docklog_pid = self._spawn_docklog(
                    cid, task_name, log_dir, ctx)
                h.log_dir = log_dir
                h.log_max_files = int(ctx.get("log_max_files", 10))
                h.log_max_file_size_mb = int(
                    ctx.get("log_max_file_size_mb", 10))
                docklog_ok = True
            except Exception:
                LOG.exception("docklog spawn for %s failed; falling "
                              "back to exit-time collection", cid[:12])

        def wait():
            code = self._wait_resilient(h.container_id)
            if log_dir and not docklog_ok:
                try:
                    self._collect_logs(h.container_id, task_name, log_dir,
                                       ctx)
                except Exception:
                    LOG.debug("log collection for %s failed",
                              h.container_id[:12])
            h.exit_code = code
            h.finished_at = time.time()
            h._done.set()

        threading.Thread(target=wait, daemon=True,
                         name=f"docker-wait-{cid[:12]}").start()
        return h

    def _spawn_docklog(self, cid: str, task_name: str, log_dir: str,
                       ctx: dict, since: int = 0) -> int:
        """Launch the detached docklog streamer (docklog.go analog).
        Returns its pid; the process exits on its own when the
        container stops."""
        import json as _json
        import subprocess
        import sys as _sys

        from .drivers import child_process_env
        spec = {"socket_path": self.api.socket_path,
                "container_id": cid,
                "task_name": task_name,
                "log_dir": log_dir,
                "log_max_files": int(ctx.get("log_max_files", 10)),
                "log_max_file_size_mb": int(
                    ctx.get("log_max_file_size_mb", 10)),
                "since": since}
        proc = subprocess.Popen(
            [_sys.executable, "-m", "nomad_tpu.client.docklog",
             cid[:12]],
            env=child_process_env(),
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, start_new_session=True)
        proc.stdin.write(_json.dumps(spec).encode())
        proc.stdin.close()
        # startup handshake: docklog prints OK once its first follow
        # request succeeded — a docklog that dies during startup must
        # not disable the exit-time collection fallback
        import select as _select
        ready, _w, _x = _select.select([proc.stdout], [], [], 10.0)
        line = proc.stdout.readline() if ready else b""
        if not line.startswith(b"OK"):
            try:
                proc.kill()
                proc.wait(timeout=5)
            except Exception:
                pass
            raise RuntimeError("docklog failed to start streaming")
        # reap + watchdog: a docklog that dies while the container
        # still runs is respawned (resuming from now) so log capture
        # doesn't silently stop mid-task
        def reap_and_respawn():
            proc.wait()
            for _attempt in range(3):
                try:
                    info = self.api.inspect(cid)
                except (DockerAPIError, OSError):
                    return
                if not (info.get("State") or {}).get("Running"):
                    return          # normal end-of-task exit
                LOG.warning("docklog for %s died mid-task; respawning",
                            cid[:12])
                try:
                    self._spawn_docklog(cid, task_name, log_dir, ctx,
                                        since=int(time.time()))
                    return          # the new spawn has its own watchdog
                except Exception:
                    LOG.exception("docklog respawn failed")
                    time.sleep(1.0)

        threading.Thread(target=reap_and_respawn, daemon=True,
                         name=f"docklog-reap-{cid[:12]}").start()
        return proc.pid

    def _collect_logs(self, cid: str, task_name: str, log_dir: str,
                      ctx: dict) -> None:
        from .logmon import RotatingWriter
        out, err = self.api.logs(cid)
        max_files = int(ctx.get("log_max_files", 10))
        max_mb = int(ctx.get("log_max_file_size_mb", 10))
        if out:
            w = RotatingWriter(log_dir, f"{task_name}.stdout",
                               max_files, max_mb)
            w.write(out)
            w.close()
        if err:
            w = RotatingWriter(log_dir, f"{task_name}.stderr",
                               max_files, max_mb)
            w.write(err)
            w.close()

    def _wait_resilient(self, cid: str) -> int:
        """api.wait that survives dockerd hiccups: the wait thread
        must ALWAYS complete the handle, or the task runner blocks in
        RUNNING forever. On persistent failure the container is
        treated as lost (137)."""
        while True:
            try:
                return self.api.wait(cid)
            except (DockerAPIError, OSError) as e:
                try:
                    info = self.api.inspect(cid)
                    state = info.get("State") or {}
                    if not state.get("Running"):
                        return int(state.get("ExitCode", 137))
                except (DockerAPIError, OSError):
                    LOG.warning("container %s unreachable (%s); "
                                "reporting lost", cid[:12], e)
                    return 137
                time.sleep(1.0)

    def stop_task(self, handle: TaskHandle, timeout_s: float = 5.0) -> None:
        cid = getattr(handle, "container_id", None)
        if not cid:
            return
        try:
            self.api.stop(cid, int(timeout_s))
        except (DockerAPIError, OSError):
            try:
                self.api.kill(cid)
            except (DockerAPIError, OSError):
                pass
        handle.wait(timeout_s + 10.0)

    def destroy_task(self, handle: TaskHandle) -> None:
        cid = getattr(handle, "container_id", None)
        if cid:
            try:
                self.api.remove(cid)
            except (DockerAPIError, OSError):
                pass

    def stats(self, handle: TaskHandle) -> Dict[str, float]:
        cid = getattr(handle, "container_id", None)
        if not cid:
            return {}
        try:
            s = self.api.stats(cid)
        except (DockerAPIError, OSError):
            return {}
        mem = (s.get("memory_stats") or {}).get("usage", 0)
        cpu = ((s.get("cpu_stats") or {}).get("cpu_usage") or {}) \
            .get("total_usage", 0)
        return {"memory_bytes": float(mem), "cpu_total_ns": float(cpu)}

    def recover_task(self, state: dict) -> Optional[TaskHandle]:
        """Re-attach to a live container after a client restart
        (RecoverTask, drivers/docker/driver.go)."""
        cid = state.get("container_id")
        if not cid or not self.available():
            return None
        try:
            info = self.api.inspect(cid)
        except (DockerAPIError, OSError):
            return None
        if not (info.get("State") or {}).get("Running"):
            return None
        h = TaskHandle(task_name=state.get("task_name", ""),
                       driver=self.name,
                       config=state.get("config") or {},
                       started_at=float(state.get("started_at")
                                        or time.time()),
                       id=state.get("id", ""))
        h.container_id = cid
        # docklog normally survives the restart (own session); respawn
        # only if it died while the container lives (docklog.go
        # re-launch on recovery)
        dl_pid = state.get("docklog_pid")
        log_dir = state.get("log_dir") or ""
        if dl_pid and log_dir:
            log_ctx = {"log_max_files": state.get("log_max_files", 10),
                       "log_max_file_size_mb":
                           state.get("log_max_file_size_mb", 10)}
            if _pid_is_docklog(dl_pid, cid):
                h.docklog_pid = dl_pid
                h.log_dir = log_dir
            else:
                try:
                    h.docklog_pid = self._spawn_docklog(
                        cid, state.get("task_name", "task"), log_dir,
                        log_ctx, since=int(time.time()))
                    h.log_dir = log_dir
                except Exception:
                    LOG.exception("docklog respawn for %s failed",
                                  cid[:12])

        def wait():
            h.exit_code = self._wait_resilient(cid)
            h.finished_at = time.time()
            h._done.set()

        threading.Thread(target=wait, daemon=True).start()
        return h

    # -- orphan reconciler (drivers/docker/reconciler.go) --------------
    def reconcile_orphans(self, live_alloc_ids) -> List[str]:
        """Stop+remove nomad-labeled containers whose alloc this agent
        no longer tracks. Returns removed container ids."""
        if not self.available():
            return []
        removed = []
        try:
            containers = self.api.list_containers(label=LABEL_ALLOC)
        except (DockerAPIError, OSError):
            return []
        live = set(live_alloc_ids)
        for c in containers:
            labels = c.get("Labels") or {}
            aid = labels.get(LABEL_ALLOC)
            if aid and aid not in live:
                cid = c.get("Id")
                try:
                    LOG.warning("reconciler: removing orphan container "
                                "%s (alloc %s)", cid[:12], aid[:8])
                    self.api.remove(cid, force=True)
                    removed.append(cid)
                except DockerAPIError:
                    pass
        return removed

    def start_reconciler(self, live_alloc_ids_fn,
                         interval_s: float = 30.0) -> None:
        """Periodic orphan sweep bound to the owning client's live
        alloc view."""
        def loop():
            while not self._reconcile_stop.wait(interval_s):
                try:
                    self.reconcile_orphans(live_alloc_ids_fn())
                except Exception:
                    LOG.exception("docker reconcile failed")
        self._reconciler = threading.Thread(target=loop, daemon=True,
                                            name="docker-reconciler")
        self._reconciler.start()

    def shutdown(self) -> None:
        self._reconcile_stop.set()
