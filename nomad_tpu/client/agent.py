"""The client node agent: fingerprint -> register -> heartbeat ->
watch allocations -> run tasks -> push status.

Reference semantics: client/client.go (registerAndHeartbeat:1526,
watchAllocations:1969 long-poll diff by modify index, runAllocs:2190),
client/allocrunner (task fan-out, status aggregation), taskrunner
(restart policy, kill handling).
"""

from __future__ import annotations

import logging
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..models import (
    Allocation, Node, NodeResources, TaskState, TaskEvent,
    ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_PENDING,
    ALLOC_CLIENT_RUNNING,
    NODE_STATUS_INIT, NODE_STATUS_READY,
)
from ..models.alloc import TASK_STATE_DEAD, TASK_STATE_PENDING, TASK_STATE_RUNNING
from ..models.resources import (NodeCpuResources, NodeDiskResources,
                                NodeMemoryResources)
from ..utils.ids import generate_uuid
from .drivers import DRIVER_CATALOG, TaskHandle
from ..utils.locks import make_lock

LOG = logging.getLogger("nomad_tpu.client")


@dataclass
class ClientConfig:
    node_name: str = ""
    datacenter: str = "dc1"
    node_class: str = ""
    cpu_shares: int = 4000
    memory_mb: int = 8192
    disk_mb: int = 100 * 1024
    # docker registers only when a reachable dockerd answers /version;
    # hosts without it drop the driver (and its node attribute) cleanly
    # conditional drivers (docker/java/qemu) drop out cleanly when
    # their binary/daemon is absent (the available() probe)
    drivers: tuple = ("mock_driver", "raw_exec", "exec", "docker",
                      "java", "qemu")
    meta: dict = field(default_factory=dict)
    poll_interval_s: float = 0.2
    heartbeat_interval_s: float = 3.0
    # durable state: when set, alloc/task/driver-handle transitions
    # persist here and a restarted client restores + re-attaches
    # (client/state/state_database.go)
    state_dir: Optional[str] = None
    # base directory for per-alloc dir trees (client/allocdir);
    # empty -> the system temp dir
    alloc_dir: str = ""
    # device fingerprinting: statically declared device groups
    # (NodeDeviceResource) plus optional JAX accelerator autodetection
    # (the TPU-native analog of devices/gpu/nvidia fingerprint)
    devices: tuple = ()
    fingerprint_accelerators: bool = False
    # drivers to run behind the plugin PROCESS boundary
    # (plugins/driver_client.py; go-plugin analog) instead of in-proc
    plugin_drivers: tuple = ()
    # accelerator fingerprint via the out-of-proc device plugin
    # (plugins/device_client.py) instead of in-proc probing
    plugin_device_fingerprint: bool = False
    # client RPC listener serving logs/fs/exec to forwarding servers
    # (client/fs_endpoint.go, client/alloc_endpoint.go); port 0 picks
    # an ephemeral port, None disables the listener. rpc_host is the
    # bind address; rpc_advertise is what goes on the node record for
    # servers to dial (cross-host deployments must set it to a
    # reachable address — loopback only works single-machine)
    rpc_port: Optional[int] = 0
    rpc_host: str = "127.0.0.1"
    rpc_advertise: str = ""
    # CSI plugins to launch behind the plugin process boundary
    # (plugins/csi_client.py CSI_PLUGIN_CATALOG names); the client
    # stages/publishes volumes through them (client/pluginmanager/
    # csimanager)
    csi_plugins: tuple = ()
    # cloud environment probes (client/fingerprint.py — env_aws.go,
    # env_gce.go, env_azure.go analogs). Off by default: a non-cloud
    # host would pay three metadata-timeout round trips per agent
    # start; NOMAD_CLOUD_FINGERPRINT=1 or the agent config turns it on
    cloud_fingerprint: bool = False
    # host/alloc stats sampler (client/stats.py, ISSUE 13): cadence of
    # the /proc + driver-stats sample loop and the retained ring's
    # depth per series. 0 disables the sampler entirely
    # (NOMAD_TPU_CLIENT_STATS=0 is the runtime kill switch) — no ring,
    # no stats heartbeat payload, stats routes report the node dark
    stats_sample_interval_s: float = 1.0
    stats_ring_slots: int = 128


def fingerprint_accelerator_devices():
    """Detect locally attached JAX accelerators as a device group
    (devices/gpu/nvidia/device.go Fingerprint, re-aimed at TPUs).
    Returns [] when no accelerator backend is available."""
    from ..models import NodeDevice, NodeDeviceResource
    try:
        import jax
        if jax.default_backend() == "cpu":
            return []
        devs = jax.devices()
    except Exception:
        return []
    if not devs:
        return []
    kind = devs[0].platform            # "tpu" / "gpu"
    name = getattr(devs[0], "device_kind", kind) or kind
    return [NodeDeviceResource(
        vendor="google" if kind == "tpu" else "",
        type=kind, name=str(name).replace(" ", "-").lower(),
        attributes={"count": len(devs)},
        instances=[NodeDevice(id=f"{kind}-{d.id}", healthy=True)
                   for d in devs])]


class TaskRunner:
    """One task's lifecycle: start -> wait -> restart policy -> dead
    (taskrunner/task_runner.go Run:456, shouldRestart:699). An attached
    handle (restored via driver RecoverTask, task_runner.go:996) skips
    the initial start and resumes at the wait."""

    def __init__(self, alloc: Allocation, task, driver, on_update,
                 attached: Optional[TaskHandle] = None,
                 node=None, alloc_dir=None, derive_vault=None,
                 vault=None, attached_vault_lease: Optional[dict] = None,
                 volume_sources: Optional[Dict[str, str]] = None,
                 stats_poll: bool = True):
        self.alloc = alloc
        self.task = task
        self.driver = driver
        self.on_update = on_update
        # legacy per-task gauge poll: superseded by the client's
        # HostStatsCollector pull (ISSUE 13) — only armed when no
        # collector covers this task (kill switch / harness callers),
        # so a node never pays BOTH a poll thread and the pull
        self.stats_poll = stats_poll
        self.node = node
        self.alloc_dir = alloc_dir
        self.derive_vault = derive_vault
        # VaultTokenRenewer (client/vaultclient.py): renewal loop +
        # re-derive-on-expiry; derive_vault stays as the bare-derive
        # fallback for harness callers without a renewer
        self.vault = vault
        self._secrets_path = ""
        # current lease, persisted with task state so a restarted
        # client re-registers it with the fresh renewer (the reference
        # persists the token in the task's local state —
        # taskrunner/vault_hook.go + state DB)
        self.vault_lease: Optional[dict] = None
        self._attached_vault_lease = attached_vault_lease
        # group volume name -> host source path (csi publish target or
        # host volume path), resolved by the alloc runner's volume hook
        self.volume_sources = volume_sources or {}
        self.state = TaskState(state=TASK_STATE_PENDING)
        self.handle: Optional[TaskHandle] = None
        self._attached = attached
        self._kill = threading.Event()
        self._force_restart = False     # `alloc restart` (no budget)
        self._thread: Optional[threading.Thread] = None

    def _prestart(self):
        """Prestart hook pipeline (taskrunner hooks: allocdir env,
        artifact fetch, template render) + driver config interpolation.
        Returns (config, env) or raises HookError."""
        from .hooks import fetch_artifacts, render_templates
        from .taskenv import build_task_env, interpolate_config
        alloc_path = task_path = secrets_path = ""
        log_dir = None
        if self.alloc_dir is not None:
            alloc_path = self.alloc_dir.shared
            task_path, local, secrets_path = \
                self.alloc_dir.task_paths(self.task.name)
            log_dir = self.alloc_dir.logs
        env = build_task_env(self.alloc, self.task, self.node,
                             alloc_dir=alloc_path, task_dir=task_path,
                             secrets_dir=secrets_path)
        # vault hook (taskrunner/vault_hook.go): derive a TTL'd token,
        # expose it as VAULT_TOKEN / secrets/vault_token, and register
        # it with the renewal loop (client/vaultclient.py); on renewal
        # failure the renewer re-derives and change_mode applies
        self._secrets_path = secrets_path
        if self.task.vault is not None and \
                (self.vault is not None or self.derive_vault is not None):
            try:
                if self.vault is not None:
                    lease = self.vault.derive(self.alloc.id,
                                              self.task.name)
                    self.vault.track(self.alloc.id, self.task.name,
                                     lease,
                                     on_new_token=self._on_new_vault_token)
                else:
                    from .vaultclient import _normalize
                    tokens = self.derive_vault(self.alloc.id,
                                               [self.task.name])
                    lease = _normalize(tokens.get(self.task.name))
                self.vault_lease = lease
                token = lease.get("token", "")
                if self.task.vault.env:
                    env["VAULT_TOKEN"] = token
                self._write_vault_token(token)
            except Exception as e:
                from .hooks import HookError
                raise HookError(f"vault token derivation failed: {e}")
        if self.alloc_dir is not None:
            fetch_artifacts(self.task, task_path, env, self.node)
            render_templates(self.task, task_path, env, self.node)
        config = interpolate_config(self.task.config, env, self.node)
        # typed config validation against the driver's declared schema
        # (plugins/shared/hclspec): unknown keys and type mismatches
        # fail the task at prestart with a spec error instead of deep
        # inside the driver; defaults fill in
        spec = None
        spec_getter = getattr(self.driver, "config_spec", None)
        if spec_getter is not None:
            try:
                spec = spec_getter()
            except Exception:
                spec = None
        else:
            spec = getattr(self.driver, "CONFIG_SPEC", None)
        if spec:
            from ..plugins.hclspec import SpecError, decode
            from .hooks import HookError
            try:
                config = decode(spec, config)
            except SpecError as e:
                raise HookError(f"driver config invalid: {e}")
        lc = self.task.log_config
        # the alloc's port offers ride into the driver ctx so port_map
        # can bind container ports to the scheduler-assigned host
        # ports (drivers/docker port_map)
        from ..utils.codec import to_wire as _to_wire
        alloc_networks = []
        if self.alloc.allocated_resources is not None:
            ar = self.alloc.allocated_resources
            # wire-shaped: ctx crosses the plugin msgpack boundary
            alloc_networks.extend(
                _to_wire(nw) for nw in (ar.shared.networks or []))
            tr = ar.tasks.get(self.task.name)
            if tr is not None:
                alloc_networks.extend(
                    _to_wire(nw) for nw in (tr.networks or []))
        # volume_mount stanzas resolve against the alloc runner's
        # mounted volume sources (csi publish targets / host volume
        # paths) — drivers receive [{volume, source, destination,
        # read_only}] (taskrunner/volume_hook.go)
        volume_mounts = []
        for vm in (self.task.volume_mounts or []):
            src = self.volume_sources.get(vm.volume)
            if src is None:
                from .hooks import HookError
                raise HookError(
                    f"volume_mount references undefined volume "
                    f"{vm.volume!r}")
            volume_mounts.append({"volume": vm.volume, "source": src,
                                  "destination": vm.destination,
                                  "read_only": bool(vm.read_only)})
        ctx = {"task_dir": task_path or None,
               "volume_mounts": volume_mounts,
               "log_dir": log_dir,
               "log_max_files": lc.max_files if lc else 10,
               "log_max_file_size_mb": lc.max_file_size_mb if lc else 10,
               "alloc_id": self.alloc.id,
               "user": self.task.user,
               "alloc_networks": alloc_networks,
               "resources": {"cpu": self.task.resources.cpu,
                             "memory_mb": self.task.resources.memory_mb}}
        return config, env, ctx

    def _write_vault_token(self, token: str) -> None:
        """secrets/vault_token (vault_hook.go writeToken). Raises on
        write failure — for a task with vault.env=false this file is
        the only token delivery channel, so prestart must fail loudly
        (the hook wraps it in a HookError)."""
        if self._secrets_path and token:
            import os
            path = os.path.join(self._secrets_path, "vault_token")
            with open(path, "w") as f:
                f.write(token)
            os.chmod(path, 0o600)

    def _on_new_vault_token(self, lease: dict) -> None:
        """Renewal-failure re-derive landed a fresh token: persist it
        and apply the task's change_mode (vault_hook.go updatedToken)."""
        token = lease.get("token", "")
        self.vault_lease = dict(lease)
        try:
            self._write_vault_token(token)
        except OSError:
            LOG.exception("vault token write failed for %s",
                          self.task.name)
        self.on_update()        # persist the fresh lease
        mode = self.task.vault.change_mode if self.task.vault else "noop"
        # a task that already exited must not be signalled or force-
        # restarted outside its restart policy — the new token is on
        # disk for whatever runs next. Act on the snapshotted handle
        # throughout: self.handle may be swapped by the run loop
        # mid-callback.
        h = self.handle
        if h is None or h.done():
            return
        if mode == "signal":
            sig = self.task.vault.change_signal or "SIGHUP"
            signal_fn = getattr(self.driver, "signal_task", None)
            if signal_fn is not None:
                try:
                    signal_fn(h, sig)
                    return
                except Exception:
                    pass
            mode = "restart"    # signal unsupported: fall back
        if mode == "restart":
            self._force_restart = True
            try:
                self.driver.stop_task(h, self.task.kill_timeout_s)
            except Exception:
                pass

    def _revault_on_attach(self) -> None:
        """A re-attached task's lease must keep renewing: the restarted
        client's renewer is empty, so re-register the persisted lease
        (renewing immediately — its remaining TTL is unknown) or, if
        none survived, derive fresh (vault_hook restore path)."""
        if self.task.vault is None or self.vault is None:
            return
        if self.alloc_dir is not None and not self._secrets_path:
            _tp, _lc, self._secrets_path = \
                self.alloc_dir.task_paths(self.task.name)
        lease = self._attached_vault_lease
        self._attached_vault_lease = None
        try:
            if lease and lease.get("accessor"):
                self.vault_lease = dict(lease)
                self.vault.track(self.alloc.id, self.task.name, lease,
                                 on_new_token=self._on_new_vault_token,
                                 renew_now=True)
            else:
                lease = self.vault.derive(self.alloc.id, self.task.name)
                self.vault_lease = dict(lease)
                self.vault.track(self.alloc.id, self.task.name, lease,
                                 on_new_token=self._on_new_vault_token)
                self._write_vault_token(lease.get("token", ""))
        except Exception:
            LOG.exception("vault lease re-registration failed for %s",
                          self.task.name)

    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name=f"task-{self.task.name}")
        self._thread.start()

    def _start_stats_poll(self, handle) -> None:
        """Task resource gauges while the task runs (task_runner.go
        :1297-1370 emitStats -> nomad.client.allocs.* gauges), fed by
        the driver's executor stats when it has one. Skipped when the
        client's HostStatsCollector already pulls this driver's stats
        (stats_poll=False): one reader per task, not two."""
        if not self.stats_poll:
            return
        stats_fn = getattr(self.driver, "stats", None)
        if stats_fn is None:
            return

        def poll():
            from ..utils import metrics
            prefix = f"nomad.client.allocs.{self.alloc.id[:8]}." \
                     f"{self.task.name}"
            while not handle.done():
                try:
                    for k, v in (stats_fn(handle) or {}).items():
                        metrics.set_gauge(f"{prefix}.{k}", v)
                except Exception:
                    pass
                time.sleep(1.0)

        threading.Thread(target=poll, daemon=True,
                         name=f"stats-{self.task.name}").start()

    def kill(self) -> None:
        self._kill.set()
        if self.handle is not None:
            self.driver.stop_task(self.handle, self.task.kill_timeout_s)

    def run(self) -> None:
        try:
            self._run()
        finally:
            # stop renewing this task's vault lease; server-side
            # revocation rides the alloc's terminal status update
            if self.vault is not None:
                self.vault.untrack(self.alloc.id, self.task.name)

    def _run(self) -> None:
        tg = self.alloc.job.lookup_task_group(self.alloc.task_group) \
            if self.alloc.job else None
        policy = tg.restart_policy if tg else None
        restarts = 0
        while not self._kill.is_set():
            if self._attached is not None:
                self.handle = self._attached
                self._attached = None
                started_at = self.handle.started_at or time.time()
                self._revault_on_attach()
            else:
                try:
                    from .hooks import HookError
                    config, env, ctx = self._prestart()
                    self.handle = self.driver.start_task(
                        self.task.name, config, env, ctx=ctx)
                except (RuntimeError, OSError, HookError) as e:
                    # OSError: isolation setup (cgroupfs writes) can
                    # fail at start; it must surface as a failed task,
                    # not a dead runner thread stuck in PENDING
                    kind = "Setup Failure" if isinstance(
                        e, HookError) else "Driver Failure"
                    self.state = TaskState(
                        state=TASK_STATE_DEAD, failed=True,
                        finished_at=time.time(),
                        events=[TaskEvent(type=kind,
                                          message=str(e),
                                          failed=True,
                                          time=int(time.time()))])
                    self.on_update()
                    return
                started_at = time.time()
            self.state = TaskState(state=TASK_STATE_RUNNING,
                                   started_at=started_at,
                                   restarts=restarts)
            self.on_update()
            self._start_stats_poll(self.handle)
            self.handle.wait()
            exit_code = self.handle.exit_code or 0
            failed = exit_code != 0
            if self._kill.is_set():
                self.state = TaskState(state=TASK_STATE_DEAD, failed=False,
                                       restarts=restarts,
                                       started_at=self.state.started_at,
                                       finished_at=time.time())
                self.on_update()
                return
            # a user-requested restart (`nomad alloc restart`) loops
            # unconditionally — any exit code, no attempt consumed
            # (the reference restarts outside the policy budget)
            if self._force_restart:
                self._force_restart = False
                self.state = TaskState(
                    state=TASK_STATE_PENDING, restarts=restarts,
                    events=[TaskEvent(type="Restart Signaled",
                                      exit_code=exit_code,
                                      time=int(time.time()))])
                self.on_update()
                continue
            # restart within the attempt budget regardless of mode; mode
            # only governs post-exhaustion behavior (restarts/restarts.go:
            # "delay" waits out the interval, "fail" marks the task dead)
            if failed and policy is not None and restarts < policy.attempts:
                restarts += 1
                # visible restart transition: the alloc health monitor
                # must see the task leave "running" or a crash-looping
                # task would be reported deployment-healthy
                self.state = TaskState(
                    state=TASK_STATE_PENDING, restarts=restarts,
                    events=[TaskEvent(type="Restarting", exit_code=exit_code,
                                      failed=failed, time=int(time.time()))])
                self.on_update()
                self._kill.wait(min(policy.delay_s, 0.2))  # test-friendly cap
                continue
            self.state = TaskState(
                state=TASK_STATE_DEAD, failed=failed, restarts=restarts,
                started_at=self.state.started_at, finished_at=time.time(),
                events=[TaskEvent(type="Terminated", exit_code=exit_code,
                                  failed=failed, time=int(time.time()))])
            self.on_update()
            return


class AllocRunner:
    """Per-allocation lifecycle (allocrunner/alloc_runner.go Run:282,
    clientAlloc:616 status aggregation)."""

    def __init__(self, alloc: Allocation, drivers: Dict[str, object],
                 push_update, persist=None, node=None,
                 alloc_dir_base: str = "", derive_vault=None,
                 vault=None, client=None):
        self.alloc = alloc
        self.drivers = drivers
        self.push_update = push_update
        self.persist = persist            # (alloc_id, task, state, handle)
        self.derive_vault = derive_vault
        self.vault = vault                # VaultTokenRenewer
        self.node = node
        self.client = client              # alloc-watcher context
        self.task_runners: List[TaskRunner] = []
        # the collector's pull supersedes per-task poll threads
        self._stats_poll = getattr(client, "host_stats", None) is None
        self.client_status = ALLOC_CLIENT_PENDING
        self.deployment_status = alloc.deployment_status
        self._l = make_lock()
        self.destroyed = False
        # volume name -> host source path tasks mount from (filled by
        # _mount_volumes: CSI publish targets + host volume paths)
        self.volume_sources: Dict[str, str] = {}
        self._csi_mounted: List[Tuple[str, str]] = []  # (plugin, vol)
        from .allocdir import AllocDir
        self.alloc_dir = AllocDir(alloc_dir_base, alloc.id)
        self.services = None
        transport = getattr(client, "transport", None)
        if transport is not None:
            from .services_hook import AllocServices
            self.services = AllocServices(self, transport)

    def run(self, attached: Optional[Dict[str, TaskHandle]] = None,
            attached_leases: Optional[Dict[str, dict]] = None) -> None:
        """Start (or, with `attached` handles from driver recovery,
        resume) the alloc's tasks."""
        tg = self.alloc.job.lookup_task_group(self.alloc.task_group) \
            if self.alloc.job else None
        if tg is None:
            self.client_status = ALLOC_CLIENT_FAILED
            self._push()
            return
        self.alloc_dir.build([t.name for t in tg.tasks])
        # csi_hook (allocrunner/csi_hook.go): stage + publish every CSI
        # volume the group requests before any task starts; a mount
        # failure fails the alloc at setup
        try:
            self._mount_volumes(tg)
        except Exception as e:
            LOG.exception("volume setup failed for %s", self.alloc.id[:8])
            for task in tg.tasks:
                tr = TaskRunner(self.alloc, task, self.drivers.get(
                    task.driver), self._on_task_update)
                tr.state = TaskState(
                    state=TASK_STATE_DEAD, failed=True,
                    finished_at=time.time(),
                    events=[TaskEvent(type="Setup Failure",
                                      message=f"volume mount: {e}",
                                      failed=True, time=int(time.time()))])
                self.task_runners.append(tr)
            self._on_task_update()
            return
        for task in tg.tasks:
            driver = self.drivers.get(task.driver)
            if driver is None:
                self.client_status = ALLOC_CLIENT_FAILED
                self._push()
                return
            tr = TaskRunner(self.alloc, task, driver, self._on_task_update,
                            attached=(attached or {}).get(task.name),
                            node=self.node, alloc_dir=self.alloc_dir,
                            derive_vault=self.derive_vault,
                            vault=self.vault,
                            attached_vault_lease=(attached_leases or {})
                            .get(task.name),
                            volume_sources=self.volume_sources,
                            stats_poll=self._stats_poll)
            self.task_runners.append(tr)
        # previous-alloc watcher (client/allocwatcher): a replacement
        # with a sticky/migrating ephemeral disk waits for its
        # predecessor and pulls the disk before tasks start — on its
        # own thread so other allocs keep flowing
        needs_watch = (
            self.client is not None and not attached
            and self.alloc.previous_allocation
            and tg.ephemeral_disk is not None
            and (tg.ephemeral_disk.sticky or tg.ephemeral_disk.migrate))

        def _start_tasks_and_health():
            for tr in self.task_runners:
                tr.start()
            # service registration + health checking (groupservice_hook
            # + taskrunner service_hook): registrations go to the
            # built-in catalog through the client transport
            if self.services is not None:
                self.services.start()
            # the deployment health clock starts only once tasks are
            # actually released — ticking through the migration wait
            # would expire healthy_deadline before tasks ever ran
            if self.alloc.deployment_id and tg.update is not None:
                threading.Thread(target=self._watch_health,
                                 args=(tg.update,), daemon=True,
                                 name=f"health-{self.alloc.id[:8]}"
                                 ).start()

        if needs_watch:
            def _watch_then_start():
                from .allocwatcher import migrate_previous
                try:
                    if not self.destroyed:
                        migrate_previous(self.client, self)
                except Exception:
                    LOG.exception("alloc watcher for %s failed; "
                                  "starting with a fresh disk",
                                  self.alloc.id[:8])
                if self.destroyed:
                    # the server stopped this alloc mid-wait: the
                    # tasks must land terminal, not PENDING forever,
                    # and nothing may write into the destroyed dir
                    for tr in self.task_runners:
                        tr.state = TaskState(state=TASK_STATE_DEAD,
                                             finished_at=time.time())
                    self._on_task_update()
                    return
                _start_tasks_and_health()
            threading.Thread(target=_watch_then_start, daemon=True,
                             name=f"allocwatch-{self.alloc.id[:8]}"
                             ).start()
        else:
            _start_tasks_and_health()

    def _watch_health(self, update) -> None:
        """Deployment health monitor (allocrunner/health_hook.go +
        allochealth/tracker.go): healthy once every task has been running
        continuously for min_healthy_time; unhealthy on task failure or
        when healthy_deadline expires first."""
        deadline = time.time() + update.healthy_deadline_s
        healthy_since: Optional[float] = None
        seen_restarts = -1
        while not self.destroyed:
            with self._l:
                states = [tr.state for tr in self.task_runners]
            if any(ts.state == TASK_STATE_DEAD and ts.failed for ts in states):
                self._set_health(False)
                return
            restarts = sum(ts.restarts for ts in states)
            if restarts != seen_restarts:
                # a restart resets the continuous-running clock
                # (allochealth/tracker.go watchTaskEvents)
                seen_restarts = restarts
                healthy_since = None
            if states and all(ts.state == TASK_STATE_RUNNING for ts in states):
                now = time.time()
                started = max(ts.started_at or now for ts in states)
                since = max(healthy_since or started, started)
                healthy_since = since
                if now - since >= update.min_healthy_time_s:
                    self._set_health(True)
                    return
            else:
                healthy_since = None
            if time.time() > deadline:
                self._set_health(False)
                return
            time.sleep(0.05)

    def _set_health(self, healthy: bool) -> None:
        from ..models.alloc import AllocDeploymentStatus
        canary = bool(self.alloc.deployment_status
                      and self.alloc.deployment_status.canary)
        self.deployment_status = AllocDeploymentStatus(
            healthy=healthy, timestamp=time.time(), canary=canary)
        self._push()

    def _mount_volumes(self, tg) -> None:
        """Resolve the group's volume requests into task-mountable
        source paths: host volumes from the node's host_volume config,
        CSI volumes via stage/publish through the csimanager."""
        if not tg.volumes:
            return
        csi = getattr(self.client, "csi_manager", None) \
            if self.client is not None else None
        transport = getattr(self.client, "transport", None) \
            if self.client is not None else None
        for name, req in tg.volumes.items():
            vtype = getattr(req, "type", "host") or "host"
            if vtype == "host":
                hv = (self.node.host_volumes or {}).get(req.source) \
                    if self.node is not None else None
                if hv and hv.get("path"):
                    self.volume_sources[name] = hv["path"]
                elif self.node is not None and self.node.host_volumes:
                    # the scheduler filtered on host volumes, so a miss
                    # here is a real config error — fail setup loudly
                    # instead of a misleading per-task mount error
                    raise RuntimeError(
                        f"host volume {req.source!r} not present on "
                        "this node")
                continue
            if vtype != "csi":
                continue
            if csi is None or transport is None:
                raise RuntimeError(
                    f"csi volume {req.source}: no csi plugins configured")
            info = transport.get_csi_volume(self.alloc.namespace,
                                            req.source)
            if not info:
                raise RuntimeError(f"csi volume {req.source} not found")
            plugin_id = info.get("plugin_id", "")
            target = csi.mount_volume(plugin_id, req.source,
                                      self.alloc.id,
                                      bool(req.read_only))
            if target is None:
                raise RuntimeError(
                    f"csi plugin {plugin_id!r} not available on node")
            self._csi_mounted.append((plugin_id, req.source))
            self.volume_sources[name] = target

    def _unmount_volumes(self) -> None:
        csi = getattr(self.client, "csi_manager", None) \
            if self.client is not None else None
        if csi is None:
            self._csi_mounted = []
            return
        for plugin_id, vol_id in self._csi_mounted:
            csi.unmount_volume(plugin_id, vol_id, self.alloc.id)
        self._csi_mounted = []

    def stop(self) -> None:
        self.destroyed = True
        if self.services is not None:
            self.services.stop()
        for tr in self.task_runners:
            tr.kill()
        self._unmount_volumes()

    def destroy(self) -> None:
        """Release the alloc's directory tree (client GC)."""
        if not self.destroyed:
            self.stop()
        self._unmount_volumes()
        self.alloc_dir.destroy()

    def _on_task_update(self) -> None:
        if self.persist is not None:
            for tr in self.task_runners:
                self.persist(
                    self.alloc.id, tr.task.name, tr.state,
                    tr.handle.recoverable_state() if tr.handle else None,
                    tr.vault_lease)
        with self._l:
            states = {tr.task.name: tr.state for tr in self.task_runners}
            # aggregate client status (alloc_runner.go getClientStatus)
            if any(ts.state == TASK_STATE_DEAD and ts.failed
                   for ts in states.values()):
                status = ALLOC_CLIENT_FAILED
            elif all(ts.state == TASK_STATE_DEAD for ts in states.values()):
                status = ALLOC_CLIENT_COMPLETE
            elif any(ts.state == TASK_STATE_RUNNING for ts in states.values()):
                status = ALLOC_CLIENT_RUNNING
            else:
                status = ALLOC_CLIENT_PENDING
            self.client_status = status
        # terminal allocs leave the catalog even without an explicit
        # stop (batch tasks finishing; groupservice_hook Postrun)
        if status in (ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED):
            if self.services is not None:
                self.services.stop()
            # csi_hook Postrun: release this alloc's volume mounts —
            # but only once EVERY task has exited. A failed sibling
            # flips aggregate status to FAILED while other tasks still
            # run; unmounting then would yank the volume out from
            # under them (the reference's Postrun runs after all task
            # runners exit).
            if all(ts.state == TASK_STATE_DEAD
                   for ts in states.values()):
                self._unmount_volumes()
        self._push()

    def _push(self) -> None:
        states = {tr.task.name: tr.state for tr in self.task_runners}
        self.push_update(Allocation(
            id=self.alloc.id, client_status=self.client_status,
            task_states=states, deployment_status=self.deployment_status,
            modify_time=int(time.time())))


class Client:
    """The node agent. Talks to the server through the narrow
    ServerTransport surface (rpc/transport.py): direct method calls
    in-process (dev agent), or the wire RPC layer in a real cluster.
    Accepts either a Server object (wrapped in InProcTransport, the
    historical signature) or any ServerTransport."""

    def __init__(self, server, config: Optional[ClientConfig] = None):
        from ..rpc.transport import InProcTransport, ServerTransport
        if isinstance(server, ServerTransport):
            self.transport = server
            self.server = getattr(server, "server", None)
        else:
            self.transport = InProcTransport(server)
            self.server = server
        self.config = config or ClientConfig()
        from .vaultclient import VaultTokenRenewer
        self.vault_renewer = VaultTokenRenewer(self.transport)
        # CSI plugins behind the process boundary + the stage/publish
        # manager (client/pluginmanager/csimanager)
        self.csi_manager = None
        if self.config.csi_plugins:
            from ..plugins.csi_client import ExternalCSIPlugin
            from .csimanager import CSIManager
            import tempfile
            self.csi_manager = CSIManager(
                node_id="", mount_root=self.config.alloc_dir
                or os.path.join(tempfile.gettempdir(), "nomad-tpu"))
            for pid in self.config.csi_plugins:
                self.csi_manager.register_plugin(
                    pid, ExternalCSIPlugin(pid))
        self.state_db = None
        if self.config.state_dir:
            from .state_db import ClientStateDB
            self.state_db = ClientStateDB(self.config.state_dir)
        self.node = self._fingerprint()
        if self.csi_manager is not None:
            # advertise healthy CSI plugins as node attributes
            # (csimanager instance fingerprint -> CSIVolumeChecker)
            self.csi_manager.node_id = self.node.id
            self.node.attributes.update(
                self.csi_manager.fingerprint_attrs())
        self.drivers = {}
        for name in self.config.drivers:
            if name in self.config.plugin_drivers:
                from ..plugins import ExternalDriver
                self.drivers[name] = ExternalDriver(name)
            else:
                self.drivers[name] = DRIVER_CATALOG[name]()
        # CONDITIONAL drivers (docker): only drivers that declare an
        # availability probe get filtered — calling fingerprint() on a
        # plugin driver here would spawn its subprocess at construction
        # and permanently drop it on one transient handshake failure,
        # defeating the relaunch supervision
        for name, drv in list(self.drivers.items()):
            probe = getattr(drv, "available", None)
            if probe is None:
                continue
            try:
                ok = probe()
                fp = drv.fingerprint() if ok else {}
            except Exception:
                ok, fp = False, {}
            if not ok or not fp:
                del self.drivers[name]
                self.node.attributes.pop(f"driver.{name}", None)
                self.node.drivers.pop(name, None)
            else:
                self.node.attributes.update(fp)
        self.runners: Dict[str, AllocRunner] = {}
        # host/alloc stats sampler (ISSUE 13): built here so tests can
        # drive sample_once() before start(); the thread starts in
        # start(). Kill switch (env or interval=0) builds nothing —
        # the degenerate path is the pre-stats client
        self.host_stats = None
        from . import stats as client_stats
        if client_stats.enabled() and \
                self.config.stats_sample_interval_s > 0:
            self.host_stats = client_stats.HostStatsCollector(
                client=self,
                interval_s=self.config.stats_sample_interval_s,
                slots=self.config.stats_ring_slots,
                alloc_dir=self.config.alloc_dir)
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._seen_index = 0

    # -- fingerprinting (client/fingerprint) ---------------------------
    def _fingerprint(self) -> Node:
        from ..models import DriverInfo, NetworkResource
        # stable node identity across restarts (client.go persists the
        # node ID in the data dir) — without it a restarted client would
        # register as a new node and orphan its allocs
        node_id = secret = None
        if self.state_db is not None:
            ident = self.state_db.load_identity()
            if ident:
                node_id = ident.get("node_id")
                secret = ident.get("secret_id")
        node = Node(
            id=node_id or generate_uuid(),
            secret_id=secret or generate_uuid(),
            name=self.config.node_name or f"client-{generate_uuid()[:8]}",
            datacenter=self.config.datacenter,
            node_class=self.config.node_class,
            status=NODE_STATUS_INIT,
            attributes={
                "kernel.name": "linux",
                "arch": "x86",
                "nomad.version": "0.1.0",
                # the embedded token authority makes every server
                # vault-capable, so every client fingerprints it
                # (fingerprint/vault.go; satisfies the implied
                # ${attr.vault.version} constraint on vault jobs)
                "vault.version": "1.0-embedded",
                "vault.accessible": "true",
            },
            meta=dict(self.config.meta),
            node_resources=NodeResources(
                cpu=NodeCpuResources(cpu_shares=self.config.cpu_shares),
                memory=NodeMemoryResources(memory_mb=self.config.memory_mb),
                disk=NodeDiskResources(disk_mb=self.config.disk_mb),
                networks=[NetworkResource(mode="host", device="eth0",
                                          ip="127.0.0.1", mbits=1000)],
            ),
        )
        for name in self.config.drivers:
            node.attributes[f"driver.{name}"] = "1"
            from ..models import DriverInfo as DI
            node.drivers[name] = DI(detected=True, healthy=True)
        node.node_resources.devices = list(self.config.devices)
        if self.config.fingerprint_accelerators:
            if self.config.plugin_device_fingerprint:
                # out-of-proc device plugin (plugins/device/device.go
                # behind the go-plugin boundary): fingerprint crosses
                # the process line, and a crashing device plugin can't
                # take the agent down
                from ..plugins.device_client import ExternalDevicePlugin
                self.device_plugin = ExternalDevicePlugin()
                try:
                    node.node_resources.devices.extend(
                        self.device_plugin.fingerprint())
                except Exception:
                    # same contract as the in-proc probe: a broken
                    # device plugin means no devices, not a dead agent
                    LOG.exception("device plugin fingerprint failed; "
                                  "continuing without devices")
            else:
                node.node_resources.devices.extend(
                    fingerprint_accelerator_devices())
        for g in node.node_resources.devices:
            node.attributes[f"device.{g.type}"] = str(len(g.instances))
        if self.config.cloud_fingerprint or \
                os.environ.get("NOMAD_CLOUD_FINGERPRINT") == "1":
            from .fingerprint import fingerprint_cloud
            attrs, links = fingerprint_cloud()
            node.attributes.update(attrs)
            node.links.update(links)
        node.compute_class()
        if self.state_db is not None:
            self.state_db.save_identity(node.id, node.secret_id)
        return node

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self.node.status = NODE_STATUS_READY
        # the logs/fs/exec service: servers forward remote requests to
        # this listener; its address rides the node record so any
        # server can find the owning client (the reference advertises
        # client ports on the Node the same way)
        if self.config.rpc_port is not None:
            from ..rpc.server import RpcServer
            from .remote import ClientRpcService
            self.rpc_service = ClientRpcService(self)
            self.rpc_server = RpcServer(
                host=self.config.rpc_host,
                port=self.config.rpc_port,
                methods=self.rpc_service.rpc_methods())
            self.rpc_server.start()
            advertise = self.config.rpc_advertise or \
                f"{self.config.rpc_host}:{self.rpc_server.port}"
            self.node.attributes["nomad.client.rpc"] = advertise
        self.transport.register_node(self.node)
        self.transport.update_node_status(self.node.id, NODE_STATUS_READY)
        self._restore_state()
        docker = self.drivers.get("docker")
        if docker is not None and hasattr(docker, "start_reconciler"):
            # orphan-container sweep (drivers/docker/reconciler.go)
            docker.start_reconciler(lambda: set(self.runners))
        if self.host_stats is not None:
            # prime one sample synchronously so the first heartbeat
            # already carries a stats payload, then background-sample
            self.host_stats.sample_once()
            self.host_stats.start()
        t1 = threading.Thread(target=self._heartbeat_loop, daemon=True)
        t2 = threading.Thread(target=self._watch_allocs, daemon=True)
        self._threads = [t1, t2]
        t1.start()
        t2.start()

    def alloc_base(self, alloc_id: str) -> Optional[str]:
        """Filesystem base of one alloc's dir tree on this node, or
        None when the alloc doesn't live here."""
        runner = self.runners.get(alloc_id)
        if runner is not None:
            return runner.alloc_dir.base
        from .allocdir import AllocDir
        base = AllocDir(self.config.alloc_dir, alloc_id).base
        return base if os.path.isdir(base) else None

    def _restore_state(self) -> None:
        """Rebuild alloc runners from the state DB, re-attaching to live
        tasks via driver RecoverTask (client.go restoreState:1055,
        task_runner.go:996). Unrecoverable tasks restart fresh."""
        if self.state_db is None:
            return
        from ..models import Allocation
        from ..utils.codec import from_wire
        for aid, rec in list(self.state_db.state.items()):
            alloc_data = rec.get("alloc")
            if not alloc_data:
                continue
            alloc = from_wire(Allocation, alloc_data)
            if alloc.terminal_status() or alloc.server_terminal_status():
                self.state_db.delete_alloc(aid)
                continue
            attached: Dict[str, TaskHandle] = {}
            attached_leases: Dict[str, dict] = {}
            for task_name, tstate in (rec.get("tasks") or {}).items():
                lease = tstate.get("vault_lease")
                if lease:
                    attached_leases[task_name] = lease
                hstate = tstate.get("handle")
                if not hstate:
                    continue
                # only re-attach tasks that were last seen running
                st = (tstate.get("state") or {}).get("state")
                if st != TASK_STATE_RUNNING:
                    continue
                driver = self.drivers.get(hstate.get("driver", ""))
                if driver is None:
                    continue
                recover = getattr(driver, "recover_task", None)
                handle = recover(hstate) if recover else None
                if handle is not None:
                    attached[task_name] = handle
                    LOG.info("re-attached task %s of alloc %s",
                             task_name, aid[:8])
            runner = AllocRunner(alloc, self.drivers, self._push_update,
                                 persist=self._persist_task,
                                 node=self.node,
                                 alloc_dir_base=self.config.alloc_dir,
                                 derive_vault=self.transport
                                 .derive_vault_token,
                                 vault=self.vault_renewer,
                                 client=self)
            # nomad-lint: allow[shared-state] _restore_state runs in start() before the _watch_allocs thread exists — Thread.start() is the happens-before edge
            self.runners[aid] = runner
            runner.run(attached=attached, attached_leases=attached_leases)

    def _persist_task(self, alloc_id, task_name, state, handle_state,
                      vault_lease=None):
        if self.state_db is not None:
            try:
                self.state_db.put_task(alloc_id, task_name, state,
                                       handle_state, vault_lease)
            except Exception:
                LOG.exception("state persist failed")

    def shutdown(self, kill_tasks: bool = True) -> None:
        """kill_tasks=False detaches without stopping tasks — the
        restart-without-killing-tasks path (the reference client leaves
        tasks running and re-attaches after restart)."""
        self._stop.set()
        self.vault_renewer.stop()
        if self.host_stats is not None:
            self.host_stats.stop()
        if self.csi_manager is not None:
            self.csi_manager.shutdown()
        if kill_tasks:
            # copy: the alloc-watch thread may still mutate the dict
            # until it observes _stop
            for r in list(self.runners.values()):
                r.stop()
        for t in self._threads:
            t.join(timeout=2)
        rpc = getattr(self, "rpc_server", None)
        if rpc is not None:
            rpc.shutdown()
        devp = getattr(self, "device_plugin", None)
        if devp is not None:
            devp.shutdown()
        close = getattr(self.transport, "close", None)
        if close is not None:
            close()
        for d in self.drivers.values():
            stop = getattr(d, "shutdown", None)
            if stop is not None:
                stop()
        if self.state_db is not None:
            self.state_db.close()

    def _heartbeat_loop(self) -> None:
        interval = self.config.heartbeat_interval_s
        while not self._stop.is_set():
            try:
                # the heartbeat doubles as the host-stats uplink: a
                # compact summary (~8 floats) rides every beat so the
                # server folds fleet economics without a scrape
                # fan-out (node_endpoint.go UpdateStatus analog)
                stats = self.host_stats.summary() \
                    if self.host_stats is not None else None
                ttl = self.transport.heartbeat(self.node.id,
                                               stats=stats or None)
                # renew at half the granted TTL (client/client.go heartbeats
                # inside the server-granted TTL window, never beyond it)
                interval = min(self.config.heartbeat_interval_s, ttl / 2.0)
                self._last_heartbeat_ok = time.time()
                self._heartbeat_ttl = ttl
            except Exception:
                LOG.warning("heartbeat failed", exc_info=True)
                self._check_heartbeat_stop()
            self._stop.wait(interval)

    def _check_heartbeat_stop(self) -> None:
        """heartbeatstop.go: when the client has lost its servers past
        the heartbeat TTL, stop allocs whose task group sets
        stop_after_client_disconnect once that duration has elapsed
        since the last successful heartbeat."""
        last = getattr(self, "_last_heartbeat_ok", None)
        if last is None:
            return
        ttl = getattr(self, "_heartbeat_ttl", self.config.heartbeat_interval_s)
        offline_for = time.time() - last
        if offline_for < ttl:
            return
        for runner in list(self.runners.values()):
            if runner.destroyed:
                continue
            tg = runner.alloc.job.lookup_task_group(runner.alloc.task_group) \
                if runner.alloc.job else None
            stop_after = getattr(tg, "stop_after_client_disconnect_s",
                                 None) if tg else None
            if stop_after is None:
                continue
            if offline_for >= stop_after:
                LOG.warning(
                    "stopping alloc %s: client disconnected %.1fs "
                    "(stop_after_client_disconnect=%.1fs)",
                    runner.alloc.id[:8], offline_for, stop_after)
                runner.stop()

    # -- alloc watching (client/client.go watchAllocations:1969) -------
    def _watch_allocs(self) -> None:
        while not self._stop.is_set():
            try:
                self._run_allocs()
            except Exception:
                LOG.exception("runAllocs failed")
                self._stop.wait(self.config.poll_interval_s)

    def _run_allocs(self) -> None:
        # long-poll: the server blocks until state moves past the index
        # we've seen (or the wait expires), node_endpoint.go:926
        allocs, index = self.transport.get_client_allocs(
            self.node.id, self._seen_index,
            max(self.config.poll_interval_s, 0.05))
        self._seen_index = index
        server_allocs = {a.id: a for a in allocs}
        # start new allocs
        for aid, alloc in server_allocs.items():
            if aid in self.runners:
                continue
            if alloc.terminal_status():
                continue
            if alloc.job is None:
                continue
            runner = AllocRunner(alloc, self.drivers, self._push_update,
                                 persist=self._persist_task,
                                 node=self.node,
                                 alloc_dir_base=self.config.alloc_dir,
                                 derive_vault=self.transport
                                 .derive_vault_token,
                                 vault=self.vault_renewer,
                                 client=self)
            self.runners[aid] = runner
            if self.state_db is not None:
                self.state_db.put_alloc(alloc)
            runner.run()
        # stop allocs the server wants stopped (or that vanished)
        for aid, runner in list(self.runners.items()):
            server_alloc = server_allocs.get(aid)
            if server_alloc is None or server_alloc.server_terminal_status():
                if not runner.destroyed:
                    runner.stop()
                if self.state_db is not None:
                    self.state_db.delete_alloc(aid)
                if server_alloc is None:
                    runner.destroy()
                    del self.runners[aid]
                continue
            # prune finished runners whose final status the server has
            # acknowledged (client gc.go analog) so long-lived clients
            # running many short batch jobs don't accumulate runners.
            # The alloc DIR stays for log inspection until the server
            # garbage-collects the alloc (the None branch above).
            if runner.client_status in ("complete", "failed") and \
                    server_alloc.client_status == runner.client_status:
                if self.state_db is not None:
                    self.state_db.delete_alloc(aid)
                del self.runners[aid]

    def _push_update(self, update: Allocation) -> None:
        try:
            self.transport.update_alloc_status([update])
        except Exception:
            LOG.exception("alloc update push failed")
