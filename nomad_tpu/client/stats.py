"""Client-side workload observability: host + per-alloc resource
usage (ISSUE 13).

The executor/docker drivers have always COLLECTED resource usage
(cgroup stats(), Engine API stats) — it just never left the client
process. This module closes that gap with the reference's shape:

- `HostStatsCollector` samples cpu/memory/disk/uptime from `/proc`
  (no new deps — the psutil-free analog of client/stats/host.go via
  gopsutil) plus every running task's driver `stats()` hook, and
  retains both in the SAME bounded struct-of-arrays ring machinery as
  the server's telemetry collector (`telemetry/collector.py`): one
  float64 column per series, slot cursor, wrap-around, series absent
  in a sample record NaN — so a dead alloc's series reads None, never
  a stale wrapped-over value, and alloc churn is hard-bounded by
  MAX_SERIES with drops counted.
- `host_stats()` / `alloc_stats()` return the reference's HostStats /
  AllocResourceUsage wire shapes (client/structs/structs.go), served
  over the client RPC listener (`ClientStats.*`) behind
  `/v1/client/stats` and `/v1/client/allocation/<id>/stats`.
- `summary()` is the compact payload heartbeats carry north so the
  server can fold fleet-wide used-vs-allocated economics without a
  per-node scrape fan-out (`Server.cluster_stats`).

Kill switch: NOMAD_TPU_CLIENT_STATS=0 (or stats_sample_interval_s=0)
builds no collector at all — heartbeats carry no stats payload and the
stats routes report the node dark, exactly the pre-r17 behavior.
"""

from __future__ import annotations

import os
import time
from typing import Dict, Optional, Tuple

from ..utils import metrics
from ..utils.locks import make_lock

DEFAULT_INTERVAL_S = 1.0
DEFAULT_SLOTS = 128


def enabled() -> bool:
    """The NOMAD_TPU_CLIENT_STATS kill switch (parallel to
    NOMAD_TPU_TELEMETRY): default on."""
    return os.environ.get("NOMAD_TPU_CLIENT_STATS", "1") \
        not in ("0", "off")


def read_proc_cpu() -> Optional[Tuple[float, float]]:
    """(total_ticks, idle_ticks) from the aggregate /proc/stat cpu
    line; None where /proc isn't mounted (non-Linux dev hosts)."""
    try:
        with open("/proc/stat") as f:
            line = f.readline()
    except OSError:
        return None
    parts = line.split()
    if not parts or parts[0] != "cpu":
        return None
    ticks = [float(x) for x in parts[1:]]
    if len(ticks) < 4:
        return None
    # idle + iowait both count as idle (host.go CPUStats)
    idle = ticks[3] + (ticks[4] if len(ticks) > 4 else 0.0)
    return sum(ticks), idle


def read_proc_meminfo() -> Dict[str, float]:
    """{total_mb, available_mb, free_mb} from /proc/meminfo; empty
    where unavailable."""
    out: Dict[str, float] = {}
    want = {"MemTotal": "total_mb", "MemAvailable": "available_mb",
            "MemFree": "free_mb"}
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                key = line.split(":", 1)[0]
                name = want.get(key)
                if name is None:
                    continue
                out[name] = float(line.split()[1]) / 1024.0  # kB -> MB
                if len(out) == len(want):
                    break
    except OSError:
        return {}
    return out


def read_uptime_s() -> float:
    try:
        with open("/proc/uptime") as f:
            return float(f.read().split()[0])
    except (OSError, ValueError, IndexError):
        return 0.0


def read_disk_mb(path: str) -> Tuple[float, float]:
    """(used_mb, total_mb) of the filesystem holding `path`."""
    try:
        st = os.statvfs(path or "/")
    except OSError:
        return 0.0, 0.0
    total = st.f_blocks * st.f_frsize / (1024.0 * 1024.0)
    free = st.f_bavail * st.f_frsize / (1024.0 * 1024.0)
    return max(total - free, 0.0), total


class HostStatsCollector:
    """Samples host + per-alloc usage into a retained ring. One
    instance per client agent; `sample_once()` is the deterministic
    entry the thread loop and the tests share (the Governor /
    TelemetryCollector idiom)."""

    def __init__(self, client=None, interval_s: float = DEFAULT_INTERVAL_S,
                 slots: int = DEFAULT_SLOTS, alloc_dir: str = ""):
        # the ring IS the r15 collector — same slot/NaN/wrap/bounding
        # discipline, host-side reads only; device_fn stays off (the
        # client samples no device economics)
        from ..telemetry import TelemetryCollector
        self.client = client
        self.alloc_dir = alloc_dir or "/"
        self.ring = TelemetryCollector(interval_s=interval_s,
                                       slots=slots,
                                       gauges_fn=self._collect,
                                       device_fn=None)
        self._l = make_lock()
        # the r17 race (heartbeat reading a half-updated sample) lived
        # exactly here: _collect PUBLISHES these by atomic rebinding
        # under _l and heartbeat/summary read them under _l. Declared
        # statically (guarded-by) and registered with the runtime
        # sanitizer at each publish — under NOMAD_TPU_RACE=1 an
        # in-place mutation of an already-published snapshot is a
        # finding with the mutating stack
        # nomad-lint: guarded-by[_l]
        self._latest_host: Dict = {}
        # nomad-lint: guarded-by[_l]
        self._latest_allocs: Dict[str, Dict] = {}
        # previous-sample anchors for percent derivations
        self._prev_cpu: Optional[Tuple[float, float]] = None
        self._prev_task_ns: Dict[Tuple[str, str], Tuple[float, float]] = {}

    # -- lifecycle (delegated to the ring's thread) --------------------
    def start(self) -> None:
        self.ring.start()

    def stop(self) -> None:
        self.ring.stop()

    def sample_once(self, now: Optional[float] = None) -> int:
        return self.ring.sample_once(now=now)

    # -- the sampling step ---------------------------------------------
    def _host_row(self, now: float) -> Dict[str, float]:
        row: Dict[str, float] = {}
        cpu = read_proc_cpu()
        cpu_pct = 0.0
        if cpu is not None:
            prev = self._prev_cpu
            self._prev_cpu = cpu
            if prev is not None:
                dt_total = cpu[0] - prev[0]
                dt_idle = cpu[1] - prev[1]
                if dt_total > 0:
                    cpu_pct = max(0.0, min(
                        100.0, 100.0 * (1.0 - dt_idle / dt_total)))
            row["host.cpu_total_ticks"] = cpu[0]
        row["host.cpu_pct"] = cpu_pct
        mem = read_proc_meminfo()
        if mem:
            row["host.mem_total_mb"] = mem.get("total_mb", 0.0)
            row["host.mem_available_mb"] = mem.get("available_mb", 0.0)
            row["host.mem_used_mb"] = max(
                mem.get("total_mb", 0.0) - mem.get("available_mb", 0.0),
                0.0)
        disk_used, disk_total = read_disk_mb(self.alloc_dir)
        row["host.disk_used_mb"] = disk_used
        row["host.disk_total_mb"] = disk_total
        row["host.uptime_s"] = read_uptime_s()
        try:
            row["host.load1"] = os.getloadavg()[0]
        except (OSError, AttributeError):
            pass
        return row

    def _alloc_rows(self, now: float) -> Tuple[Dict[str, float], Dict]:
        """Poll every live task's driver stats() (pull model — no
        per-task poll threads); derive cpu percent from cumulative
        ns deltas between our own samples. Returns (ring row, latest
        per-alloc AllocResourceUsage snapshots)."""
        row: Dict[str, float] = {}
        latest: Dict[str, Dict] = {}
        runners = dict(getattr(self.client, "runners", None) or {})
        live: set = set()
        for alloc_id, runner in runners.items():
            tasks: Dict[str, Dict] = {}
            for tr in getattr(runner, "task_runners", []):
                live.add((alloc_id, tr.task.name))
                handle = tr.handle
                stats_fn = getattr(tr.driver, "stats", None)
                if handle is None or stats_fn is None or handle.done():
                    continue
                try:
                    raw = stats_fn(handle) or {}
                except Exception:
                    continue
                if not raw:
                    continue
                rss = float(raw.get("memory_bytes", 0.0))
                cpu_ns = float(raw.get("cpu_total_ns", 0.0))
                key = (alloc_id, tr.task.name)
                prev = self._prev_task_ns.get(key)
                self._prev_task_ns[key] = (cpu_ns, now)
                cpu_pct = 0.0
                if prev is not None and now > prev[1] and \
                        cpu_ns >= prev[0]:
                    cpu_pct = (cpu_ns - prev[0]) / 1e9 \
                        / (now - prev[1]) * 100.0
                tasks[tr.task.name] = {
                    "ResourceUsage": {
                        "MemoryStats": {"RSS": int(rss)},
                        "CpuStats": {"TotalTicks": cpu_ns / 1e6,
                                     "Percent": round(cpu_pct, 3)},
                    },
                    "Timestamp": int(now * 1e9),
                }
                short = alloc_id[:8]
                row[f"alloc.{short}.{tr.task.name}.rss_mb"] = \
                    rss / (1024.0 * 1024.0)
                row[f"alloc.{short}.{tr.task.name}.cpu_pct"] = cpu_pct
                # keep the legacy per-task poll's registry family
                # alive (nomad.client.allocs.*): same values, one
                # reader — the poll thread this pull superseded
                prefix = f"nomad.client.allocs.{short}.{tr.task.name}"
                for k, v in raw.items():
                    metrics.set_gauge(f"{prefix}.{k}", float(v))
            if tasks:
                rss_sum = sum(t["ResourceUsage"]["MemoryStats"]["RSS"]
                              for t in tasks.values())
                pct_sum = sum(t["ResourceUsage"]["CpuStats"]["Percent"]
                              for t in tasks.values())
                ticks = sum(t["ResourceUsage"]["CpuStats"]["TotalTicks"]
                            for t in tasks.values())
                latest[alloc_id] = {
                    "ResourceUsage": {
                        "MemoryStats": {"RSS": int(rss_sum)},
                        "CpuStats": {"TotalTicks": ticks,
                                     "Percent": round(pct_sum, 3)},
                    },
                    "Tasks": tasks,
                    "Timestamp": int(now * 1e9),
                }
        row["host.allocs_running"] = float(len(runners))
        # drop anchors only for tasks that left the NODE (not tasks
        # that merely skipped one sample on a transient read failure —
        # resetting those would fake a cpu dip), so the dict can't
        # grow with alloc churn
        for key in list(self._prev_task_ns):
            if key not in live:
                del self._prev_task_ns[key]
        return row, latest, set(runners)

    def _collect(self) -> Dict[str, float]:
        """The ring's gauges_fn: one full host + alloc sample,
        published atomically (host_stats/summary readers never see a
        half-updated sample). Host gauges mirror into the process
        metrics registry so `/v1/metrics?format=prometheus` exposes
        the host-stats family (in the dev agent the client shares the
        server's registry)."""
        now = time.time()
        row = self._host_row(now)
        alloc_row, latest, runner_ids = self._alloc_rows(now)
        row.update(alloc_row)
        with self._l:
            # an alloc still ON the node whose only task transiently
            # failed its stats read keeps its last-known snapshot (the
            # Timestamp shows its age) — only allocs that LEFT drop,
            # matching the cpu-anchor transient-miss stance above
            for aid, prev in self._latest_allocs.items():
                if aid in runner_ids and aid not in latest:
                    latest[aid] = prev
            # published snapshots are immutable once out (readers
            # copy under _l): register each with the race sanitizer
            # so an in-place mutation after publish is a finding
            from ..analysis import race as _race
            self._latest_host = _race.guard(
                {"ts": now, **row}, self._l,
                "HostStatsCollector._latest_host")
            self._latest_allocs = _race.guard(
                latest, self._l, "HostStatsCollector._latest_allocs")
        for k in ("host.cpu_pct", "host.mem_used_mb",
                  "host.disk_used_mb", "host.allocs_running"):
            if k in row:
                metrics.set_gauge(f"nomad.client.{k}", row[k])
        return row

    # -- reads (the RPC/HTTP surface) ----------------------------------
    def host_stats(self) -> Dict:
        """Latest sample in the reference HostStats wire shape
        (command/agent/stats_endpoint.go serves client.StatsReporter's
        LatestHostStats)."""
        with self._l:
            h = dict(self._latest_host)
            n_allocs = len(self._latest_allocs)
        return {
            "Timestamp": int(h.get("ts", 0.0) * 1e9),
            "CPU": [{"CPU": "cpu-total",
                     "TotalPercent": h.get("host.cpu_pct", 0.0)}],
            "CPUTicksConsumed": h.get("host.cpu_total_ticks", 0.0),
            "Memory": {
                "Total": int(h.get("host.mem_total_mb", 0.0) * 1024
                             * 1024),
                "Available": int(h.get("host.mem_available_mb", 0.0)
                                 * 1024 * 1024),
                "Used": int(h.get("host.mem_used_mb", 0.0) * 1024
                            * 1024),
            },
            "DiskStats": [{
                "Device": "alloc_dir", "Mountpoint": self.alloc_dir,
                "Size": int(h.get("host.disk_total_mb", 0.0) * 1024
                            * 1024),
                "Used": int(h.get("host.disk_used_mb", 0.0) * 1024
                            * 1024),
                "UsedPercent": round(
                    100.0 * h.get("host.disk_used_mb", 0.0)
                    / max(h.get("host.disk_total_mb", 0.0), 1e-9), 2),
            }],
            "Uptime": h.get("host.uptime_s", 0.0),
            # running = alloc runners on this node; reporting = those
            # whose tasks returned driver stats this sample (drivers
            # without a stats() hook run without reporting)
            "AllocsRunning": int(h.get("host.allocs_running", 0.0)),
            "AllocsReporting": n_allocs,
            "ring": self.ring.status(),
        }

    def alloc_stats(self, alloc_id: str) -> Optional[Dict]:
        """Latest AllocResourceUsage for one alloc (full id or unique
        prefix), or None when the alloc isn't reporting here."""
        with self._l:
            hit = self._latest_allocs.get(alloc_id)
            if hit is None:
                pref = [a for a in self._latest_allocs
                        if a.startswith(alloc_id)]
                hit = (self._latest_allocs[pref[0]]
                       if len(pref) == 1 else None)
            return dict(hit) if hit is not None else None

    def summary(self) -> Dict[str, float]:
        """The compact host-stats payload heartbeats carry: what the
        server's cluster rollup needs, ~8 floats, nothing per-alloc."""
        with self._l:
            h = dict(self._latest_host)
        if not h:
            return {}
        return {
            "ts": h.get("ts", 0.0),
            "cpu_pct": round(h.get("host.cpu_pct", 0.0), 3),
            "mem_used_mb": round(h.get("host.mem_used_mb", 0.0), 1),
            "mem_total_mb": round(h.get("host.mem_total_mb", 0.0), 1),
            "disk_used_mb": round(h.get("host.disk_used_mb", 0.0), 1),
            "disk_total_mb": round(h.get("host.disk_total_mb", 0.0), 1),
            "uptime_s": round(h.get("host.uptime_s", 0.0), 1),
            "allocs": h.get("host.allocs_running", 0.0),
        }

    def history(self, last: Optional[int] = None) -> Dict:
        return self.ring.history(last=last)

    def status(self) -> Dict:
        return self.ring.status()
