"""Alloc filesystem + exec service: the client-side implementation of
logs/fs/exec shared by the co-located HTTP fast path and the client's
RPC listener (servers forward remote requests here).

Reference surface: client/fs_endpoint.go (logs/ls/cat/stream),
client/lib/streamframer/framer.go (the frame shape: File/Offset/Data/
FileEvent, heartbeat when idle), client/alloc_endpoint.go:163
(Allocations.Exec). Transport differs by design: the reference speaks
framed streaming over yamux; here frames batch over poll-style RPC
round trips (offset-resumable, heartbeat frames when idle), which the
blocking-query RPC layer already models well.
"""

from __future__ import annotations

import os
import subprocess
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.ids import generate_uuid
from ..utils.locks import make_lock

MAX_FRAME_BYTES = 64 * 1024
MAX_FRAMES_PER_POLL = 16


class PathEscapeError(ValueError):
    pass


def _resolve(base: str, rel: str) -> str:
    target = os.path.realpath(os.path.join(base, rel.lstrip("/")))
    real_base = os.path.realpath(base)
    if target != real_base and not target.startswith(real_base + os.sep):
        raise PathEscapeError("path escapes the alloc dir")
    return target


def list_dir(base: str, rel: str) -> Optional[List[Dict]]:
    target = _resolve(base, rel)
    if not os.path.isdir(target):
        return None
    out = []
    for name in sorted(os.listdir(target)):
        p = os.path.join(target, name)
        is_file = os.path.isfile(p)
        entry = {"Name": name, "IsDir": os.path.isdir(p),
                 "Size": os.path.getsize(p) if is_file else 0}
        if is_file:
            # permission bits ride along so migrated executables keep
            # their +x (allocwatcher migrateRemoteAllocDir preserves
            # FileInfo modes)
            entry["FileMode"] = os.stat(p).st_mode & 0o7777
        out.append(entry)
    return out


def cat_file(base: str, rel: str) -> Optional[bytes]:
    target = _resolve(base, rel)
    if not os.path.isfile(target):
        return None
    with open(target, "rb") as f:
        return f.read()


def _log_files(base: str, task: str, stream: str) -> List[str]:
    log_dir = os.path.join(base, "alloc", "logs")
    try:
        names = sorted(
            (f for f in os.listdir(log_dir)
             if f.startswith(f"{task}.{stream}.")),
            key=lambda f: int(f.rsplit(".", 1)[1]))
    except (FileNotFoundError, ValueError):
        names = []
    return [os.path.join(log_dir, f) for f in names]


def read_logs(base: str, task: str, stream: str,
              offset: int) -> Tuple[bytes, int]:
    """(data from offset, total size) over the task's rotated log
    chain. Offset-aware: stats sizes, opens only tail files."""
    paths = _log_files(base, task, stream)
    sizes = [os.path.getsize(p) for p in paths]
    total = sum(sizes)
    chunks = []
    skip = offset
    for p, size in zip(paths, sizes):
        if skip >= size:
            skip -= size
            continue
        with open(p, "rb") as f:
            if skip:
                f.seek(skip)
                skip = 0
            chunks.append(f.read())
    return b"".join(chunks), total


def stream_frames(base: str, rel: Optional[str], offset: int,
                  task: str = "", log_type: str = "",
                  wait_s: float = 0.0) -> List[Dict]:
    """Framed read (streamframer shape): data frames carry
    File/Offset/Data; an idle source past `wait_s` yields ONE heartbeat
    frame (empty Data, current Offset) so pollers distinguish
    'no new bytes' from 'gone'. Callers resume from the last frame's
    Offset + len(Data)."""
    deadline = time.monotonic() + max(wait_s, 0.0)
    while True:
        if log_type:
            data, total = read_logs(base, task, log_type, offset)
            fname = f"{task}.{log_type}"
        else:
            target = _resolve(base, rel or "/")
            fname = rel or "/"
            if not os.path.isfile(target):
                return [{"File": fname, "Offset": offset, "Data": b"",
                         "FileEvent": "deleted"}]
            size = os.path.getsize(target)
            if offset > size:
                # rotation/truncation: restart from zero, tell the
                # consumer why (framer FileEvent "file truncated")
                return [{"File": fname, "Offset": 0, "Data": b"",
                         "FileEvent": "truncated"}]
            with open(target, "rb") as f:
                f.seek(offset)
                data = f.read()
            total = size
        if data:
            frames = []
            pos = offset
            for i in range(0, len(data), MAX_FRAME_BYTES):
                if len(frames) >= MAX_FRAMES_PER_POLL:
                    break
                chunk = data[i:i + MAX_FRAME_BYTES]
                frames.append({"File": fname, "Offset": pos,
                               "Data": chunk})
                pos += len(chunk)
            return frames
        if time.monotonic() >= deadline:
            return [{"File": fname, "Offset": total, "Data": b"",
                     "Heartbeat": True}]
        time.sleep(0.05)


class ExecSession:
    """One in-flight `alloc exec`: a command run inside the task's
    environment with piped stdin/stdout/stderr. Poll-based: io() feeds
    stdin and drains output frames until the process exits."""

    def __init__(self, argv: List[str], cwd: Optional[str],
                 env: Optional[Dict[str, str]]):
        self.id = generate_uuid()
        self._proc = subprocess.Popen(
            argv, cwd=cwd or None, env=env or None,
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE)
        self._out = b""
        self._err = b""
        self._l = make_lock()
        self._readers = [
            threading.Thread(target=self._pump, args=("_out",
                             self._proc.stdout), daemon=True),
            threading.Thread(target=self._pump, args=("_err",
                             self._proc.stderr), daemon=True)]
        for t in self._readers:
            t.start()

    def _pump(self, field: str, pipe) -> None:
        # read1: partial output must surface immediately — a buffered
        # read(4096) would hold an interactive session's output hostage
        # until 4KB accumulate or the process exits
        read1 = getattr(pipe, "read1", None)
        while True:
            chunk = read1(4096) if read1 is not None else pipe.read(4096)
            if not chunk:
                return
            with self._l:
                setattr(self, field, getattr(self, field) + chunk)

    def write_stdin(self, data: bytes, close: bool = False) -> None:
        if self._proc.stdin is not None:
            try:
                if data:
                    self._proc.stdin.write(data)
                    self._proc.stdin.flush()
                if close:
                    self._proc.stdin.close()
            except (BrokenPipeError, ValueError, OSError):
                pass

    def poll(self, wait_s: float = 0.0) -> Dict:
        deadline = time.monotonic() + max(wait_s, 0.0)
        while True:
            code = self._proc.poll()
            if code is not None:
                # drain completely before declaring exit: a fast
                # command can finish before the reader threads have
                # pulled its output off the pipes — the pipes hit EOF
                # now that the process is gone, so the joins are bounded
                for t in self._readers:
                    t.join(timeout=5.0)
            with self._l:
                out, self._out = self._out, b""
                err, self._err = self._err, b""
            if out or err or code is not None or \
                    time.monotonic() >= deadline:
                exited = code is not None and not out and not err
                return {"stdout": out, "stderr": err,
                        "exited": exited,
                        "exit_code": code if code is not None else -1}
            time.sleep(0.02)

    def signal(self, sig: int) -> None:
        try:
            self._proc.send_signal(sig)
        except (ProcessLookupError, OSError):
            pass

    def stop(self) -> None:
        try:
            self._proc.kill()
        except (ProcessLookupError, OSError):
            pass


class MockExecSession:
    """Exec against the mock driver: echoes stdin back on stdout (the
    fake the reference mock driver's Exec provides for tests)."""

    def __init__(self, argv: List[str]):
        self.id = generate_uuid()
        self._buf = b"" if not argv else (" ".join(argv) + "\n").encode()
        self._closed = False

    def write_stdin(self, data: bytes, close: bool = False) -> None:
        self._buf += data
        if close:
            self._closed = True

    def poll(self, wait_s: float = 0.0) -> Dict:
        out, self._buf = self._buf, b""
        exited = self._closed and not out
        return {"stdout": out, "stderr": b"", "exited": exited,
                "exit_code": 0 if exited else -1}

    def signal(self, sig: int) -> None:
        pass

    def stop(self) -> None:
        self._closed = True


class TaskExecSession:
    """`alloc exec` backed by the out-of-proc executor's Exec verb: the
    command runs INSIDE the task's isolation (same cgroup + chroot —
    executor_linux.go Exec). One-shot: output is delivered when the
    command completes; stdin is not streamed (the reference's non-tty
    exec shape)."""

    def __init__(self, driver, handle, argv: List[str],
                 env: Optional[Dict[str, str]] = None,
                 timeout_s: float = 300.0):
        import threading as _threading
        self.id = generate_uuid()
        self._out = b""
        self._exit: Optional[int] = None
        self._done = _threading.Event()
        self._l = make_lock()

        def run():
            try:
                res = driver.exec_in_task(handle, argv,
                                          timeout_s=timeout_s)
                with self._l:
                    self._out = bytes(res.get("output") or b"")
                    self._exit = int(res.get("exit_code", -1))
            except Exception as e:
                with self._l:
                    self._out = f"exec failed: {e}\n".encode()
                    self._exit = -1
            self._done.set()

        _threading.Thread(target=run, daemon=True,
                          name=f"task-exec-{self.id[:8]}").start()

    def write_stdin(self, data: bytes, close: bool = False) -> None:
        pass        # non-interactive

    def poll(self, wait_s: float = 0.0) -> Dict:
        self._done.wait(wait_s)
        with self._l:
            out, self._out = self._out, b""
            exited = self._done.is_set() and not out
            return {"stdout": out, "stderr": b"", "exited": exited,
                    "exit_code": self._exit if self._exit is not None
                    else -1}

    def signal(self, sig: int) -> None:
        pass

    def stop(self) -> None:
        self._done.set()


class ExecRegistry:
    """Session table for one client agent; sessions are garbage
    collected when stopped or after idle timeout."""

    IDLE_LIMIT_S = 300.0

    def __init__(self):
        self._l = make_lock()
        self._sessions: Dict[str, Tuple[object, float]] = {}

    def add(self, session) -> str:
        with self._l:
            self._gc()
            self._sessions[session.id] = (session, time.monotonic())
        return session.id

    def get(self, sid: str):
        with self._l:
            # gc here too: a node that never starts another exec must
            # still reap sessions whose caller vanished mid-stream
            self._gc()
            hit = self._sessions.get(sid)
            if hit is None:
                return None
            self._sessions[sid] = (hit[0], time.monotonic())
            return hit[0]

    def remove(self, sid: str) -> None:
        with self._l:
            hit = self._sessions.pop(sid, None)
        if hit is not None:
            hit[0].stop()

    def _gc(self) -> None:
        now = time.monotonic()
        for sid, (sess, seen) in list(self._sessions.items()):
            if now - seen > self.IDLE_LIMIT_S:
                sess.stop()
                del self._sessions[sid]
