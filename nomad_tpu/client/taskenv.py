"""Task environment construction and interpolation.

Reference: client/taskenv/env.go — the NOMAD_* variable set (alloc,
task, job identity; resource limits; ADDR_/IP_/PORT_ port mappings;
META_ both as-written and upper-cased), plus ${...} interpolation over
node attributes/meta and the environment itself, used by driver configs
and templates (client/taskenv/env.go NewTaskEnv/ReplaceEnv).
"""

from __future__ import annotations

import re
from typing import Dict, Optional

_VAR = re.compile(r"\$\{([^}]+)\}")


def build_task_env(alloc, task, node=None,
                   alloc_dir: str = "", task_dir: str = "",
                   secrets_dir: str = "") -> Dict[str, str]:
    """The NOMAD_* env map for one task instance (env.go buildEnv)."""
    env: Dict[str, str] = {}
    job = alloc.job
    env["NOMAD_ALLOC_ID"] = alloc.id
    env["NOMAD_SHORT_ALLOC_ID"] = alloc.id[:8]
    env["NOMAD_ALLOC_NAME"] = alloc.name
    env["NOMAD_ALLOC_INDEX"] = str(alloc.index())
    env["NOMAD_GROUP_NAME"] = alloc.task_group
    env["NOMAD_TASK_NAME"] = task.name
    env["NOMAD_NAMESPACE"] = alloc.namespace
    if job is not None:
        env["NOMAD_JOB_ID"] = job.id
        env["NOMAD_JOB_NAME"] = job.name
        if job.parent_id:
            env["NOMAD_JOB_PARENT_ID"] = job.parent_id
        env["NOMAD_REGION"] = getattr(job, "region", "") or "global"
    env["NOMAD_DC"] = node.datacenter if node is not None else ""
    if alloc_dir:
        env["NOMAD_ALLOC_DIR"] = alloc_dir
    if task_dir:
        env["NOMAD_TASK_DIR"] = task_dir
    if secrets_dir:
        env["NOMAD_SECRETS_DIR"] = secrets_dir

    env["NOMAD_CPU_LIMIT"] = str(task.resources.cpu)
    env["NOMAD_MEMORY_LIMIT"] = str(task.resources.memory_mb)

    # meta: job < group < task precedence, exported as-written AND
    # upper-cased (env.go:823)
    meta: Dict[str, str] = {}
    if job is not None:
        meta.update(job.meta or {})
        tg = job.lookup_task_group(alloc.task_group)
        if tg is not None:
            meta.update(tg.meta or {})
    meta.update(task.meta or {})
    for k, v in meta.items():
        env[f"NOMAD_META_{k}"] = str(v)
        env[f"NOMAD_META_{k.upper()}"] = str(v)

    # network: ADDR_/IP_/PORT_<task>_<label> from allocated resources
    res = alloc.allocated_resources
    if res is not None:
        for tname, tr in res.tasks.items():
            for nw in tr.networks:
                for p in list(nw.reserved_ports) + list(nw.dynamic_ports):
                    label = f"{tname}_{p.label}"
                    env[f"NOMAD_IP_{label}"] = nw.ip
                    env[f"NOMAD_PORT_{label}"] = str(p.value)
                    env[f"NOMAD_ADDR_{label}"] = f"{nw.ip}:{p.value}"
        shared = getattr(res, "shared", None)
        if shared is not None:
            for nw in shared.networks or []:
                for p in list(nw.reserved_ports) + list(nw.dynamic_ports):
                    env[f"NOMAD_IP_{p.label}"] = nw.ip
                    env[f"NOMAD_PORT_{p.label}"] = str(p.value)
                    env[f"NOMAD_ADDR_{p.label}"] = f"{nw.ip}:{p.value}"

    # connect upstream bindings (env.go AddUpstreams:
    # NOMAD_UPSTREAM_{IP,PORT,ADDR}_<service>): the sidecar proxy
    # listens on localhost:<local_bind_port> for each upstream
    if job is not None:
        tg = job.lookup_task_group(alloc.task_group)
        for svc in (tg.services if tg is not None else []):
            cn = svc.connect
            if cn is None or cn.sidecar_service is None or \
                    cn.sidecar_service.proxy is None:
                continue
            for up in cn.sidecar_service.proxy.upstreams:
                key = up.destination_name.replace("-", "_")
                env[f"NOMAD_UPSTREAM_IP_{key}"] = "127.0.0.1"
                env[f"NOMAD_UPSTREAM_PORT_{key}"] = str(up.local_bind_port)
                env[f"NOMAD_UPSTREAM_ADDR_{key}"] = \
                    f"127.0.0.1:{up.local_bind_port}"

    # user-declared env LAST so it can reference nothing but wins keys
    for k, v in (task.env or {}).items():
        env[k] = interpolate(str(v), env, node)
    return env


def interpolate(s: str, env: Dict[str, str], node=None) -> str:
    """${...} interpolation (env.go ReplaceEnv): env. / meta. / attr. /
    node.* selectors plus bare env-var names."""
    if "${" not in s:
        return s

    def sub(m: re.Match) -> str:
        key = m.group(1).strip()
        if key.startswith("env."):
            return env.get(key[4:], "")
        if node is not None:
            if key == "node.unique.id":
                return node.id
            if key == "node.datacenter":
                return node.datacenter
            if key == "node.unique.name":
                return node.name
            if key == "node.class":
                return node.node_class
            if key.startswith("attr."):
                v = node.attributes.get(key[5:])
                return "" if v is None else str(v)
            if key.startswith("meta."):
                v = node.meta.get(key[5:])
                return "" if v is None else str(v)
        return env.get(key, m.group(0))

    return _VAR.sub(sub, s)


def interpolate_config(config, env: Dict[str, str], node=None):
    """Recursively interpolate a driver config tree."""
    if isinstance(config, str):
        return interpolate(config, env, node)
    if isinstance(config, dict):
        return {k: interpolate_config(v, env, node)
                for k, v in config.items()}
    if isinstance(config, list):
        return [interpolate_config(v, env, node) for v in config]
    return config
