"""Client-side durable state: alloc/task/driver-handle transitions.

Reference: client/state/state_database.go persists every alloc, task
state, and driver task-handle transition to boltdb so a restarted
client can re-attach to live tasks (client.go restoreState:1055,
task_runner.go RestoreState:996). Here the store is an append-only
JSONL journal with snapshot compaction — the same shape as the server's
WAL (server/persistence.py), sized for a node agent's update rate.

Layout under state_dir:
    client.json        — node identity (id, secret) — client.go keeps
                         the node ID stable across restarts
    state.snap.json    — last compacted snapshot
    state.journal      — JSONL of entries since the snapshot
"""

from __future__ import annotations

import json
import os
from typing import Dict, Optional

COMPACT_EVERY = 512


class ClientStateDB:
    def __init__(self, state_dir: str):
        self.dir = state_dir
        os.makedirs(state_dir, exist_ok=True)
        self._snap_path = os.path.join(state_dir, "state.snap.json")
        self._journal_path = os.path.join(state_dir, "state.journal")
        self._identity_path = os.path.join(state_dir, "client.json")
        # alloc_id -> {"alloc": wire-dict,
        #              "tasks": {name: {"state":..., "handle":...}}}
        self.state: Dict[str, dict] = {}
        self._journal_len = 0
        self._journal_f = None
        self._load()

    # -- node identity -------------------------------------------------
    def load_identity(self) -> Optional[dict]:
        try:
            with open(self._identity_path) as f:
                return json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            return None

    def save_identity(self, node_id: str, secret_id: str) -> None:
        tmp = self._identity_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"node_id": node_id, "secret_id": secret_id}, f)
        os.replace(tmp, self._identity_path)

    # -- load / compact -----------------------------------------------
    def _load(self) -> None:
        try:
            with open(self._snap_path) as f:
                self.state = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            self.state = {}
        try:
            with open(self._journal_path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        self._apply(json.loads(line))
                        self._journal_len += 1
                    except json.JSONDecodeError:
                        break      # torn tail write: ignore the rest
        except FileNotFoundError:
            pass

    def _apply(self, entry: dict) -> None:
        op = entry.get("op")
        aid = entry.get("alloc_id", "")
        if op == "put_alloc":
            rec = self.state.setdefault(aid, {"tasks": {}})
            rec["alloc"] = entry["alloc"]
        elif op == "put_task":
            rec = self.state.setdefault(aid, {"tasks": {}})
            rec.setdefault("tasks", {})[entry["task"]] = {
                "state": entry.get("state"),
                "handle": entry.get("handle"),
                "vault_lease": entry.get("vault_lease"),
            }
        elif op == "del_alloc":
            self.state.pop(aid, None)

    def _append(self, entry: dict) -> None:
        self._apply(entry)
        if self._journal_f is None:
            self._journal_f = open(self._journal_path, "a")
        self._journal_f.write(json.dumps(entry) + "\n")
        self._journal_f.flush()
        self._journal_len += 1
        if self._journal_len >= COMPACT_EVERY:
            self.compact()

    def compact(self) -> None:
        tmp = self._snap_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.state, f)
        os.replace(tmp, self._snap_path)
        if self._journal_f is not None:
            self._journal_f.close()
            self._journal_f = None
        with open(self._journal_path, "w"):
            pass
        self._journal_len = 0

    def close(self) -> None:
        if self._journal_f is not None:
            self._journal_f.close()
            self._journal_f = None

    # -- writes --------------------------------------------------------
    def put_alloc(self, alloc) -> None:
        from ..utils.codec import to_wire
        self._append({"op": "put_alloc", "alloc_id": alloc.id,
                      "alloc": to_wire(alloc)})

    def put_task(self, alloc_id: str, task: str, state,
                 handle_state: Optional[dict],
                 vault_lease: Optional[dict] = None) -> None:
        from ..utils.codec import to_wire
        self._append({"op": "put_task", "alloc_id": alloc_id,
                      "task": task, "state": to_wire(state),
                      "handle": handle_state,
                      "vault_lease": vault_lease})

    def delete_alloc(self, alloc_id: str) -> None:
        self._append({"op": "del_alloc", "alloc_id": alloc_id})
