"""Client-side vault token management.

Reference: client/vaultclient/vaultclient.go — the client keeps every
derived token in a renewal heap, renews each at half its lease, and
surfaces renewal failure to the task's vault hook, which re-derives and
applies the task's change_mode. Here the renewer is one daemon thread
over the agent's server transport (Node.DeriveVaultToken /
Node.RenewVaultToken)."""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, Optional, Tuple
from ..utils.locks import make_lock

LOG = logging.getLogger("nomad_tpu.client.vault")


def _normalize(info) -> dict:
    """Accept the lease dict, a legacy bare token string, or a missing
    entry (-> empty token, nothing exported)."""
    if isinstance(info, dict):
        return dict(info)
    if info is None:
        return {"token": "", "accessor": "", "ttl_s": 0.0}
    return {"token": str(info), "accessor": "", "ttl_s": 0.0}


class VaultTokenRenewer:
    """Tracks derived tokens and renews each at renew_fraction of its
    TTL; on renewal failure re-derives and hands the fresh lease to the
    task's callback (the vault_hook change_mode path)."""

    def __init__(self, transport, renew_fraction: float = 0.5,
                 tick_s: float = 0.05):
        self.transport = transport
        self.renew_fraction = renew_fraction
        self.tick_s = tick_s
        self._tracked: Dict[Tuple[str, str], dict] = {}
        self._lock = make_lock()
        self._stop = threading.Event()
        self._wake = threading.Event()   # set on track() / stop()
        self._thread: Optional[threading.Thread] = None
        self.stats = {"renewals": 0, "rederives": 0, "failures": 0}

    # -- derivation ----------------------------------------------------
    def derive(self, alloc_id: str, task: str) -> dict:
        tokens = self.transport.derive_vault_token(alloc_id, [task])
        return _normalize(tokens.get(task))

    # -- tracking ------------------------------------------------------
    def track(self, alloc_id: str, task: str, lease: dict,
              on_new_token: Optional[Callable[[dict], None]] = None,
              renew_now: bool = False) -> None:
        """`renew_now` schedules an immediate renewal — used for leases
        restored from the client state DB, whose remaining TTL is
        unknown (renewal either refreshes it or fails into re-derive)."""
        lease = _normalize(lease)
        ttl = float(lease.get("ttl_s") or 0.0)
        if ttl <= 0 or not lease.get("accessor"):
            return      # legacy/no-lease token: nothing to renew
        entry = {"alloc_id": alloc_id, "task": task, "lease": lease,
                 "next_renew": time.monotonic() if renew_now
                 else time.monotonic() + ttl * self.renew_fraction,
                 "fails": 0,
                 "on_new_token": on_new_token}
        with self._lock:
            self._tracked[(alloc_id, task)] = entry
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, daemon=True, name="vault-renewer")
                self._thread.start()
        self._wake.set()

    def untrack(self, alloc_id: str, task: str) -> None:
        with self._lock:
            self._tracked.pop((alloc_id, task), None)

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()

    # -- renewal loop --------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            with self._lock:
                entries = list(self._tracked.values())
            due = [e for e in entries if now >= e["next_renew"]]
            for e in due:
                self._renew_one(e)
            # sleep until the earliest next renewal (coarse 30 s cap so
            # freshly-derived hour-long leases don't pin the wakeup),
            # waking early when track()/stop() changes the set
            with self._lock:
                nexts = [e["next_renew"] for e in self._tracked.values()]
            wait = min([n - time.monotonic() for n in nexts] + [30.0])
            self._wake.wait(max(wait, self.tick_s))
            self._wake.clear()

    def _renew_one(self, e: dict) -> None:
        key = (e["alloc_id"], e["task"])
        lease = e["lease"]
        try:
            ttl = self.transport.renew_vault_token(
                lease["accessor"], lease["token"])
            e["next_renew"] = time.monotonic() \
                + float(ttl) * self.renew_fraction
            e["fails"] = 0
            self.stats["renewals"] += 1
            return
        except Exception as renew_err:
            # retry transient failures (network blip, leader election)
            # with a short backoff before giving up on the lease — only
            # a persistent failure re-derives and fires change_mode
            # (vaultclient.go renewal backoff)
            e["fails"] += 1
            if e["fails"] < 3:
                ttl = float(lease.get("ttl_s") or 1.0)
                e["next_renew"] = time.monotonic() \
                    + min(1.0, ttl * 0.1)
                return
            LOG.info("vault renewal for %s failed (%s); re-deriving",
                     key, renew_err)
        # renewal failed persistently: re-derive, hand the new token to
        # the task (vault_hook.go: renewal failure -> deriveVaultToken
        # -> change_mode)
        try:
            fresh = self.derive(e["alloc_id"], e["task"])
            e["lease"] = fresh
            e["fails"] = 0
            ttl = float(fresh.get("ttl_s") or 0.0)
            e["next_renew"] = time.monotonic() \
                + max(ttl, 0.1) * self.renew_fraction
            self.stats["rederives"] += 1
            cb = e.get("on_new_token")
            if cb is not None:
                cb(fresh)
        except Exception as derive_err:
            # alloc gone/terminal: stop tracking
            self.stats["failures"] += 1
            LOG.warning("vault re-derive for %s failed: %s; untracking",
                        key, derive_err)
            self.untrack(*key)
