"""Containment bootstrap, run as a FRESH interpreter between the client
and the task: joins the cgroup, builds the mount-ns chroot, then
execve()s the task command.

The reference re-execs its own binary for exactly this job (main.go:16
logmon/executor re-exec): running containment code via preexec_fn would
fork a multithreaded parent (the client embeds JAX), which risks
deadlocking in the child on locks held by other threads at fork time.
A spawned helper has no such baggage.

Invoked as: python -m nomad_tpu.client.exec_helper  (spec JSON on
STDIN — argv is world-readable via /proc/*/cmdline and the spec can
carry secrets like VAULT_TOKEN)
spec: {procs_files: [..], chroot_dir: str|null, chroot_dirs: [..],
       command: str, args: [..], env: {..}, cwd: str|null}

NOTE: the interpreter briefly occupies the task's cgroup before
execve replaces it — memory limits below ~16MB can OOM the bootstrap
itself.
"""

import json
import os
import sys

MS_NOSUID = 0x2
MS_NODEV = 0x4
MS_NOEXEC = 0x8


def _statvfs_ms_flags(path: str) -> int:
    """Current nosuid/nodev/noexec flags of the mount at `path`, as
    MS_* bits (a remount must carry locked flags forward or the kernel
    refuses it with EPERM)."""
    try:
        st = os.statvfs(path)
    except OSError:
        return 0
    out = 0
    if st.f_flag & os.ST_NOSUID:
        out |= MS_NOSUID
    if st.f_flag & os.ST_NODEV:
        out |= MS_NODEV
    if st.f_flag & os.ST_NOEXEC:
        out |= MS_NOEXEC
    return out


def contain(spec: dict) -> None:
    os.setsid()
    for pf in spec.get("procs_files", []):
        with open(pf, "w") as f:
            f.write("0")            # 0 == the calling process
    chroot_dir = spec.get("chroot_dir")
    if chroot_dir:
        from nomad_tpu.client.executor import (
            CLONE_NEWNS, MS_BIND, MS_PRIVATE, MS_RDONLY, MS_REC,
            MS_REMOUNT, _get_libc)
        import ctypes
        libc = _get_libc()
        if libc.unshare(CLONE_NEWNS) != 0:
            raise OSError(ctypes.get_errno(), "unshare(CLONE_NEWNS)")
        if libc.mount(b"none", b"/", None, MS_REC | MS_PRIVATE,
                      None) != 0:
            raise OSError(ctypes.get_errno(), "make-rprivate /")
        for src in spec.get("chroot_dirs", []):
            if not os.path.isdir(src):
                continue
            dst = chroot_dir + src
            os.makedirs(dst, exist_ok=True)
            if libc.mount(src.encode(), dst.encode(), None,
                          MS_BIND | MS_REC, None) != 0:
                raise OSError(ctypes.get_errno(), f"bind {src}")
            # the RO downgrade must not fail silently: a writable /etc
            # or /usr inside the chroot defeats the allowlist's point.
            # The kernel rejects a bind-remount that would CLEAR locked
            # flags (user namespaces, locked nosuid/nodev/noexec), so
            # re-assert the source mount's current flags alongside RO
            flags = MS_BIND | MS_REMOUNT | MS_RDONLY | _statvfs_ms_flags(dst)
            if libc.mount(src.encode(), dst.encode(), None,
                          flags, None) != 0:
                raise OSError(ctypes.get_errno(), f"remount-ro {src}")
        # volume_mount stanzas: bind each resolved volume source
        # (CSI publish target / host volume path) at its destination
        # inside the chroot (taskrunner/volume_hook + executor mounts)
        for vm in spec.get("bind_mounts") or []:
            src = os.path.realpath(vm.get("source") or "")
            dest = vm.get("destination") or ""
            if not vm.get("source") or not dest:
                continue            # malformed stanza
            if not os.path.isdir(src):
                # a missing volume source must FAIL the launch — a
                # silently skipped mount means the task writes into a
                # chroot-local stub dir and the data is lost on GC
                raise OSError(2, f"volume source missing: "
                                 f"{vm.get('source')} -> {dest}")
            dst = chroot_dir + "/" + dest.lstrip("/")
            os.makedirs(dst, exist_ok=True)
            if libc.mount(src.encode(), dst.encode(), None,
                          MS_BIND | MS_REC, None) != 0:
                raise OSError(ctypes.get_errno(),
                              f"bind volume {src} -> {dest}")
            if vm.get("read_only"):
                flags = MS_BIND | MS_REMOUNT | MS_RDONLY \
                    | _statvfs_ms_flags(dst)
                if libc.mount(src.encode(), dst.encode(), None,
                              flags, None) != 0:
                    raise OSError(ctypes.get_errno(),
                                  f"remount-ro volume {dest}")
        os.makedirs(chroot_dir + "/tmp", exist_ok=True)
        os.makedirs(chroot_dir + "/dev", exist_ok=True)
        for dev in ("null", "zero", "urandom"):
            src = "/dev/" + dev
            dst = chroot_dir + src
            if not os.path.exists(dst):
                open(dst, "a").close()
            libc.mount(src.encode(), dst.encode(), None, MS_BIND, None)
        os.chroot(chroot_dir)
        os.chdir("/")
    else:
        if spec.get("bind_mounts"):
            # without a chroot there is nowhere to bind the volumes —
            # starting anyway would silently write to raw host paths
            raise RuntimeError(
                "volume mounts require chroot isolation")
        if spec.get("cwd"):
            os.chdir(spec["cwd"])


DEFAULT_PATH = "/usr/local/bin:/usr/bin:/bin:/usr/sbin:/sbin"


def resolve_user(name: str):
    """(uid, gid, home) for the task's `user` stanza. Resolved BEFORE
    the chroot so the host's passwd database answers."""
    import pwd
    rec = pwd.getpwnam(name)
    return rec.pw_uid, rec.pw_gid, rec.pw_dir


def chown_tree(path: str, uid: int, gid: int) -> None:
    # lchown ONLY: this runs as root, and a task artifact can smuggle a
    # symlink to any host file — following it would chown /etc/shadow
    # to the task user
    os.lchown(path, uid, gid)
    for root, dirs, files in os.walk(path):
        for name in dirs + files:
            try:
                os.lchown(os.path.join(root, name), uid, gid)
            except OSError:
                pass


def main() -> None:
    spec = json.loads(sys.stdin.read())
    # user switching (drivers/shared/executor/executor.go: the task
    # runs as the jobspec `user`, default unprivileged — an isolated
    # task must not inherit the agent's root): resolve before the
    # chroot, chown the task's writable tree, drop after containment
    user = spec.get("user") or ""
    creds = None
    if user and hasattr(os, "geteuid") and os.geteuid() == 0:
        uid, gid, _home = resolve_user(user)
        creds = (uid, gid)
        for d in spec.get("chown_dirs") or []:
            if os.path.isdir(d):
                chown_tree(d, uid, gid)
        # supplementary groups from the HOST group database — after
        # the chroot, a task-shipped etc/group could grant itself
        # gid 0 through this lookup
        os.initgroups(user, gid)
    contain(spec)
    env = dict(spec.get("env") or {})
    # execvpe resolves the command via the TASK env's PATH; a jobspec
    # that omits PATH would fail to launch here while the raw_exec
    # fallback (which inherits the client env) would succeed — resolve
    # against a sane default instead
    env.setdefault("PATH", DEFAULT_PATH)
    if creds is not None:
        uid, gid = creds
        os.setgid(gid)
        os.setuid(uid)
        env.setdefault("USER", user)
        env.setdefault("LOGNAME", user)
    cmd = spec["command"]
    os.execvpe(cmd, [cmd] + list(spec.get("args", [])), env)


if __name__ == "__main__":
    main()
