"""The client agent's RPC service: logs/fs/exec served to SERVERS that
forward user requests for allocs living on this node.

Reference topology: servers forward fs/logs/exec RPCs to the owning
client (nomad/client_fs_endpoint.go, client/alloc_endpoint.go:163
Allocations.Exec; the client-side handlers live in
client/fs_endpoint.go / client/alloc_endpoint.go). Here the client
runs its own RPC listener (rpc/server.py with a custom method table)
and advertises its address on the node record; the reference reuses
the client->server yamux session instead, but the listener gives the
same reachability with the transport this codebase already has.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from . import fs_service


def _frame_wire(fr: Dict) -> Dict:
    # msgpack carries bytes natively; keep frames wire-shaped
    return fr


class ClientRpcService:
    def __init__(self, client):
        self.client = client
        self.exec_sessions = fs_service.ExecRegistry()

    # -- helpers -------------------------------------------------------
    def _base(self, alloc_id: str) -> str:
        base = self.client.alloc_base(alloc_id)
        if base is None:
            raise KeyError(f"alloc {alloc_id[:8]} not on this node")
        return base

    def _task_runner(self, alloc_id: str, task: str):
        runner = self.client.runners.get(alloc_id)
        if runner is None:
            raise KeyError(f"alloc {alloc_id[:8]} not on this node")
        for tr in runner.task_runners:
            if tr.task.name == task or not task:
                return runner, tr
        raise KeyError(f"unknown task {task!r} for alloc {alloc_id[:8]}")

    # -- fs/logs -------------------------------------------------------
    def fs_logs(self, args: Dict) -> Dict:
        data, total = fs_service.read_logs(
            self._base(args["alloc_id"]), args["task"],
            args.get("type", "stdout"), int(args.get("offset", 0)))
        return {"Data": data, "Offset": total}

    def fs_list(self, args: Dict) -> Dict:
        out = fs_service.list_dir(self._base(args["alloc_id"]),
                                  args.get("path", "/"))
        return {"Entries": out}

    def fs_cat(self, args: Dict) -> Dict:
        data = fs_service.cat_file(self._base(args["alloc_id"]),
                                   args.get("path", "/"))
        return {"Data": data}

    def fs_stream(self, args: Dict) -> Dict:
        frames = fs_service.stream_frames(
            self._base(args["alloc_id"]),
            args.get("path"), int(args.get("offset", 0)),
            task=args.get("task", ""),
            log_type=args.get("log_type", ""),
            wait_s=float(args.get("wait_s", 0.0)))
        return {"Frames": [_frame_wire(f) for f in frames]}

    # -- exec (client/alloc_endpoint.go:163) ---------------------------
    def exec_start(self, args: Dict) -> Dict:
        alloc_id = args["alloc_id"]
        task = args.get("task", "")
        argv = list(args.get("cmd") or [])
        if not argv:
            raise ValueError("exec requires a command")
        runner, tr = self._task_runner(alloc_id, task)
        if tr.task.driver in ("mock", "mock_driver"):
            sess = fs_service.MockExecSession(argv)
        elif hasattr(tr.driver, "exec_in_task") and \
                getattr(tr.handle, "executor_rpc", None) is not None:
            # exec INSIDE the task's isolation through the out-of-proc
            # executor (same cgroup + chroot view — executor_linux.go
            # Exec)
            sess = fs_service.TaskExecSession(tr.driver, tr.handle,
                                              argv)
        else:
            from .taskenv import build_task_env
            task_path, _local, secrets = \
                runner.alloc_dir.task_paths(tr.task.name)
            env = build_task_env(
                runner.alloc, tr.task, self.client.node,
                alloc_dir=runner.alloc_dir.shared,
                task_dir=task_path, secrets_dir=secrets)
            # SCRUBBED env, same stance as task launches: only the
            # task's own variables plus a sane PATH — merging the agent
            # process env would hand an alloc-exec caller the agent's
            # credentials. This branch is the fallback for drivers
            # without an isolation boundary (raw_exec); isolated exec
            # tasks take the TaskExecSession path above, inside the
            # chroot/cgroup.
            env.setdefault(
                "PATH", "/usr/local/bin:/usr/bin:/bin:/usr/sbin:/sbin")
            sess = fs_service.ExecSession(argv, cwd=task_path, env=env)
        self.exec_sessions.add(sess)
        return {"session_id": sess.id}

    def exec_io(self, args: Dict) -> Dict:
        sess = self.exec_sessions.get(args["session_id"])
        if sess is None:
            raise KeyError("unknown exec session")
        stdin = args.get("stdin") or b""
        if stdin or args.get("close_stdin"):
            sess.write_stdin(bytes(stdin),
                             close=bool(args.get("close_stdin")))
        sig = args.get("signal")
        if sig:
            sess.signal(int(sig))
        out = sess.poll(wait_s=float(args.get("wait_s", 0.0)))
        if out["exited"]:
            self.exec_sessions.remove(args["session_id"])
        return out

    def exec_stop(self, args: Dict) -> Dict:
        self.exec_sessions.remove(args["session_id"])
        return {}

    # -- alloc lifecycle (client/alloc_endpoint.go Restart/Signal) -----
    def _task_runners_for(self, alloc_id: str, task: str):
        runner = self.client.runners.get(alloc_id)
        if runner is None:
            raise KeyError(f"alloc {alloc_id[:8]} not on this node")
        out = [tr for tr in runner.task_runners
               if not task or tr.task.name == task]
        if not out:
            raise KeyError(f"unknown task {task!r}")
        return out

    def alloc_signal(self, args: Dict) -> Dict:
        """Deliver a signal to the task process(es). Unknown signal
        names are an ERROR — silently substituting a default would
        deliver the wrong signal while reporting success."""
        import signal as _signal
        sig = args.get("signal") or _signal.SIGUSR1
        if isinstance(sig, str):
            name = sig.upper()
            if not name.startswith("SIG"):
                name = f"SIG{name}"
            resolved = getattr(_signal, name, None)
            if resolved is None:
                raise ValueError(f"unknown signal {sig!r}")
            sig = resolved
        delivered = 0
        for tr in self._task_runners_for(args["alloc_id"],
                                         args.get("task", "")):
            proc = getattr(tr.handle, "proc", None) if tr.handle else None
            if proc is not None:
                try:
                    proc.send_signal(int(sig))
                    delivered += 1
                except (ProcessLookupError, OSError):
                    pass
        return {"delivered": delivered}

    def alloc_restart(self, args: Dict) -> Dict:
        """Restart the task(s): flag the runner for an unconditional
        restart (any exit code, outside the policy budget) and stop
        the process; the run loop brings it straight back."""
        restarted = 0
        for tr in self._task_runners_for(args["alloc_id"],
                                         args.get("task", "")):
            h = tr.handle
            if h is None:
                continue
            tr._force_restart = True
            try:
                tr.driver.stop_task(h, 5.0)
                restarted += 1
            except Exception:
                tr._force_restart = False
        return {"restarted": restarted}

    # -- host/alloc stats (command/agent/stats_endpoint.go +
    # client/alloc_endpoint.go Stats — ISSUE 13) -----------------------
    def stats_host(self, args: Dict) -> Dict:
        """This node's latest HostStats sample; reports the sampler
        dark (enabled: False) under the kill switch instead of erroring
        — a fleet-wide poller must distinguish 'off' from 'down'."""
        hs = getattr(self.client, "host_stats", None)
        if hs is None:
            return {"enabled": False}
        out = hs.host_stats()
        out["enabled"] = True
        if args.get("history"):
            out["history"] = hs.history(
                last=int(args.get("n", 0)) or None)
        return out

    def stats_alloc(self, args: Dict) -> Dict:
        hs = getattr(self.client, "host_stats", None)
        if hs is None:
            return {"enabled": False, "stats": None}
        stats = hs.alloc_stats(args["alloc_id"])
        if stats is None:
            # distinguish "not on this node" (a real routing error)
            # from "running but no usage reported" (driver without a
            # stats() hook, or the first sample hasn't landed): the
            # latter answers cleanly with stats: None — the shape the
            # CLI renders as "no live usage reported"
            aid = args["alloc_id"]
            if not any(rid.startswith(aid)
                       for rid in self.client.runners):
                raise KeyError(
                    f"alloc {aid[:8]} not on this node")
        return {"enabled": True, "stats": stats}

    # -- the method table ---------------------------------------------
    def rpc_methods(self) -> Dict:
        return {
            "ClientFS.Logs": self.fs_logs,
            "ClientFS.List": self.fs_list,
            "ClientFS.Cat": self.fs_cat,
            "ClientFS.Stream": self.fs_stream,
            "ClientExec.Start": self.exec_start,
            "ClientExec.Io": self.exec_io,
            "ClientExec.Stop": self.exec_stop,
            "ClientAlloc.Signal": self.alloc_signal,
            "ClientAlloc.Restart": self.alloc_restart,
            "ClientStats.Host": self.stats_host,
            "ClientStats.Alloc": self.stats_alloc,
        }
