"""Client-side CSI volume manager.

Reference: client/pluginmanager/csimanager/volume.go — the volumeManager
drives MountVolume (ControllerPublish → NodeStage once per volume per
node → NodePublish per allocation) and UnmountVolume (NodeUnpublish per
allocation → NodeUnstage when the node's last claim goes away), with
usage tracked per (volume, alloc). Here the plugin lives behind the
repo's plugin process boundary (plugins/csi_client.ExternalCSIPlugin)
and staging is refcounted in-process.
"""

from __future__ import annotations

import logging
import os
import threading
from typing import Dict, Optional, Tuple
from ..utils.locks import make_lock

LOG = logging.getLogger("nomad_tpu.client.csi")


class CSIManager:
    def __init__(self, node_id: str, mount_root: str):
        self.node_id = node_id
        # <mount_root>/staging/<plugin>/<vol> and
        # <mount_root>/per-alloc/<alloc>/<vol> (csimanager mountRoot)
        self.mount_root = mount_root
        self.plugins: Dict[str, object] = {}
        self._lock = make_lock()
        # (plugin_id, volume_id) -> set of alloc ids staged against it
        self._stage_users: Dict[Tuple[str, str], set] = {}
        # per-volume locks held ACROSS the plugin RPC sequence: a
        # last-user unstage racing a new first-user stage must not
        # interleave, and a failed mount must not leave a phantom user
        self._key_locks: Dict[Tuple[str, str], threading.Lock] = {}

    def _key_lock(self, key: Tuple[str, str]) -> threading.Lock:
        with self._lock:
            lock = self._key_locks.get(key)
            if lock is None:
                lock = self._key_locks[key] = make_lock()
            return lock

    def register_plugin(self, plugin_id: str, plugin) -> None:
        self.plugins[plugin_id] = plugin

    def fingerprint_attrs(self) -> Dict[str, str]:
        """Node attributes advertising healthy plugins
        (client/pluginmanager/csimanager instanceManager fingerprint)."""
        out = {}
        for pid, p in self.plugins.items():
            try:
                if p.probe():
                    out[f"csi.plugin.{pid}"] = "1"
            except Exception:
                LOG.warning("csi plugin %s probe failed", pid)
        return out

    def _staging_path(self, plugin_id: str, volume_id: str) -> str:
        return os.path.join(self.mount_root, "csi", "staging",
                            plugin_id, volume_id)

    def _target_path(self, alloc_id: str, volume_id: str) -> str:
        return os.path.join(self.mount_root, "csi", "per-alloc",
                            alloc_id, volume_id)

    def mount_volume(self, plugin_id: str, volume_id: str,
                     alloc_id: str, readonly: bool) -> Optional[str]:
        """MountVolume (volume.go:46): controller-publish + stage (first
        user on this node) + publish. Returns the per-alloc source path
        tasks mount from, or None if the plugin is unknown."""
        plugin = self.plugins.get(plugin_id)
        if plugin is None:
            return None
        staging = self._staging_path(plugin_id, volume_id)
        target = self._target_path(alloc_id, volume_id)
        key = (plugin_id, volume_id)
        with self._key_lock(key):
            users = self._stage_users.setdefault(key, set())
            plugin.controller_publish(volume_id, self.node_id)
            if not users:
                plugin.node_stage(volume_id, staging)
            plugin.node_publish(volume_id, staging, target, readonly)
            # the alloc becomes a stage user only once the full mount
            # sequence succeeded — a failed RPC above must not leave a
            # phantom user that suppresses re-stage/unstage
            users.add(alloc_id)
        return target

    def unmount_volume(self, plugin_id: str, volume_id: str,
                       alloc_id: str) -> None:
        """UnmountVolume (volume.go:239): unpublish this alloc's target;
        unstage + controller-unpublish when the node's last user left."""
        plugin = self.plugins.get(plugin_id)
        if plugin is None:
            return
        target = self._target_path(alloc_id, volume_id)
        key = (plugin_id, volume_id)
        with self._key_lock(key):
            users = self._stage_users.get(key)
            if users is None or alloc_id not in users:
                return      # never mounted (or already unmounted)
            try:
                plugin.node_unpublish(volume_id, target)
            except Exception:
                LOG.exception("NodeUnpublishVolume failed for %s",
                              volume_id)
            users.discard(alloc_id)
            if not users:
                self._stage_users.pop(key, None)
                try:
                    plugin.node_unstage(
                        volume_id,
                        self._staging_path(plugin_id, volume_id))
                    plugin.controller_unpublish(volume_id, self.node_id)
                except Exception:
                    LOG.exception("NodeUnstageVolume failed for %s",
                                  volume_id)

    def shutdown(self) -> None:
        for p in self.plugins.values():
            try:
                p.shutdown()
            except Exception:
                pass
