"""Mesh-sharded resident node table: the r7 delta machinery made
mesh-native (ROADMAP "Device-sharded state: break the single-chip
ceiling").

The single-device mirror (ops/device_table.py DeviceNodeTable) made
steady-state dispatch cheap on ONE chip: columns resident across evals,
advanced by batched row scatters. The mesh path (parallel/sharded.py)
had none of that — every non-capacity column was re-uploaded host ->
device on every dispatch, which caps the scale ladder at whatever one
chip's H2D bandwidth tolerates. This module keeps the hot columns —
capacity, used, free_ports — *sharded-resident* over the mesh
(`NamedSharding` over the `nodes` axis) and advances them with the same
delta protocol:

  - cold start / node-set rebuild: ONE sharded H2D per column
    (`jax.device_put(col, NamedSharding(mesh, P("nodes", ...)))` — jax
    splits the transfer per device), counted as a `reshard_upload`.
  - alloc-delta refreshes: the cache's DeviceNodeTable journals every
    refresh's touched row indices (`delta_log`); this mirror catches up
    from its version to the request table's version by scatter-setting
    the journaled rows from the CURRENT host columns, as a sharded jit
    program — each shard scatters only the rows it owns. `.set` with
    host-latest values makes replay order-free and bit-identical to a
    rebuild by construction.
  - per-eval plan overlays apply as sparse `.at[rows].add` over the
    resident used column, on device, like the single-chip mirror.

MVCC staleness: the (mirror identity, version) token carried by every
NodeTable gates reuse exactly like the single-device path — a snapshot
older than the resident state falls back to dense shipping, a journal
gap (rebuild, ring truncation, cache replacement) triggers one
contiguous re-upload.

Fold-to-rebuild: scattered-row debt since the last contiguous upload is
tracked per mirror; the governor's `mesh.reshard_debt` watermark
(ServerConfig.mesh_reshard_debt_high) reclaims by re-uploading once,
replacing the scatter history.

Kill switches: `NOMAD_TPU_MESH_RESIDENT=0` (env, wins) or
`ServerConfig.mesh_resident=False` fall back to the capacity-only
per-eval upload path — the bisection escape hatch.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np

from ..utils.locks import make_lock
from ..ops.device_table import (DeviceTableState, SPARSE_MAX_FRAC,
                                _bucket_rows, _overlay_add, _scatter_set,
                                enable_row_journal)

MESH_RESIDENT_ENV = "NOMAD_TPU_MESH_RESIDENT"

# ServerConfig.mesh_resident lands here (server/core.py configure());
# the env kill switch wins over it either way
_RESIDENT_CFG = True


def configure(resident: bool) -> None:
    global _RESIDENT_CFG
    _RESIDENT_CFG = bool(resident)


def resident_enabled() -> bool:
    v = os.environ.get(MESH_RESIDENT_ENV)
    if v is not None:
        return v not in ("0", "off", "no")
    return _RESIDENT_CFG


def pad_for_mesh(mesh, n: int) -> int:
    """Pad N so it divides evenly over the mesh, VPU-lane aligned —
    the one padding rule shared by the sharded dispatcher and this
    resident table (their shapes must agree or residency never hits)."""
    shards = mesh.devices.size
    per = -(-n // shards)
    per = max(8, per)
    return per * shards


class ShardedDeviceNodeTable:
    """The mesh-resident mirror one process-wide ShardedSelect owns.

    Tracks ONE (host mirror, version) pair — the latest NodeTableCache
    generation it served. `arrays_for(table)` returns sharded device
    columns for that table, advancing by journal replay when the table
    is ahead, or None for stale snapshots (dense fallback)."""

    def __init__(self, mesh):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        # a companion now exists: host mirrors start journaling row
        # indices (entries before this read as a gap -> one re-upload)
        enable_row_journal()
        self.mesh = mesh
        self.node_sharding = NamedSharding(mesh, P("nodes"))
        self.node2_sharding = NamedSharding(mesh, P("nodes", None))
        self.replicated = NamedSharding(mesh, P())
        self._jax = jax
        self._l = make_lock()
        self._state: Optional[DeviceTableState] = None
        self._mirror = None         # the host cache's DeviceNodeTable
        self._version = -1
        self._epoch = -1
        self.delta_debt = 0         # rows scattered since last upload
        self.stats: Dict[str, int] = {
            "reshard_uploads": 0, "reshard_bytes": 0,
            "delta_scatters": 0, "delta_rows": 0,
            "resident_hits": 0, "stale_misses": 0, "journal_gaps": 0,
            "overlay_dispatches": 0, "folds": 0,
        }

    # -- kernel-side access --------------------------------------------
    def arrays_for(self, table) -> Optional[DeviceTableState]:
        """Sharded device columns for `table`, or None when this table
        is a stale snapshot (the resident state moved past it — dense
        fallback, like the single-device mirror). A table ahead of the
        resident version catches the mirror up by journal replay; a
        gap or a new cache generation pays one contiguous sharded
        re-upload."""
        mirror = getattr(table, "device_mirror", None)
        token = getattr(table, "device_version", -1)
        if mirror is None or token < 0:
            return None
        with self._l:
            st = self._state
            if st is None or self._mirror is not mirror \
                    or self._epoch != mirror.epoch:
                return self._upload_locked(table, mirror, token)
            if token == self._version:
                self.stats["resident_hits"] += 1
                return st
            if token < self._version:
                # older snapshot than the resident state: MVCC says it
                # must not read newer columns
                self.stats["stale_misses"] += 1
                return None
            entries = mirror.deltas_since(self._version)
            if entries is None:
                self.stats["journal_gaps"] += 1
                return self._upload_locked(table, mirror, token)
            # drop journal entries past the request's version: the
            # mirror may already be ahead of this table's snapshot
            rows_l = [r for v, r in entries if v <= token and len(r)]
            rows = (np.unique(np.concatenate(rows_l)) if rows_l
                    else np.zeros(0, np.int32))
            if len(rows) > st.n * SPARSE_MAX_FRAC:
                # wide delta: one contiguous upload beats scattering
                # most of the table
                return self._upload_locked(table, mirror, token)
            if len(rows):
                try:
                    st = self._scatter_locked(st, table, rows)
                except Exception:   # pragma: no cover — defensive: a
                    # failed device op must not poison scheduling
                    self._state = None
                    self.stats["stale_misses"] += 1
                    return None
                self._state = st
            self._version = token
            self.stats["resident_hits"] += 1
            return self._state

    def _scatter_locked(self, st: DeviceTableState, table,
                        rows: np.ndarray) -> DeviceTableState:
        m = len(rows)
        idx = rows.astype(np.int32)
        from ..analysis import sanitizer
        if sanitizer.enabled():
            sanitizer.check_rows("sharded_table.scatter", idx, st.n)
        b = _bucket_rows(m)
        if b > m:
            # pad with repeats of the first row carrying its own value:
            # duplicate .set with an identical payload is deterministic
            idx = np.concatenate([idx, np.full(b - m, idx[0], np.int32)])
        used_rows = table.base_used[idx].astype(np.float32)
        port_rows = table.free_ports[idx].astype(np.float32)
        # row payloads ride replicated; the resident operands are
        # sharded, so XLA partitions the scatter — each shard sets only
        # the rows it owns
        put = self._jax.device_put
        used, ports = _scatter_set(st.used, st.free_ports,
                                   put(idx, self.replicated),
                                   put(used_rows, self.replicated),
                                   put(port_rows, self.replicated))
        self.delta_debt += m
        self.stats["delta_scatters"] += 1
        self.stats["delta_rows"] += m
        return DeviceTableState(st.version, st.epoch, st.n, st.n_pad,
                                st.capacity, used, ports)

    def _upload_locked(self, table, mirror, token) -> DeviceTableState:
        """One contiguous sharded H2D per column (capacity, used,
        free_ports) — the cold-start / catch-up-miss path, and the
        shard-aware `build_from_columns` upload at cold start
        (NodeTableCache.prefetch_device)."""
        from ..utils import stages
        import time as _time
        t0 = _time.perf_counter() if stages.enabled else 0.0
        n = table.n
        n_pad = pad_for_mesh(self.mesh, n)
        d = table.base_used.shape[1]
        cap = np.zeros((n_pad, d), np.float32)
        cap[:n] = table.capacity
        used = np.zeros((n_pad, d), np.float32)
        used[:n] = table.base_used
        ports = np.zeros(n_pad, np.float32)
        ports[:n] = table.free_ports
        put = self._jax.device_put
        st = DeviceTableState(token, mirror.epoch, n, n_pad,
                              put(cap, self.node2_sharding),
                              put(used, self.node2_sharding),
                              put(ports, self.node_sharding))
        if stages.enabled:
            stages.add("h2d", _time.perf_counter() - t0)
        self._state = st
        self._mirror = mirror
        self._version = token
        self._epoch = mirror.epoch
        self.delta_debt = 0
        self.stats["reshard_uploads"] += 1
        self.stats["reshard_bytes"] += cap.nbytes + used.nbytes \
            + ports.nbytes
        return st

    def overlay_used(self, st: DeviceTableState, rows, deltas):
        """used0 = resident used + sparse per-eval plan overlay,
        computed on the mesh. Returns a sharded device array (async),
        st.used itself for an empty overlay, or None when the overlay
        is too dense to be worth scattering."""
        m = len(rows)
        if m == 0:
            return st.used
        if m > st.n * SPARSE_MAX_FRAC:
            return None
        idx = np.asarray(rows, np.int32)
        vals = np.asarray(deltas, np.float32)
        from ..analysis import sanitizer
        if sanitizer.enabled():
            sanitizer.check_rows("sharded_table.overlay", idx, st.n)
            sanitizer.check_finite("sharded_table.overlay", deltas=vals)
        b = _bucket_rows(m)
        if b > m:
            idx = np.concatenate([idx, np.zeros(b - m, np.int32)])
            vals = np.concatenate(
                [vals, np.zeros((b - m, vals.shape[1]), np.float32)])
        put = self._jax.device_put
        self.stats["overlay_dispatches"] += 1
        return _overlay_add(st.used, put(idx, self.replicated),
                            put(vals, self.replicated))

    # -- governor integration ------------------------------------------
    def fold(self, table, version: Optional[int] = None) -> dict:
        """Reclaim (mesh.reshard_debt watermark): replace the scatter
        history with one contiguous sharded re-upload from the current
        host table."""
        with self._l:
            mirror = getattr(table, "device_mirror", None)
            token = getattr(table, "device_version", -1)
            if version is not None and version != token:
                return {"folded": False, "reason": "stale table"}
            if self._state is None or mirror is None:
                self.delta_debt = 0
                return {"folded": False, "reason": "not materialized"}
            if token < self._version:
                return {"folded": False, "reason": "stale table"}
            debt = self.delta_debt
            self._upload_locked(table, mirror, token)
            self.stats["folds"] += 1
            return {"folded": True, "debt_cleared": debt}

    def debt(self) -> int:
        return self.delta_debt

    def device_bytes(self) -> int:
        """Bytes the resident columns pin across the mesh (shape
        metadata only — reading .nbytes never syncs a device)."""
        with self._l:
            st = self._state
        if st is None:
            return 0
        total = 0
        for arr in (st.capacity, st.used, st.free_ports):
            total += int(getattr(arr, "nbytes", 0))
        return total

    def snapshot(self) -> dict:
        with self._l:
            return {"materialized": self._state is not None,
                    "version": self._version,
                    "reshard_debt": self.delta_debt, **self.stats}
