from .sharded import ShardedSelect, make_mesh

__all__ = ["ShardedSelect", "make_mesh"]
