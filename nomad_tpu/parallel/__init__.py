from .sharded import ShardedSelect, make_mesh
from .sharded_table import ShardedDeviceNodeTable, resident_enabled

__all__ = ["ShardedSelect", "ShardedDeviceNodeTable", "make_mesh",
           "resident_enabled"]
