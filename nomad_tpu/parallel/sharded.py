"""Multi-chip scheduling: the node axis sharded over a device mesh.

The reference scales the node dimension by sampling (log2(n) candidates,
stack.go:77-89); we scale it by sharding: the NodeTable's (N, dims)
arrays live sharded over the `nodes` mesh axis, the fused select kernel
runs SPMD under jit, and XLA inserts the cross-shard collectives for the
argmax/top-k reduction and the one-hot carry updates (all-gather of the
chosen index). This is the orchestrator's analog of data parallelism:
feasibility+scoring are embarrassingly parallel per node; only the
winner reduction crosses ICI (SURVEY.md §2.6/§2.7).

Multi-host: the same jit program runs under multi-process JAX, with the
node axis sharded across hosts' devices; DCN only carries the per-eval
ask vectors and result placements (small), never the node table.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.select import (PACK_SHARD_KINDS, SelectRequest, _bucket_k,
                          _select_scan, pack_request, unpack_result)
from .sharded_table import (ShardedDeviceNodeTable, pad_for_mesh,
                            resident_enabled)

# capacity-only fallback cache bound (tables WITHOUT a mirror token —
# private builds, older snapshots): evict-oldest past this many entries
CAPACITY_CACHE_MAX = 16


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=("nodes",))


class ShardedSelect:
    """Dispatches the fused placement kernel with the node axis sharded
    over a mesh. The same _select_scan program is used — sharding is
    expressed purely through input shardings (SPMD via pjit)."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.node_sharding = NamedSharding(mesh, P("nodes"))
        self.node2_sharding = NamedSharding(mesh, P("nodes", None))
        self.code_sharding = NamedSharding(mesh, P(None, "nodes"))
        self.replicated = NamedSharding(mesh, P())
        # mesh-resident node table (sharded_table.py): ALL hot columns
        # — capacity, used, free_ports — live sharded across evals,
        # advanced by the cache's delta journal; steady-state dispatches
        # ship only per-request arrays (ask, feasible, pre_score, ...)
        self.resident = ShardedDeviceNodeTable(mesh)
        # capacity-only fallback for tables without a mirror token
        # (keyed by the host array's identity — NodeTable versions
        # share the capacity array until a node-set rebuild)
        self._resident: dict = {}
        self.stats = {"capacity_evictions": 0}

    def pad_to_shards(self, n: int) -> int:
        """Pad N so it divides evenly over the mesh."""
        return pad_for_mesh(self.mesh, n)

    def _sharding_for(self, kind: str):
        return {"node": self.node_sharding, "node2": self.node2_sharding,
                "code": self.code_sharding, "rep": self.replicated,
                "scalar": None}[kind]

    def select(self, req: SelectRequest):
        """Full sharded dispatch of a SelectRequest: identical semantics
        to SelectKernel.select, with the node axis spread over the mesh.
        Packing is shared with the single-device path (pack_request);
        only the device placement differs. When the request carries a
        live mirror token, the table-shaped columns come off the
        mesh-resident table instead of crossing the bus."""
        n_pad = self.pad_to_shards(len(req.feasible))
        k = _bucket_k(max(req.count, 1))
        args, statics = pack_request(req, n_pad)
        resident = self.resident_args(req, n_pad)
        placed_args = {}
        for name, value in args.items():
            if resident is not None and name in resident:
                placed_args[name] = resident[name]
                continue
            if name == "capacity":
                placed_args[name] = self._resident_capacity(req.capacity,
                                                            value)
                continue
            sharding = self._sharding_for(PACK_SHARD_KINDS[name])
            placed_args[name] = (value if sharding is None
                                 else jax.device_put(value, sharding))
        with self.mesh:
            _carry, outs = _select_scan(**placed_args, k_steps=k, **statics)
        return unpack_result(req, outs)

    def resident_args(self, req: SelectRequest,
                      n_pad: int) -> Optional[dict]:
        """Mesh-resident replacements for the table-shaped inputs
        (capacity, used0, free_ports) — the sharded analog of
        SelectKernel._resident_args, sharing the same assembly
        (device_table.resident_request_args): used0 computed ON the
        mesh as resident-used + the sparse per-eval plan overlay, with
        dense fallback for stale snapshots, shape mismatches, or
        overlays too wide to scatter."""
        if not resident_enabled():
            return None
        from ..ops.device_table import resident_request_args
        return resident_request_args(self.resident, req, n_pad,
                                     "nomad.select.mesh_resident")

    def _resident_capacity(self, src, padded):
        """Device-put the padded capacity once per (source array, pad)
        and keep it sharded on the mesh across evals — the fallback for
        tables without a mirror token (the full resident table serves
        tokened requests). `src` is the host NodeTable's capacity array
        whose identity keys the cache; eviction is oldest-first, never
        a wholesale clear (dropping the hot table on churn re-uploads
        it on the very next eval)."""
        key = (id(src), padded.shape[0])
        hit = self._resident.get(key)
        if hit is not None and hit[0] is src:
            return hit[1]
        arr = jax.device_put(padded, self.node2_sharding)
        while len(self._resident) >= CAPACITY_CACHE_MAX:
            # dicts preserve insertion order: drop the oldest entry
            self._resident.pop(next(iter(self._resident)))
            self.stats["capacity_evictions"] += 1
        self._resident[key] = (src, arr)
        return arr

    def stats_snapshot(self) -> dict:
        """One read for the governor gauges, the telemetry device.*
        family, and the bench artifact (ops/select.mesh_stats_snapshot
        fronts this for the process-wide instance)."""
        ndev = int(self.mesh.devices.size)
        total = self.resident.device_bytes()
        out = {
            "devices": ndev,
            "resident_bytes": total,
            "resident_bytes_per_device": total / max(ndev, 1),
            "capacity_cache_entries": len(self._resident),
            "capacity_cache_evictions": self.stats["capacity_evictions"],
        }
        out.update(self.resident.snapshot())
        return out

    def _resident_capacity_for_table(self, table, n_pad: int):
        """The mesh-resident capacity column for a tokened table, or
        None (caller falls back to the identity-keyed cache). Batched
        lanes share one capacity array but carry per-lane used0, so
        only capacity rides the full resident table here."""
        if table is None or not resident_enabled():
            return None
        state = self.resident.arrays_for(table)
        if state is None or state.n_pad != n_pad:
            return None
        return state.capacity

    def place_batched_chunked_args(self, cargs: dict,
                                   capacity_src=None,
                                   table=None) -> dict:
        """Shard the BATCHED K-way kernel's argument dict: per-lane
        arrays carry a leading batch axis (B, ...) that stays
        replicated while the node axis shards — the multi-eval batch
        (select_many) runs as one SPMD program over the mesh. Capacity
        is unstacked (all lanes share one table; that's the batching
        precondition) and rides the mesh-resident table when a mirror
        token is available, else the identity-keyed cache."""
        batched = {
            "node": NamedSharding(self.mesh, P(None, "nodes")),
            "node2": NamedSharding(self.mesh, P(None, "nodes", None)),
            "code": NamedSharding(self.mesh, P(None, None, "nodes")),
            "rep": self.replicated,
            "scalar": self.replicated,      # scalars stack to (B,)
        }
        placed = {}
        for name, value in cargs.items():
            if name == "capacity":
                cap = self._resident_capacity_for_table(
                    table, value.shape[0])
                if cap is not None:
                    placed[name] = cap
                elif capacity_src is not None:
                    placed[name] = self._resident_capacity(capacity_src,
                                                           value)
                else:
                    placed[name] = jax.device_put(
                        value, self.node2_sharding)
                continue
            sharding = batched[PACK_SHARD_KINDS[name]]
            placed[name] = jax.device_put(np.asarray(value), sharding)
        return placed

    def place_chunked_args(self, cargs: dict,
                           capacity_src=None,
                           req: Optional[SelectRequest] = None) -> dict:
        """Shard the K-way kernel's argument dict over the mesh (same
        kind table as the scan). When `req` carries a live mirror
        token, the table-shaped columns (capacity, used0, free_ports)
        come off the mesh-resident table; else capacity_src rides the
        identity-keyed cache."""
        resident = None
        if req is not None:
            resident = self.resident_args(req,
                                          cargs["capacity"].shape[0])
        placed = {}
        for name, value in cargs.items():
            if resident is not None and name in resident:
                placed[name] = resident[name]
                continue
            if name == "capacity" and capacity_src is not None:
                placed[name] = self._resident_capacity(capacity_src,
                                                       value)
                continue
            sharding = self._sharding_for(PACK_SHARD_KINDS[name])
            placed[name] = (value if sharding is None
                            else jax.device_put(value, sharding))
        return placed

    def place(self, capacity, used, feasible, ask, count, *,
              tg_collisions=None, job_count=None, spread_alg=False):
        """Convenience wrapper: basic sharded multi-placement."""
        n = capacity.shape[0]
        req = SelectRequest(
            ask=np.asarray(ask, np.float32), count=count,
            feasible=feasible, capacity=capacity, used=used,
            desired_count=float(max(count, 1)),
            tg_collisions=(tg_collisions if tg_collisions is not None
                           else np.zeros(n, np.int32)),
            job_count=(job_count if job_count is not None
                       else np.zeros(n, np.int32)),
            algorithm="spread" if spread_alg else "binpack",
        )
        res = self.select(req)
        return res.node_idx, res.final_score
