"""Multi-chip scheduling: the node axis sharded over a device mesh.

The reference scales the node dimension by sampling (log2(n) candidates,
stack.go:77-89); we scale it by sharding: the NodeTable's (N, dims)
arrays live sharded over the `nodes` mesh axis, the fused select kernel
runs SPMD under jit, and XLA inserts the cross-shard collectives for the
argmax/top-k reduction and the one-hot carry updates (all-gather of the
chosen index). This is the orchestrator's analog of data parallelism:
feasibility+scoring are embarrassingly parallel per node; only the
winner reduction crosses ICI (SURVEY.md §2.6/§2.7).

Multi-host: the same jit program runs under multi-process JAX, with the
node axis sharded across hosts' devices; DCN only carries the per-eval
ask vectors and result placements (small), never the node table.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.select import (PACK_SHARD_KINDS, SelectRequest, _bucket_k,
                          _select_scan, pack_request, unpack_result)


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=("nodes",))


class ShardedSelect:
    """Dispatches the fused placement kernel with the node axis sharded
    over a mesh. The same _select_scan program is used — sharding is
    expressed purely through input shardings (SPMD via pjit)."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.node_sharding = NamedSharding(mesh, P("nodes"))
        self.node2_sharding = NamedSharding(mesh, P("nodes", None))
        self.code_sharding = NamedSharding(mesh, P(None, "nodes"))
        self.replicated = NamedSharding(mesh, P())
        # resident device state: the node table's immutable capacity
        # columns live sharded on the mesh across evals (keyed by the
        # host array's identity — NodeTable versions share the array
        # until a node-set rebuild), so steady-state evals ship only
        # their per-eval columns
        self._resident: dict = {}

    def pad_to_shards(self, n: int) -> int:
        """Pad N so it divides evenly over the mesh."""
        shards = self.mesh.devices.size
        per = -(-n // shards)
        # keep lanes aligned for the VPU
        per = max(8, per)
        return per * shards

    def _sharding_for(self, kind: str):
        return {"node": self.node_sharding, "node2": self.node2_sharding,
                "code": self.code_sharding, "rep": self.replicated,
                "scalar": None}[kind]

    def select(self, req: SelectRequest):
        """Full sharded dispatch of a SelectRequest: identical semantics
        to SelectKernel.select, with the node axis spread over the mesh.
        Packing is shared with the single-device path (pack_request);
        only the device placement differs."""
        n_pad = self.pad_to_shards(len(req.feasible))
        k = _bucket_k(max(req.count, 1))
        args, statics = pack_request(req, n_pad)
        placed_args = {}
        for name, value in args.items():
            if name == "capacity":
                placed_args[name] = self._resident_capacity(req.capacity,
                                                            value)
                continue
            sharding = self._sharding_for(PACK_SHARD_KINDS[name])
            placed_args[name] = (value if sharding is None
                                 else jax.device_put(value, sharding))
        with self.mesh:
            _carry, outs = _select_scan(**placed_args, k_steps=k, **statics)
        return unpack_result(req, outs)

    def _resident_capacity(self, src, padded):
        """Device-put the padded capacity once per (source array, pad)
        and keep it sharded on the mesh across evals — the resident
        node-table property (SURVEY §7.2 step 8). `src` is the host
        NodeTable's capacity array whose identity keys the cache."""
        key = (id(src), padded.shape[0])
        hit = self._resident.get(key)
        if hit is not None and hit[0] is src:
            return hit[1]
        arr = jax.device_put(padded, self.node2_sharding)
        if len(self._resident) > 16:
            self._resident.clear()
        self._resident[key] = (src, arr)
        return arr

    def place_batched_chunked_args(self, cargs: dict,
                                   capacity_src=None) -> dict:
        """Shard the BATCHED K-way kernel's argument dict: per-lane
        arrays carry a leading batch axis (B, ...) that stays
        replicated while the node axis shards — the multi-eval batch
        (select_many) runs as one SPMD program over the mesh. Capacity
        is unstacked (all lanes share one table; that's the batching
        precondition) and rides the cross-eval resident cache."""
        batched = {
            "node": NamedSharding(self.mesh, P(None, "nodes")),
            "node2": NamedSharding(self.mesh, P(None, "nodes", None)),
            "code": NamedSharding(self.mesh, P(None, None, "nodes")),
            "rep": self.replicated,
            "scalar": self.replicated,      # scalars stack to (B,)
        }
        placed = {}
        for name, value in cargs.items():
            if name == "capacity":
                placed[name] = (self._resident_capacity(capacity_src,
                                                        value)
                                if capacity_src is not None
                                else jax.device_put(
                                    value, self.node2_sharding))
                continue
            sharding = batched[PACK_SHARD_KINDS[name]]
            placed[name] = jax.device_put(np.asarray(value), sharding)
        return placed

    def place_chunked_args(self, cargs: dict,
                           capacity_src=None) -> dict:
        """Shard the K-way kernel's argument dict over the mesh (same
        kind table as the scan). When capacity_src (the host table's
        array) is given, capacity rides the cross-eval resident cache."""
        placed = {}
        for name, value in cargs.items():
            if name == "capacity" and capacity_src is not None:
                placed[name] = self._resident_capacity(capacity_src,
                                                       value)
                continue
            sharding = self._sharding_for(PACK_SHARD_KINDS[name])
            placed[name] = (value if sharding is None
                            else jax.device_put(value, sharding))
        return placed

    def place(self, capacity, used, feasible, ask, count, *,
              tg_collisions=None, job_count=None, spread_alg=False):
        """Convenience wrapper: basic sharded multi-placement."""
        n = capacity.shape[0]
        req = SelectRequest(
            ask=np.asarray(ask, np.float32), count=count,
            feasible=feasible, capacity=capacity, used=used,
            desired_count=float(max(count, 1)),
            tg_collisions=(tg_collisions if tg_collisions is not None
                           else np.zeros(n, np.int32)),
            job_count=(job_count if job_count is not None
                       else np.zeros(n, np.int32)),
            algorithm="spread" if spread_alg else "binpack",
        )
        res = self.select(req)
        return res.node_idx, res.final_score
