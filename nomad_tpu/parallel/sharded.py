"""Multi-chip scheduling: the node axis sharded over a device mesh.

The reference scales the node dimension by sampling (log2(n) candidates,
stack.go:77-89); we scale it by sharding: the NodeTable's (N, dims)
arrays live sharded over the `nodes` mesh axis, the fused select kernel
runs SPMD under jit, and XLA inserts the cross-shard collectives for the
argmax/top-k reduction and the one-hot carry updates (all-gather of the
chosen index). This is the orchestrator's analog of data parallelism:
feasibility+scoring are embarrassingly parallel per node; only the
winner reduction crosses ICI (SURVEY.md §2.6/§2.7).

Multi-host: the same jit program runs under multi-process JAX, with the
node axis sharded across hosts' devices; DCN only carries the per-eval
ask vectors and result placements (small), never the node table.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.select import (C_MAX, P_MAX, S_MAX, _bucket_k, _select_scan)


def make_mesh(n_devices: Optional[int] = None) -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.array(devices), axis_names=("nodes",))


class ShardedSelect:
    """Dispatches the fused placement kernel with the node axis sharded
    over a mesh. The same _select_scan program is used — sharding is
    expressed purely through input shardings (SPMD via pjit)."""

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self.node_sharding = NamedSharding(mesh, P("nodes"))
        self.node2_sharding = NamedSharding(mesh, P("nodes", None))
        self.code_sharding = NamedSharding(mesh, P(None, "nodes"))
        self.replicated = NamedSharding(mesh, P())

    def pad_to_shards(self, n: int) -> int:
        """Pad N so it divides evenly over the mesh."""
        shards = self.mesh.devices.size
        per = -(-n // shards)
        # keep lanes aligned for the VPU
        per = max(8, per)
        return per * shards

    def place(self, capacity, used, feasible, ask, count, *,
              tg_collisions=None, job_count=None, spread_alg=False):
        """Sharded multi-placement. Arrays are host numpy; this puts them
        onto the mesh with the node axis sharded and runs the scan."""
        n = capacity.shape[0]
        n_pad = self.pad_to_shards(n)

        def pad1(a, fill, dtype):
            out = np.full(n_pad, fill, dtype=dtype)
            out[:n] = a
            return out

        def pad2(a):
            out = np.zeros((n_pad, a.shape[1]), dtype=np.float32)
            out[:n] = a
            return out

        dev = jax.device_put
        k = _bucket_k(max(count, 1))
        c_axis = C_MAX + 1
        args = dict(
            capacity=dev(pad2(capacity), self.node2_sharding),
            used0=dev(pad2(used), self.node2_sharding),
            feasible=dev(pad1(feasible, False, bool), self.node_sharding),
            ask=dev(np.asarray(ask, np.float32), self.replicated),
            k_valid=jnp.int32(count),
            tg_coll0=dev(pad1(tg_collisions if tg_collisions is not None
                              else np.zeros(n, np.int32), 0, np.int32),
                         self.node_sharding),
            job_count0=dev(pad1(job_count if job_count is not None
                                else np.zeros(n, np.int32), 0, np.int32),
                           self.node_sharding),
            distinct_hosts_flag=jnp.float32(0.0),
            scan_exclusive=jnp.float32(0.0),
            penalty=dev(np.zeros(n_pad, bool), self.node_sharding),
            affinity_norm=dev(np.zeros(n_pad, np.float32), self.node_sharding),
            desired_count=jnp.float32(max(count, 1)),
            port_need=jnp.float32(0.0),
            free_ports=dev(np.full(n_pad, 1e9, np.float32), self.node_sharding),
            port_ok=dev(np.ones(n_pad, bool), self.node_sharding),
            sp_codes=dev(np.full((S_MAX, n_pad), C_MAX, np.int32),
                         self.code_sharding),
            sp_counts0=dev(np.zeros((S_MAX, c_axis), np.float32), self.replicated),
            sp_present0=dev(np.zeros((S_MAX, c_axis), bool), self.replicated),
            sp_desired=dev(np.full((S_MAX, c_axis), -1.0, np.float32),
                           self.replicated),
            sp_weight=dev(np.zeros(S_MAX, np.float32), self.replicated),
            sp_has_targets=dev(np.zeros(S_MAX, bool), self.replicated),
            sp_valid=dev(np.zeros(S_MAX, bool), self.replicated),
            sum_spread_w=jnp.float32(0.0),
            dp_codes=dev(np.full((P_MAX, n_pad), C_MAX, np.int32),
                         self.code_sharding),
            dp_counts0=dev(np.zeros((P_MAX, c_axis), np.float32), self.replicated),
            dp_limit=dev(np.zeros(P_MAX, np.float32), self.replicated),
            dp_valid=dev(np.zeros(P_MAX, bool), self.replicated),
        )
        with self.mesh:
            carry, outs = _select_scan(
                *args.values(), k_steps=k, spread_alg=spread_alg,
                s_live=0, p_live=0)
        choices = np.asarray(outs[0])[:count]
        scores = np.asarray(outs[1])[:count]
        # clamp padding wins (shouldn't happen: padded lanes are infeasible)
        choices = np.where(choices >= n, -1, choices)
        return choices, scores
