"""Network resources and the port-accounting index.

Reference semantics: nomad/structs/network.go (NetworkIndex:35,
AssignPorts:316, AssignNetwork:406). Port bitmaps here are Python
arbitrary-precision ints (bit i set == port i used), which gives the
same set/check/popcount semantics as the reference's Bitmap with far
less code. Dynamic port selection probes randomly up to 20 attempts
then falls back to a linear scan, matching the reference's
stochastic-then-precise strategy.

TPU note: on-device feasibility only needs per-node *free dynamic port
counts* and reserved-port conflict bits (precomputed host-side into the
NodeTable); actual port number assignment runs host-side for the single
chosen node after the kernel's argmax (SURVEY.md §7.3 item 1).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

MIN_DYNAMIC_PORT = 20000
MAX_DYNAMIC_PORT = 32000
MAX_VALID_PORT = 65536
_MAX_RAND_ATTEMPTS = 20


@dataclass
class Port:
    label: str = ""
    value: int = 0          # host port (0 == dynamic, to be assigned)
    to: int = 0             # container-side mapped port (-1 == same as value)
    host_network: str = "default"


@dataclass
class DNSConfig:
    servers: List[str] = field(default_factory=list)
    searches: List[str] = field(default_factory=list)
    options: List[str] = field(default_factory=list)


@dataclass
class NetworkResource:
    """One network ask/grant (structs.go NetworkResource)."""
    mode: str = ""          # "", "host", "bridge", "none"
    device: str = ""
    cidr: str = ""
    ip: str = ""
    mbits: int = 0
    dns: Optional[DNSConfig] = None
    reserved_ports: List[Port] = field(default_factory=list)
    dynamic_ports: List[Port] = field(default_factory=list)

    def canonicalize(self) -> None:
        if not self.mode:
            self.mode = "host"

    def copy(self) -> "NetworkResource":
        return NetworkResource(
            mode=self.mode, device=self.device, cidr=self.cidr, ip=self.ip,
            mbits=self.mbits, dns=self.dns,
            reserved_ports=[Port(p.label, p.value, p.to, p.host_network)
                            for p in self.reserved_ports],
            dynamic_ports=[Port(p.label, p.value, p.to, p.host_network)
                           for p in self.dynamic_ports],
        )

    def port_labels(self) -> Dict[str, int]:
        return {p.label: p.value
                for p in list(self.reserved_ports) + list(self.dynamic_ports)}


@dataclass
class AllocatedPortMapping:
    label: str = ""
    value: int = 0
    to: int = 0
    host_ip: str = ""


def parse_port_ranges(spec: str) -> List[int]:
    """Parse "80,100-200,205" -> sorted port list (helper/parse_port_ranges)."""
    out = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "-" in part:
            lo, hi = part.split("-", 1)
            lo_i, hi_i = int(lo), int(hi)
            if lo_i > hi_i:
                raise ValueError(f"invalid port range {part}")
            out.extend(range(lo_i, hi_i + 1))
        else:
            out.append(int(part))
    for p in out:
        if p < 0 or p >= MAX_VALID_PORT:
            raise ValueError(f"port must be < {MAX_VALID_PORT} but found {p}")
    return sorted(set(out))


class NetworkIndex:
    """Indexes available networks + used ports on one node.

    Mirrors structs.NetworkIndex behavior: SetNode/AddAllocs return True
    on collision; AssignNetwork satisfies an ask with an offer.
    """

    def __init__(self, rng: Optional[random.Random] = None):
        self.avail_networks: List[NetworkResource] = []
        self.avail_bandwidth: Dict[str, int] = {}
        self.used_ports: Dict[str, int] = {}   # ip -> int bitset
        self.used_bandwidth: Dict[str, int] = {}
        self._rng = rng or random

    # -- building ------------------------------------------------------
    @staticmethod
    def ip_of(n: NetworkResource) -> str:
        """Canonical IP key for a network (falls back to the CIDR host)."""
        if n.ip:
            return n.ip
        if n.cidr:
            return n.cidr.split("/")[0]
        return ""

    def set_node(self, node) -> bool:
        collide = False
        networks = node.node_resources.networks if node.node_resources else []
        for n in networks:
            if n.device:
                self.avail_networks.append(n)
                self.avail_bandwidth[n.device] = n.mbits
        reserved = node.reserved_resources
        if reserved and reserved.reserved_host_ports:
            if self._add_reserved_port_range(reserved.reserved_host_ports):
                collide = True
        return collide

    def add_allocs(self, allocs) -> bool:
        collide = False
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            res = alloc.allocated_resources
            if res is None:
                continue
            for network in res.shared.networks:
                if self.add_reserved(network):
                    collide = True
            for task in res.tasks.values():
                if task.networks:
                    if self.add_reserved(task.networks[0]):
                        collide = True
        return collide

    def add_reserved(self, n: NetworkResource) -> bool:
        collide = False
        ip = self.ip_of(n)
        for ports in (n.reserved_ports, n.dynamic_ports):
            for port in ports:
                if port.value < 0 or port.value >= MAX_VALID_PORT:
                    return True
                bit = 1 << port.value
                if self.used_ports.get(ip, 0) & bit:
                    collide = True
                else:
                    # write through immediately so valid marks survive an
                    # early return on a later invalid port (the reference
                    # mutates the shared bitmap in place)
                    self.used_ports[ip] = self.used_ports.get(ip, 0) | bit
        self.used_bandwidth[n.device] = self.used_bandwidth.get(n.device, 0) + n.mbits
        return collide

    def _add_reserved_port_range(self, ports: str) -> bool:
        try:
            res_ports = parse_port_ranges(ports)
        except ValueError:
            return False
        collide = False
        for n in self.avail_networks:
            self.used_ports.setdefault(self.ip_of(n), 0)
        for ip in list(self.used_ports):
            used = self.used_ports[ip]
            for port in res_ports:
                bit = 1 << port
                if used & bit:
                    collide = True
                else:
                    used |= bit
            self.used_ports[ip] = used
        return collide

    def overcommitted(self) -> bool:
        return False  # bandwidth deprecated in reference too

    # -- assignment ----------------------------------------------------
    def assign_network(self, ask: NetworkResource) -> Tuple[Optional[NetworkResource], str]:
        """Satisfy an ask; returns (offer, "") or (None, reason)."""
        err = "no networks available"
        for n in self.avail_networks:
            ip = self.ip_of(n)
            if not ip:
                continue
            avail_bw = self.avail_bandwidth.get(n.device, 0)
            used_bw = self.used_bandwidth.get(n.device, 0)
            if used_bw + ask.mbits > avail_bw:
                err = "bandwidth exceeded"
                continue
            used = self.used_ports.get(ip, 0)
            collision = False
            for port in ask.reserved_ports:
                if port.value < 0 or port.value >= MAX_VALID_PORT:
                    return None, f"invalid port {port.value} (out of range)"
                if used & (1 << port.value):
                    err = f"reserved port collision {port.label}={port.value}"
                    collision = True
                    break
            if collision:
                continue
            dyn_ports, dyn_err = self._pick_dynamic_ports(
                used, ask.reserved_ports, len(ask.dynamic_ports))
            if dyn_err:
                err = dyn_err
                continue
            offer = NetworkResource(
                mode=ask.mode, device=n.device, ip=ip, mbits=ask.mbits,
                dns=ask.dns,
                reserved_ports=[Port(p.label, p.value, p.to, p.host_network)
                                for p in ask.reserved_ports],
                dynamic_ports=[Port(p.label, p.value, p.to, p.host_network)
                               for p in ask.dynamic_ports],
            )
            for i, port in enumerate(dyn_ports):
                offer.dynamic_ports[i].value = port
                if offer.dynamic_ports[i].to == -1:
                    offer.dynamic_ports[i].to = port
            return offer, ""
        return None, err

    def _pick_dynamic_ports(self, used: int, reserved: List[Port],
                            count: int) -> Tuple[List[int], str]:
        if count == 0:
            return [], ""
        res_bits = 0
        for p in reserved:
            res_bits |= 1 << p.value
        blocked = used | res_bits
        # stochastic probe (reference getDynamicPortsStochastic)
        picked: List[int] = []
        picked_bits = 0
        ok = True
        for _ in range(count):
            found = False
            for _ in range(_MAX_RAND_ATTEMPTS):
                port = self._rng.randint(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT)
                bit = 1 << port
                if not ((blocked | picked_bits) & bit):
                    picked.append(port)
                    picked_bits |= bit
                    found = True
                    break
            if not found:
                ok = False
                break
        if ok:
            return picked, ""
        # precise linear scan (reference getDynamicPortsPrecise)
        picked = []
        for port in range(MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT + 1):
            if not (blocked & (1 << port)):
                picked.append(port)
                if len(picked) == count:
                    return picked, ""
        return [], "dynamic port selection failed"

    # -- tensorization support ----------------------------------------
    def free_dynamic_port_count(self, ip: str = "") -> int:
        """Free ports in the dynamic range for the NodeTable column."""
        if not ip:
            if not self.avail_networks:
                return MAX_DYNAMIC_PORT - MIN_DYNAMIC_PORT + 1
            ip = self.avail_networks[0].ip
        used = self.used_ports.get(ip, 0)
        span = MAX_DYNAMIC_PORT - MIN_DYNAMIC_PORT + 1
        mask = ((1 << span) - 1) << MIN_DYNAMIC_PORT
        return span - (used & mask).bit_count()
