"""Plan — the output of a scheduler run, applied by the plan applier.

Reference semantics: nomad/structs/structs.go Plan:10221, PlanResult:10404.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .alloc import (Allocation, ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT,
                    ALLOC_CLIENT_LOST, ALLOC_CLIENT_FAILED)
from .job import Job


@dataclass
class DesiredUpdates:
    """Per-task-group counts of what the plan intends (structs.go DesiredUpdates)."""
    ignore: int = 0
    place: int = 0
    migrate: int = 0
    stop: int = 0
    in_place_update: int = 0
    destructive_update: int = 0
    canary: int = 0
    preemptions: int = 0


@dataclass
class PlanAnnotations:
    desired_tg_updates: Dict[str, DesiredUpdates] = field(default_factory=dict)
    preempted_allocs: List[dict] = field(default_factory=list)   # alloc stubs


@dataclass
class Plan:
    eval_id: str = ""
    eval_token: str = ""
    priority: int = 50
    all_at_once: bool = False
    job: Optional[Job] = None
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    annotations: Optional[PlanAnnotations] = None
    deployment: Optional[object] = None        # Deployment
    deployment_updates: List[object] = field(default_factory=list)
    snapshot_index: int = 0

    # -- construction (structs.go Plan.AppendStoppedAlloc etc.) --------
    def append_stopped_alloc(self, alloc: Allocation, desired_desc: str,
                             client_status: str = "",
                             followup_eval_id: str = "") -> None:
        new_alloc = alloc.copy_skip_job()
        # Deregistration plans carry no job: lift it off the alloc so the
        # applier knows which job is being stopped (structs.go:10288-10291).
        if self.job is None and alloc.job is not None:
            self.job = alloc.job
        new_alloc.job = None
        new_alloc.desired_status = ALLOC_DESIRED_STOP
        new_alloc.desired_description = desired_desc
        if client_status:
            new_alloc.client_status = client_status
        if followup_eval_id:
            new_alloc.follow_up_eval_id = followup_eval_id
        self.node_update.setdefault(alloc.node_id, []).append(new_alloc)

    def append_preempted_alloc(self, alloc: Allocation,
                               preempting_alloc_id: str) -> None:
        new_alloc = Allocation(
            id=alloc.id, namespace=alloc.namespace, node_id=alloc.node_id,
            desired_status=ALLOC_DESIRED_EVICT,
            preempted_by_allocation=preempting_alloc_id,
            desired_description=(
                f"Preempted by alloc ID {preempting_alloc_id}"),
        )
        self.node_preemptions.setdefault(alloc.node_id, []).append(new_alloc)

    def append_alloc(self, alloc: Allocation) -> None:
        # strip the job snapshot: the plan carries the job once
        alloc.job = None
        self.node_allocation.setdefault(alloc.node_id, []).append(alloc)

    def pop_update(self, alloc: Allocation) -> None:
        """Remove the last stopped-alloc entry if it is this alloc
        (used when an updated alloc is placed back on the same node)."""
        existing = self.node_update.get(alloc.node_id, [])
        if existing and existing[-1].id == alloc.id:
            existing.pop()
            if not existing:
                del self.node_update[alloc.node_id]

    def remove_update(self, alloc: Allocation) -> None:
        """Remove a staged stop for this alloc wherever it sits in the
        node's update list (batched placement failure back-out)."""
        existing = self.node_update.get(alloc.node_id)
        if not existing:
            return
        remaining = [a for a in existing if a.id != alloc.id]
        if remaining:
            self.node_update[alloc.node_id] = remaining
        else:
            del self.node_update[alloc.node_id]

    def is_no_op(self) -> bool:
        return (not self.node_update and not self.node_allocation
                and self.deployment is None and not self.deployment_updates)

    def normalize_allocations(self) -> None:
        """Strip stopped/preempted allocs to id-only stubs for the wire
        (structs.go Plan.NormalizeAllocations)."""
        for node_id, allocs in self.node_update.items():
            self.node_update[node_id] = [
                Allocation(id=a.id,
                           desired_description=a.desired_description,
                           client_status=a.client_status,
                           desired_status=a.desired_status,
                           follow_up_eval_id=a.follow_up_eval_id)
                for a in allocs
            ]
        for node_id, allocs in self.node_preemptions.items():
            self.node_preemptions[node_id] = [
                Allocation(id=a.id,
                           preempted_by_allocation=a.preempted_by_allocation)
                for a in allocs
            ]


@dataclass
class PlanResult:
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    deployment: Optional[object] = None
    deployment_updates: List[object] = field(default_factory=list)
    refresh_index: int = 0
    alloc_index: int = 0

    def full_commit(self, plan: Plan):
        """(bool fully_committed, expected, actual) — structs.go PlanResult.FullCommit."""
        expected = sum(len(v) for v in plan.node_allocation.values())
        actual = sum(len(v) for v in self.node_allocation.values())
        return expected == actual, expected, actual

    def is_no_op(self) -> bool:
        return (not self.node_update and not self.node_allocation
                and self.deployment is None and not self.deployment_updates)
