"""Runtime-mutable scheduler configuration.

Reference semantics: nomad/structs/operator.go:128-166
(SchedulerConfiguration, PreemptionConfig) — stored in Raft, read
per-eval by the placement stack; this is also the switch that selects
the TPU-batch pipeline.
"""

from __future__ import annotations

from dataclasses import dataclass, field

SCHED_ALG_BINPACK = "binpack"
SCHED_ALG_SPREAD = "spread"


@dataclass
class PreemptionConfig:
    system_scheduler_enabled: bool = True
    batch_scheduler_enabled: bool = False
    service_scheduler_enabled: bool = False


@dataclass
class SchedulerConfiguration:
    scheduler_algorithm: str = SCHED_ALG_BINPACK
    preemption_config: PreemptionConfig = field(default_factory=PreemptionConfig)
    # TPU rebuild extension: run placement through the batched device
    # kernel (ops/select.py) instead of the scalar host pipeline.
    tpu_batch_enabled: bool = True
    create_index: int = 0
    modify_index: int = 0

    def effective_algorithm(self) -> str:
        return self.scheduler_algorithm or SCHED_ALG_BINPACK
