"""Constraint / Affinity / Spread stanzas (structs.go Constraint:8023,
Affinity:8145, Spread:8233). In their own module so both job.py and
resources.py (device asks) can reference them in type annotations."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List

CONSTRAINT_DISTINCT_PROPERTY = "distinct_property"
CONSTRAINT_DISTINCT_HOSTS = "distinct_hosts"
CONSTRAINT_REGEX = "regexp"
CONSTRAINT_VERSION = "version"
CONSTRAINT_SEMVER = "semver"
CONSTRAINT_SET_CONTAINS = "set_contains"
CONSTRAINT_SET_CONTAINS_ALL = "set_contains_all"
CONSTRAINT_SET_CONTAINS_ANY = "set_contains_any"
CONSTRAINT_IS_SET = "is_set"
CONSTRAINT_IS_NOT_SET = "is_not_set"

COMPARISON_OPERANDS = ("=", "==", "is", "!=", "not", "<", "<=", ">", ">=")


@dataclass
class Constraint:
    ltarget: str = ""    # left-hand target, e.g. "${attr.kernel.name}"
    rtarget: str = ""
    operand: str = "="

    def validate(self) -> List[str]:
        errs = []
        if not self.operand:
            errs.append("missing constraint operand")
        # distinct_property's RTarget is an optional count (structs.go:8089)
        req_rtarget = self.operand not in (
            CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY,
            CONSTRAINT_IS_SET, CONSTRAINT_IS_NOT_SET)
        if req_rtarget and self.rtarget == "":
            errs.append(f"operand {self.operand} requires an RTarget")
        if self.operand == CONSTRAINT_DISTINCT_PROPERTY and self.rtarget != "":
            try:
                if int(self.rtarget) < 1:
                    errs.append("distinct_property count must be >= 1")
            except ValueError:
                errs.append(
                    f"distinct_property count {self.rtarget} is not an integer")
        req_ltarget = self.operand != CONSTRAINT_DISTINCT_HOSTS
        if req_ltarget and self.ltarget == "":
            errs.append("no LTarget provided but is required by constraint")
        return errs

    def key(self):
        return (self.ltarget, self.rtarget, self.operand)

    def __str__(self):
        return f"{self.ltarget} {self.operand} {self.rtarget}"


@dataclass
class Affinity:
    ltarget: str = ""
    rtarget: str = ""
    operand: str = "="
    weight: int = 50     # [-100, 100], negative == anti-affinity

    def validate(self) -> List[str]:
        errs = []
        if not self.operand:
            errs.append("missing affinity operand")
        req_rtarget = self.operand not in (CONSTRAINT_IS_SET,
                                           CONSTRAINT_IS_NOT_SET)
        if req_rtarget and self.rtarget == "":
            errs.append(f"operand {self.operand} requires an RTarget")
        if self.ltarget == "":
            errs.append("no LTarget provided but is required by affinity")
        if self.weight > 100 or self.weight < -100:
            errs.append("affinity weight must be within the range [-100,100]")
        if self.weight == 0:
            errs.append("affinity weight cannot be zero")
        return errs

    def key(self):
        return (self.ltarget, self.rtarget, self.operand, self.weight)


@dataclass
class SpreadTarget:
    value: str = ""
    percent: int = 0


@dataclass
class Spread:
    attribute: str = ""
    weight: int = 50     # (0, 100]
    spread_target: List[SpreadTarget] = field(default_factory=list)

    def validate(self) -> List[str]:
        errs = []
        if not self.attribute:
            errs.append("missing spread attribute")
        if self.weight <= 0 or self.weight > 100:
            errs.append("spread stanza must have a positive weight from 0 to 100")
        seen = set()
        total = 0
        for t in self.spread_target:
            if t.value in seen:
                errs.append(f"spread target value {t.value} already defined")
            seen.add(t.value)
            if t.percent < 0 or t.percent > 100:
                errs.append(
                    f"spread target percentage for value {t.value} "
                    f"must be between 0 and 100")
            total += t.percent
        if total > 100:
            errs.append(
                f"sum of spread target percentages must not be greater "
                f"than 100, got {total}")
        return errs
