"""Consul Connect service-mesh model + the built-in service registry.

Reference: nomad/structs/services.go — ConsulConnect:672,
ConsulSidecarService:781, SidecarTask:830, ConsulProxy:1024,
ConsulUpstream:1121, ConsulExposeConfig:1169, ConsulGateway:1221 —
plus CheckRestart (structs.go:6378). The reference registers services
into an external Consul agent; here registrations land in the
framework's own replicated state store (a built-in catalog), so
service discovery works with no external dependency while the job-spec
surface stays the same.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

CONNECT_PROXY_PREFIX = "connect-proxy"
CONNECT_NATIVE_PREFIX = "connect-native"
CONNECT_INGRESS_PREFIX = "connect-ingress"


@dataclass
class CheckRestart:
    """Restart a task when its check stays unhealthy (structs.go
    CheckRestart:6378): `limit` consecutive unhealthy intervals after a
    `grace` warm-up restarts the task."""
    limit: int = 0
    grace_s: float = 1.0
    ignore_warnings: bool = False


@dataclass
class ConsulUpstream:
    """services.go ConsulUpstream:1121."""
    destination_name: str = ""
    local_bind_port: int = 0

    def validate(self) -> List[str]:
        errs = []
        if not self.destination_name:
            errs.append("upstream destination name is required")
        if self.local_bind_port <= 0:
            errs.append(f"upstream local bind port {self.local_bind_port} "
                        "must be > 0")
        return errs


@dataclass
class ConsulExposePath:
    """services.go ConsulExposePath:1174."""
    path: str = ""
    protocol: str = ""
    local_path_port: int = 0
    listener_port: str = ""


@dataclass
class ConsulExposeConfig:
    """services.go ConsulExposeConfig:1169."""
    paths: List[ConsulExposePath] = field(default_factory=list)


@dataclass
class ConsulProxy:
    """services.go ConsulProxy:1024."""
    local_service_address: str = ""
    local_service_port: int = 0
    upstreams: List[ConsulUpstream] = field(default_factory=list)
    expose: Optional[ConsulExposeConfig] = None
    config: Dict[str, object] = field(default_factory=dict)

    def validate(self) -> List[str]:
        errs = []
        seen = set()
        for u in self.upstreams:
            errs.extend(u.validate())
            key = (u.destination_name, u.local_bind_port)
            if key in seen:
                errs.append(f"duplicate upstream {u.destination_name}")
            seen.add(key)
        return errs


@dataclass
class ConsulSidecarService:
    """services.go ConsulSidecarService:781."""
    tags: List[str] = field(default_factory=list)
    port: str = ""
    proxy: Optional[ConsulProxy] = None

    def has_upstreams(self) -> bool:
        return self.proxy is not None and bool(self.proxy.upstreams)


@dataclass
class SidecarTask:
    """Operator overrides merged onto the injected proxy task
    (services.go SidecarTask:830 MergeIntoTask)."""
    name: str = ""
    driver: str = ""
    user: str = ""
    config: Dict[str, object] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    resources: Optional[object] = None          # models.Resources
    meta: Dict[str, str] = field(default_factory=dict)
    kill_timeout_s: Optional[float] = None
    shutdown_delay_s: Optional[float] = None
    kill_signal: str = ""

    def merge_into(self, task) -> None:
        """services.go MergeIntoTask:905 — non-zero fields override."""
        if self.name:
            task.name = self.name
        if self.driver:
            task.driver = self.driver
        if self.user:
            task.user = self.user
        if self.config:
            task.config = dict(self.config)
        if self.env:
            task.env.update(self.env)
        if self.resources is not None:
            task.resources = self.resources
        if self.meta:
            task.meta = dict(self.meta)
        if self.kill_timeout_s is not None:
            task.kill_timeout_s = self.kill_timeout_s
        if self.shutdown_delay_s is not None:
            task.shutdown_delay_s = self.shutdown_delay_s
        if self.kill_signal:
            task.kill_signal = self.kill_signal


@dataclass
class ConsulIngressService:
    """services.go ConsulIngressService:~"""
    name: str = ""
    hosts: List[str] = field(default_factory=list)


@dataclass
class ConsulIngressListener:
    """services.go ConsulIngressListener."""
    port: int = 0
    protocol: str = "tcp"
    services: List[ConsulIngressService] = field(default_factory=list)

    def validate(self) -> List[str]:
        errs = []
        if self.port <= 0:
            errs.append("ingress listener requires a port")
        if self.protocol not in ("tcp", "http"):
            errs.append(f"invalid listener protocol {self.protocol!r}")
        if not self.services:
            errs.append("ingress listener requires one or more services")
        return errs


@dataclass
class ConsulGateway:
    """services.go ConsulGateway:1221 (ingress subset)."""
    ingress_listeners: List[ConsulIngressListener] = field(
        default_factory=list)

    def validate(self) -> List[str]:
        errs = []
        if not self.ingress_listeners:
            errs.append("gateway requires an ingress block")
        for lst in self.ingress_listeners:
            errs.extend(lst.validate())
        return errs


@dataclass
class ConsulConnect:
    """services.go ConsulConnect:672 — exactly one of native, sidecar,
    gateway."""
    native: bool = False
    sidecar_service: Optional[ConsulSidecarService] = None
    sidecar_task: Optional[SidecarTask] = None
    gateway: Optional[ConsulGateway] = None

    def has_sidecar(self) -> bool:
        return self.sidecar_service is not None

    def is_native(self) -> bool:
        return self.native

    def is_gateway(self) -> bool:
        return self.gateway is not None

    def validate(self) -> List[str]:
        count = sum((self.has_sidecar(), self.is_native(),
                     self.is_gateway()))
        if count != 1:
            return ["Consul Connect must be exclusively native, make use "
                    "of a sidecar, or represent a Gateway"]
        errs = []
        if self.is_gateway():
            errs.extend(self.gateway.validate())
        if self.has_sidecar() and self.sidecar_service.proxy is not None:
            errs.extend(self.sidecar_service.proxy.validate())
        return errs


# -- the built-in catalog ---------------------------------------------
SERVICE_STATUS_PASSING = "passing"
SERVICE_STATUS_CRITICAL = "critical"
SERVICE_STATUS_PENDING = "pending"


@dataclass
class ServiceRegistration:
    """One live instance of a service in the built-in catalog. The
    reference delegates this row to Consul's agent
    (command/agent/consul/service_client.go serviceRegistration); here
    it is first-class replicated state keyed
    `<alloc_id>-<group|task>-<service>`."""
    id: str = ""
    service_name: str = ""
    namespace: str = "default"
    node_id: str = ""
    job_id: str = ""
    alloc_id: str = ""
    task_name: str = ""                 # "" for group services
    tags: List[str] = field(default_factory=list)
    address: str = ""
    port: int = 0
    status: str = SERVICE_STATUS_PENDING   # aggregate check status
    checks: Dict[str, str] = field(default_factory=dict)  # name->status
    create_index: int = 0
    modify_index: int = 0


def registration_id(alloc_id: str, owner: str, service_name: str) -> str:
    """Stable catalog row key: owner is the group or task name."""
    return f"_nomad-{alloc_id}-{owner}-{service_name}"
