"""Node (client machine) model.

Reference semantics: nomad/structs/structs.go Node:1761 and
nomad/structs/node_class.go (ComputedClass — the feasibility
memoization key).
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .resources import NodeResources, NodeReservedResources

NODE_STATUS_INIT = "initializing"
NODE_STATUS_READY = "ready"
NODE_STATUS_DOWN = "down"

NODE_SCHED_ELIGIBLE = "eligible"
NODE_SCHED_INELIGIBLE = "ineligible"


@dataclass
class DrainSpec:
    deadline_s: float = 0.0
    ignore_system_jobs: bool = False


@dataclass
class DrainStrategy:
    drain_spec: DrainSpec = field(default_factory=DrainSpec)
    force_deadline: float = 0.0   # unix seconds; 0 == no deadline


@dataclass
class DriverInfo:
    """Fingerprinted driver state on a node (structs.go DriverInfo)."""
    attributes: Dict[str, str] = field(default_factory=dict)
    detected: bool = False
    healthy: bool = False
    health_description: str = ""
    update_time: int = 0


@dataclass
class NodeEvent:
    message: str = ""
    subsystem: str = ""
    details: Dict[str, str] = field(default_factory=dict)
    timestamp: int = 0


# Attributes that are node-unique and therefore excluded from the
# computed class hash (node_class.go EscapedConstraints analog).
_UNIQUE_ATTR_PREFIX = "unique."


@dataclass
class Node:
    id: str = ""
    secret_id: str = ""
    datacenter: str = "dc1"
    name: str = ""
    http_addr: str = ""
    attributes: Dict[str, str] = field(default_factory=dict)
    node_resources: NodeResources = field(default_factory=NodeResources)
    reserved_resources: NodeReservedResources = field(default_factory=NodeReservedResources)
    links: Dict[str, str] = field(default_factory=dict)
    meta: Dict[str, str] = field(default_factory=dict)
    node_class: str = ""
    computed_class: str = ""
    drain: bool = False
    drain_strategy: Optional[DrainStrategy] = None
    scheduling_eligibility: str = NODE_SCHED_ELIGIBLE
    status: str = NODE_STATUS_INIT
    status_description: str = ""
    status_updated_at: int = 0
    events: List[NodeEvent] = field(default_factory=list)
    drivers: Dict[str, DriverInfo] = field(default_factory=dict)
    host_volumes: Dict[str, dict] = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0

    def ready(self) -> bool:
        """structs.go Node.Ready: status ready, NOT draining, eligible —
        a draining node whose eligibility was set before the drain began
        must still refuse new placements."""
        return (self.status == NODE_STATUS_READY
                and not self.drain
                and self.scheduling_eligibility == NODE_SCHED_ELIGIBLE)

    def canonicalize(self) -> None:
        if self.scheduling_eligibility == "":
            self.scheduling_eligibility = (
                NODE_SCHED_INELIGIBLE if self.drain else NODE_SCHED_ELIGIBLE)

    def compute_class(self) -> None:
        """Hash of non-unique attributes -> memoization key for feasibility
        (node_class.go ComputeClass). Unique attrs (node id, name, ips,
        "unique."-prefixed attributes/meta) are excluded so identical
        machines share a class."""
        h = hashlib.sha256()
        payload = {
            "datacenter": self.datacenter,
            "node_class": self.node_class,
            "attributes": {k: v for k, v in sorted(self.attributes.items())
                           if not k.startswith(_UNIQUE_ATTR_PREFIX)},
            "meta": {k: v for k, v in sorted(self.meta.items())
                     if not k.startswith(_UNIQUE_ATTR_PREFIX)},
            "drivers": sorted(d for d, info in self.drivers.items() if info.detected),
        }
        h.update(json.dumps(payload, sort_keys=True).encode())
        self.computed_class = "v1:" + h.hexdigest()[:16]

    def comparable_resources(self):
        return self.node_resources.comparable()

    def comparable_reserved_resources(self):
        return self.reserved_resources.comparable()

    def terminal_status(self) -> bool:
        return self.status == NODE_STATUS_DOWN

    def copy(self) -> "Node":
        from ..utils.codec import to_wire, from_wire
        return from_wire(Node, to_wire(self))

    def stub(self) -> dict:
        return {
            "id": self.id, "datacenter": self.datacenter, "name": self.name,
            "node_class": self.node_class, "drain": self.drain,
            "scheduling_eligibility": self.scheduling_eligibility,
            "status": self.status, "modify_index": self.modify_index,
        }
