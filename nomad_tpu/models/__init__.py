"""The domain model: the single source of truth for all orchestrator
types (reference: nomad/structs/structs.go, 18.8k LoC).

Everything here is host-side Python; the tensorized projections of
nodes/allocs used by the scheduler kernels live in nomad_tpu/ops/tables.py.
"""

from .resources import (
    Resources,
    NodeResources,
    NodeReservedResources,
    AllocatedResources,
    AllocatedTaskResources,
    AllocatedSharedResources,
    ComparableResources,
    NodeDeviceResource,
    NodeDevice,
    AllocatedDeviceResource,
    RequestedDevice,
)
from .networks import NetworkResource, Port, NetworkIndex
from .job import (
    Job,
    ScalingPolicy,
    TaskGroup,
    Task,
    Constraint,
    Affinity,
    Spread,
    SpreadTarget,
    RestartPolicy,
    ReschedulePolicy,
    EphemeralDisk,
    UpdateStrategy,
    MigrateStrategy,
    PeriodicConfig,
    ParameterizedJobConfig,
    DispatchPayloadConfig,
    TaskLifecycleConfig,
    LogConfig,
    Service,
    ServiceCheck,
    Template,
    TaskArtifact,
    VaultConfig,
    VolumeRequest,
    VolumeMount,
    JOB_TYPE_SERVICE,
    JOB_TYPE_BATCH,
    JOB_TYPE_SYSTEM,
    JOB_TYPE_CORE,
    JOB_STATUS_PENDING,
    JOB_STATUS_RUNNING,
    JOB_STATUS_DEAD,
)
from .node import (
    Node,
    DriverInfo,
    NODE_STATUS_INIT,
    NODE_STATUS_READY,
    NODE_STATUS_DOWN,
    NODE_SCHED_ELIGIBLE,
    NODE_SCHED_INELIGIBLE,
    DrainStrategy,
    DrainSpec,
)
from .alloc import (
    Allocation,
    AllocMetric,
    NodeScoreMeta,
    TaskState,
    TaskEvent,
    RescheduleTracker,
    RescheduleEvent,
    AllocDeploymentStatus,
    DesiredTransition,
    ALLOC_DESIRED_RUN,
    ALLOC_DESIRED_STOP,
    ALLOC_DESIRED_EVICT,
    ALLOC_CLIENT_PENDING,
    ALLOC_CLIENT_RUNNING,
    ALLOC_CLIENT_COMPLETE,
    ALLOC_CLIENT_FAILED,
    ALLOC_CLIENT_LOST,
)
from .evaluation import (
    Evaluation,
    EVAL_STATUS_PENDING,
    EVAL_STATUS_BLOCKED,
    EVAL_STATUS_COMPLETE,
    EVAL_STATUS_FAILED,
    EVAL_STATUS_CANCELED,
    TRIGGER_JOB_REGISTER,
    TRIGGER_JOB_DEREGISTER,
    TRIGGER_PERIODIC_JOB,
    TRIGGER_NODE_DRAIN,
    TRIGGER_NODE_UPDATE,
    TRIGGER_ALLOC_STOP,
    TRIGGER_SCHEDULED,
    TRIGGER_ROLLING_UPDATE,
    TRIGGER_DEPLOYMENT_WATCHER,
    TRIGGER_FAILED_FOLLOW_UP,
    TRIGGER_MAX_PLANS,
    TRIGGER_ALLOC_FAILURE,
    TRIGGER_RETRY_FAILED_ALLOC,
    TRIGGER_QUEUED_ALLOCS,
    TRIGGER_PREEMPTION,
    TRIGGER_JOB_SCALE,
)
from .plan import Plan, PlanResult, PlanAnnotations, DesiredUpdates
from .deployment import (
    Deployment,
    DeploymentState,
    DeploymentStatusUpdate,
    DEPLOYMENT_STATUS_RUNNING,
    DEPLOYMENT_STATUS_PAUSED,
    DEPLOYMENT_STATUS_FAILED,
    DEPLOYMENT_STATUS_SUCCESSFUL,
    DEPLOYMENT_STATUS_CANCELLED,
)
from .funcs import (
    AllocsFit,
    ScoreFitBinPack,
    ScoreFitSpread,
    FilterTerminalAllocs,
)
from .scheduler_config import SchedulerConfiguration, PreemptionConfig
from .csi import CSIVolume
