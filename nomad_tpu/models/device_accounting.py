"""Device instance accounting (reference: nomad/structs/devices.go
DeviceAccounter) — tracks per-device-instance usage on a node for the
oversubscription check in AllocsFit and the device allocator."""

from __future__ import annotations

from typing import Dict, List, Tuple


class DeviceAccounter:
    def __init__(self, node):
        # (vendor, type, name) -> {instance_id: count}
        self.devices: Dict[Tuple[str, str, str], Dict[str, int]] = {}
        for group in node.node_resources.devices:
            insts = {}
            for inst in group.instances:
                if inst.healthy:
                    insts[inst.id] = 0
            self.devices[group.id_tuple()] = insts

    def add_allocs(self, allocs: List) -> bool:
        """Account the allocs' device usage; True on oversubscription or
        use of an unknown/collided instance."""
        collision = False
        for alloc in allocs:
            if alloc.terminal_status():
                continue
            res = alloc.allocated_resources
            if res is None:
                continue
            for task in res.tasks.values():
                for dev in task.devices:
                    insts = self.devices.get(dev.id_tuple())
                    if insts is None:
                        continue
                    for inst_id in dev.device_ids:
                        if inst_id not in insts:
                            continue
                        insts[inst_id] += 1
                        if insts[inst_id] > 1:
                            collision = True
        return collision

    def add_reserved(self, dev) -> bool:
        """Mark an AllocatedDeviceResource as used; True on collision."""
        collision = False
        insts = self.devices.get(dev.id_tuple())
        if insts is None:
            return False
        for inst_id in dev.device_ids:
            if inst_id not in insts:
                continue
            insts[inst_id] += 1
            if insts[inst_id] > 1:
                collision = True
        return collision

    def free_instances(self, id_tuple) -> List[str]:
        insts = self.devices.get(id_tuple, {})
        return [i for i, c in insts.items() if c == 0]
