"""Resource types and algebra.

Reference semantics: nomad/structs/structs.go — Resources:2129,
NodeResources:2727, AllocatedResources:3302, ComparableResources:3709 —
and the Add/Subtract/Superset algebra consumed by AllocsFit
(nomad/structs/funcs.go:102).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .networks import NetworkResource
from .constraints import Affinity, Constraint

# Default resources for a task when unspecified (structs.go DefaultResources)
DEFAULT_CPU_SHARES = 100
DEFAULT_MEMORY_MB = 300

# Minimums (structs.go MinResources)
MIN_CPU_SHARES = 1
MIN_MEMORY_MB = 10


@dataclass
class RequestedDevice:
    """A task's device ask (structs.go RequestedDevice:2xxx).
    name is "<vendor>/<type>/<model>", "<type>/<model>", or "<type>"."""
    name: str = ""
    count: int = 1
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)

    def id_tuple(self):
        parts = self.name.split("/")
        # (vendor, type, model) with empty wildcards; the 2-part form
        # is <vendor>/<type> (structs.go RequestedDevice.Name docs,
        # exercised by feasible_test.go TestDeviceChecker
        # "gpu devices by vendor/type")
        if len(parts) >= 3:
            return (parts[0], parts[1], "/".join(parts[2:]))
        if len(parts) == 2:
            return (parts[0], parts[1], "")
        return ("", self.name, "")


@dataclass
class Resources:
    """Per-task resource ask (structs.go Resources:2129)."""
    cpu: int = DEFAULT_CPU_SHARES          # MHz shares
    memory_mb: int = DEFAULT_MEMORY_MB
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[RequestedDevice] = field(default_factory=list)

    def canonicalize(self) -> None:
        for n in self.networks:
            n.canonicalize()

    def validate(self) -> List[str]:
        errs = []
        if self.cpu < MIN_CPU_SHARES:
            errs.append(f"minimum CPU value is {MIN_CPU_SHARES}; got {self.cpu}")
        if self.memory_mb < MIN_MEMORY_MB:
            errs.append(f"minimum MemoryMB value is {MIN_MEMORY_MB}; got {self.memory_mb}")
        return errs

    def merge(self, other: "Resources") -> None:
        if other.cpu:
            self.cpu = other.cpu
        if other.memory_mb:
            self.memory_mb = other.memory_mb
        if other.disk_mb:
            self.disk_mb = other.disk_mb
        if other.networks:
            self.networks = list(other.networks)
        if other.devices:
            self.devices = list(other.devices)

    def copy(self) -> "Resources":
        return Resources(
            cpu=self.cpu,
            memory_mb=self.memory_mb,
            disk_mb=self.disk_mb,
            networks=[n.copy() for n in self.networks],
            devices=[RequestedDevice(d.name, d.count, list(d.constraints),
                                     list(d.affinities))
                     for d in self.devices],
        )


@dataclass
class NodeCpuResources:
    cpu_shares: int = 0


@dataclass
class NodeMemoryResources:
    memory_mb: int = 0


@dataclass
class NodeDiskResources:
    disk_mb: int = 0


@dataclass
class NodeDevice:
    """One device instance on a node (structs.go NodeDevice)."""
    id: str = ""
    healthy: bool = True
    health_description: str = ""
    locality: Optional[dict] = None


@dataclass
class NodeDeviceResource:
    """A homogeneous device group on a node (structs.go NodeDeviceResource)."""
    vendor: str = ""
    type: str = ""
    name: str = ""
    instances: List[NodeDevice] = field(default_factory=list)
    attributes: Dict[str, object] = field(default_factory=dict)

    def id_tuple(self):
        return (self.vendor, self.type, self.name)

    def matches_request(self, req: RequestedDevice) -> bool:
        """Does this group satisfy the request name? (device.go nodeDeviceMatches)"""
        rv, rt, rm = req.id_tuple()
        if rt and rt != self.type:
            return False
        if rv and rv != self.vendor:
            return False
        if rm and rm != self.name:
            return False
        return True


@dataclass
class NodeResources:
    """Total resources on a node (structs.go NodeResources:2727)."""
    cpu: NodeCpuResources = field(default_factory=NodeCpuResources)
    memory: NodeMemoryResources = field(default_factory=NodeMemoryResources)
    disk: NodeDiskResources = field(default_factory=NodeDiskResources)
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[NodeDeviceResource] = field(default_factory=list)

    def comparable(self) -> "ComparableResources":
        return ComparableResources(
            cpu_shares=self.cpu.cpu_shares,
            memory_mb=self.memory.memory_mb,
            disk_mb=self.disk.disk_mb,
        )


@dataclass
class NodeReservedResources:
    """Resources reserved for the OS/agent (structs.go NodeReservedResources)."""
    cpu_shares: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    reserved_host_ports: str = ""   # e.g. "22,80,8000-9000"

    def comparable(self) -> "ComparableResources":
        return ComparableResources(
            cpu_shares=self.cpu_shares,
            memory_mb=self.memory_mb,
            disk_mb=self.disk_mb,
        )


@dataclass
class AllocatedCpuResources:
    cpu_shares: int = 0

    def add(self, o): self.cpu_shares += o.cpu_shares
    def subtract(self, o): self.cpu_shares -= o.cpu_shares


@dataclass
class AllocatedMemoryResources:
    memory_mb: int = 0

    def add(self, o): self.memory_mb += o.memory_mb
    def subtract(self, o): self.memory_mb -= o.memory_mb


@dataclass
class AllocatedDeviceResource:
    """Devices granted to a task (structs.go AllocatedDeviceResource)."""
    vendor: str = ""
    type: str = ""
    name: str = ""
    device_ids: List[str] = field(default_factory=list)

    def id_tuple(self):
        return (self.vendor, self.type, self.name)


@dataclass
class AllocatedTaskResources:
    """Resources granted to a single task (structs.go AllocatedTaskResources)."""
    cpu: AllocatedCpuResources = field(default_factory=AllocatedCpuResources)
    memory: AllocatedMemoryResources = field(default_factory=AllocatedMemoryResources)
    networks: List[NetworkResource] = field(default_factory=list)
    devices: List[AllocatedDeviceResource] = field(default_factory=list)

    def copy(self) -> "AllocatedTaskResources":
        return AllocatedTaskResources(
            cpu=AllocatedCpuResources(self.cpu.cpu_shares),
            memory=AllocatedMemoryResources(self.memory.memory_mb),
            networks=[n.copy() for n in self.networks],
            devices=[AllocatedDeviceResource(d.vendor, d.type, d.name, list(d.device_ids))
                     for d in self.devices],
        )


@dataclass
class AllocatedSharedResources:
    """Task-group-shared resources (structs.go AllocatedSharedResources)."""
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)

    def copy(self) -> "AllocatedSharedResources":
        return AllocatedSharedResources(
            disk_mb=self.disk_mb,
            networks=[n.copy() for n in self.networks],
        )


@dataclass
class AllocatedResources:
    """All resources granted to an allocation (structs.go AllocatedResources:3302)."""
    tasks: Dict[str, AllocatedTaskResources] = field(default_factory=dict)
    shared: AllocatedSharedResources = field(default_factory=AllocatedSharedResources)

    def comparable(self) -> "ComparableResources":
        c = ComparableResources(disk_mb=self.shared.disk_mb)
        networks: List[NetworkResource] = list(self.shared.networks)
        for tr in self.tasks.values():
            c.cpu_shares += tr.cpu.cpu_shares
            c.memory_mb += tr.memory.memory_mb
            networks.extend(tr.networks)
        c.networks = networks
        return c

    def copy(self) -> "AllocatedResources":
        return AllocatedResources(
            tasks={k: v.copy() for k, v in self.tasks.items()},
            shared=self.shared.copy(),
        )


@dataclass
class ComparableResources:
    """Flattened, comparable resource vector (structs.go ComparableResources:3709).
    The algebra behind AllocsFit / bin-pack scoring."""
    cpu_shares: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    networks: List[NetworkResource] = field(default_factory=list)

    def add(self, other: Optional["ComparableResources"]) -> None:
        if other is None:
            return
        self.cpu_shares += other.cpu_shares
        self.memory_mb += other.memory_mb
        self.disk_mb += other.disk_mb
        self.networks = self.networks + other.networks

    def subtract(self, other: Optional["ComparableResources"]) -> None:
        if other is None:
            return
        self.cpu_shares -= other.cpu_shares
        self.memory_mb -= other.memory_mb
        self.disk_mb -= other.disk_mb

    def superset(self, other: "ComparableResources"):
        """Is self >= other on every dimension? Returns (bool, failing_dim)."""
        if self.cpu_shares < other.cpu_shares:
            return False, "cpu"
        if self.memory_mb < other.memory_mb:
            return False, "memory"
        if self.disk_mb < other.disk_mb:
            return False, "disk"
        return True, ""

    def net_index(self, n: NetworkResource) -> int:
        for i, nw in enumerate(self.networks):
            if nw.device == n.device:
                return i
        return -1

    def copy(self) -> "ComparableResources":
        return ComparableResources(
            cpu_shares=self.cpu_shares,
            memory_mb=self.memory_mb,
            disk_mb=self.disk_mb,
            networks=[n.copy() for n in self.networks],
        )
