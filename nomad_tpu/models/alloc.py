"""Allocation — a job task group placed on a node — plus the per-eval
scoring metadata (AllocMetric) that the TPU kernel emits as debug output.

Reference semantics: nomad/structs/structs.go Allocation:8873,
AllocMetric:9580.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .resources import AllocatedResources
from .job import Job, ReschedulePolicy

ALLOC_DESIRED_RUN = "run"
ALLOC_DESIRED_STOP = "stop"
ALLOC_DESIRED_EVICT = "evict"

ALLOC_CLIENT_PENDING = "pending"
ALLOC_CLIENT_RUNNING = "running"
ALLOC_CLIENT_COMPLETE = "complete"
ALLOC_CLIENT_FAILED = "failed"
ALLOC_CLIENT_LOST = "lost"

TASK_STATE_PENDING = "pending"
TASK_STATE_RUNNING = "running"
TASK_STATE_DEAD = "dead"


@dataclass
class TaskEvent:
    type: str = ""
    time: int = 0
    message: str = ""
    display_message: str = ""
    details: Dict[str, str] = field(default_factory=dict)
    exit_code: int = 0
    signal: int = 0
    failed: bool = False
    restart_reason: str = ""


@dataclass
class TaskState:
    state: str = TASK_STATE_PENDING
    failed: bool = False
    restarts: int = 0
    last_restart: float = 0.0
    started_at: float = 0.0
    finished_at: float = 0.0
    events: List[TaskEvent] = field(default_factory=list)

    def successful(self) -> bool:
        return self.state == TASK_STATE_DEAD and not self.failed


@dataclass
class NodeScoreMeta:
    """Per-node scoring breakdown kept for observability
    (structs.go NodeScoreMeta; populated from the kernel's score vectors)."""
    node_id: str = ""
    scores: Dict[str, float] = field(default_factory=dict)
    norm_score: float = 0.0


@dataclass
class AllocMetric:
    """Scheduling metrics for one placement attempt (structs.go:9580)."""
    nodes_evaluated: int = 0
    nodes_filtered: int = 0
    nodes_available: Dict[str, int] = field(default_factory=dict)      # dc -> count
    class_filtered: Dict[str, int] = field(default_factory=dict)
    constraint_filtered: Dict[str, int] = field(default_factory=dict)
    nodes_exhausted: int = 0
    class_exhausted: Dict[str, int] = field(default_factory=dict)
    dimension_exhausted: Dict[str, int] = field(default_factory=dict)
    quota_exhausted: List[str] = field(default_factory=list)
    score_meta_data: List[NodeScoreMeta] = field(default_factory=list)
    allocation_time_ns: int = 0
    coalesced_failures: int = 0

    def evaluate_node(self):
        self.nodes_evaluated += 1

    def filter_node(self, node, constraint: str):
        self.nodes_filtered += 1
        if node is not None and node.node_class:
            self.class_filtered[node.node_class] = self.class_filtered.get(node.node_class, 0) + 1
        if constraint:
            self.constraint_filtered[constraint] = self.constraint_filtered.get(constraint, 0) + 1

    def exhausted_node(self, node, dimension: str):
        self.nodes_exhausted += 1
        if node is not None and node.node_class:
            self.class_exhausted[node.node_class] = self.class_exhausted.get(node.node_class, 0) + 1
        if dimension:
            self.dimension_exhausted[dimension] = self.dimension_exhausted.get(dimension, 0) + 1

    def copy(self) -> "AllocMetric":
        from ..utils.codec import to_wire, from_wire
        return from_wire(AllocMetric, to_wire(self))

    def max_normalized_score(self) -> float:
        if not self.score_meta_data:
            return 0.0
        return max(s.norm_score for s in self.score_meta_data)


@dataclass
class DesiredTransition:
    """Server-desired alloc transitions (structs.go DesiredTransition)."""
    migrate: Optional[bool] = None
    reschedule: Optional[bool] = None
    force_reschedule: Optional[bool] = None

    def should_migrate(self) -> bool:
        return bool(self.migrate)

    def should_force_reschedule(self) -> bool:
        return bool(self.force_reschedule)


@dataclass
class RescheduleEvent:
    reschedule_time: float = 0.0    # unix seconds
    prev_alloc_id: str = ""
    prev_node_id: str = ""
    delay_s: float = 0.0


@dataclass
class RescheduleTracker:
    events: List[RescheduleEvent] = field(default_factory=list)


@dataclass
class AllocDeploymentStatus:
    healthy: Optional[bool] = None
    timestamp: float = 0.0
    canary: bool = False
    modify_index: int = 0

    def is_healthy(self) -> bool:
        return self.healthy is True

    def is_unhealthy(self) -> bool:
        return self.healthy is False


@dataclass
class Allocation:
    id: str = ""
    namespace: str = "default"
    eval_id: str = ""
    name: str = ""              # "<job>.<group>[<index>]"
    node_id: str = ""
    node_name: str = ""
    job_id: str = ""
    job: Optional[Job] = None   # job snapshot at placement time
    task_group: str = ""
    allocated_resources: Optional[AllocatedResources] = None
    metrics: Optional[AllocMetric] = None
    desired_status: str = ALLOC_DESIRED_RUN
    desired_description: str = ""
    desired_transition: DesiredTransition = field(default_factory=DesiredTransition)
    client_status: str = ALLOC_CLIENT_PENDING
    client_description: str = ""
    task_states: Dict[str, TaskState] = field(default_factory=dict)
    deployment_id: str = ""
    deployment_status: Optional[AllocDeploymentStatus] = None
    reschedule_tracker: Optional[RescheduleTracker] = None
    follow_up_eval_id: str = ""
    previous_allocation: str = ""
    next_allocation: str = ""
    preempted_allocations: List[str] = field(default_factory=list)
    preempted_by_allocation: str = ""
    create_index: int = 0
    modify_index: int = 0
    alloc_modify_index: int = 0
    create_time: int = 0
    modify_time: int = 0

    # -- status predicates (structs.go Allocation.TerminalStatus) ------
    def terminal_status(self) -> bool:
        """Desired or actual status is terminal: the alloc no longer
        consumes resources."""
        if self.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT):
            return True
        return self.client_terminal_status()

    def client_terminal_status(self) -> bool:
        return self.client_status in (
            ALLOC_CLIENT_COMPLETE, ALLOC_CLIENT_FAILED, ALLOC_CLIENT_LOST)

    def server_terminal_status(self) -> bool:
        return self.desired_status in (ALLOC_DESIRED_STOP, ALLOC_DESIRED_EVICT)

    def ran_successfully(self) -> bool:
        if not self.task_states:
            return False
        return all(ts.successful() for ts in self.task_states.values())

    def comparable_resources(self):
        if self.allocated_resources is None:
            return None
        return self.allocated_resources.comparable()

    def index(self) -> int:
        """Parse the bracketed index out of the alloc name."""
        l, r = self.name.rfind("["), self.name.rfind("]")
        if l == -1 or r == -1 or r < l:
            return -1
        try:
            return int(self.name[l + 1:r])
        except ValueError:
            return -1

    def job_namespaced_id(self):
        return (self.namespace, self.job_id)

    def reschedule_policy(self) -> Optional[ReschedulePolicy]:
        if self.job is None:
            return None
        tg = self.job.lookup_task_group(self.task_group)
        return tg.reschedule_policy if tg else None

    def last_event_time(self) -> float:
        """Latest task finished_at across task states (unix seconds)."""
        last = 0.0
        for ts in self.task_states.values():
            if ts.finished_at and ts.finished_at > last:
                last = ts.finished_at
        return last

    def next_reschedule_time(self):
        """(eligible_time_unix_s, policy_has_delay) for delayed reschedule
        (structs.go Allocation.NextRescheduleTime)."""
        fail_time = self.last_event_time()
        policy = self.reschedule_policy()
        if policy is None or fail_time == 0.0:
            return 0.0, False
        if self.client_status != ALLOC_CLIENT_FAILED and self.client_status != ALLOC_CLIENT_LOST:
            return 0.0, False
        if not policy.enabled():
            return 0.0, False
        delay = self._next_delay(policy)
        if policy.unlimited or (policy.attempts > 0 and self.reschedule_tracker is None):
            return fail_time + delay, True
        attempted = 0
        if self.reschedule_tracker:
            window_start = fail_time - policy.interval_s
            for ev in self.reschedule_tracker.events:
                if ev.reschedule_time > window_start:
                    attempted += 1
        # Once the backoff delay outgrows the sliding interval the policy can
        # never legitimately fire again (structs.go:9226 nextDelay < Interval).
        eligible = attempted < policy.attempts and delay < policy.interval_s
        return fail_time + delay, eligible

    def _next_delay(self, policy: ReschedulePolicy) -> float:
        """Delay for the next reschedule attempt given the delay function."""
        n_prev = len(self.reschedule_tracker.events) if self.reschedule_tracker else 0
        base = policy.delay_s
        if policy.delay_function == "constant":
            return base
        if policy.delay_function == "exponential":
            d = base * (2 ** n_prev)
        elif policy.delay_function == "fibonacci":
            a, b = base, base
            for _ in range(n_prev):
                a, b = b, a + b
            d = a
        else:
            d = base
        if policy.max_delay_s > 0:
            d = min(d, policy.max_delay_s)
        return d

    def should_reschedule(self, now: float) -> bool:
        t, ok = self.next_reschedule_time()
        return ok and t <= now

    def copy(self) -> "Allocation":
        from ..utils.codec import to_wire, from_wire
        return from_wire(Allocation, to_wire(self))

    def copy_skip_job(self) -> "Allocation":
        job = self.job
        self.job = None
        try:
            c = self.copy()
        finally:
            self.job = job
        c.job = job
        return c

    def stub(self) -> dict:
        return {
            "id": self.id, "name": self.name, "node_id": self.node_id,
            "job_id": self.job_id, "task_group": self.task_group,
            "desired_status": self.desired_status,
            "client_status": self.client_status,
            "deployment_id": self.deployment_id,
            "follow_up_eval_id": self.follow_up_eval_id,
            "create_index": self.create_index, "modify_index": self.modify_index,
        }
