"""CSI volume model (reference: nomad/structs/csi.go — CSIVolume with
access/attachment modes and read/write claim tracking; claim capacity
rules per access mode).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

# access modes (structs/csi.go CSIVolumeAccessMode)
ACCESS_SINGLE_NODE_READER = "single-node-reader-only"
ACCESS_SINGLE_NODE_WRITER = "single-node-writer"
ACCESS_MULTI_NODE_READER = "multi-node-reader-only"
ACCESS_MULTI_NODE_SINGLE_WRITER = "multi-node-single-writer"
ACCESS_MULTI_NODE_MULTI_WRITER = "multi-node-multi-writer"

ATTACHMENT_FILE_SYSTEM = "file-system"
ATTACHMENT_BLOCK_DEVICE = "block-device"

CLAIM_READ = "read"
CLAIM_WRITE = "write"


@dataclass
class CSIVolume:
    id: str = ""
    namespace: str = "default"
    name: str = ""
    plugin_id: str = ""
    access_mode: str = ACCESS_SINGLE_NODE_WRITER
    attachment_mode: str = ATTACHMENT_FILE_SYSTEM
    schedulable: bool = True
    # topology: node ids where the volume is reachable; empty == all
    topology_node_ids: List[str] = field(default_factory=list)
    read_allocs: Dict[str, str] = field(default_factory=dict)   # id->node
    write_allocs: Dict[str, str] = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0

    # -- claim capacity (csi.go WriteFreeClaims/ReadSchedulable) -------
    def write_schedulable(self) -> bool:
        if not self.schedulable:
            return False
        if self.access_mode in (ACCESS_SINGLE_NODE_WRITER,
                                ACCESS_MULTI_NODE_SINGLE_WRITER):
            return len(self.write_allocs) == 0
        if self.access_mode == ACCESS_MULTI_NODE_MULTI_WRITER:
            return True
        return False                         # reader-only modes

    def read_schedulable(self) -> bool:
        # reads are never claim-limited, in any access mode
        # (csi.go ReadSchedulable:361 checks volume health only)
        return self.schedulable

    def claimable(self, read_only: bool) -> bool:
        return self.read_schedulable() if read_only \
            else self.write_schedulable()

    def claim(self, alloc_id: str, node_id: str, read_only: bool) -> None:
        if read_only:
            self.read_allocs[alloc_id] = node_id
        else:
            self.write_allocs[alloc_id] = node_id

    def release(self, alloc_id: str) -> bool:
        hit = self.read_allocs.pop(alloc_id, None) is not None
        hit = (self.write_allocs.pop(alloc_id, None) is not None) or hit
        return hit

    def stub(self) -> dict:
        return {"id": self.id, "namespace": self.namespace,
                "name": self.name, "plugin_id": self.plugin_id,
                "access_mode": self.access_mode,
                "schedulable": self.schedulable,
                "current_readers": len(self.read_allocs),
                "current_writers": len(self.write_allocs),
                "modify_index": self.modify_index}
