"""Deployment — tracks a rolling update of a job version.

Reference semantics: nomad/structs/structs.go Deployment:8532.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..utils.ids import generate_uuid

DEPLOYMENT_STATUS_RUNNING = "running"
DEPLOYMENT_STATUS_PAUSED = "paused"
DEPLOYMENT_STATUS_FAILED = "failed"
DEPLOYMENT_STATUS_SUCCESSFUL = "successful"
DEPLOYMENT_STATUS_CANCELLED = "cancelled"

TERMINAL_STATUSES = (DEPLOYMENT_STATUS_FAILED, DEPLOYMENT_STATUS_SUCCESSFUL,
                     DEPLOYMENT_STATUS_CANCELLED)

DESC_PROGRESS_DEADLINE = "Failed due to progress deadline"
DESC_NEW_JOB_VERSION = "Cancelled because job is stopped or a newer version was posted"
DESC_SUCCESSFUL = "Deployment completed successfully"
DESC_RUNNING = "Deployment is running"
DESC_RUNNING_NEEDS_PROMOTION = "Deployment is running but requires manual promotion"
DESC_RUNNING_AUTO_PROMOTION = "Deployment is running pending automatic promotion"
DESC_FAILED_ALLOCATIONS = "Failed due to unhealthy allocations"
DESC_FAILED_BY_USER = "Deployment marked as failed"


@dataclass
class DeploymentState:
    """Per-task-group deployment progress (structs.go DeploymentState)."""
    auto_revert: bool = False
    auto_promote: bool = False
    promoted: bool = False
    placed_canaries: List[str] = field(default_factory=list)
    desired_canaries: int = 0
    desired_total: int = 0
    placed_allocs: int = 0
    healthy_allocs: int = 0
    unhealthy_allocs: int = 0
    progress_deadline_s: float = 0.0
    require_progress_by: float = 0.0   # unix seconds


@dataclass
class DeploymentStatusUpdate:
    deployment_id: str = ""
    status: str = ""
    status_description: str = ""


@dataclass
class Deployment:
    id: str = field(default_factory=generate_uuid)
    namespace: str = "default"
    job_id: str = ""
    job_version: int = 0
    job_modify_index: int = 0
    job_spec_modify_index: int = 0
    job_create_index: int = 0
    is_multiregion: bool = False
    task_groups: Dict[str, DeploymentState] = field(default_factory=dict)
    status: str = DEPLOYMENT_STATUS_RUNNING
    status_description: str = DESC_RUNNING
    create_index: int = 0
    modify_index: int = 0
    create_time: int = 0
    modify_time: int = 0

    @classmethod
    def from_job(cls, job) -> "Deployment":
        d = cls(
            namespace=job.namespace,
            job_id=job.id,
            job_version=job.version,
            job_modify_index=job.modify_index,
            job_spec_modify_index=job.job_modify_index,
            job_create_index=job.create_index,
        )
        for tg in job.task_groups:
            u = tg.update
            if u is None:
                continue
            d.task_groups[tg.name] = DeploymentState(
                auto_revert=u.auto_revert,
                auto_promote=u.auto_promote,
                desired_total=tg.count,
                desired_canaries=u.canary,
                progress_deadline_s=u.progress_deadline_s,
            )
        return d

    def active(self) -> bool:
        return self.status in (DEPLOYMENT_STATUS_RUNNING, DEPLOYMENT_STATUS_PAUSED)

    def requires_promotion(self) -> bool:
        return any(s.desired_canaries > 0 and not s.promoted
                   for s in self.task_groups.values())

    def has_auto_promote(self) -> bool:
        states = self.task_groups.values()
        return bool(states) and all(s.auto_promote for s in states)

    def copy(self) -> "Deployment":
        from ..utils.codec import to_wire, from_wire
        return from_wire(Deployment, to_wire(self))
