"""Resource fit and scoring functions — the scalar golden reference for
the TPU kernels in nomad_tpu/ops/.

Reference semantics: nomad/structs/funcs.go — AllocsFit:102,
ScoreFitBinPack:174 (BestFit v3: score = 20 - 10^freeCpuPct - 10^freeMemPct,
clamped to [0,18]), ScoreFitSpread:201 (worst fit: 10^fc + 10^fm - 2).
"""

from __future__ import annotations

import math
from typing import List, Optional, Tuple

from .networks import NetworkIndex
from .resources import ComparableResources


def FilterTerminalAllocs(allocs: List) -> Tuple[List, dict]:
    """Remove terminal allocs; also return latest terminal alloc by name
    (structs.go FilterTerminalAllocs)."""
    terminal = {}
    live = []
    for alloc in allocs:
        if alloc.terminal_status():
            prev = terminal.get(alloc.name)
            if prev is None or alloc.create_index > prev.create_index:
                terminal[alloc.name] = alloc
        else:
            live.append(alloc)
    return live, terminal


def AllocsFit(node, allocs: List, net_idx: Optional[NetworkIndex] = None,
              check_devices: bool = False) -> Tuple[bool, str, ComparableResources]:
    """Do these allocs (live only) fit on the node? Returns
    (fit, failing_dimension, used)."""
    used = ComparableResources()
    for alloc in allocs:
        if alloc.terminal_status():
            continue
        used.add(alloc.comparable_resources())

    available = node.comparable_resources()
    available.subtract(node.comparable_reserved_resources())
    ok, dim = available.superset(used)
    if not ok:
        return False, dim, used

    if net_idx is None:
        net_idx = NetworkIndex()
        if net_idx.set_node(node) or net_idx.add_allocs(allocs):
            return False, "reserved port collision", used

    if net_idx.overcommitted():
        return False, "bandwidth exceeded", used

    if check_devices:
        from .device_accounting import DeviceAccounter
        acct = DeviceAccounter(node)
        if acct.add_allocs(allocs):
            return False, "device oversubscribed", used

    return True, "", used


def _free_percentages(node, util: ComparableResources) -> Tuple[float, float]:
    res = node.comparable_resources()
    reserved = node.comparable_reserved_resources()
    node_cpu = float(res.cpu_shares) - float(reserved.cpu_shares)
    node_mem = float(res.memory_mb) - float(reserved.memory_mb)
    free_cpu = 1.0 - (float(util.cpu_shares) / node_cpu) if node_cpu else 0.0
    free_mem = 1.0 - (float(util.memory_mb) / node_mem) if node_mem else 0.0
    return free_cpu, free_mem


def ScoreFitBinPack(node, util: ComparableResources) -> float:
    """BestFit v3: prefer nodes that end up fuller. Score in [0, 18]."""
    free_cpu, free_mem = _free_percentages(node, util)
    total = math.pow(10, free_cpu) + math.pow(10, free_mem)
    score = 20.0 - total
    return max(0.0, min(18.0, score))


def ScoreFitSpread(node, util: ComparableResources) -> float:
    """Worst fit: prefer nodes that end up emptier. Score in [0, 18]."""
    free_cpu, free_mem = _free_percentages(node, util)
    total = math.pow(10, free_cpu) + math.pow(10, free_mem)
    score = total - 2.0
    return max(0.0, min(18.0, score))
