"""Namespaces (structs.go Namespace:4719).

Logical grouping for jobs and their objects; replicated from the
authoritative region by non-authoritative leaders
(nomad/leader.go replicateNamespaces:352).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List

# structs.go validNamespaceName:188
_VALID_NAME = re.compile(r"^[a-zA-Z0-9-]{1,128}$")
MAX_DESCRIPTION = 256
DEFAULT_NAMESPACE = "default"


@dataclass
class Namespace:
    name: str = ""
    description: str = ""
    meta: Dict[str, str] = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0

    def validate(self) -> List[str]:
        """structs.go Namespace.Validate:4739."""
        errs = []
        if not _VALID_NAME.match(self.name or ""):
            errs.append(f"invalid name {self.name!r}. Must match regex "
                        f"{_VALID_NAME.pattern}")
        if len(self.description) > MAX_DESCRIPTION:
            errs.append(f"description longer than {MAX_DESCRIPTION}")
        return errs
