"""Job diff engine — powers `job plan` dry-run output.

Reference semantics: nomad/structs/diff.go (JobDiff / TaskGroupDiff /
TaskDiff / ObjectDiff / FieldDiff, 2,074 LoC of hand-rolled per-type
diffing). The rebuild replaces that with ONE reflective differ over the
dataclass domain model: primitives become FieldDiffs, nested dataclasses
and dicts become ObjectDiffs, and lists of named objects (task groups,
tasks, constraints) are matched by their `name` attribute. Diff types
mirror the reference: Added / Deleted / Edited / None.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

DIFF_ADDED = "Added"
DIFF_DELETED = "Deleted"
DIFF_EDITED = "Edited"
DIFF_NONE = "None"

# job-level fields that are bookkeeping, not spec (diff.go jobDiff skips)
_SKIP_FIELDS = {
    "id", "status", "status_description", "stable", "version",
    "create_index", "modify_index", "job_modify_index", "submit_time",
    "payload", "dispatched",
}


def _is_primitive(v: Any) -> bool:
    return isinstance(v, (str, int, float, bool)) or v is None


def _field_diff(name: str, old: Any, new: Any) -> Optional[dict]:
    if old == new:
        return None
    if old in (None, "", [], {}) and new not in (None, "", [], {}):
        dtype = DIFF_ADDED
    elif new in (None, "", [], {}) and old not in (None, "", [], {}):
        dtype = DIFF_DELETED
    else:
        dtype = DIFF_EDITED
    return {"Type": dtype, "Name": name,
            "Old": "" if old is None else str(old),
            "New": "" if new is None else str(new)}


def _name_of(item: Any) -> str:
    for attr in ("name", "id", "label"):
        v = getattr(item, attr, None)
        if v:
            return str(v)
    return str(item)


def diff_objects(old: Any, new: Any, name: str,
                 skip: frozenset = frozenset()) -> Optional[dict]:
    """Recursive diff of two same-type dataclasses (ObjectDiff)."""
    if old is None and new is None:
        return None
    dtype = DIFF_EDITED
    if old is None:
        dtype = DIFF_ADDED
    elif new is None:
        dtype = DIFF_DELETED
    fields: List[dict] = []
    objects: List[dict] = []
    probe = old if old is not None else new
    for f in dataclasses.fields(probe):
        if f.name in skip:
            continue
        ov = getattr(old, f.name, None) if old is not None else None
        nv = getattr(new, f.name, None) if new is not None else None
        label = f.name
        if _is_primitive(ov) and _is_primitive(nv):
            fd = _field_diff(label, ov, nv)
            if fd:
                fields.append(fd)
        elif isinstance(ov or nv, dict):
            sub_fields = []
            for k in sorted(set(ov or {}) | set(nv or {})):
                fd = _field_diff(f"{label}[{k}]", (ov or {}).get(k),
                                 (nv or {}).get(k))
                if fd:
                    sub_fields.append(fd)
            if sub_fields:
                objects.append({"Type": DIFF_EDITED, "Name": label,
                                "Fields": sub_fields, "Objects": []})
        elif isinstance(ov or nv, list):
            items = _diff_lists(label, ov or [], nv or [])
            objects.extend(items)
        elif dataclasses.is_dataclass(ov or nv):
            od = diff_objects(ov, nv, label)
            if od and od["Type"] != DIFF_NONE:
                objects.append(od)
        else:
            fd = _field_diff(label, ov, nv)
            if fd:
                fields.append(fd)
    if not fields and not objects and dtype == DIFF_EDITED:
        return {"Type": DIFF_NONE, "Name": name, "Fields": [], "Objects": []}
    return {"Type": dtype, "Name": name, "Fields": fields,
            "Objects": objects}


def _diff_lists(name: str, old: list, new: list) -> List[dict]:
    out: List[dict] = []
    if all(_is_primitive(x) for x in old + new):
        fd = _field_diff(name, old or None, new or None)
        return [{"Type": fd["Type"], "Name": name, "Fields": [fd],
                 "Objects": []}] if fd else []
    olds = {_name_of(x): x for x in old}
    news = {_name_of(x): x for x in new}
    for key in sorted(set(olds) | set(news)):
        od = diff_objects(olds.get(key), news.get(key), f"{name}[{key}]")
        if od and od["Type"] != DIFF_NONE:
            out.append(od)
    return out


def job_diff(old, new) -> dict:
    """JobDiff (diff.go Job.Diff): top-level fields + task-group diffs,
    groups matched by name, tasks matched by name within each group."""
    if old is None and new is None:
        return {"Type": DIFF_NONE, "ID": "", "Fields": [], "Objects": [],
                "TaskGroups": []}
    dtype = DIFF_EDITED
    if old is None:
        dtype = DIFF_ADDED
    elif new is None:
        dtype = DIFF_DELETED
    job_id = (new or old).id

    top = diff_objects(old, new, "Job",
                       skip=frozenset(_SKIP_FIELDS | {"task_groups"}))
    tg_diffs = []
    olds = {tg.name: tg for tg in (old.task_groups if old else [])}
    news = {tg.name: tg for tg in (new.task_groups if new else [])}
    for name in sorted(set(olds) | set(news)):
        d = diff_objects(olds.get(name), news.get(name), name,
                         skip=frozenset({"tasks"}))
        if d is None:
            continue
        task_diffs = []
        t_old = {t.name: t for t in getattr(olds.get(name), "tasks", []) or []}
        t_new = {t.name: t for t in getattr(news.get(name), "tasks", []) or []}
        for tname in sorted(set(t_old) | set(t_new)):
            td = diff_objects(t_old.get(tname), t_new.get(tname), tname)
            if td and td["Type"] != DIFF_NONE:
                task_diffs.append(td)
        if d["Type"] == DIFF_NONE and not task_diffs:
            continue
        d["Tasks"] = task_diffs
        if d["Type"] == DIFF_NONE and task_diffs:
            d["Type"] = DIFF_EDITED
        tg_diffs.append(d)

    if dtype == DIFF_EDITED and top["Type"] == DIFF_NONE and not tg_diffs:
        dtype = DIFF_NONE
    return {"Type": dtype, "ID": job_id, "Fields": top["Fields"],
            "Objects": top["Objects"], "TaskGroups": tg_diffs}
