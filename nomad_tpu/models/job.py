"""Job / TaskGroup / Task and the placement-shaping stanzas.

Reference semantics: nomad/structs/structs.go — Job:3805, TaskGroup:5780,
Task:6491, Constraint:8023, Affinity:8145, Spread:8233 — plus the
canonicalize/validate behaviors the schedulers depend on.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..utils.ids import generate_uuid
from .resources import Resources
from .networks import NetworkResource
from .services import CheckRestart, ConsulConnect  # noqa: F401 -- re-exported

# services.go validateServiceNameRe
_SERVICE_NAME_RE = re.compile(
    r"^(?i:[a-z0-9]|[a-z0-9][a-z0-9\-]{0,61}[a-z0-9])$")
from .constraints import (  # noqa: F401 -- re-exported
    Affinity, Constraint, Spread, SpreadTarget,
    COMPARISON_OPERANDS,
    CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY,
    CONSTRAINT_IS_NOT_SET, CONSTRAINT_IS_SET, CONSTRAINT_REGEX,
    CONSTRAINT_SEMVER, CONSTRAINT_SET_CONTAINS,
    CONSTRAINT_SET_CONTAINS_ALL, CONSTRAINT_SET_CONTAINS_ANY,
    CONSTRAINT_VERSION,
)

# Job types (structs.go JobTypeService etc.)
JOB_TYPE_SERVICE = "service"
JOB_TYPE_BATCH = "batch"
JOB_TYPE_SYSTEM = "system"
JOB_TYPE_CORE = "_core"

JOB_STATUS_PENDING = "pending"
JOB_STATUS_RUNNING = "running"
JOB_STATUS_DEAD = "dead"

JOB_MIN_PRIORITY = 1
JOB_DEFAULT_PRIORITY = 50
JOB_MAX_PRIORITY = 100

DEFAULT_NAMESPACE = "default"



@dataclass
class RestartPolicy:
    """Client-local restart policy (structs.go RestartPolicy)."""
    attempts: int = 2
    interval_s: float = 30 * 60.0
    delay_s: float = 15.0
    mode: str = "fail"   # "delay" | "fail"


@dataclass
class ReschedulePolicy:
    """Server-side rescheduling policy (structs.go ReschedulePolicy)."""
    attempts: int = 0
    interval_s: float = 0.0
    delay_s: float = 30.0
    delay_function: str = "exponential"   # "constant" | "exponential" | "fibonacci"
    max_delay_s: float = 3600.0
    unlimited: bool = True

    def enabled(self) -> bool:
        return self.unlimited or (self.attempts > 0 and self.interval_s > 0)

    def validate(self) -> List[str]:
        errs = []
        if self.delay_function not in ("constant", "exponential", "fibonacci"):
            errs.append(f"invalid delay function {self.delay_function}")
        if not self.unlimited:
            if self.attempts < 0:
                errs.append("attempts must be >= 0")
        return errs


def default_service_reschedule_policy() -> ReschedulePolicy:
    return ReschedulePolicy(delay_s=30.0, delay_function="exponential",
                            max_delay_s=3600.0, unlimited=True)


def default_batch_reschedule_policy() -> ReschedulePolicy:
    return ReschedulePolicy(attempts=1, interval_s=24 * 3600.0, delay_s=5.0,
                            delay_function="constant", unlimited=False)


@dataclass
class EphemeralDisk:
    sticky: bool = False
    size_mb: int = 300
    migrate: bool = False


@dataclass
class UpdateStrategy:
    """Rolling update strategy (structs.go UpdateStrategy)."""
    stagger_s: float = 30.0
    max_parallel: int = 1
    health_check: str = "checks"   # "checks" | "task_states" | "manual"
    min_healthy_time_s: float = 10.0
    healthy_deadline_s: float = 300.0
    progress_deadline_s: float = 600.0
    auto_revert: bool = False
    auto_promote: bool = False
    canary: int = 0

    def rolling(self) -> bool:
        return self.stagger_s > 0 and self.max_parallel > 0

    def is_empty(self) -> bool:
        """structs.go UpdateStrategy.IsEmpty:4644 — max_parallel == 0
        means no rolling updates at all (no deployments, no limits)."""
        return self.max_parallel == 0


@dataclass
class MigrateStrategy:
    max_parallel: int = 1
    health_check: str = "checks"
    min_healthy_time_s: float = 10.0
    healthy_deadline_s: float = 300.0


@dataclass
class PeriodicConfig:
    # a present periodic stanza defaults to enabled (api/jobs.go
    # canonicalizes Enabled=true when the block exists); "no periodic"
    # is represented by Job.periodic is None
    enabled: bool = True
    spec: str = ""             # cron expression
    spec_type: str = "cron"
    prohibit_overlap: bool = False
    timezone: str = "UTC"


@dataclass
class ParameterizedJobConfig:
    payload: str = "optional"  # "optional" | "required" | "forbidden"
    meta_required: List[str] = field(default_factory=list)
    meta_optional: List[str] = field(default_factory=list)


@dataclass
class DispatchPayloadConfig:
    file: str = ""


@dataclass
class TaskLifecycleConfig:
    hook: str = ""         # "prestart" | "poststart" | "poststop"
    sidecar: bool = False


@dataclass
class LogConfig:
    max_files: int = 10
    max_file_size_mb: int = 10


@dataclass
class ServiceCheck:
    """Health check spec (services.go ServiceCheck:42)."""
    name: str = ""
    type: str = ""          # http | tcp | script | grpc
    path: str = ""
    interval_s: float = 10.0
    timeout_s: float = 2.0
    port_label: str = ""
    method: str = ""                        # http method, GET default
    protocol: str = ""                      # http|https for http checks
    address_mode: str = ""
    initial_status: str = ""
    expose: bool = False
    success_before_passing: int = 0
    failures_before_critical: int = 0
    task_name: str = ""
    check_restart: Optional["CheckRestart"] = None

    def validate(self) -> List[str]:
        """services.go ServiceCheck.validate: known type, http checks
        need a path, intervals/timeouts have 1 s floors."""
        errs = []
        kind = self.type.lower()
        if kind not in ("http", "tcp", "script", "grpc"):
            errs.append(f"invalid check type {self.type!r}")
        if kind == "http" and not self.path:
            errs.append(f"http check {self.name or '(unnamed)'} requires "
                        "a path")
        if self.interval_s < 1.0:
            errs.append(f"check interval {self.interval_s}s below 1s "
                        "minimum")
        if self.timeout_s < 1.0:
            errs.append(f"check timeout {self.timeout_s}s below 1s "
                        "minimum")
        if self.check_restart is not None and self.check_restart.limit < 0:
            errs.append("check_restart limit can't be negative")
        return errs


@dataclass
class Service:
    """services.go Service:~380 (group- or task-level)."""
    name: str = ""
    port_label: str = ""
    tags: List[str] = field(default_factory=list)
    checks: List[ServiceCheck] = field(default_factory=list)
    address_mode: str = "auto"
    task_name: str = ""                     # which task backs it
    meta: Dict[str, str] = field(default_factory=dict)
    connect: Optional["ConsulConnect"] = None

    def canonicalize(self, job: str, group: str, task: str) -> None:
        """services.go Service.Canonicalize:450 — resolve the
        JOB/TASKGROUP/TASK/BASE name variables so validation sees the
        real name."""
        base = f"{job}-{group}-{task}" if task else f"{job}-{group}"
        if not self.name:
            self.name = base
        for var, val in (("JOB", job), ("TASKGROUP", group),
                         ("TASK", task), ("BASE", base)):
            self.name = self.name.replace("${" + var + "}", val)
        for c in self.checks:
            if not c.name:
                c.name = f"service: {self.name!r} check"

    def validate(self) -> List[str]:
        """services.go Service.Validate: RFC-1123-ish name + checks +
        connect exclusivity (the group-shape connect rules live in the
        admission hook, job_endpoint_hook_connect.go)."""
        errs = []
        if not _SERVICE_NAME_RE.match(self.name or ""):
            errs.append(
                f"service name {self.name!r} must be 1-63 characters, "
                "alphanumeric or -, and start/end alphanumeric")
        for c in self.checks:
            errs.extend(f"check {c.name or c.type}: {e}"
                        for e in c.validate())
        if self.connect is not None:
            errs.extend(self.connect.validate())
        return errs


@dataclass
class Template:
    source_path: str = ""
    dest_path: str = ""
    embedded_tmpl: str = ""
    change_mode: str = "restart"
    change_signal: str = ""


@dataclass
class TaskArtifact:
    getter_source: str = ""
    getter_options: Dict[str, str] = field(default_factory=dict)
    relative_dest: str = ""


@dataclass
class VaultConfig:
    policies: List[str] = field(default_factory=list)
    change_mode: str = "restart"
    change_signal: str = ""
    env: bool = True


@dataclass
class VolumeRequest:
    name: str = ""
    type: str = ""          # "host" | "csi"
    source: str = ""
    read_only: bool = False


@dataclass
class VolumeMount:
    volume: str = ""
    destination: str = ""
    read_only: bool = False


@dataclass
class Task:
    """One process to run (structs.go Task:6491)."""
    name: str = ""
    driver: str = ""
    user: str = ""
    # "connect-proxy:<svc>" / "connect-native:<svc>" /
    # "connect-ingress:<svc>" (structs.go TaskKind)
    kind: str = ""
    config: Dict[str, object] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    services: List[Service] = field(default_factory=list)
    vault: Optional[VaultConfig] = None
    templates: List[Template] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    resources: Resources = field(default_factory=Resources)
    meta: Dict[str, str] = field(default_factory=dict)
    kill_timeout_s: float = 5.0
    log_config: LogConfig = field(default_factory=LogConfig)
    artifacts: List[TaskArtifact] = field(default_factory=list)
    leader: bool = False
    shutdown_delay_s: float = 0.0
    volume_mounts: List[VolumeMount] = field(default_factory=list)
    kill_signal: str = ""
    lifecycle: Optional[TaskLifecycleConfig] = None
    dispatch_payload: Optional[DispatchPayloadConfig] = None

    def canonicalize(self, job: "Job", tg: "TaskGroup") -> None:
        if self.resources is None:
            self.resources = Resources()
        self.resources.canonicalize()
        for s in self.services:
            s.canonicalize(job.name, tg.name, self.name)

    def validate(self) -> List[str]:
        errs = []
        if not self.name:
            errs.append("missing task name")
        elif any(c in self.name for c in "/\\"):
            errs.append(f"task name {self.name} cannot include slashes")
        if not self.driver:
            errs.append("missing task driver")
        if self.kill_timeout_s < 0:
            errs.append("kill timeout cannot be negative")
        errs.extend(self.resources.validate())
        for c in self.constraints:
            if c.operand in (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY):
                errs.append(f"task level: {c.operand} constraint not allowed")
            errs.extend(c.validate())
        for a in self.affinities:
            errs.extend(a.validate())
        for s in self.services:
            errs.extend(f"service {s.name}: {e}" for e in s.validate())
            for c in s.checks:
                if c.type.lower() in ("tcp", "http") and \
                        not c.port_label and not s.port_label:
                    errs.append(
                        f"service {s.name}: check "
                        f"{c.name or c.type} requires a port but the "
                        "service doesn't have one")
        return errs

    def is_prestart(self) -> bool:
        return self.lifecycle is not None and self.lifecycle.hook == "prestart"


@dataclass
class Scaling:
    enabled: bool = True
    min: int = 0
    max: int = 0
    policy: Dict[str, object] = field(default_factory=dict)


@dataclass
class ScalingPolicy:
    """structs.ScalingPolicy — the external autoscaler's unit of
    consumption (nomad/scaling_endpoint.go:24,90). Derived from task
    groups' scaling blocks at job registration and stored in the
    scaling_policies table (nomad/state/schema.go:36-62). The id is a
    UUIDv5 of the target so every replica's FSM derives the SAME id
    (the reference assigns ids server-side pre-raft; here derivation
    happens inside the apply, which must stay deterministic)."""
    id: str = ""
    namespace: str = "default"
    # Target: {"Namespace": ns, "Job": job, "Group": group}
    target: Dict[str, str] = field(default_factory=dict)
    min: int = 0
    max: int = 0
    policy: Dict[str, object] = field(default_factory=dict)
    type: str = "horizontal"
    enabled: bool = True
    create_index: int = 0
    modify_index: int = 0

    @staticmethod
    def id_for(namespace: str, job_id: str, group: str) -> str:
        import uuid
        return str(uuid.uuid5(uuid.NAMESPACE_URL,
                              f"nomad-scaling/{namespace}/{job_id}/{group}"))

    def stub(self) -> Dict:
        return {"ID": self.id, "Enabled": self.enabled,
                "Type": self.type, "Target": dict(self.target),
                "CreateIndex": self.create_index,
                "ModifyIndex": self.modify_index}


@dataclass
class TaskGroup:
    """A co-scheduled set of tasks (structs.go TaskGroup:5780)."""
    name: str = ""
    count: int = 1
    update: Optional[UpdateStrategy] = None
    migrate: Optional[MigrateStrategy] = None
    constraints: List[Constraint] = field(default_factory=list)
    scaling: Optional[Scaling] = None
    restart_policy: Optional[RestartPolicy] = None
    reschedule_policy: Optional[ReschedulePolicy] = None
    affinities: List[Affinity] = field(default_factory=list)
    spreads: List[Spread] = field(default_factory=list)
    networks: List[NetworkResource] = field(default_factory=list)
    tasks: List[Task] = field(default_factory=list)
    ephemeral_disk: EphemeralDisk = field(default_factory=EphemeralDisk)
    meta: Dict[str, str] = field(default_factory=dict)
    stop_after_client_disconnect_s: Optional[float] = None
    volumes: Dict[str, VolumeRequest] = field(default_factory=dict)
    shutdown_delay_s: float = 0.0
    services: List[Service] = field(default_factory=list)

    def canonicalize(self, job: "Job") -> None:
        if self.restart_policy is None:
            self.restart_policy = RestartPolicy()
        if self.reschedule_policy is None:
            if job.type == JOB_TYPE_BATCH:
                self.reschedule_policy = default_batch_reschedule_policy()
            elif job.type == JOB_TYPE_SERVICE:
                self.reschedule_policy = default_service_reschedule_policy()
            else:
                self.reschedule_policy = ReschedulePolicy(
                    attempts=0, interval_s=0, unlimited=False)
        if self.ephemeral_disk is None:
            self.ephemeral_disk = EphemeralDisk()
        # NOTE: the update stanza is NOT defaulted here — that is API-layer
        # behavior in the reference (api/tasks.go), not structs canonicalize;
        # defaulting it at this layer would create deployments for every
        # bare service job.
        for s in self.services:
            s.canonicalize(job.name, self.name, "")
        for t in self.tasks:
            t.canonicalize(job, self)

    def validate(self, job: "Job") -> List[str]:
        errs = []
        if not self.name:
            errs.append("missing task group name")
        if self.count < 0:
            errs.append("task group count can't be negative")
        if not self.tasks:
            errs.append(f"task group {self.name} missing tasks")
        names = set()
        for t in self.tasks:
            if t.name in names:
                errs.append(f"task {t.name} defined multiple times")
            names.add(t.name)
            errs.extend(f"task {t.name}: {e}" for e in t.validate())
        for s in self.services:
            errs.extend(f"service {s.name}: {e}" for e in s.validate())
            # tcp/http checks probe a real socket: without a port label
            # on the check or service they'd probe port 0 forever (the
            # reference rejects these at submit, services.go
            # validateCheckPort)
            for c in s.checks:
                if c.type.lower() in ("tcp", "http") and \
                        not c.port_label and not s.port_label:
                    errs.append(
                        f"service {s.name}: check "
                        f"{c.name or c.type} requires a port but the "
                        "service doesn't have one")
        for c in self.constraints:
            errs.extend(c.validate())
        for s in self.spreads:
            errs.extend(s.validate())
        for a in self.affinities:
            errs.extend(a.validate())
        return errs

    def lookup_task(self, name: str) -> Optional[Task]:
        for t in self.tasks:
            if t.name == name:
                return t
        return None


@dataclass
class MultiregionStrategy:
    """structs.go MultiregionStrategy:4706."""
    max_parallel: int = 0
    on_failure: str = ""    # "" | "fail_all" | "fail_local"


@dataclass
class MultiregionRegion:
    """structs.go MultiregionRegion:4711."""
    name: str = ""
    count: int = 0
    datacenters: List[str] = field(default_factory=list)
    meta: Dict[str, str] = field(default_factory=dict)


@dataclass
class Multiregion:
    """structs.go Multiregion:4658. The reference gates the fan-out
    behind its enterprise build (structs_oss.go:12 rejects outright);
    here the register fan-out is implemented over federation peers,
    while cross-region deployment PACING (blocked deployments unblocked
    region by region) remains a gap."""
    strategy: Optional[MultiregionStrategy] = None
    regions: List[MultiregionRegion] = field(default_factory=list)

    def canonicalize(self) -> None:
        if self.strategy is None:
            self.strategy = MultiregionStrategy()

    def validate(self) -> List[str]:
        errs = []
        if not self.regions:
            errs.append("multiregion requires at least one region")
        seen = set()
        for r in self.regions:
            if not r.name:
                errs.append("multiregion region requires a name")
            elif r.name in seen:
                errs.append(f"multiregion region {r.name!r} declared "
                            "twice")
            seen.add(r.name)
            if r.count < 0:
                errs.append(f"region {r.name}: count can't be negative")
        if self.strategy is not None:
            if self.strategy.max_parallel < 0:
                errs.append("max_parallel can't be negative")
            if self.strategy.on_failure not in ("", "fail_all",
                                                "fail_local"):
                errs.append(f"invalid on_failure "
                            f"{self.strategy.on_failure!r}")
        return errs


@dataclass
class Job:
    """The unit of submission (structs.go Job:3805)."""
    id: str = ""
    name: str = ""
    namespace: str = DEFAULT_NAMESPACE
    region: str = "global"
    type: str = JOB_TYPE_SERVICE
    priority: int = JOB_DEFAULT_PRIORITY
    all_at_once: bool = False
    datacenters: List[str] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    affinities: List[Affinity] = field(default_factory=list)
    spreads: List[Spread] = field(default_factory=list)
    task_groups: List[TaskGroup] = field(default_factory=list)
    update: Optional[UpdateStrategy] = None
    multiregion: Optional[Multiregion] = None
    periodic: Optional[PeriodicConfig] = None
    parameterized_job: Optional[ParameterizedJobConfig] = None
    dispatched: bool = False
    payload: bytes = b""
    meta: Dict[str, str] = field(default_factory=dict)
    consul_token: str = ""
    vault_token: str = ""
    stop: bool = False
    parent_id: str = ""
    stable: bool = False
    version: int = 0
    status: str = JOB_STATUS_PENDING
    status_description: str = ""
    create_index: int = 0
    modify_index: int = 0
    job_modify_index: int = 0
    submit_time: int = 0

    # -- lifecycle -----------------------------------------------------
    def canonicalize(self) -> None:
        if not self.id:
            self.id = generate_uuid()
        if not self.name:
            self.name = self.id
        if not self.namespace:
            self.namespace = DEFAULT_NAMESPACE
        if self.priority == 0:
            self.priority = JOB_DEFAULT_PRIORITY
        if self.multiregion is not None:
            self.multiregion.canonicalize()
        for tg in self.task_groups:
            tg.canonicalize(self)

    def validate(self) -> List[str]:
        errs = []
        if not self.id:
            errs.append("missing job ID")
        elif " " in self.id:
            errs.append("job ID contains a space")
        if not self.name:
            errs.append("missing job name")
        if self.type not in (JOB_TYPE_SERVICE, JOB_TYPE_BATCH, JOB_TYPE_SYSTEM, JOB_TYPE_CORE):
            errs.append(f"invalid job type: {self.type}")
        if self.priority < JOB_MIN_PRIORITY or self.priority > JOB_MAX_PRIORITY:
            errs.append(f"job priority must be between [{JOB_MIN_PRIORITY}, {JOB_MAX_PRIORITY}]")
        # multiregion jobs may omit datacenters — each region entry
        # supplies its own (structs.go:4039)
        if not self.datacenters and self.multiregion is None:
            errs.append("missing job datacenters")
        if self.multiregion is not None:
            errs.extend(self.multiregion.validate())
        if not self.task_groups:
            errs.append("missing job task groups")
        names = set()
        for tg in self.task_groups:
            if tg.name in names:
                errs.append(f"job task group {tg.name} defined multiple times")
            names.add(tg.name)
            errs.extend(tg.validate(self))
        for c in self.constraints:
            errs.extend(c.validate())
        for s in self.spreads:
            errs.extend(s.validate())
        if self.type == JOB_TYPE_SYSTEM:
            if self.affinities:
                errs.append("system jobs may not have an affinity stanza")
            if self.spreads:
                errs.append("system jobs may not have a spread stanza")
        if self.periodic is not None and self.periodic.enabled:
            # structs.go:4126 — periodic only with the batch scheduler
            if self.type != JOB_TYPE_BATCH:
                errs.append(
                    f"periodic can only be used with {JOB_TYPE_BATCH!r} jobs")
            if self.periodic.timezone not in ("", "UTC", "Etc/UTC"):
                errs.append("periodic timezone must be UTC")
            if self.periodic.spec_type != "cron":
                errs.append(
                    f"unknown periodic spec type {self.periodic.spec_type!r}")
            else:
                from ..utils.cron import Cron, CronParseError
                try:
                    Cron(self.periodic.spec)
                except CronParseError as e:
                    errs.append(f"invalid cron spec: {e}")
        if self.periodic is not None and self.periodic.enabled \
                and self.parameterized_job is not None:
            errs.append("a job cannot be both periodic and parameterized")
        if self.parameterized_job is not None:
            # structs.go:4137 — parameterized only with the batch scheduler
            if self.type != JOB_TYPE_BATCH:
                errs.append(
                    f"parameterized job can only be used with "
                    f"{JOB_TYPE_BATCH!r} jobs")
            if self.parameterized_job.payload not in (
                    "optional", "required", "forbidden"):
                errs.append(
                    f"invalid parameterized payload mode "
                    f"{self.parameterized_job.payload!r}")
        return errs

    # -- queries -------------------------------------------------------
    def namespaced_id(self):
        return (self.namespace, self.id)

    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.task_groups:
            if tg.name == name:
                return tg
        return None

    def stopped(self) -> bool:
        return self.stop

    def is_periodic(self) -> bool:
        return self.periodic is not None and self.periodic.enabled

    def is_parameterized(self) -> bool:
        return self.parameterized_job is not None and not self.dispatched

    def copy(self) -> "Job":
        # deep copy via the wire codec: cheap and always in sync with fields
        from ..utils.codec import to_wire, from_wire
        return from_wire(Job, to_wire(self))

    def specchanged(self, other: "Job") -> bool:
        """Whether non-bookkeeping spec fields differ (structs.go Job.SpecChanged)."""
        from ..utils.codec import to_wire
        a, b = to_wire(self), to_wire(other)
        for skip in ("status", "status_description", "stable", "version",
                     "create_index", "modify_index", "job_modify_index",
                     "submit_time"):
            a.pop(skip, None)
            b.pop(skip, None)
        return a != b
