"""Evaluation — the unit of scheduling work.

Reference semantics: nomad/structs/structs.go Evaluation:9928.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..utils.ids import generate_uuid
from .alloc import AllocMetric

EVAL_STATUS_PENDING = "pending"
EVAL_STATUS_BLOCKED = "blocked"
EVAL_STATUS_COMPLETE = "complete"
EVAL_STATUS_FAILED = "failed"
EVAL_STATUS_CANCELED = "canceled"

TRIGGER_JOB_REGISTER = "job-register"
TRIGGER_JOB_DEREGISTER = "job-deregister"
TRIGGER_PERIODIC_JOB = "periodic-job"
TRIGGER_NODE_DRAIN = "node-drain"
TRIGGER_NODE_UPDATE = "node-update"
TRIGGER_ALLOC_STOP = "alloc-stop"
TRIGGER_SCHEDULED = "scheduled"
TRIGGER_ROLLING_UPDATE = "rolling-update"
TRIGGER_DEPLOYMENT_WATCHER = "deployment-watcher"
TRIGGER_FAILED_FOLLOW_UP = "failed-follow-up"
TRIGGER_MAX_PLANS = "max-plan-attempts"
TRIGGER_ALLOC_FAILURE = "alloc-failure"
TRIGGER_RETRY_FAILED_ALLOC = "alloc-failure"
TRIGGER_QUEUED_ALLOCS = "queued-allocs"
TRIGGER_PREEMPTION = "preemption"
TRIGGER_JOB_SCALE = "job-scaling"

CORE_JOB_EVAL_GC = "eval-gc"
CORE_JOB_NODE_GC = "node-gc"
CORE_JOB_JOB_GC = "job-gc"
CORE_JOB_DEPLOYMENT_GC = "deployment-gc"
CORE_JOB_CSI_VOLUME_CLAIM_GC = "csi-volume-claim-gc"
CORE_JOB_FORCE_GC = "force-gc"


@dataclass
class Evaluation:
    id: str = field(default_factory=generate_uuid)
    namespace: str = "default"
    priority: int = 50
    type: str = "service"            # job type / scheduler type
    triggered_by: str = ""
    job_id: str = ""
    job_modify_index: int = 0
    node_id: str = ""
    node_modify_index: int = 0
    deployment_id: str = ""
    status: str = EVAL_STATUS_PENDING
    status_description: str = ""
    wait_s: float = 0.0              # delay before processing (failed follow-up)
    wait_until: float = 0.0          # unix seconds; delayed reschedule
    next_eval: str = ""
    previous_eval: str = ""
    blocked_eval: str = ""
    related_evals: List[str] = field(default_factory=list)
    failed_tg_allocs: Dict[str, AllocMetric] = field(default_factory=dict)
    class_eligibility: Dict[str, bool] = field(default_factory=dict)
    escaped_computed_class: bool = False
    quota_limit_reached: str = ""
    annotate_plan: bool = False
    queued_allocations: Dict[str, int] = field(default_factory=dict)
    leader_acl: str = ""
    snapshot_index: int = 0
    create_index: int = 0
    modify_index: int = 0
    create_time: int = 0
    modify_time: int = 0

    def terminal_status(self) -> bool:
        return self.status in (EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED,
                               EVAL_STATUS_CANCELED)

    def should_enqueue(self) -> bool:
        return self.status == EVAL_STATUS_PENDING

    def should_block(self) -> bool:
        return self.status == EVAL_STATUS_BLOCKED

    def copy(self) -> "Evaluation":
        from ..utils.codec import to_wire, from_wire
        return from_wire(Evaluation, to_wire(self))

    def make_plan(self, job):
        from .plan import Plan
        return Plan(
            eval_id=self.id,
            priority=self.priority if job is None else job.priority,
            job=job,
            all_at_once=False if job is None else job.all_at_once,
        )

    def next_rolling_eval(self, wait_s: float) -> "Evaluation":
        """Create the eval for the next rolling-update batch
        (structs.go Evaluation.NextRollingEval)."""
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=TRIGGER_ROLLING_UPDATE,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_PENDING,
            wait_s=wait_s,
            previous_eval=self.id,
        )

    def create_blocked_eval(self, class_eligibility: Dict[str, bool],
                            escaped: bool, quota_reached: str) -> "Evaluation":
        """structs.go Evaluation.CreateBlockedEval."""
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=TRIGGER_QUEUED_ALLOCS,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_BLOCKED,
            previous_eval=self.id,
            class_eligibility=class_eligibility,
            escaped_computed_class=escaped,
            quota_limit_reached=quota_reached,
        )

    def create_failed_follow_up_eval(self, wait_s: float) -> "Evaluation":
        """structs.go Evaluation.CreateFailedFollowUpEval."""
        return Evaluation(
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=TRIGGER_FAILED_FOLLOW_UP,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_PENDING,
            wait_s=wait_s,
            previous_eval=self.id,
        )

    def stub(self) -> dict:
        return {
            "id": self.id, "priority": self.priority, "type": self.type,
            "triggered_by": self.triggered_by, "job_id": self.job_id,
            "node_id": self.node_id, "deployment_id": self.deployment_id,
            "status": self.status, "previous_eval": self.previous_eval,
            "next_eval": self.next_eval, "blocked_eval": self.blocked_eval,
            "create_index": self.create_index, "modify_index": self.modify_index,
        }
