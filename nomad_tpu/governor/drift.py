"""Rolling-window drift detection over throughput / p99 / RSS.

The detector answers the question the round-5 soak raised: "the
process was fast an hour ago and is slow now — what grew?" Each
tracked metric keeps a bounded rolling window of (t, value) samples; a
least-squares slope plus a last-half/first-half ratio classify the
series as flat or drifting. When a performance series (p99 up,
throughput down, RSS up) drifts, the detector names the registered
structure gauge whose own normalized growth over the same window is
largest — the structure most likely responsible — in the emitted
event. Pure functions over explicit samples, so synthetic series test
it without a clock.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Optional, Tuple
from ..utils.locks import make_lock

# direction a metric degrades in: p99/rss degrade upward, throughput
# degrades downward
DEGRADES_UP = "up"
DEGRADES_DOWN = "down"


def least_squares_slope(points: List[Tuple[float, float]]) -> float:
    """Slope of a least-squares fit over (t, value) points, in
    value-units per t-unit. Shared by the drift detector and the soak
    verdict (bench/soak.py) so the regression math exists once."""
    n = len(points)
    if n < 2:
        return 0.0
    mt = sum(t for t, _ in points) / n
    mv = sum(v for _, v in points) / n
    num = sum((t - mt) * (v - mv) for t, v in points)
    den = sum((t - mt) ** 2 for t, _ in points)
    if den <= 0:
        return 0.0
    return num / den


class RollingSeries:
    """Bounded (t, value) window with slope and half-over-half ratio."""

    def __init__(self, maxlen: int = 60):
        self._q: deque = deque(maxlen=maxlen)
        self._l = make_lock()

    def add(self, t: float, value: float) -> None:
        with self._l:
            self._q.append((float(t), float(value)))

    def __len__(self) -> int:
        return len(self._q)

    def samples(self) -> List[Tuple[float, float]]:
        with self._l:
            return list(self._q)

    def last(self) -> Optional[float]:
        with self._l:
            return self._q[-1][1] if self._q else None

    def slope_per_hour(self) -> float:
        """Least-squares slope in value-units per hour (t is seconds)."""
        return least_squares_slope(self.samples()) * 3600.0

    def ratio(self) -> float:
        """Mean of the last half over mean of the first half (>=0).
        1.0 == flat; 2.0 == doubled across the window."""
        pts = [v for _, v in self.samples()]
        n = len(pts)
        if n < 4:
            return 1.0
        half = n // 2
        first = sum(pts[:half]) / half
        last = sum(pts[n - half:]) / half
        if first <= 0:
            # a zero first half means "no signal yet" (empty latency
            # reservoir, idle counter), not an infinite degradation
            return 1.0
        return last / first


class DriftDetector:
    """Tracks performance series and structure-size series; check()
    returns structured drift findings."""

    def __init__(self, window: int = 60, min_samples: int = 10,
                 ratio_max: float = 1.5):
        self.window = window
        self.min_samples = min_samples
        self.ratio_max = ratio_max          # degradation ratio threshold
        # name -> (series, degrade direction)
        self._perf: Dict[str, Tuple[RollingSeries, str]] = {}
        # name -> series of structure sizes (suspects)
        self._structs: Dict[str, RollingSeries] = {}
        self._l = make_lock()

    # -- feeding -------------------------------------------------------
    def observe_perf(self, name: str, t: float, value: float,
                     degrades: str = DEGRADES_UP) -> None:
        with self._l:
            entry = self._perf.get(name)
            if entry is None:
                entry = (RollingSeries(self.window), degrades)
                self._perf[name] = entry
        entry[0].add(t, value)

    def observe_struct(self, name: str, t: float, value: float) -> None:
        with self._l:
            s = self._structs.get(name)
            if s is None:
                s = RollingSeries(self.window)
                self._structs[name] = s
        s.add(t, value)

    # -- checking ------------------------------------------------------
    def _suspect(self) -> Optional[Tuple[str, float]]:
        """The structure with the largest half-over-half growth ratio
        (> 1.05, i.e. actually growing), or None."""
        best = None
        with self._l:
            structs = list(self._structs.items())
        for name, series in structs:
            if len(series) < 4:
                continue
            r = series.ratio()
            if r <= 1.05:
                continue
            if best is None or r > best[1]:
                best = (name, r)
        return best

    def check(self) -> List[dict]:
        """Drift findings for every degrading performance series."""
        findings: List[dict] = []
        with self._l:
            perf = list(self._perf.items())
        for name, (series, degrades) in perf:
            if len(series) < self.min_samples:
                continue
            r = series.ratio()
            drifting = (r >= self.ratio_max if degrades == DEGRADES_UP
                        else (r > 0 and 1.0 / r >= self.ratio_max))
            if not drifting:
                continue
            finding = {
                "kind": "drift",
                "metric": name,
                "ratio": round(r, 3),
                "slope_per_hour": round(series.slope_per_hour(), 3),
                "degrades": degrades,
            }
            suspect = self._suspect()
            if suspect is not None:
                finding["suspect_structure"] = suspect[0]
                finding["suspect_growth_ratio"] = round(suspect[1], 3)
            findings.append(finding)
        return findings
