"""Steady-state governor: runtime resource governance, drift
detection, and backpressure.

The round-5 soak (SOAK_r05.json) showed the system does not hold its
numbers over time: service p99 drifted 69.5 -> 208 ms, placement
throughput decayed ~3.4x and RSS grew at ~875 MB/hour. The reference
Nomad keeps long-running servers flat with an auxiliary
runtime-governance layer (leader GC in nomad/core_sched.go, broker and
plan-queue EmitStats loops); this package is that layer for the
repo's long-lived structures:

  accounting  -- GaugeRegistry: every long-lived structure (state
                 store tables, broker queues, event buffers, kernel
                 caches) registers a size gauge, sampled on a cadence
                 alongside process RSS and GC counters.
  bounding    -- WatermarkPolicy per structure: crossing the high
                 watermark triggers targeted, rate-limited reclamation
                 (store layer compaction, event-buffer truncation,
                 kernel-cache eviction) instead of unbounded growth.
  backpressure-- when sampled service p99 or queue depth crosses its
                 watermark the eval broker sheds new work onto an
                 admission-controlled requeue path and workers shrink
                 batch lanes, recovering when the gauge clears.
  drift       -- DriftDetector: rolling-window regression over
                 throughput/p99/RSS emits structured `governor` events
                 naming the structure whose growth best explains the
                 drift (surfaced via /v1/operator/governor, /v1/metrics
                 counters, and `operator debug` archives).
"""

from .drift import DriftDetector, RollingSeries
from .governor import Governor
from .policy import WatermarkPolicy
from .registry import GaugeRegistry, Registration

__all__ = [
    "DriftDetector",
    "GaugeRegistry",
    "Governor",
    "Registration",
    "RollingSeries",
    "WatermarkPolicy",
]
