"""Watermark policies: the bounding rule attached to a registered gauge.

A policy is a high/low watermark pair with hysteresis: the structure is
OVER once its gauge reaches `high` and stays over until the gauge falls
back to `low` (default 80% of high), so a gauge oscillating around the
threshold doesn't flap reclamation or backpressure on and off every
sample. Reclamation is additionally rate-limited by
`min_reclaim_interval_s` — reclaim work (layer folds, cache clears)
must never itself become the latency problem it exists to prevent.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

STATUS_OK = "ok"
STATUS_OVER = "over"


@dataclass
class WatermarkPolicy:
    high: float
    low: Optional[float] = None          # default: 0.8 * high
    min_reclaim_interval_s: float = 5.0
    # gauges that participate in admission control (broker depth,
    # service p99): crossing high engages backpressure as well as any
    # reclaim callback
    pressure: bool = False
    # watermark only applies once the gauge is backed by at least this
    # many observations (the p99 gauge is meaningless off two samples)
    min_samples: int = 0

    def __post_init__(self):
        if self.low is None:
            self.low = 0.8 * self.high
        if self.low > self.high:
            raise ValueError(
                f"low watermark {self.low} above high {self.high}")

    def next_status(self, prev: str, value: float) -> str:
        """Hysteresis step: over at >= high, ok again only at <= low."""
        if prev == STATUS_OVER:
            return STATUS_OK if value <= self.low else STATUS_OVER
        return STATUS_OVER if value >= self.high else STATUS_OK
