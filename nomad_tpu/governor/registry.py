"""Gauge registry: the accounting half of the governor.

Long-lived structures register a zero-argument gauge function (current
size in its unit — entries or bytes) plus an optional WatermarkPolicy
and reclaim callback. sample() reads every gauge, publishes it to the
process metrics registry under `nomad.governor.<name>` (so /v1/metrics
carries the full accounting picture), steps each watermark's
hysteresis state, and runs due reclaims rate-limited per policy. A
gauge or reclaim that raises is isolated — one broken structure must
not blind the governor to the rest.
"""

from __future__ import annotations

import logging
import time
from typing import Callable, Dict, List, Optional

from ..utils import metrics
from .policy import STATUS_OK, STATUS_OVER, WatermarkPolicy
from ..utils.locks import make_lock

LOG = logging.getLogger("nomad_tpu.governor")


class Registration:
    __slots__ = ("name", "gauge_fn", "watermark", "reclaim", "unit",
                 "suspect", "value", "status", "samples", "reclaims",
                 "last_reclaim_t", "errors")

    def __init__(self, name: str, gauge_fn: Callable[[], float],
                 watermark: Optional[WatermarkPolicy] = None,
                 reclaim: Optional[Callable[[], object]] = None,
                 unit: str = "count", suspect: bool = True):
        self.name = name
        self.gauge_fn = gauge_fn
        self.watermark = watermark
        self.reclaim = reclaim
        self.unit = unit
        # eligible as a drift-finding suspect: False for monotone
        # counters and performance gauges, whose unbounded "growth"
        # would always out-rank the actually leaking structure
        self.suspect = suspect
        self.value: float = 0.0
        self.status: str = STATUS_OK
        self.samples: int = 0
        self.reclaims: int = 0
        # -inf: the FIRST over-watermark reclaim must never be rate
        # limited by the epoch of the monotonic clock
        self.last_reclaim_t: float = float("-inf")
        self.errors: int = 0

    def as_dict(self) -> dict:
        out = {"name": self.name, "value": self.value, "unit": self.unit,
               "status": self.status, "samples": self.samples,
               "reclaims": self.reclaims, "errors": self.errors}
        if self.watermark is not None:
            out["high"] = self.watermark.high
            out["low"] = self.watermark.low
            out["pressure"] = self.watermark.pressure
        return out


class GaugeRegistry:
    def __init__(self):
        self._l = make_lock()
        self._regs: Dict[str, Registration] = {}

    def register(self, name: str, gauge_fn: Callable[[], float],
                 watermark: Optional[WatermarkPolicy] = None,
                 reclaim: Optional[Callable[[], object]] = None,
                 unit: str = "count",
                 suspect: bool = True) -> Registration:
        reg = Registration(name, gauge_fn, watermark, reclaim, unit,
                           suspect)
        with self._l:
            self._regs[name] = reg
        return reg

    def deregister(self, name: str) -> None:
        with self._l:
            self._regs.pop(name, None)

    def get(self, name: str) -> Optional[Registration]:
        with self._l:
            return self._regs.get(name)

    def names(self) -> List[str]:
        with self._l:
            return sorted(self._regs)

    def rows(self) -> List[dict]:
        with self._l:
            regs = list(self._regs.values())
        return [r.as_dict() for r in sorted(regs, key=lambda r: r.name)]

    def force_reclaim(self, name: Optional[str] = None,
                      on_event: Optional[Callable[[dict], None]] = None
                      ) -> List[dict]:
        """Run registered reclaim callbacks NOW, watermark state and
        rate limit bypassed — the chaos governor-pressure fault
        (ISSUE 15) and operator tooling. `name=None` fires every
        reclaimable registration; returns one event dict per reclaim
        that ran (same shape the watermark path emits)."""
        with self._l:
            regs = [r for r in self._regs.values()
                    if r.reclaim is not None
                    and (name is None or r.name == name)]
        fired: List[dict] = []
        for reg in regs:
            try:
                detail = reg.reclaim()
                reg.reclaims += 1
                reg.last_reclaim_t = time.monotonic()
                metrics.incr_counter(
                    f"nomad.governor.reclaim.{reg.name}")
                ev = {"kind": "reclaim", "structure": reg.name,
                      "value": reg.value, "forced": True,
                      "detail": detail}
                fired.append(ev)
                if on_event is not None:
                    on_event(ev)
            except Exception:
                reg.errors += 1
                LOG.exception("forced reclaim %s failed", reg.name)
        return fired

    # -- sampling ------------------------------------------------------
    def sample(self, now: Optional[float] = None,
               on_event: Optional[Callable[[dict], None]] = None
               ) -> List[Registration]:
        """Read every gauge, publish metrics, step watermark states and
        run due reclaims. Returns the registrations (with fresh
        .value/.status) for the caller's backpressure/drift logic."""
        now = time.monotonic() if now is None else now
        with self._l:
            regs = list(self._regs.values())
        for reg in regs:
            try:
                reg.value = float(reg.gauge_fn())
            except Exception:
                reg.errors += 1
                if reg.errors <= 3:
                    LOG.exception("governor gauge %s failed", reg.name)
                continue
            reg.samples += 1
            metrics.set_gauge(f"nomad.governor.{reg.name}", reg.value)
            wm = reg.watermark
            if wm is None or reg.samples < wm.min_samples:
                continue
            prev = reg.status
            reg.status = wm.next_status(prev, reg.value)
            if reg.status == STATUS_OVER and prev == STATUS_OK \
                    and on_event is not None:
                on_event({"kind": "watermark", "structure": reg.name,
                          "value": reg.value, "high": wm.high})
            if reg.status == STATUS_OVER and reg.reclaim is not None \
                    and now - reg.last_reclaim_t >= \
                    wm.min_reclaim_interval_s:
                reg.last_reclaim_t = now
                try:
                    detail = reg.reclaim()
                    reg.reclaims += 1
                    metrics.incr_counter(
                        f"nomad.governor.reclaim.{reg.name}")
                    if on_event is not None:
                        on_event({"kind": "reclaim",
                                  "structure": reg.name,
                                  "value": reg.value,
                                  "detail": detail})
                except Exception:
                    reg.errors += 1
                    LOG.exception("governor reclaim %s failed", reg.name)
        return regs
