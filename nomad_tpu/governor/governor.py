"""The governor: sampler loop + backpressure signal + event log.

One Governor per server (or per bench harness). It owns the
GaugeRegistry and DriftDetector, keeps a bounded reservoir of recent
eval latencies (the sampled service p99 that the backpressure rule and
drift detector read), and exposes:

  sample_once()        -- one accounting/bounding/drift step; the
                          background thread calls it on the cadence,
                          benches call it explicitly for determinism
  observe_eval_latency -- workers report per-eval scheduling latency
  backpressure()       -- admission-control signal: True while any
                          pressure-marked gauge (queue depth, p99) is
                          over its watermark; the eval broker's shed
                          path and the workers' lane shrink read this
  status()             -- full structured state for
                          /v1/operator/governor and `operator governor`

Structured events (watermark crossings, reclaims, drift findings) land
in a bounded ring surfaced by status() and counted in /v1/metrics as
`nomad.governor.events`; `operator debug` archives capture status()
alongside the metrics time series.
"""

from __future__ import annotations

import gc
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..utils import metrics
from .drift import DEGRADES_DOWN, DEGRADES_UP, DriftDetector
from .policy import STATUS_OVER, WatermarkPolicy
from .registry import GaugeRegistry, Registration
from ..utils.locks import make_lock

EVENT_LOG_MAX = 256
LATENCY_RESERVOIR = 2048


def rss_mb() -> float:
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) / 1024.0
    except OSError:
        pass
    return 0.0


class Governor:
    def __init__(self, interval_s: float = 1.0,
                 drift_window: int = 120, drift_min_samples: int = 30,
                 drift_ratio_max: float = 1.5,
                 drift_check_every: int = 10):
        self.interval_s = interval_s
        self.registry = GaugeRegistry()
        self.drift = DriftDetector(window=drift_window,
                                   min_samples=drift_min_samples,
                                   ratio_max=drift_ratio_max)
        self._drift_check_every = max(1, drift_check_every)
        self._bp = threading.Event()
        self._events: deque = deque(maxlen=EVENT_LOG_MAX)
        self._events_l = make_lock()
        self._lat: deque = deque(maxlen=LATENCY_RESERVOIR)
        # full latency incl. broker queue wait — attribution/bench
        # percentiles only, never the backpressure gauge (see
        # observe_eval_latency)
        self._lat_full: deque = deque(maxlen=LATENCY_RESERVOIR)
        self._lat_l = make_lock()
        self._evals_observed = 0
        self._last_lat_t = 0.0          # monotonic of newest latency
        self._last_throughput_mark = (0, 0.0)  # (evals, monotonic)
        self._samples = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.started_at = time.time()
        # drift-finding subscribers (ISSUE 9 satellite): the server
        # auto-pins the flight recorder's exemplar set when a finding
        # names a suspect structure — the span trees that existed when
        # the drift was detected ARE the capture worth keeping. Hooks
        # run on the sampler thread; exceptions are isolated.
        self.drift_hooks: List[Callable[[dict], None]] = []
        # named extra sections merged into status() (e.g. the race
        # sanitizer's `locks` block with worst-holder exemplars); a
        # section that raises is dropped, not fatal
        self.extra_status: Dict[str, Callable[[], object]] = {}

    # -- registration proxy -------------------------------------------
    def register(self, name: str,
                 gauge_fn: Callable[[], float],
                 watermark: Optional[WatermarkPolicy] = None,
                 reclaim: Optional[Callable[[], object]] = None,
                 unit: str = "count",
                 suspect: bool = True) -> Registration:
        return self.registry.register(name, gauge_fn, watermark,
                                      reclaim, unit, suspect)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="governor")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=2)

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.sample_once()
            except Exception:
                import logging
                logging.getLogger("nomad_tpu.governor").exception(
                    "governor sample failed")

    # -- observations --------------------------------------------------
    def observe_eval_latency(self, seconds: float,
                             queue_wait_s: float = 0.0) -> None:
        """`seconds` is the HOST processing latency — it feeds the
        backpressure p99 gauge, whose meaning is "the host is the
        bottleneck" (lane shrink + admission shed react to it).
        `queue_wait_s` is broker READY-queue wait: it joins only the
        FULL-latency reservoir (latency_percentile_ms — what an eval
        actually experienced, the bench/attribution number). Folding
        wait into the pressure gauge would be a positive feedback
        loop: a backlog inflates p99, p99 sheds enqueues and shrinks
        lanes, the queue deepens, p99 inflates further."""
        with self._lat_l:
            self._lat.append(seconds * 1000.0)
            self._lat_full.append((seconds + max(queue_wait_s, 0.0))
                                  * 1000.0)
            self._evals_observed += 1
            self._last_lat_t = time.monotonic()

    # the sampled p99 reads the most RECENT slice of the reservoir, so
    # cold-start JIT compiles (seconds each) age out of the gauge once
    # warm traffic flows instead of pinning it over the watermark for
    # the reservoir's whole lifetime
    P99_WINDOW = 512
    # p99 readings older than this are not load evidence: while
    # backpressure sheds enqueues the workers go idle, no new
    # latencies arrive, and a frozen over-watermark p99 would latch
    # admission control shut forever. A stale reservoir reads as "no
    # recent traffic", the gauge drops to 0, hysteresis releases, and
    # the parked evals re-admit (re-engaging only if still slow).
    P99_STALE_S = 10.0

    def recent_p99_ms(self) -> float:
        """The p99 gauge for watermark/backpressure decisions: the
        reservoir p99 while latencies are flowing, 0.0 once the
        newest sample is older than P99_STALE_S."""
        with self._lat_l:
            if not self._lat or \
                    time.monotonic() - self._last_lat_t > self.P99_STALE_S:
                return 0.0
        return self.p99_ms()

    def p99_ms(self) -> float:
        with self._lat_l:
            lat = list(self._lat)[-self.P99_WINDOW:]
        if not lat:
            return 0.0
        lat.sort()
        return lat[min(len(lat) - 1, int(0.99 * len(lat)))]

    def latency_samples(self) -> int:
        with self._lat_l:
            return len(self._lat)

    def latency_percentile_ms(self, pct: float,
                              window: Optional[int] = None) -> float:
        """Arbitrary percentile over the most recent `window` FULL
        latency samples — host processing PLUS broker queue wait, what
        an eval actually experienced (the bench reads p50/p99 of this
        for the micro-batch on/off comparison). Distinct from the
        host-only reservoir behind the backpressure p99 gauge."""
        with self._lat_l:
            lat = list(self._lat_full)
        if window is not None:
            lat = lat[-window:]
        if not lat:
            return 0.0
        lat.sort()
        return lat[min(len(lat) - 1, int(pct / 100.0 * len(lat)))]

    # -- events --------------------------------------------------------
    def emit(self, event: dict) -> None:
        event = dict(event, ts=time.time())
        with self._events_l:
            self._events.append(event)
        metrics.incr_counter("nomad.governor.events")
        kind = event.get("kind", "event")
        metrics.incr_counter(f"nomad.governor.events.{kind}")

    def events(self, limit: int = 50) -> List[dict]:
        with self._events_l:
            out = list(self._events)
        return out[-limit:]

    # -- the sampling step ---------------------------------------------
    def sample_once(self, now: Optional[float] = None) -> List[Registration]:
        now = time.monotonic() if now is None else now
        regs = self.registry.sample(now=now, on_event=self.emit)

        # process-level gauges ride every sample
        rss = rss_mb()
        metrics.set_gauge("nomad.governor.process.rss_mb", rss)
        counts = gc.get_count()
        metrics.set_gauge("nomad.governor.process.gc_gen0", counts[0])
        # raw reservoir p99, distinct from nomad.governor.service.p99_ms
        # (the registered gauge's key, gated on warm-up/staleness) —
        # one name must not carry two disagreeing values
        p99 = self.p99_ms()
        metrics.set_gauge("nomad.governor.service.p99_raw_ms", p99)

        # backpressure: any pressure-marked gauge over its watermark
        over = [r for r in regs
                if r.watermark is not None and r.watermark.pressure
                and r.status == STATUS_OVER]
        was = self._bp.is_set()
        if over and not was:
            self._bp.set()
            self.emit({"kind": "backpressure", "state": "engaged",
                       "structure": over[0].name,
                       "value": over[0].value})
        elif not over and was:
            self._bp.clear()
            self.emit({"kind": "backpressure", "state": "released"})
        metrics.set_gauge("nomad.governor.backpressure",
                          1.0 if self._bp.is_set() else 0.0)

        # drift series: p99 up = bad, throughput down = bad, rss up =
        # bad. p99 joins only once latencies exist — zeros are "no
        # traffic yet", and mixing them in fabricates a drift edge
        if p99 > 0:
            self.drift.observe_perf("service.p99_ms", now, p99,
                                    DEGRADES_UP)
        self.drift.observe_perf("process.rss_mb", now, rss, DEGRADES_UP)
        with self._lat_l:
            evals = self._evals_observed
        last_evals, last_t = self._last_throughput_mark
        if last_t > 0 and now > last_t:
            thr = (evals - last_evals) / (now - last_t)
            metrics.set_gauge("nomad.governor.throughput_eps", thr)
            if evals > last_evals:
                self.drift.observe_perf("throughput_eps", now, thr,
                                        DEGRADES_DOWN)
        self._last_throughput_mark = (evals, now)
        for reg in regs:
            if reg.suspect:
                self.drift.observe_struct(reg.name, now, reg.value)

        self._samples += 1
        if self._samples % self._drift_check_every == 0:
            for finding in self.drift.check():
                self.emit(finding)
                for hook in list(self.drift_hooks):
                    try:
                        hook(finding)
                    except Exception:   # pragma: no cover — defensive
                        import logging
                        logging.getLogger(
                            "nomad_tpu.governor").exception(
                            "drift hook failed")
        return regs

    # -- signals / status ----------------------------------------------
    def backpressure(self) -> bool:
        return self._bp.is_set()

    def force_reclaim(self, name: Optional[str] = None) -> List[dict]:
        """Drive registered reclaims immediately (chaos
        governor-pressure fault, ISSUE 15): every reclaimable
        structure when `name` is None. The reclaim events land in the
        governor event ring like watermark-driven ones, tagged
        forced=True."""
        return self.registry.force_reclaim(name, on_event=self.emit)

    def status(self) -> dict:
        out = {
            "enabled": True,
            "running": self._thread is not None,
            "interval_s": self.interval_s,
            "samples": self._samples,
            "backpressure": self._bp.is_set(),
            "service_p99_ms": round(self.p99_ms(), 2),
            "latency_samples": self.latency_samples(),
            "process_rss_mb": round(rss_mb(), 1),
            "gauges": self.registry.rows(),
            "events": self.events(),
        }
        for key, fn in list(self.extra_status.items()):
            try:
                out[key] = fn()
            except Exception:   # pragma: no cover — defensive
                pass
        return out
