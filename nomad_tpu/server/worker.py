"""Scheduling worker: dequeue -> snapshot fence -> scheduler.process ->
ack/nack. Implements the scheduler's Planner interface against the
server (plan queue + raft shim).

Reference semantics: nomad/worker.go — run:105-138, dequeueEvaluation:142,
snapshotMinIndex:228, invokeScheduler:244, SubmitPlan:277-343 (snapshot
index fencing + RefreshIndex handling), exponential backoff, pause
during leadership transitions.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import List, Optional

from ..models import Evaluation, JOB_TYPE_CORE, Plan, PlanResult
from ..scheduler import new_scheduler

LOG = logging.getLogger("nomad_tpu.worker")

BACKOFF_BASE_S = 0.05
BACKOFF_LIMIT_S = 3.0
DEQUEUE_TIMEOUT_S = 0.5
RAFT_SYNC_LIMIT = 10.0


class Worker:
    def __init__(self, server, enabled_schedulers: List[str], wid: int = 0):
        self.server = server
        self.schedulers = list(enabled_schedulers)
        self.id = wid
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # per-eval state while processing
        self._eval: Optional[Evaluation] = None
        self._token: str = ""
        self._snapshot_index = 0
        self.stats = {"processed": 0, "failed": 0}

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name=f"worker-{self.id}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    def set_pause(self, paused: bool) -> None:
        if paused:
            self._paused.set()
        else:
            self._paused.clear()

    def run(self) -> None:
        while not self._stop.is_set():
            if self._paused.is_set():
                time.sleep(0.05)
                continue
            # NOTE: workers never consume the failed queue — the leader's
            # reaper turns those into delayed follow-up evals
            # (leader.go reapFailedEvaluations:766 / Server._reap_failed_evals)
            ev, token = self.server.eval_broker.dequeue(
                self.schedulers, DEQUEUE_TIMEOUT_S)
            if ev is None:
                continue
            self.process_eval(ev, token)

    # -- single eval ---------------------------------------------------
    def process_eval(self, ev: Evaluation, token: str) -> None:
        from ..utils import metrics
        self._eval = ev
        self._token = token
        try:
            # wait for the state store to catch up to the eval
            t0 = time.monotonic()
            snap = self.server.store.snapshot_min_index(
                ev.modify_index, timeout_s=RAFT_SYNC_LIMIT)
            metrics.measure_since("nomad.worker.wait_for_index", t0)
            self._snapshot_index = snap.latest_index()
            if ev.type == JOB_TYPE_CORE:
                # worker.go invokeScheduler: _core evals get the GC
                # pseudo-scheduler, not a placement scheduler
                from .core_sched import CoreScheduler
                sched = CoreScheduler(snap, self.server)
            else:
                sched = new_scheduler(self._scheduler_for(ev), snap, self)
            t0 = time.monotonic()
            sched.process(ev)
            metrics.measure_since(
                f"nomad.worker.invoke_scheduler_{self._scheduler_for(ev)}"
                if ev.type != JOB_TYPE_CORE
                else "nomad.worker.invoke_scheduler_core", t0)
            self.server.eval_broker.ack(ev.id, token)
            self.stats["processed"] += 1
        except Exception:
            LOG.exception("worker %d: eval %s failed", self.id, ev.id)
            self.stats["failed"] += 1
            try:
                self.server.eval_broker.nack(ev.id, token)
            except Exception:
                pass
        finally:
            self._eval = None
            self._token = ""

    @staticmethod
    def _scheduler_for(ev: Evaluation) -> str:
        return ev.type if ev.type in ("service", "batch", "system") else "batch"

    # -- Planner interface --------------------------------------------
    def submit_plan(self, plan: Plan) -> Optional[PlanResult]:
        from ..utils import metrics
        t0 = time.monotonic()
        plan.eval_token = self._token
        plan.snapshot_index = self._snapshot_index
        future = self.server.plan_queue.enqueue(plan)
        result: PlanResult = future.result(timeout=30)
        metrics.measure_since("nomad.worker.submit_plan", t0)
        # if some placements were rejected, wait for the refresh index so
        # the next attempt sees why (worker.go:318-340)
        if result.refresh_index:
            self.server.store.block_min_index(result.refresh_index - 1,
                                              timeout_s=RAFT_SYNC_LIMIT)
        return result

    def refreshed_state(self, index: int):
        return self.server.store.snapshot_min_index(index,
                                                    timeout_s=RAFT_SYNC_LIMIT)

    def update_eval(self, ev: Evaluation) -> None:
        self.server.raft_apply("eval_update", dict(evals=[ev]))

    def create_eval(self, ev: Evaluation) -> None:
        ev.snapshot_index = self._snapshot_index
        self.server.raft_apply("eval_update", dict(evals=[ev]))

    def reblock_eval(self, ev: Evaluation) -> None:
        self.server.blocked_evals.block(ev)
