"""Scheduling worker: dequeue -> snapshot fence -> scheduler.process ->
ack/nack. Implements the scheduler's Planner interface against the
server (plan queue + raft shim).

Reference semantics: nomad/worker.go — run:105-138, dequeueEvaluation:142,
snapshotMinIndex:228, invokeScheduler:244, SubmitPlan:277-343 (snapshot
index fencing + RefreshIndex handling), exponential backoff, pause
during leadership transitions.

Multi-eval batching (SURVEY §2.6 row 1: "batch multiple evals per
device dispatch"): after a blocking dequeue lands one eval, the worker
drains up to eval_batch_size-1 more READY evals without waiting and
processes them as concurrent lanes whose kernel dispatches meet at a
BatchGateway barrier — one vmapped select_many per rendezvous instead
of one device round trip per eval. The broker's one-outstanding-per-job
invariant guarantees the lanes are distinct jobs; plans still serialize
through the plan applier.
"""

from __future__ import annotations

import logging
import threading
import time

from .. import trace
from ..chaos import faults as chaos_faults
from ..utils import gcsafe
from typing import List, Optional

from ..models import Evaluation, JOB_TYPE_CORE, Plan, PlanResult
from ..rpc.codec import RpcError, RpcRefused
from ..scheduler import new_scheduler
from ..utils.locks import make_condition, make_lock

LOG = logging.getLogger("nomad_tpu.worker")

BACKOFF_BASE_S = 0.05
BACKOFF_LIMIT_S = 3.0
DEQUEUE_TIMEOUT_S = 0.5
RAFT_SYNC_LIMIT = 10.0
# micro-batch lane concurrency per worker: enough overlapping evals to
# feed the gateway's coalescing, few enough that GIL-sharing host
# phases don't inflate each other into the latency the gateway saves
MICRO_LANES = 4


class BatchGateway:
    """Rendezvous point turning concurrent per-lane kernel dispatches
    into one multi-eval device dispatch (ops/select.py select_many).

    Each lane is one in-flight eval. A lane interacts in exactly two
    ways: dispatch(req) — block until the coalesced result is ready —
    and lane_finished() when its eval completes. A batch fires when
    every still-active lane is parked in dispatch() (maximum width), or
    when the oldest parked request has waited out a short window —
    adaptive behavior: host-bound runs degrade toward per-eval
    dispatches instead of serializing behind stragglers, device-bound
    runs (short host phases) reach full width. Firing a partial batch
    is always safe: late lanes simply form the next batch."""

    WINDOW_S = 0.02

    def __init__(self, kernel, lanes: int, lane_base: int = 0,
                 lane_total: Optional[int] = None):
        self._kernel = kernel
        self._cv = make_condition()
        self._active = lanes
        # cross-worker decorrelation for batched lanes: each worker's
        # gateway slices the node hash space at an offset so two
        # workers' lane 0 don't fight over the same winners
        self._lane_base = lane_base
        self._lane_total = lane_total or lanes
        self._waiting: List = []        # [(req, slot_dict)]
        self._open_t = 0.0              # arrival of the oldest waiter
        self._part_cache = (None, None)  # (n, lanes) -> lane ids per node
        # rendezvous window scaled to the measured dispatch latency: on
        # a tunneled accelerator one round trip costs ~70-250 ms, so a
        # fixed 20 ms window never forms a batch there (VERDICT r4:
        # service_broker_batches=0) — waiting up to half an RTT to
        # share a dispatch is always worth it
        self.window_s = self.WINDOW_S
        try:
            import jax

            from ..ops.select import _accel_roundtrip_s
            if jax.default_backend() != "cpu":
                self.window_s = min(max(0.5 * _accel_roundtrip_s(),
                                        self.WINDOW_S), 0.15)
        except Exception:
            pass

    def dispatch(self, req):
        slot = {}
        with self._cv:
            if not self._waiting:
                self._open_t = time.monotonic()
            self._waiting.append((req, slot))
            self._fire_if_ready()
            while "out" not in slot:
                if self._waiting:
                    remaining = self.window_s - (time.monotonic()
                                                 - self._open_t)
                    if remaining <= 0:
                        self._fire()
                        continue
                    self._cv.wait(remaining)
                else:
                    self._cv.wait(0.5)
        out = slot["out"]
        if isinstance(out, Exception):
            raise out
        return out

    def lane_finished(self) -> None:
        with self._cv:
            self._active -= 1
            self._fire_if_ready()

    def _fire_if_ready(self) -> None:
        # cv held. Full width: every active lane is parked here, so no
        # later request can join this batch anyway.
        if not self._waiting or len(self._waiting) < self._active:
            return
        self._fire()

    def _fire(self) -> None:
        # cv held on entry; the kernel work runs with it RELEASED so
        # lanes that arrive mid-dispatch can enqueue (and other lanes'
        # host phases overlap the device round trip). Concurrent fires
        # are safe — each pops its own batch.
        batch, self._waiting = self._waiting, []
        if not batch:
            return
        reqs = [r for r, _ in batch]
        self._cv.release()
        try:
            try:
                originals = self._partition(reqs) if len(reqs) > 1 \
                    else None
                results = self._kernel.select_many(reqs)
                if originals is not None:
                    # a lane that could not fill its slice retries solo
                    # on the FULL node set — partitioning is a
                    # throughput heuristic and must never change
                    # failure semantics
                    for i, (req, res) in enumerate(zip(reqs, results)):
                        if originals[i] is not None and \
                                res.placed < req.count:
                            req.feasible = originals[i]
                            results[i] = self._kernel.select(req)
                outs = results
            except Exception as e:  # pragma: no cover - defensive
                outs = [e] * len(batch)
        finally:
            self._cv.acquire()
        for (_r, slot), res in zip(batch, outs):
            slot["out"] = res
        self._cv.notify_all()

    def _partition(self, reqs):
        """Decorrelate concurrent lanes (ops/select.partition_lanes:
        hash-partition + capacity-aware headroom, retry-on-shortfall
        semantics — one shared rule with the worker's solo
        decorrelation and the micro-batch gateway)."""
        from ..ops.select import partition_lanes
        originals, self._part_cache = partition_lanes(
            reqs, self._lane_base, self._lane_total, self._part_cache)
        return originals


class MicroBatchGateway:
    """Continuous micro-batching for eval kernel dispatches (ISSUE 7) —
    the LLM-inference-server shape applied to eval dispatch: concurrent
    evals' feasibility/rank requests accumulate in a lane for a short
    ADAPTIVE deadline and ship as one vmapped padded kernel call
    (ops/select.select_many), instead of each paying a full solo
    dispatch.

    One gateway per server (all workers and all their lane threads
    share it — unlike the per-drain BatchGateway rendezvous above,
    coalescing is continuous across dequeues and across workers).
    Triggers, in priority order:

      occupancy  len(waiting) >= gateway_min_batch (and a pipeline
                 slot is free): the batch is wide enough — fire now,
                 waiting longer only adds latency
      immediate  the cost model says batched dispatch doesn't pay at
                 this shape, or the lane is idle (nothing in flight
                 and the EWMA of inter-arrival gaps says no companion
                 is expected within the window): dispatch NOW,
                 protecting p99
      drain      an in-flight dispatch IS the window (continuous
                 batching): requests that arrived while the device was
                 busy park, and the moment the pipeline empties they
                 fire as one batch — self-clocking, so occupancy grows
                 with load and the added wait is bounded by a dispatch
                 the request could not have started anyway
      deadline   the oldest parked request waited out the adaptive
                 window while requests were streaming: fire whatever
                 accumulated (falls through SOLO when both pipeline
                 slots are busy, so the cap never wedges an eval)

    The window adapts in both directions: broker queue depth above
    `governor_gateway_depth_high` widens it (up to 4x — under a
    backlog, occupancy is worth more than per-eval latency) and a
    shallow queue decays it back; the governor's reclaim hook
    (widen_window) doubles it when the READY-depth watermark trips.
    Two-deep pipeline: at most MAX_INFLIGHT device batches are in
    flight — the condition variable is RELEASED around the kernel call
    (extending the r7 double-buffering), so later evals' host phases
    (reconcile, stack setup) overlap an in-flight device batch and
    accumulate the next one. A fire takes at most
    ops/select.GATEWAY_MAX_LANES requests (lane padding then lands on
    {2,4,8,16}, bounding trace signatures).

    Degeneration: `gateway_window_us=0` or NOMAD_TPU_MICROBATCH=0 mean
    the server never constructs a gateway and the worker path is
    exactly the pre-ISSUE-7 one."""

    MAX_INFLIGHT = 2        # two-deep dispatch pipeline
    SCALE_MAX = 4.0         # widest backpressure window multiplier
    GAP_ALPHA = 0.5         # inter-arrival EWMA: recover from an idle
                            # period within ~3 burst arrivals
    GAP_CAP_WINDOWS = 8.0   # idle gaps fold in capped at 8 windows
    STREAM_FACTOR = 2.0     # gap EWMA <= 2 windows == streaming
    STRAGGLER_GAPS = 4.0    # idle-engine wait bound in arrival gaps:
                            # if no companion shows within ~4 expected
                            # gaps the stream has ended — fire rather
                            # than pin the last eval of a burst to the
                            # full window (p99 protection)
    COST_TOLERANCE = 1.5    # coalesce unless the batched arm measures
                            # decisively slower (the per-lane EWMA
                            # folds widths: width 2 ~parity, width 8
                            # wins — strict < would flap batching off)

    def __init__(self, kernel=None, window_us: int = 2000,
                 min_batch: int = 4, depth_fn=None, depth_high: int = 0,
                 partition: bool = True):
        if kernel is None:
            from ..ops import SelectKernel
            kernel = SelectKernel()
        self._kernel = kernel
        self._cv = make_condition()
        self._waiting: List = []    # [[req, slot, arrival_t, decor]]
        self._inflight = 0
        self.min_batch = max(2, int(min_batch))
        self.partition = partition
        self._depth_fn = depth_fn
        self._depth_high = int(depth_high)
        self._scale = 1.0
        self._gap_ewma: Optional[float] = None
        self._last_arrival: Optional[float] = None
        self._dispatch_ewma = 0.0   # EWMA of fire wall clock: while a
        # dispatch is in flight, parked requests extend their deadline
        # to cover it — the drain trigger (not a premature solo
        # deadline fire) should collect them when the window is
        # shorter than one dispatch
        self._part_cache = (None, None)
        self._solo_decor_cache = (None, None)
        # rotating lane-partition offset: two batches fired while both
        # in flight must not hand their lane 0 the SAME hash slice of
        # the node table — they would argmax the same winners and
        # collide in the plan applier exactly like unpartitioned lanes
        self._part_rot = 0
        self.stats = {"requests": 0, "dispatches": 0, "batches": 0,
                      "lanes_sum": 0, "immediate_dispatches": 0,
                      "occupancy_dispatches": 0, "drain_dispatches": 0,
                      "deadline_dispatches": 0,
                      "wait_s_sum": 0.0, "partition_retries": 0}
        # window scaled to the measured dispatch latency, like the
        # rendezvous gateway: over a tunneled accelerator one round
        # trip costs ~70-250 ms and a ~2 ms window never forms a batch
        # there — waiting up to half an RTT to share a dispatch is
        # always worth it
        self.base_window_s = max(window_us, 0) / 1e6
        try:
            import jax

            from ..ops.select import _accel_roundtrip_s
            if jax.default_backend() != "cpu":
                self.base_window_s = min(
                    max(0.5 * _accel_roundtrip_s(), self.base_window_s),
                    0.15)
        except Exception:
            pass

    # -- window --------------------------------------------------------
    def window_s(self) -> float:
        return self.base_window_s * self._scale

    def window_us(self) -> float:
        return self.window_s() * 1e6

    def occupancy_mean(self) -> float:
        return self.stats["lanes_sum"] / max(self.stats["dispatches"], 1)

    def widen_window(self) -> dict:
        """Governor reclaim hook for the READY-depth watermark: under a
        queue backlog, a wider window buys occupancy (one padded
        dispatch for many evals) at the cost of per-eval wait — the
        right trade exactly when the queue, not the eval, dominates
        latency. Decays back via _adapt once the depth clears."""
        with self._cv:
            self._scale = min(self._scale * 2.0, self.SCALE_MAX)
            return {"window_us": round(self.window_us(), 1)}

    def _adapt(self) -> None:
        """Depth-coupled window adaptation (cv held): widen while the
        broker's READY depth is over `governor_gateway_depth_high`,
        decay back toward the configured target once the queue is
        shallow — idle lanes additionally dispatch immediately via the
        streaming test, so p99 is protected from both directions."""
        if self._depth_fn is None or self._depth_high <= 0:
            return
        try:
            depth = self._depth_fn()
        except Exception:       # pragma: no cover — defensive
            return
        if depth > self._depth_high:
            self._scale = min(self._scale * 1.5, self.SCALE_MAX)
        elif depth * 4 < self._depth_high and self._scale > 1.0:
            self._scale = max(self._scale * 0.75, 1.0)

    # -- arrival-rate model --------------------------------------------
    def _note_arrival(self, now: float) -> None:
        if self._last_arrival is not None:
            cap = self.GAP_CAP_WINDOWS * max(self.base_window_s, 1e-4)
            gap = min(now - self._last_arrival, cap)
            if self._gap_ewma is None:
                self._gap_ewma = gap
            else:
                self._gap_ewma += self.GAP_ALPHA * (gap - self._gap_ewma)
        self._last_arrival = now

    def _streaming(self) -> bool:
        """Are more requests expected within the window? Cold and idle
        lanes say no — their requests dispatch immediately instead of
        paying a window that nothing will share."""
        if self.window_s() <= 0:
            return False
        if self._gap_ewma is None:
            return False
        return self._gap_ewma <= self.STREAM_FACTOR * self.window_s()

    def _worth_waiting(self, req) -> bool:
        """Cost-model gate: coalescing pays where one batched dispatch
        beats per-lane solo dispatches within COST_TOLERANCE
        (measured, seeded by the startup calibration probe;
        exploration probes keep the batched side measured either
        way)."""
        try:
            return self._kernel.batch_dispatch_profitable(
                len(req.feasible), count_hint=max(req.count, 1),
                tolerance=self.COST_TOLERANCE)
        except Exception:       # pragma: no cover — defensive
            return True

    # -- dispatch ------------------------------------------------------
    def dispatch(self, req, decorrelate=None):
        """Block until this request's result is ready; requests that
        overlap in the window return from ONE coalesced select_many.
        `decorrelate` carries the worker's (lane, lanes) so solo fires
        keep the cross-worker hash-slice decorrelation the direct
        kernel path applies."""
        import time as _time
        slot: dict = {}
        now = _time.monotonic()
        # flight recorder (ISSUE 9): capture the DISPATCHING eval's
        # trace context now — the fire that eventually serves this
        # request runs on whichever thread triggered it, so the park
        # span must attach through the entry, not thread-locals
        entry = [req, slot, now, decorrelate, trace.current_all()]
        with self._cv:
            self._note_arrival(now)
            self._adapt()
            self.stats["requests"] += 1
            self._waiting.append(entry)
            worth = self._worth_waiting(req)
            if worth and len(self._waiting) >= self.min_batch and \
                    self._inflight < self.MAX_INFLIGHT:
                self._fire("occupancy")
            elif not worth or (self._inflight == 0
                               and not self._streaming()):
                self._fire("immediate")
            while "out" not in slot:
                if self._waiting:
                    if self._inflight == 0 and len(self._waiting) >= 2:
                        # the dispatch that just landed was this
                        # group's window: drain it as one batch
                        if self._fire("drain"):
                            continue
                    eff_window = self.window_s()
                    if self._inflight > 0:
                        # engine busy: don't deadline-fire a parked
                        # request solo moments before the in-flight
                        # dispatch would have drained it into a batch
                        eff_window = max(
                            eff_window,
                            min(self._dispatch_ewma * 2.0, 0.25))
                    elif self._gap_ewma is not None:
                        # engine idle: a companion is only expected
                        # within ~the arrival gap — when none shows in
                        # a few gaps the stream has ended, and the last
                        # eval of a burst must not eat the full window
                        eff_window = min(
                            eff_window,
                            max(self.STRAGGLER_GAPS * self._gap_ewma,
                                1e-4))
                    remaining = (self._waiting[0][2] + eff_window
                                 - _time.monotonic())
                    if remaining <= 0:
                        if not self._fire("deadline"):
                            # racing fire emptied the lane under us
                            self._cv.wait(0.01)
                        continue
                    self._cv.wait(remaining)
                else:
                    self._cv.wait(0.5)
        out = slot["out"]
        if isinstance(out, Exception):
            raise out
        return out

    def _take_batch(self, max_width: int) -> Optional[List]:
        """Pop the oldest waiter's shared-table group (same node count,
        same capacity identity, same algorithm — select_many's batching
        precondition), capped at max_width. Waiters left behind fire on
        their own deadline."""
        if not self._waiting:
            return None
        head = self._waiting[0][0]
        key = (len(head.feasible), id(head.capacity), head.algorithm)
        batch, rest = [], []
        for e in self._waiting:
            r = e[0]
            if len(batch) < max_width and \
                    (len(r.feasible), id(r.capacity),
                     r.algorithm) == key:
                batch.append(e)
            else:
                rest.append(e)
        self._waiting = rest
        return batch

    def _fire(self, trigger: str) -> bool:
        # cv held on entry; the kernel work runs with it RELEASED so
        # later evals' host phases overlap the in-flight device batch
        # and accumulate the next one (two-deep pipeline: at most
        # MAX_INFLIGHT BATCHED dispatches in flight). With both
        # pipeline slots busy — in practice only during a cold-start
        # compile storm — the oldest waiter falls through SOLO, the
        # exact unbounded-concurrency behavior of the direct kernel
        # path, so the cap can delay coalescing but never an eval
        from ..ops.select import GATEWAY_MAX_LANES
        width = GATEWAY_MAX_LANES if self._inflight < self.MAX_INFLIGHT \
            else 1
        batch = self._take_batch(width)
        if not batch:
            return False
        import time as _time
        from ..utils import stages
        now = _time.monotonic()
        self.stats[trigger + "_dispatches"] += 1
        self.stats["dispatches"] += 1
        self.stats["lanes_sum"] += len(batch)
        if len(batch) > 1:
            self.stats["batches"] += 1
        batch_id = self.stats["dispatches"]
        for e in batch:
            waited = now - e[2]
            self.stats["wait_s_sum"] += waited
            if stages.enabled:
                stages.add("gateway_wait", waited)
            # flight recorder: the park span lands on the PARKED
            # eval's trace (captured at dispatch()) with the batch
            # anatomy — the firing thread belongs to some other eval
            for tr_ in e[4]:
                tr_.add_span("gateway_wait", waited, end_mono=now,
                             track="gateway",
                             attrs={"trigger": trigger,
                                    "batch": batch_id,
                                    "lanes": len(batch)})
        # every fire counts as in-flight (the drain trigger's
        # engine-busy signal); the MAX_INFLIGHT cap only limits how
        # WIDE a fire may be, so solo fallthroughs can exceed it
        self._inflight += 1
        reqs = [e[0] for e in batch]
        decors = [e[3] for e in batch]
        # the shared device dispatch fans out to every lane's trace
        # (kernel/h2d/d2h spans attach to each eval that rode it)
        fan = [t for e in batch for t in e[4]]
        self._cv.release()
        try:
            with trace.use_many(fan, track="gateway"):
                outs = self._run(reqs, decors)
        finally:
            self._cv.acquire()
            self._inflight -= 1
            wall = _time.monotonic() - now
            self._dispatch_ewma += 0.3 * (wall - self._dispatch_ewma)
        for e, res in zip(batch, outs):
            e[1]["out"] = res
        self._cv.notify_all()
        return True

    def _run(self, reqs, decors) -> List:
        try:
            if len(reqs) == 1:
                return [self._solo(reqs[0], decors[0])]
            originals = None
            if self.partition:
                from ..ops.select import (GATEWAY_MAX_LANES,
                                          partition_lanes)
                # cache read/advance/writeback under the cv: two
                # pipelined in-flight fires racing an unlocked
                # reassignment would lose the (n, total)->lane_ids
                # memo every time they overlap
                with self._cv:
                    base = self._part_rot
                    self._part_rot = (self._part_rot + len(reqs)) \
                        % GATEWAY_MAX_LANES
                    cache = self._part_cache
                originals, cache = partition_lanes(
                    reqs, base, GATEWAY_MAX_LANES, cache)
                with self._cv:
                    self._part_cache = cache
            results = self._kernel.select_many(reqs)
            if originals is not None:
                # a lane that could not fill its slice retries solo on
                # the FULL node set — partitioning must never change
                # failure semantics
                for i, (req, res) in enumerate(zip(reqs, results)):
                    if originals[i] is not None and \
                            res.placed < req.count:
                        req.feasible = originals[i]
                        self.stats["partition_retries"] += 1
                        results[i] = self._kernel.select(req)
            return results
        except Exception as e:  # pragma: no cover — defensive
            return [e] * len(reqs)

    def _solo(self, req, decor):
        """Solo fire with the worker's cross-worker decorrelation (the
        same hash-slice + retry-on-shortfall rule the direct kernel
        path applies for large batch asks)."""
        if decor is not None and req.count >= 256:
            from ..ops.select import decorrelation_slice
            lane, lanes = decor
            with self._cv:
                cache = self._solo_decor_cache
            slice_mask, cache = decorrelation_slice(
                req, lane, lanes, cache)
            with self._cv:
                self._solo_decor_cache = cache
            if slice_mask is not None:
                original = req.feasible
                req.feasible = slice_mask
                res = self._kernel.select(req)
                if res.placed < req.count:
                    req.feasible = original
                    res = self._kernel.select(req)
                return res
        return self._kernel.select(req)


class EvalLane:
    """Planner bound to ONE in-flight eval (worker.go binds this state
    to the worker itself; concurrent batch lanes each need their own
    token/snapshot-index)."""

    def __init__(self, server, ev: Evaluation, token: str):
        self.server = server
        self.eval = ev
        self.token = token
        self.snapshot_index = 0

    # -- Planner interface --------------------------------------------
    def submit_plan(self, plan: Plan) -> Optional[PlanResult]:
        from ..utils import metrics
        t0 = time.monotonic()
        plan.eval_token = self.token
        plan.snapshot_index = self.snapshot_index
        # flight recorder: the applier/committer threads attribute
        # their verify/commit spans through the plan, not thread-locals
        plan._trace = trace.current()
        future = self.server.plan_queue.enqueue(plan)
        result: PlanResult = future.result(timeout=30)
        if chaos_faults.ACTIVE:
            # chaos hook (ISSUE 15): the plan IS committed at this
            # point but the eval is not acked — an armed worker-kill
            # fault raises here, modeling a scheduler worker dying
            # mid-commit. The broker's nack path redelivers the eval
            # and the retry's reconcile must see these placements
            chaos_faults.fire(
                "worker.plan_committed", eval_id=self.eval.id,
                placements=sum(len(a) for a in
                               plan.node_allocation.values()))
        metrics.measure_since("nomad.worker.submit_plan", t0)
        # if some placements were rejected, wait for the refresh index so
        # the next attempt sees why (worker.go:318-340)
        if result.refresh_index:
            self.server.store.block_min_index(result.refresh_index - 1,
                                              timeout_s=RAFT_SYNC_LIMIT)
        return result

    def refreshed_state(self, index: int):
        return self.server.store.snapshot_min_index(index,
                                                    timeout_s=RAFT_SYNC_LIMIT)

    def update_eval(self, ev: Evaluation) -> None:
        self.server.raft_apply("eval_update", dict(evals=[ev]))

    def create_eval(self, ev: Evaluation) -> None:
        ev.snapshot_index = self.snapshot_index
        self.server.raft_apply("eval_update", dict(evals=[ev]))

    def reblock_eval(self, ev: Evaluation) -> None:
        self.server.blocked_evals.block(ev)


class Worker:
    def __init__(self, server, enabled_schedulers: List[str], wid: int = 0):
        self.server = server
        self.schedulers = list(enabled_schedulers)
        self.id = wid
        self.batch_size = max(1, getattr(server.config,
                                         "eval_batch_size", 1))
        # pluggable eval source/sink (ISSUE 16): local workers drain
        # the in-process broker; FollowerWorker swaps in a RemoteBroker
        # that reaches the leader's broker over RPC
        self.broker = server.eval_broker
        # snapshot-fence budget: how long to wait for the local store
        # to reach the eval's modify index before nacking. Local
        # workers share the store that took the write (RAFT_SYNC_LIMIT
        # is generous); followers shrink this to follower_fence_timeout_s
        self.fence_timeout_s = RAFT_SYNC_LIMIT
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stats = {"processed": 0, "failed": 0, "batches": 0,
                      "pipelined_finishes": 0, "fence_timeouts": 0}
        # pipelined dispatch: eval N's terminal bookkeeping (broker
        # ack + latency accounting) runs on a finisher thread while
        # this thread dequeues eval N+1 and starts its host phase —
        # bounded to a DOUBLE BUFFER (one finish in flight + one
        # queued) so a wedged ack applies backpressure instead of
        # accumulating unacked evals
        self.pipeline = bool(getattr(server.config, "worker_pipeline",
                                     True))
        self._finish_q = None
        self._finisher: Optional[threading.Thread] = None
        # one kernel shared by this worker's gateways (jit caches warm
        # across batches)
        from ..ops import SelectKernel
        self._kernel = SelectKernel()

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self.pipeline:
            import queue
            self._finish_q = queue.Queue(maxsize=2)
            self._finisher = threading.Thread(
                target=self._finish_loop, daemon=True,
                name=f"worker-{self.id}-finisher")
            self._finisher.start()
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name=f"worker-{self.id}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        if self._finish_q is not None:
            # drain: the sentinel rides behind any pending finishes, so
            # deferred acks land before shutdown returns
            import queue as _queue
            try:
                self._finish_q.put(None, timeout=5.0)
            except _queue.Full:
                LOG.warning(
                    "worker %d: finish queue wedged at shutdown; "
                    "pending deferred acks will be dropped (evals "
                    "redeliver after nack timeout)", self.id)
            if self._finisher:
                self._finisher.join(timeout=5)
                if self._finisher.is_alive():
                    LOG.warning(
                        "worker %d: finisher did not drain at "
                        "shutdown", self.id)

    def _finish_loop(self) -> None:
        while True:
            fn = self._finish_q.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:       # pragma: no cover — defensive
                LOG.exception("worker %d: deferred finish failed",
                              self.id)

    def set_pause(self, paused: bool) -> None:
        if paused:
            self._paused.set()
        else:
            self._paused.clear()

    def run(self) -> None:
        # GC safepoints (utils/gcsafe.py): automatic collections on a
        # C2M-sized heap land mid-eval and cost 30-60 ms of scheduling
        # latency; when enabled, collection happens between evals
        # instead — coordinated across workers, restored on exit
        use_safepoints = getattr(self.server.config,
                                 "gc_safepoints", False)
        if use_safepoints:
            gcsafe.enter()
        try:
            self._run_loop(use_safepoints)
        finally:
            if use_safepoints:
                gcsafe.exit_()

    def _run_loop(self, use_safepoints: bool) -> None:
        while not self._stop.is_set():
            if self._paused.is_set():
                time.sleep(0.05)
                continue
            # NOTE: workers never consume the failed queue — the leader's
            # reaper turns those into delayed follow-up evals
            # (leader.go reapFailedEvaluations:766 / Server._reap_failed_evals)
            ev, token = self.broker.dequeue(
                self.schedulers, DEQUEUE_TIMEOUT_S)
            if ev is None:
                continue
            batch = [(ev, token)]
            batch_size = self._effective_batch_size()
            if batch_size > 1 and ev.type != JOB_TYPE_CORE:
                # drain already-READY compatible evals without waiting
                # (eval_broker.go:329 Dequeue; the queue depth IS the
                # batching opportunity)
                while len(batch) < batch_size:
                    ev2, tok2 = self.broker.dequeue(
                        self.schedulers, timeout_s=0)
                    if ev2 is None:
                        break
                    if ev2.type == JOB_TYPE_CORE:
                        # core evals don't place; run solo afterwards
                        self.process_eval(ev2, tok2)
                        continue
                    batch.append((ev2, tok2))
            if len(batch) == 1:
                self.process_eval(ev, token)
            else:
                self.process_eval_batch(batch)
            if use_safepoints:
                gcsafe.safepoint()

    def _effective_batch_size(self) -> int:
        """Configured lane width, shrunk to solo dispatches while the
        governor signals backpressure — wide lanes multiply in-flight
        host work exactly when sampled p99 says the host is the
        bottleneck; width recovers when the gauge clears."""
        if self.batch_size <= 1:
            return self.batch_size
        gov = getattr(self.server, "governor", None)
        if gov is not None and gov.backpressure():
            return 1
        return self.batch_size

    def _micro_gateway(self):
        """The server-wide micro-batch gateway, or None when disabled
        (gateway_window_us=0 / NOMAD_TPU_MICROBATCH=0 — the server
        never constructs one) or when tests force the legacy per-drain
        rendezvous path with NOMAD_TPU_EVAL_BATCH=force."""
        import os
        if os.environ.get("NOMAD_TPU_EVAL_BATCH") == "force":
            return None
        return getattr(self.server, "gateway", None)

    def _make_lane(self, ev: Evaluation, token: str) -> "EvalLane":
        """Planner-lane factory seam: FollowerWorker returns a
        RemoteEvalLane whose plans travel over Plan.Submit."""
        return EvalLane(self.server, ev, token)

    def _note_fence(self, seconds: float) -> None:
        """Fence-wait observation hook (FollowerWorker feeds the
        cluster_sched.fence_wait_p99_ms reservoir through this)."""

    # -- single eval ---------------------------------------------------
    def process_eval(self, ev: Evaluation, token: str,
                     dispatch=None, lat_scale: int = 1) -> None:
        from ..utils import metrics
        lane = self._make_lane(ev, token)
        if dispatch is None and ev.type != JOB_TYPE_CORE:
            # continuous micro-batching (ISSUE 7): every eval's kernel
            # dispatches flow through the server-wide gateway, where
            # requests that overlap within the adaptive window coalesce
            # into one padded device call — across lanes AND across
            # workers. The gateway's solo path preserves the
            # cross-worker decorrelation the direct kernel path applies
            gw = self._micro_gateway()
            if gw is not None:
                n_workers = len(getattr(self.server, "workers", []) or [])
                if n_workers > 1:
                    from functools import partial
                    dispatch = partial(gw.dispatch,
                                       decorrelate=(self.id, n_workers))
                else:
                    dispatch = gw.dispatch
        # flight recorder (ISSUE 9): one span tree per eval, anchored
        # back at broker enqueue. The context installs the trace as
        # this thread's span target, so the stage report sites inside
        # the fence + Process() window (reconcile, table_build, h2d,
        # kernel, d2h, sched_host) attribute to THIS eval; the plan
        # applier and gateway attach their spans through the plan /
        # dispatch entry instead. Core evals don't place — not traced.
        from ..utils import stages
        tr = None
        if ev.type != JOB_TYPE_CORE:
            tr = trace.begin(ev, track=f"worker-{self.id}")
            if stages.enabled:
                stages.add("queue_wait",
                           getattr(ev, "queue_wait_s", 0.0) or 0.0)
        try:
            with trace.use(tr):
                # the snapshot fence (ISSUE 16 names it): wait for the
                # LOCAL state store to catch up to the eval's modify
                # index. Free on the leader; on a follower this is
                # replication lag made visible — surfaced as the
                # fence_wait stage so the stage report separates it
                # from sched_host
                t0 = time.monotonic()
                snap = self.server.store.snapshot_min_index(
                    ev.modify_index, timeout_s=self.fence_timeout_s)
                fence_dt = time.monotonic() - t0
                metrics.measure_since("nomad.worker.wait_for_index", t0)
                if stages.enabled and ev.type != JOB_TYPE_CORE:
                    stages.add("fence_wait", fence_dt)
                self._note_fence(fence_dt)
                lane.snapshot_index = snap.latest_index()
                if self.pipeline and ev.type != JOB_TYPE_CORE:
                    # pipelined dispatch: refresh the resident table
                    # NOW — the host row deltas apply here and the
                    # device mirror's scatter is dispatched
                    # asynchronously (never blocked on), so the device
                    # absorbs the table update while this thread
                    # builds the scheduler and its masks. build=False:
                    # a stale snapshot must not pay a private full
                    # build just to warm a cache it can't use
                    try:
                        snap.node_table(build=False)
                    except Exception:   # pragma: no cover — defensive
                        pass
                if ev.type == JOB_TYPE_CORE:
                    # worker.go invokeScheduler: _core evals get the GC
                    # pseudo-scheduler, not a placement scheduler
                    from .core_sched import CoreScheduler
                    sched = CoreScheduler(snap, self.server)
                else:
                    sched = new_scheduler(self._scheduler_for(ev), snap,
                                          lane)
                    if dispatch is not None and \
                            hasattr(sched, "kernel_dispatch"):
                        sched.kernel_dispatch = dispatch
                    # cross-worker decorrelation: concurrent workers
                    # must not all argmax onto the same winners
                    # (ops/select.py SelectKernel.decorrelate;
                    # propagated onto the engine's kernel by
                    # _process_once)
                    n_workers = len(getattr(self.server, "workers", [])
                                    or [])
                    if n_workers > 1:
                        sched.kernel_decorrelate = (self.id, n_workers)
                t0 = time.monotonic()
                sched.process(ev)
                if stages.enabled and ev.type != JOB_TYPE_CORE:
                    stages.add("sched_host", time.monotonic() - t0)
            metrics.measure_since(
                f"nomad.worker.invoke_scheduler_{self._scheduler_for(ev)}"
                if ev.type != JOB_TYPE_CORE
                else "nomad.worker.invoke_scheduler_core", t0)
            gov = getattr(self.server, "governor", None)
            elapsed = time.monotonic() - t0

            # service-latency attribution fix (ISSUE 7 satellite): the
            # broker stamps how long the eval sat in the READY queue;
            # without it latency reporting starts at dequeue and a
            # backed-up queue reads as a healthy server. It feeds the
            # governor's FULL-latency reservoir only — the
            # backpressure p99 gauge stays host-processing-only, or a
            # backlog would inflate the very gauge that sheds
            # enqueues and shrinks lanes (positive feedback)
            q_wait = getattr(ev, "queue_wait_s", 0.0)

            def _finish():
                from ..utils import stages
                if gov is not None and ev.type != JOB_TYPE_CORE:
                    # lat_scale normalizes batched lanes: B concurrent
                    # GIL-sharing lanes each see ~B× their own host
                    # work in wall clock, and feeding that raw into
                    # the p99 gauge would engage backpressure on
                    # healthy wide batches (then oscillate lane width)
                    gov.observe_eval_latency(elapsed / lat_scale,
                                             queue_wait_s=q_wait)
                a0 = time.perf_counter() if stages.enabled else 0.0
                with trace.use(tr):
                    self.broker.ack(ev.id, token)
                    if stages.enabled:
                        stages.add("broker_ack",
                                   time.perf_counter() - a0)
                # the ack closes the span tree: enqueue -> ... -> ack
                trace.finish(tr, status="acked")
                self.stats["processed"] += 1
                # counter (not just the periodic total_processed
                # gauge): the telemetry ring derives evals/s from
                # slot-to-slot deltas of this
                metrics.incr_counter("nomad.worker.eval_processed")

            if self._finish_q is not None:
                # overlap the ack-side bookkeeping with the next
                # eval's dequeue + host phase (double-buffered)
                self.stats["pipelined_finishes"] += 1
                self._finish_q.put(_finish)
            else:
                _finish()
        except Exception as e:
            if isinstance(e, chaos_faults.WorkerKilled):
                # an INJECTED kill (chaos cell), not a scheduler bug:
                # the nack below is exactly the redelivery the cell's
                # no-double-commit invariant exercises
                LOG.warning("worker %d: %s", self.id, e)
            elif isinstance(e, TimeoutError):
                # snapshot fence expired: the local store never reached
                # the eval's modify index (a lagging follower, or a
                # leader mid-restore). NACK — never drop — so the eval
                # redelivers to a scheduler whose store caught up
                self.stats["fence_timeouts"] += 1
                LOG.debug("worker %d: eval %s fence timed out; nacked",
                          self.id, ev.id)
            elif isinstance(e, (ConnectionError, RpcError,
                                RpcRefused)):
                # the transport under this eval died mid-flight (a
                # killed leader during failover, a server shutting
                # down): expected during leadership transfer — nack
                # and let the new leader's restored broker redeliver
                LOG.debug("worker %d: eval %s lost its transport (%s);"
                          " nacked", self.id, ev.id, e)
            else:
                LOG.exception("worker %d: eval %s failed", self.id,
                              ev.id)
            self.stats["failed"] += 1
            try:
                self.broker.nack(ev.id, token)
            except Exception:
                pass
            trace.finish(tr, status="failed")

    # -- batched evals -------------------------------------------------
    def process_eval_batch(self, batch: List) -> None:
        """Process B dequeued evals as concurrent lanes. With the
        micro-batch gateway live (ISSUE 7), the lanes simply run
        concurrently and their kernel dispatches flow into the
        server-wide gateway, where the window/occupancy triggers — not
        a per-drain pre-decision — determine coalescing (lanes from
        OTHER workers join the same batches). Legacy path (gateway off
        or NOMAD_TPU_EVAL_BATCH=force): one per-drain BatchGateway
        rendezvous; their kernel dispatches coalesce into select_many
        calls. Host-side work (reconcile, plan build) interleaves under
        the GIL; the device sees whole batches. When the kernel's cost
        model says these shapes route to the host CPU anyway, the
        drained evals are processed sequentially instead — lanes would
        only add thread overhead there."""
        # profitability needs the real ask size: a 10k-count batch job
        # routes to the accelerator where lane coalescing pays, while
        # the default hint (16) would route to CPU and skip batching
        count_hint = 16
        try:
            for ev, _tok in batch:
                job = self.server.store.job_by_id(ev.namespace,
                                                  ev.job_id)
                if job is not None:
                    count_hint = max(count_hint,
                                     sum(tg.count
                                         for tg in job.task_groups))
        except Exception:
            pass
        micro = self._micro_gateway() is not None
        if not self._kernel.batch_dispatch_profitable(
                self.server.store.node_count(), count_hint=count_hint,
                tolerance=(MicroBatchGateway.COST_TOLERANCE
                           if micro else 1.0)):
            # host-routed shapes: B solo dispatches beat one vmapped
            # dispatch and the GIL serializes lane host work — with or
            # without the gateway, lane threads would only add overhead
            for ev, token in batch:
                self.process_eval(ev, token)
            return
        if micro:
            # bounded lane concurrency: the gateway only needs ENOUGH
            # overlap to coalesce (its occupancy grows with load via
            # the drain trigger), while every extra GIL-sharing host
            # phase inflates ALL of them — lane threads PULL from the
            # drained batch instead of one-thread-per-eval
            lanes = min(MICRO_LANES, len(batch))
            lock = make_lock()
            it = iter(batch)

            def lane_run():
                while True:
                    with lock:
                        ev_tok = next(it, None)
                    if ev_tok is None:
                        return
                    self.process_eval(ev_tok[0], ev_tok[1],
                                      lat_scale=lanes)

            threads = [threading.Thread(
                target=lane_run, daemon=True,
                name=f"worker-{self.id}-lane-{i}")
                for i in range(lanes)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return
        n_workers = max(1, len(getattr(self.server, "workers", []) or []))
        gateway = BatchGateway(self._kernel, lanes=len(batch),
                               lane_base=self.id * len(batch),
                               lane_total=n_workers * len(batch))
        threads = []

        def lane_run(ev, token):
            try:
                self.process_eval(ev, token, dispatch=gateway.dispatch,
                                  lat_scale=len(batch))
            finally:
                gateway.lane_finished()

        for ev, token in batch:
            t = threading.Thread(target=lane_run, args=(ev, token),
                                 daemon=True,
                                 name=f"worker-{self.id}-lane-{ev.id[:8]}")
            threads.append(t)
            t.start()
        for t in threads:
            t.join()
        self.stats["batches"] += 1

    @staticmethod
    def _scheduler_for(ev: Evaluation) -> str:
        return ev.type if ev.type in ("service", "batch", "system") else "batch"
