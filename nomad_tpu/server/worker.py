"""Scheduling worker: dequeue -> snapshot fence -> scheduler.process ->
ack/nack. Implements the scheduler's Planner interface against the
server (plan queue + raft shim).

Reference semantics: nomad/worker.go — run:105-138, dequeueEvaluation:142,
snapshotMinIndex:228, invokeScheduler:244, SubmitPlan:277-343 (snapshot
index fencing + RefreshIndex handling), exponential backoff, pause
during leadership transitions.

Multi-eval batching (SURVEY §2.6 row 1: "batch multiple evals per
device dispatch"): after a blocking dequeue lands one eval, the worker
drains up to eval_batch_size-1 more READY evals without waiting and
processes them as concurrent lanes whose kernel dispatches meet at a
BatchGateway barrier — one vmapped select_many per rendezvous instead
of one device round trip per eval. The broker's one-outstanding-per-job
invariant guarantees the lanes are distinct jobs; plans still serialize
through the plan applier.
"""

from __future__ import annotations

import logging
import threading
import time

from ..utils import gcsafe
from typing import List, Optional

from ..models import Evaluation, JOB_TYPE_CORE, Plan, PlanResult
from ..scheduler import new_scheduler

LOG = logging.getLogger("nomad_tpu.worker")

BACKOFF_BASE_S = 0.05
BACKOFF_LIMIT_S = 3.0
DEQUEUE_TIMEOUT_S = 0.5
RAFT_SYNC_LIMIT = 10.0


class BatchGateway:
    """Rendezvous point turning concurrent per-lane kernel dispatches
    into one multi-eval device dispatch (ops/select.py select_many).

    Each lane is one in-flight eval. A lane interacts in exactly two
    ways: dispatch(req) — block until the coalesced result is ready —
    and lane_finished() when its eval completes. A batch fires when
    every still-active lane is parked in dispatch() (maximum width), or
    when the oldest parked request has waited out a short window —
    adaptive behavior: host-bound runs degrade toward per-eval
    dispatches instead of serializing behind stragglers, device-bound
    runs (short host phases) reach full width. Firing a partial batch
    is always safe: late lanes simply form the next batch."""

    WINDOW_S = 0.02

    def __init__(self, kernel, lanes: int, lane_base: int = 0,
                 lane_total: Optional[int] = None):
        self._kernel = kernel
        self._cv = threading.Condition()
        self._active = lanes
        # cross-worker decorrelation for batched lanes: each worker's
        # gateway slices the node hash space at an offset so two
        # workers' lane 0 don't fight over the same winners
        self._lane_base = lane_base
        self._lane_total = lane_total or lanes
        self._waiting: List = []        # [(req, slot_dict)]
        self._open_t = 0.0              # arrival of the oldest waiter
        self._part_cache = (None, None)  # (n, lanes) -> lane ids per node
        # rendezvous window scaled to the measured dispatch latency: on
        # a tunneled accelerator one round trip costs ~70-250 ms, so a
        # fixed 20 ms window never forms a batch there (VERDICT r4:
        # service_broker_batches=0) — waiting up to half an RTT to
        # share a dispatch is always worth it
        self.window_s = self.WINDOW_S
        try:
            import jax

            from ..ops.select import _accel_roundtrip_s
            if jax.default_backend() != "cpu":
                self.window_s = min(max(0.5 * _accel_roundtrip_s(),
                                        self.WINDOW_S), 0.15)
        except Exception:
            pass

    def dispatch(self, req):
        slot = {}
        with self._cv:
            if not self._waiting:
                self._open_t = time.monotonic()
            self._waiting.append((req, slot))
            self._fire_if_ready()
            while "out" not in slot:
                if self._waiting:
                    remaining = self.window_s - (time.monotonic()
                                                 - self._open_t)
                    if remaining <= 0:
                        # nomad-lint: allow[lock-discipline] _fire releases the cv around the kernel dispatch (see its body)
                        self._fire()
                        continue
                    self._cv.wait(remaining)
                else:
                    self._cv.wait(0.5)
        out = slot["out"]
        if isinstance(out, Exception):
            raise out
        return out

    def lane_finished(self) -> None:
        with self._cv:
            self._active -= 1
            self._fire_if_ready()

    def _fire_if_ready(self) -> None:
        # cv held. Full width: every active lane is parked here, so no
        # later request can join this batch anyway.
        if not self._waiting or len(self._waiting) < self._active:
            return
        self._fire()

    def _fire(self) -> None:
        # cv held on entry; the kernel work runs with it RELEASED so
        # lanes that arrive mid-dispatch can enqueue (and other lanes'
        # host phases overlap the device round trip). Concurrent fires
        # are safe — each pops its own batch.
        batch, self._waiting = self._waiting, []
        if not batch:
            return
        reqs = [r for r, _ in batch]
        self._cv.release()
        try:
            try:
                originals = self._partition(reqs) if len(reqs) > 1 \
                    else None
                results = self._kernel.select_many(reqs)
                if originals is not None:
                    # a lane that could not fill its slice retries solo
                    # on the FULL node set — partitioning is a
                    # throughput heuristic and must never change
                    # failure semantics
                    for i, (req, res) in enumerate(zip(reqs, results)):
                        if originals[i] is not None and \
                                res.placed < req.count:
                            req.feasible = originals[i]
                            results[i] = self._kernel.select(req)
                outs = results
            except Exception as e:  # pragma: no cover - defensive
                outs = [e] * len(batch)
        finally:
            self._cv.acquire()
        for (_r, slot), res in zip(batch, outs):
            slot["out"] = res
        self._cv.notify_all()

    def _partition(self, reqs):
        """Decorrelate concurrent lanes: identical argmax sequences
        would make every lane place on the same winners and collide in
        the plan applier (optimistic concurrency). The reference
        decorrelates workers by shuffling the node list per eval
        (stack.go:70-90); the columnar analog restricts each lane to a
        hash-partitioned slice of the feasible set — only when the
        slice still leaves generous headroom over the lane's ask.
        Returns the original feasible masks (None where untouched) so
        unlucky lanes can retry unpartitioned."""
        from ..ops.select import decorrelation_slice
        lanes = len(reqs)
        total = max(self._lane_total, lanes)
        originals = [None] * lanes
        n = len(reqs[0].feasible)
        for i, req in enumerate(reqs):
            if len(req.feasible) != n:
                continue
            # one shared rule with the worker's solo decorrelation
            # (ops/select.decorrelation_slice): hash-partition +
            # capacity-aware headroom, retry-on-shortfall semantics
            slice_mask, self._part_cache = decorrelation_slice(
                req, self._lane_base + i, total, self._part_cache)
            if slice_mask is None:
                continue
            originals[i] = req.feasible
            req.feasible = slice_mask
        return originals


class EvalLane:
    """Planner bound to ONE in-flight eval (worker.go binds this state
    to the worker itself; concurrent batch lanes each need their own
    token/snapshot-index)."""

    def __init__(self, server, ev: Evaluation, token: str):
        self.server = server
        self.eval = ev
        self.token = token
        self.snapshot_index = 0

    # -- Planner interface --------------------------------------------
    def submit_plan(self, plan: Plan) -> Optional[PlanResult]:
        from ..utils import metrics
        t0 = time.monotonic()
        plan.eval_token = self.token
        plan.snapshot_index = self.snapshot_index
        future = self.server.plan_queue.enqueue(plan)
        result: PlanResult = future.result(timeout=30)
        metrics.measure_since("nomad.worker.submit_plan", t0)
        # if some placements were rejected, wait for the refresh index so
        # the next attempt sees why (worker.go:318-340)
        if result.refresh_index:
            self.server.store.block_min_index(result.refresh_index - 1,
                                              timeout_s=RAFT_SYNC_LIMIT)
        return result

    def refreshed_state(self, index: int):
        return self.server.store.snapshot_min_index(index,
                                                    timeout_s=RAFT_SYNC_LIMIT)

    def update_eval(self, ev: Evaluation) -> None:
        self.server.raft_apply("eval_update", dict(evals=[ev]))

    def create_eval(self, ev: Evaluation) -> None:
        ev.snapshot_index = self.snapshot_index
        self.server.raft_apply("eval_update", dict(evals=[ev]))

    def reblock_eval(self, ev: Evaluation) -> None:
        self.server.blocked_evals.block(ev)


class Worker:
    def __init__(self, server, enabled_schedulers: List[str], wid: int = 0):
        self.server = server
        self.schedulers = list(enabled_schedulers)
        self.id = wid
        self.batch_size = max(1, getattr(server.config,
                                         "eval_batch_size", 1))
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.stats = {"processed": 0, "failed": 0, "batches": 0,
                      "pipelined_finishes": 0}
        # pipelined dispatch: eval N's terminal bookkeeping (broker
        # ack + latency accounting) runs on a finisher thread while
        # this thread dequeues eval N+1 and starts its host phase —
        # bounded to a DOUBLE BUFFER (one finish in flight + one
        # queued) so a wedged ack applies backpressure instead of
        # accumulating unacked evals
        self.pipeline = bool(getattr(server.config, "worker_pipeline",
                                     True))
        self._finish_q = None
        self._finisher: Optional[threading.Thread] = None
        # one kernel shared by this worker's gateways (jit caches warm
        # across batches)
        from ..ops import SelectKernel
        self._kernel = SelectKernel()

    # -- lifecycle -----------------------------------------------------
    def start(self) -> None:
        if self.pipeline:
            import queue
            self._finish_q = queue.Queue(maxsize=2)
            self._finisher = threading.Thread(
                target=self._finish_loop, daemon=True,
                name=f"worker-{self.id}-finisher")
            self._finisher.start()
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name=f"worker-{self.id}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)
        if self._finish_q is not None:
            # drain: the sentinel rides behind any pending finishes, so
            # deferred acks land before shutdown returns
            import queue as _queue
            try:
                self._finish_q.put(None, timeout=5.0)
            except _queue.Full:
                LOG.warning(
                    "worker %d: finish queue wedged at shutdown; "
                    "pending deferred acks will be dropped (evals "
                    "redeliver after nack timeout)", self.id)
            if self._finisher:
                self._finisher.join(timeout=5)
                if self._finisher.is_alive():
                    LOG.warning(
                        "worker %d: finisher did not drain at "
                        "shutdown", self.id)

    def _finish_loop(self) -> None:
        while True:
            fn = self._finish_q.get()
            if fn is None:
                return
            try:
                fn()
            except Exception:       # pragma: no cover — defensive
                LOG.exception("worker %d: deferred finish failed",
                              self.id)

    def set_pause(self, paused: bool) -> None:
        if paused:
            self._paused.set()
        else:
            self._paused.clear()

    def run(self) -> None:
        # GC safepoints (utils/gcsafe.py): automatic collections on a
        # C2M-sized heap land mid-eval and cost 30-60 ms of scheduling
        # latency; when enabled, collection happens between evals
        # instead — coordinated across workers, restored on exit
        use_safepoints = getattr(self.server.config,
                                 "gc_safepoints", False)
        if use_safepoints:
            gcsafe.enter()
        try:
            self._run_loop(use_safepoints)
        finally:
            if use_safepoints:
                gcsafe.exit_()

    def _run_loop(self, use_safepoints: bool) -> None:
        while not self._stop.is_set():
            if self._paused.is_set():
                time.sleep(0.05)
                continue
            # NOTE: workers never consume the failed queue — the leader's
            # reaper turns those into delayed follow-up evals
            # (leader.go reapFailedEvaluations:766 / Server._reap_failed_evals)
            ev, token = self.server.eval_broker.dequeue(
                self.schedulers, DEQUEUE_TIMEOUT_S)
            if ev is None:
                continue
            batch = [(ev, token)]
            batch_size = self._effective_batch_size()
            if batch_size > 1 and ev.type != JOB_TYPE_CORE:
                # drain already-READY compatible evals without waiting
                # (eval_broker.go:329 Dequeue; the queue depth IS the
                # batching opportunity)
                while len(batch) < batch_size:
                    ev2, tok2 = self.server.eval_broker.dequeue(
                        self.schedulers, timeout_s=0)
                    if ev2 is None:
                        break
                    if ev2.type == JOB_TYPE_CORE:
                        # core evals don't place; run solo afterwards
                        self.process_eval(ev2, tok2)
                        continue
                    batch.append((ev2, tok2))
            if len(batch) == 1:
                self.process_eval(ev, token)
            else:
                self.process_eval_batch(batch)
            if use_safepoints:
                gcsafe.safepoint()

    def _effective_batch_size(self) -> int:
        """Configured lane width, shrunk to solo dispatches while the
        governor signals backpressure — wide lanes multiply in-flight
        host work exactly when sampled p99 says the host is the
        bottleneck; width recovers when the gauge clears."""
        if self.batch_size <= 1:
            return self.batch_size
        gov = getattr(self.server, "governor", None)
        if gov is not None and gov.backpressure():
            return 1
        return self.batch_size

    # -- single eval ---------------------------------------------------
    def process_eval(self, ev: Evaluation, token: str,
                     dispatch=None, lat_scale: int = 1) -> None:
        from ..utils import metrics
        lane = EvalLane(self.server, ev, token)
        try:
            # wait for the state store to catch up to the eval
            t0 = time.monotonic()
            snap = self.server.store.snapshot_min_index(
                ev.modify_index, timeout_s=RAFT_SYNC_LIMIT)
            metrics.measure_since("nomad.worker.wait_for_index", t0)
            lane.snapshot_index = snap.latest_index()
            if self.pipeline and ev.type != JOB_TYPE_CORE:
                # pipelined dispatch: refresh the resident table NOW —
                # the host row deltas apply here and the device mirror's
                # scatter is dispatched asynchronously (never blocked
                # on), so the device absorbs the table update while
                # this thread builds the scheduler and its masks.
                # build=False: a stale snapshot must not pay a private
                # full build just to warm a cache it can't use
                try:
                    snap.node_table(build=False)
                except Exception:   # pragma: no cover — defensive
                    pass
            if ev.type == JOB_TYPE_CORE:
                # worker.go invokeScheduler: _core evals get the GC
                # pseudo-scheduler, not a placement scheduler
                from .core_sched import CoreScheduler
                sched = CoreScheduler(snap, self.server)
            else:
                sched = new_scheduler(self._scheduler_for(ev), snap, lane)
                if dispatch is not None and \
                        hasattr(sched, "kernel_dispatch"):
                    sched.kernel_dispatch = dispatch
                # cross-worker decorrelation: concurrent workers must
                # not all argmax onto the same winners (ops/select.py
                # SelectKernel.decorrelate; propagated onto the
                # engine's kernel by _process_once)
                n_workers = len(getattr(self.server, "workers", []) or [])
                if n_workers > 1:
                    sched.kernel_decorrelate = (self.id, n_workers)
            from ..utils import stages
            t0 = time.monotonic()
            sched.process(ev)
            if stages.enabled and ev.type != JOB_TYPE_CORE:
                stages.add("sched_host", time.monotonic() - t0)
            metrics.measure_since(
                f"nomad.worker.invoke_scheduler_{self._scheduler_for(ev)}"
                if ev.type != JOB_TYPE_CORE
                else "nomad.worker.invoke_scheduler_core", t0)
            gov = getattr(self.server, "governor", None)
            elapsed = time.monotonic() - t0

            def _finish():
                from ..utils import stages
                if gov is not None and ev.type != JOB_TYPE_CORE:
                    # lat_scale normalizes batched lanes: B concurrent
                    # GIL-sharing lanes each see ~B× their own host
                    # work in wall clock, and feeding that raw into
                    # the p99 gauge would engage backpressure on
                    # healthy wide batches (then oscillate lane width)
                    gov.observe_eval_latency(elapsed / lat_scale)
                a0 = time.perf_counter() if stages.enabled else 0.0
                self.server.eval_broker.ack(ev.id, token)
                if stages.enabled:
                    stages.add("broker_ack", time.perf_counter() - a0)
                self.stats["processed"] += 1

            if self._finish_q is not None:
                # overlap the ack-side bookkeeping with the next
                # eval's dequeue + host phase (double-buffered)
                self.stats["pipelined_finishes"] += 1
                self._finish_q.put(_finish)
            else:
                _finish()
        except Exception:
            LOG.exception("worker %d: eval %s failed", self.id, ev.id)
            self.stats["failed"] += 1
            try:
                self.server.eval_broker.nack(ev.id, token)
            except Exception:
                pass

    # -- batched evals -------------------------------------------------
    def process_eval_batch(self, batch: List) -> None:
        """Process B dequeued evals as concurrent lanes sharing one
        BatchGateway: their kernel dispatches coalesce into select_many
        calls. Host-side work (reconcile, plan build) interleaves under
        the GIL; the device sees whole batches. When the kernel's cost
        model says these shapes route to the host CPU anyway, the
        drained evals are processed sequentially instead — lanes would
        only add thread overhead there."""
        # profitability needs the real ask size: a 10k-count batch job
        # routes to the accelerator where lane coalescing pays, while
        # the default hint (16) would route to CPU and skip batching
        count_hint = 16
        try:
            for ev, _tok in batch:
                job = self.server.store.job_by_id(ev.namespace,
                                                  ev.job_id)
                if job is not None:
                    count_hint = max(count_hint,
                                     sum(tg.count
                                         for tg in job.task_groups))
        except Exception:
            pass
        if not self._kernel.batch_dispatch_profitable(
                self.server.store.node_count(), count_hint=count_hint):
            for ev, token in batch:
                self.process_eval(ev, token)
            return
        n_workers = max(1, len(getattr(self.server, "workers", []) or []))
        gateway = BatchGateway(self._kernel, lanes=len(batch),
                               lane_base=self.id * len(batch),
                               lane_total=n_workers * len(batch))
        threads = []

        def lane_run(ev, token):
            try:
                self.process_eval(ev, token, dispatch=gateway.dispatch,
                                  lat_scale=len(batch))
            finally:
                gateway.lane_finished()

        for ev, token in batch:
            t = threading.Thread(target=lane_run, args=(ev, token),
                                 daemon=True,
                                 name=f"worker-{self.id}-lane-{ev.id[:8]}")
            threads.append(t)
            t.start()
        for t in threads:
            t.join()
        self.stats["batches"] += 1

    @staticmethod
    def _scheduler_for(ev: Evaluation) -> str:
        return ev.type if ev.type in ("service", "batch", "system") else "batch"
