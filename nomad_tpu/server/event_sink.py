"""Durable event sinks: at-least-once webhook delivery of the event
stream with raft-committed progress.

Reference semantics: nomad/stream/sink.go (SinkWriter + progress),
nomad/stream/webhook_sink.go (NDJSON POST), nomad/event_sink_manager.go
(the leader runs one managed writer per registered sink; progress is
periodically committed through raft so a new leader resumes where the
old one stopped — redelivery of the tail is allowed, loss is not).
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Dict, List, Optional
from ..utils.locks import make_lock

LOG = logging.getLogger("nomad_tpu.event_sink")

SINK_WEBHOOK = "webhook"

PROGRESS_COMMIT_EVERY_S = 2.0
RETRY_BASE_S = 0.5
RETRY_MAX_S = 15.0


@dataclass
class EventSink:
    """structs.EventSink (nomad/stream/sink.go)."""
    id: str = ""
    type: str = SINK_WEBHOOK
    address: str = ""               # webhook URL
    # topic -> keys filter, same shape the broker's subscriptions use
    topics: Dict[str, List[str]] = field(default_factory=dict)
    latest_index: int = 0           # committed delivery progress
    create_index: int = 0
    modify_index: int = 0

    def stub(self) -> Dict:
        return {"ID": self.id, "Type": self.type, "Address": self.address,
                "Topics": dict(self.topics),
                "LatestIndex": self.latest_index,
                "CreateIndex": self.create_index,
                "ModifyIndex": self.modify_index}


def _post_ndjson(address: str, events: List, timeout_s: float) -> None:
    from ..utils.codec import to_wire
    body = "".join(json.dumps(to_wire(e)) + "\n"
                   for e in events).encode()
    req = urllib.request.Request(
        address, data=body, method="POST",
        headers={"Content-Type": "application/x-ndjson"})
    with urllib.request.urlopen(req, timeout=timeout_s) as resp:
        if resp.status >= 300:
            raise RuntimeError(f"webhook returned {resp.status}")


class _SinkWorker:
    """One managed writer: broker subscription from the sink's
    committed progress, delivery with retry/backoff, periodic progress
    commits through raft."""

    def __init__(self, manager: "EventSinkManager", sink: EventSink):
        self.manager = manager
        self.sink = sink
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"event-sink-{sink.id[:8]}")
        self._delivered_index = sink.latest_index
        self._committed_index = sink.latest_index

    def start(self) -> None:
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _lost_marker(self, reason: str):
        """A synthetic frame telling the consumer events were
        unrecoverable — loss past the broker's replay horizon can
        happen (restart with cold buffer, consumer slower than the
        ring), but it must never happen SILENTLY."""
        from .event_broker import Event
        from ..utils import metrics
        metrics.incr_counter("nomad.event_sink.events_lost")
        LOG.warning("sink %s: events lost (%s)", self.sink.id[:8], reason)
        return Event(topic="_sink", type="EventsLost", key=self.sink.id,
                     index=self._delivered_index,
                     payload={"reason": reason})

    def _subscribe(self, server, inclusive: bool = False):
        """(sub, initial_pending) from the committed progress, with
        replay-gap detection: trimmed_through is the highest index the
        broker has PROVABLY dropped, and epoch_floor marks where this
        broker's event history begins (restarts don't republish) —
        progress at or below either means unrecoverable events, which
        must surface as an EventsLost frame, never a silent skip.
        `inclusive` replays events AT the progress index too (overflow
        recovery: a same-index batch can split, and redelivery is the
        at-least-once answer)."""
        topics = self.sink.topics or None
        from_idx = self._delivered_index - 1 if inclusive \
            else self._delivered_index
        sub, backlog = server.events.subscribe(
            topics, from_index=max(from_idx, 0), max_queued=8192)
        pending: List = []
        if self._delivered_index > 0:
            trimmed = server.events.trimmed_through
            if trimmed > self._delivered_index:
                pending.append(self._lost_marker(
                    f"ring buffer trimmed through index {trimmed}, "
                    f"progress was {self._delivered_index}"))
            elif server.events.epoch_floor > self._delivered_index:
                pending.append(self._lost_marker(
                    "progress predates this server's event history"))
        pending.extend(backlog)
        return sub, pending

    def _run(self) -> None:
        server = self.manager.server
        sub, pending = self._subscribe(server)
        try:
            last_commit = time.monotonic()
            backoff = RETRY_BASE_S
            while not self._stop.is_set():
                if sub.overflowed:
                    # slow-consumer drop: resubscribe INCLUSIVE of the
                    # delivered index — a same-index batch may have
                    # split across the drop, and redelivering already-
                    # sent events is what at-least-once permits; the
                    # ring usually still covers the gap, and
                    # _subscribe marks the loss if it doesn't
                    sub.unsubscribe()
                    sub, replay = self._subscribe(server, inclusive=True)
                    pending.extend(e for e in replay
                                   if e.index >= self._delivered_index
                                   or e.type == "EventsLost")
                if not pending:
                    fresh = sub.next_events(timeout_s=0.5)
                    pending = [e for e in fresh
                               if e.index > self._delivered_index]
                if pending:
                    try:
                        _post_ndjson(self.sink.address, pending,
                                     timeout_s=10.0)
                        self._delivered_index = max(
                            self._delivered_index,
                            max(e.index for e in pending))
                        pending = []
                        backoff = RETRY_BASE_S
                    except Exception as e:
                        LOG.warning("sink %s delivery failed: %s "
                                    "(retrying)", self.sink.id[:8], e)
                        if self._stop.wait(backoff):
                            break
                        backoff = min(backoff * 2, RETRY_MAX_S)
                        continue
                now = time.monotonic()
                if self._delivered_index > self._committed_index and \
                        now - last_commit >= PROGRESS_COMMIT_EVERY_S:
                    last_commit = now
                    if self._commit_progress():
                        self._committed_index = self._delivered_index
        finally:
            sub.unsubscribe()
            # best-effort final progress commit on clean shutdown
            if self._delivered_index > self._committed_index:
                self._commit_progress()

    def _commit_progress(self) -> bool:
        try:
            self.manager.server.raft_apply(
                "event_sink_progress",
                dict(sink_id=self.sink.id,
                     index=self._delivered_index))
            return True
        except Exception as e:
            LOG.warning("sink %s progress commit failed: %s",
                        self.sink.id[:8], e)
            return False


class EventSinkManager:
    """Leader-only lifecycle of sink workers (event_sink_manager.go):
    enabled on establishLeadership, disabled on revoke; watches the
    sink set and reconciles workers."""

    def __init__(self, server):
        self.server = server
        self._l = make_lock()
        self._enabled = False
        self._gen = 0               # retires stale watcher threads
        self._workers: Dict[str, _SinkWorker] = {}

    def set_enabled(self, enabled: bool) -> None:
        with self._l:
            if enabled == self._enabled:
                return
            self._enabled = enabled
            self._gen += 1
            if not enabled:
                for w in self._workers.values():
                    w.stop()
                self._workers.clear()
                return
            threading.Thread(target=self._watch, args=(self._gen,),
                             daemon=True,
                             name="event-sink-mgr").start()

    def _watch(self, gen: int) -> None:
        # generation guard (the drainer's pattern): a leadership flap
        # inside our sleep must retire THIS thread, or every flap
        # leaks one reconciler forever
        while True:
            with self._l:
                if not self._enabled or self._gen != gen:
                    return
            try:
                self.reconcile()
            except Exception:       # pragma: no cover - defensive
                LOG.exception("sink reconcile failed")
            time.sleep(1.0)

    def reconcile(self) -> None:
        sinks = {s.id: s for s in self.server.store.event_sinks()}
        with self._l:
            if not self._enabled:
                return
            for sid in list(self._workers):
                w = self._workers[sid]
                cur = sinks.get(sid)
                if cur is None or cur.address != w.sink.address or \
                        cur.topics != w.sink.topics:
                    w.stop()
                    del self._workers[sid]
            for sid, sink in sinks.items():
                if sid not in self._workers:
                    w = _SinkWorker(self, sink)
                    self._workers[sid] = w
                    w.start()
