"""The plan applier: THE serialization point of the cluster.

Reference semantics: nomad/plan_apply.go — planApply:71 single goroutine,
evaluatePlan:400 (per-node feasibility against the freshest snapshot),
partial commits set RefreshIndex to force worker state refresh,
preemption follow-up evals:287-310. The reference overlaps Raft-apply of
plan N with verification of plan N+1; here commit is a fast in-memory
state-store apply so the overlap is unnecessary, but the verification
batches all touched nodes at once (the EvaluatePool:NumCPU/2 goroutines
become one vectorized pass).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..models import (
    Allocation, AllocsFit, Evaluation, Plan, PlanResult,
    EVAL_STATUS_PENDING,
)
from ..models.evaluation import TRIGGER_PREEMPTION
from .plan_queue import PlanQueue


class PlanApplier:
    def __init__(self, queue: PlanQueue, server):
        self.queue = queue
        self.server = server      # provides .store and .raft_apply()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="plan-applier")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=2)

    def _run(self) -> None:
        while not self._stop.is_set():
            pending = self.queue.dequeue(timeout_s=0.2)
            if pending is None:
                continue
            try:
                result = self.apply(pending.plan)
                pending.future.set_result(result)
            except Exception as e:      # pragma: no cover - defensive
                pending.future.set_exception(e)

    # -- the core ------------------------------------------------------
    def apply(self, plan: Plan) -> PlanResult:
        import time as _time
        from ..utils import metrics
        _t0 = _time.monotonic()
        try:
            return self._apply(plan)
        finally:
            metrics.measure_since("nomad.plan.evaluate", _t0)
            metrics.incr_counter("nomad.plan.apply")

    def _apply(self, plan: Plan) -> PlanResult:
        store = self.server.store
        snapshot = store.snapshot()

        result = PlanResult()
        rejected = False

        # verify each touched node (evaluatePlan / evaluateNodePlan)
        for node_id, placements in plan.node_allocation.items():
            if self._evaluate_node(snapshot, plan, node_id):
                result.node_allocation[node_id] = placements
            else:
                rejected = True
        # stops are always committable; preemptions commit only when the
        # placement they made room for was accepted — otherwise victims
        # would be evicted for an alloc that never enters state
        result.node_update = dict(plan.node_update)
        result.node_preemptions = {
            node_id: victims
            for node_id, victims in plan.node_preemptions.items()
            if node_id in result.node_allocation
            or node_id not in plan.node_allocation}
        result.deployment = plan.deployment
        result.deployment_updates = list(plan.deployment_updates)
        if rejected:
            result.refresh_index = snapshot.latest_index()
        if result.is_no_op():
            return result

        # commit through the raft shim (FSM ApplyPlanResults)
        stopped = [a for allocs in result.node_update.values() for a in allocs]
        placed = [a for allocs in result.node_allocation.values()
                  for a in allocs]
        preempted = [a for allocs in result.node_preemptions.values()
                     for a in allocs]
        for a in placed:
            if a.job is None:
                a.job = plan.job

        # preempted allocs spawn follow-up evals for their jobs
        # (plan_apply.go:287-310)
        preempted_jobs = set()
        evals: List[Evaluation] = []
        for a in preempted:
            existing = snapshot.alloc_by_id(a.id)
            if existing is None:
                continue
            key = (existing.namespace, existing.job_id)
            if key in preempted_jobs:
                continue
            preempted_jobs.add(key)
            job = snapshot.job_by_id(*key)
            if job is None:
                continue
            evals.append(Evaluation(
                namespace=job.namespace, priority=job.priority,
                type=job.type, triggered_by=TRIGGER_PREEMPTION,
                job_id=job.id, status=EVAL_STATUS_PENDING))

        index = self.server.raft_apply(
            "plan_results",
            dict(allocs_stopped=stopped, allocs_placed=placed,
                 allocs_preempted=preempted, deployment=result.deployment,
                 deployment_updates=result.deployment_updates, evals=evals))
        result.alloc_index = index
        for ev in evals:
            self.server.enqueue_eval(ev)
        return result

    def _evaluate_node(self, snapshot, plan: Plan, node_id: str) -> bool:
        """evaluateNodePlan (plan_apply.go:629): would this node's
        placements fit against the freshest state?"""
        node = snapshot.node_by_id(node_id)
        if node is None:
            return False
        if node.status != "ready" and not plan.node_update.get(node_id):
            return False
        if node.drain or node.status != "ready":
            # placements on draining/non-ready nodes rejected; pure stops ok
            if plan.node_allocation.get(node_id):
                return False

        remove_ids = {a.id for a in plan.node_update.get(node_id, [])}
        remove_ids |= {a.id for a in plan.node_preemptions.get(node_id, [])}
        # In-place updates reuse the alloc ID: the planned version replaces
        # the snapshot version, so drop the old copy before appending or the
        # node double-counts its resources (plan_apply.go:674-678).
        placements = plan.node_allocation.get(node_id, [])
        remove_ids |= {a.id for a in placements}
        proposed = [a for a in snapshot.allocs_by_node(node_id)
                    if not a.terminal_status() and a.id not in remove_ids]
        proposed.extend(placements)
        fit, _dim, _used = AllocsFit(
            node, proposed,
            check_devices=bool(node.node_resources.devices))
        return fit
